"""NMFXRouter: the health-checked front door over a replica pool.

The service tier's other half (ISSUE 15; ``nmfx/replica.py`` is the
pool). An :class:`NMFXRouter` exposes the SAME ``submit() -> Future``
surface as one ``NMFXServer`` and places each request on one of N
replicas — MPI-FAUN (arxiv 1609.09154) closes the worker-failure gap at
the algorithm level with redundancy-free work distribution; this is the
request-level analogue: no request is computed twice by design, and no
replica death strands one.

Placement — **content-hash stickiness broken by least-loaded**: the
request matrix's content hash picks a preferred replica by
highest-random-weight (rendezvous) hashing, so repeat submissions of
one dataset land where its device-resident input cache (and padded
exec-cache bucket) is already warm, and the preference is STABLE under
pool membership changes (only keys owned by a removed replica move).
Stickiness yields when the preferred replica's outstanding load exceeds
the least-loaded replica's by more than ``RouterConfig
.stickiness_slack`` — cache affinity is a latency optimization, never a
hot-spot generator.

Failure handling, layer by layer (docs/serving.md "Service tier"):

* **Forward failure / replica-side typed failure** (``QueueFull``,
  ``RequestFailed``, ``ServerCrashed``, ``ServerClosed``, the armed
  ``router.forward`` chaos site): exponential-backoff retry on ANOTHER
  replica, up to ``forward_retries`` re-forwards; exhaustion resolves
  the future with a typed :class:`ForwardFailed` chaining the last
  cause.
* **At-most-once**: a forward timeout on a LIVE replica re-forwards
  only when the original provably never dispatched — the router
  cancels the thread-replica future (succeeds until dispatch) or
  claims the process-replica inbox record back (succeeds until the
  worker claims it); otherwise it keeps waiting. Every resolution is
  keyed by the router request id, so a late duplicate (a readmitted
  copy racing its original) is discarded, never double-delivered.
* **Stale heartbeat ⇒ drain**: a replica whose heartbeat
  (``replica_<id>.json``, the shared ledger) ages past
  ``stale_after_s`` is marked unroutable, in-flight work finishes, and
  its queued requests spill — each spill record is claimed by the
  router and readmitted on a survivor, joined back to the original
  future by request id.
* **Killed replica**: a dead worker's unfinished inbox records are
  reclaimed (breaking the dead pid's claims) and readmitted on
  survivors through the one ``spill_submit_kwargs`` funnel —
  bit-identical to the original submission by the serving exactness
  contract.

Elasticity: ``scale_up()`` spawns a replica against the warm disk
executable cache (~1 s cold start, ISSUE 4 — what makes autoscaling
feasible at all), ``scale_down()`` drains via spill-migration, and
overload sheds at the ROUTER on the ISSUE 14 SLO burn-rate signal
(``RouterConfig.shed_on_burn``) instead of per-replica queue depth
alone — with ``quality_elastic``, a burn-shed request is degraded to
the sketched engine (tagged, never silent) instead of rejected.
"""

from __future__ import annotations

import dataclasses
import hashlib
import heapq
import itertools
import os
import threading
import time
from concurrent.futures import Future

import numpy as np

from nmfx.guards import guarded_by
from nmfx.obs import flight as _flight
from nmfx.obs import metrics as _metrics
from nmfx.serve import (QueueFull, RequestFailed, ServeError,
                        ServerClosed, ServerCrashed)

__all__ = ["ForwardFailed", "NMFXRouter", "NoRoutableReplicas",
           "RouterClosed", "RouterConfig", "RouterError",
           "RouterOverloaded", "RouterStats"]


# --------------------------------------------------------------------------
# metrics (docs/observability.md table; lint NMFX010 cross-references)
_forwards_total = _metrics.counter(
    "nmfx_router_forwards_total",
    "requests forwarded to a replica (re-forwards included)",
    labelnames=("replica",))
_retries_total = _metrics.counter(
    "nmfx_router_retries_total",
    "re-forwards onto another replica, by cause",
    labelnames=("cause",))
_shed_total = _metrics.counter(
    "nmfx_router_shed_total",
    "requests the router shed or degraded instead of queueing",
    labelnames=("action", "cause"))
_readmits_total = _metrics.counter(
    "nmfx_router_readmits_total",
    "spilled requests claimed from a drained/dead replica and "
    "readmitted on a survivor")
_outstanding_gauge = _metrics.gauge(
    "nmfx_router_outstanding",
    "requests accepted by the router and not yet resolved")
_placement_total = _metrics.counter(
    "nmfx_router_placement_total",
    "placements by capability class — the device count of the chosen "
    "replica's mesh (1 = a plain single-device replica)",
    labelnames=("class",))
_router_e2e_hist = _metrics.histogram(
    "nmfx_router_e2e_seconds",
    "router submit-to-resolution latency", labelnames=("outcome",))
# declared identically in nmfx.result_cache / nmfx.serve — the registry
# get-or-creates, so whichever module imports first owns the instance
_coalesced_total = _metrics.counter(
    "nmfx_result_cache_coalesced_total",
    "requests attached as followers to an identical in-flight solve "
    "instead of dispatching their own", labelnames=("layer",))


class RouterError(ServeError):
    """Base class of the router's typed failures."""


class RouterClosed(RouterError):
    """The router no longer accepts (or will not complete) requests."""


class RouterOverloaded(RouterError):
    """The router shed this request — its outstanding bound is hit, or
    the SLO burn-rate signal says the fleet is eating error budget too
    fast to take more load (``RouterConfig.shed_on_burn``). Back off
    and resubmit."""


class NoRoutableReplicas(RouterError):
    """No replica is currently routable (all drained/dead and nothing
    respawned) — the request cannot be placed."""


class ForwardFailed(RouterError):
    """Every forward attempt failed — the initial placement plus
    ``RouterConfig.forward_retries`` re-forwards on other replicas.
    ``__cause__`` chains the last underlying failure."""


#: replica-side failures that justify retrying ON ANOTHER replica:
#: the request provably did not (and will not) produce a result there
_RETRYABLE = (QueueFull, RequestFailed, ServerClosed, ServerCrashed)


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Router policy (frozen, all fields compare — the ``ServeConfig``
    discipline)."""

    #: router-wide admission bound on accepted-but-unresolved requests
    max_outstanding: int = 256
    #: re-forwards on OTHER replicas after a failed forward (the
    #: initial placement is not counted)
    forward_retries: int = 2
    #: base seconds of the exponential backoff between re-forwards
    #: (re-forward i waits ``retry_backoff_s * 2**(i-1)``)
    retry_backoff_s: float = 0.05
    #: per-forward timeout: a forward outstanding longer than this on a
    #: LIVE replica is re-placed only if it provably never dispatched
    #: (see the module docstring); None = no timeout
    forward_timeout_s: "float | None" = None
    #: heartbeat age past which a replica is drained (stale ⇒ mark
    #: unroutable, let in-flight finish, readmit the rest elsewhere)
    stale_after_s: float = 3.0
    #: maintenance loop cadence (health checks, outbox polling,
    #: retry dispatch, deadline enforcement)
    health_interval_s: float = 0.1
    #: how far above the least-loaded replica's outstanding count the
    #: content-sticky replica may be before stickiness yields to
    #: least-loaded placement
    stickiness_slack: int = 4
    #: shed new load while the SLO burn-rate signal reports a fast
    #: burn on one of ``shed_objectives`` (the ISSUE 14 engine)
    shed_on_burn: bool = False
    #: objectives whose fast burn triggers shedding
    shed_objectives: "tuple[str, ...]" = ("availability", "latency_p99")
    #: degrade burn-shed requests to the sketched engine (tagged,
    #: never silent) instead of rejecting them
    quality_elastic: bool = False
    #: SLO evaluation cadence inside the maintenance loop
    slo_interval_s: float = 1.0
    #: metrics-driven elasticity: run the autoscale policy in the
    #: maintenance loop (scale_up/scale_down stay callable either way)
    autoscale: bool = False
    min_replicas: int = 1
    max_replicas: int = 4
    #: mean outstanding per routable replica beyond which the
    #: autoscaler spawns one more (a burn also triggers scale-up)
    scale_up_outstanding: float = 4.0
    #: zero-outstanding streak after which the autoscaler drains one
    scale_down_idle_s: float = 30.0
    #: claims older than this on a dead replica's records are broken
    #: during recovery even when the owner pid is unknown
    break_claims_after_s: float = 30.0
    #: staleness grace for a replica that has not heartbeat YET: a
    #: subprocess worker spends seconds importing its runtime before
    #: its first beat, and draining it in that window would kill every
    #: scale-up (a dead PROCESS is still recovered immediately — the
    #: grace only covers the silent-but-alive startup window)
    spawn_grace_s: float = 120.0
    #: SIGTERM→SIGKILL escalation: a draining process replica still
    #: alive this long after its SIGTERM is presumed wedged (stuck
    #: syscall, ignored signal) and is killed so recovery can reclaim
    #: its records — an alive-but-unresponsive worker must not hold
    #: its queued requests hostage
    drain_kill_after_s: float = 60.0
    #: coalesce concurrent identical submissions (same content hash +
    #: result-affecting config) onto ONE forwarded solve: followers
    #: never forward, attach to the leader's outcome, and survive
    #: replica failover through the leader's re-forward (exactly one
    #: re-dispatch fleet-wide). Deadline'd requests never coalesce.
    #: Opt-in: deduplication changes per-replica dispatch observables
    #: that placement tests and A/B baselines key on
    coalesce_requests: bool = False
    #: directory for the router-level content-addressed result cache
    #: (``nmfx.result_cache``) — a warm hit resolves at the router with
    #: zero forwards; None disables the disk tier and the cache
    result_cache_dir: "str | None" = None
    #: cost-priced placement over a heterogeneous fleet (ISSUE 19,
    #: docs/serving.md "Mesh tier"): partition the routable set into
    #: CAPABILITY CLASSES by replica device count, price each request
    #: from the analytic cost model (solve FLOPs + per-iteration comm
    #: bytes + queue depth — the inputs land in
    #: ``RouterStats.placement_inputs``), and restrict placement to one
    #: class: atlas-shaped requests (input ≥ ``atlas_floor_bytes``) go
    #: to the LARGEST routable class — never to a 1-chip replica while
    #: a mesh replica is routable — and small requests stay on the
    #: SMALLEST (mesh time is too expensive to burn on work a single
    #: chip serves at equal latency). Content-hash stickiness then
    #: operates WITHIN the chosen class. Default-on is safe: a
    #: homogeneous fleet has one class, where this is exactly the old
    #: placement.
    price_placement: bool = True
    #: input-matrix bytes at and above which a request is atlas-class
    atlas_floor_bytes: int = 64 << 20

    def __post_init__(self):
        if self.max_outstanding < 1:
            raise ValueError("max_outstanding must be >= 1")
        if self.forward_retries < 0:
            raise ValueError("forward_retries must be >= 0")
        if self.retry_backoff_s < 0:
            raise ValueError("retry_backoff_s must be >= 0")
        if self.forward_timeout_s is not None \
                and self.forward_timeout_s <= 0:
            raise ValueError("forward_timeout_s must be positive or "
                             "None")
        if self.stale_after_s <= 0:
            raise ValueError("stale_after_s must be positive")
        if self.health_interval_s <= 0:
            raise ValueError("health_interval_s must be positive")
        if self.stickiness_slack < 0:
            raise ValueError("stickiness_slack must be >= 0")
        if self.slo_interval_s <= 0:
            raise ValueError("slo_interval_s must be positive")
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError("need 1 <= min_replicas <= max_replicas")
        if self.scale_up_outstanding <= 0:
            raise ValueError("scale_up_outstanding must be positive")
        if self.scale_down_idle_s <= 0:
            raise ValueError("scale_down_idle_s must be positive")
        if self.break_claims_after_s <= 0:
            raise ValueError("break_claims_after_s must be positive")
        if self.spawn_grace_s < 0:
            raise ValueError("spawn_grace_s must be >= 0")
        if self.drain_kill_after_s <= 0:
            raise ValueError("drain_kill_after_s must be positive")
        if self.atlas_floor_bytes < 1:
            raise ValueError("atlas_floor_bytes must be >= 1")


@dataclasses.dataclass
class RouterStats:
    """Per-request routing spans, readable on the returned future
    (``future.stats``)."""

    #: the router-assigned request id (rides every spill record as
    #: ``router_request_id`` — the dedup key of at-most-once delivery)
    request_id: "str | None" = None
    #: the replica that produced (or last attempted) the result
    replica: "str | None" = None
    #: forward attempts (1 = first placement succeeded)
    attempts: int = 0
    #: whether the final placement was the content-sticky choice
    sticky: "bool | None" = None
    #: submit → resolution wall
    latency_s: "float | None" = None
    #: why the router degraded this request ("slo_burn"), None when
    #: served as requested
    degraded_cause: "str | None" = None
    #: causes of the re-forwards this request survived
    retried: "list[str]" = dataclasses.field(default_factory=list)
    #: capability class the request placed into: the device count of
    #: the chosen replica's mesh (1 = plain replica); recorded on every
    #: placement, priced or not (it is telemetry); None before the
    #: first placement
    placement_class: "int | None" = None
    #: the priced-placement decision inputs (ISSUE 19): input bytes,
    #: the atlas verdict, the per-iteration solve FLOPs and meshed comm
    #: bytes the cost model priced the chosen class at, and the queue
    #: depth the load comparison saw — the audit trail for "why did
    #: this land on an 8-chip mesh"
    placement_inputs: "dict | None" = None


class _RouterFuture(Future):
    def __init__(self, stats: RouterStats):
        super().__init__()
        self.stats = stats


@dataclasses.dataclass
class _Pending:
    rid: str
    a: np.ndarray
    meta: dict
    future: _RouterFuture
    chash: str
    submitted: float
    deadline: "float | None"
    replica_id: "str | None" = None
    inner: "Future | None" = None
    attempts: int = 0
    exclude: set = dataclasses.field(default_factory=set)
    retry_due: "float | None" = None
    retry_cause: "BaseException | None" = None
    forwarded_at: float = 0.0
    #: content-addressed result key — set (leaders only) when this
    #: request coalesces or populates the result cache; None otherwise
    ckey: "str | None" = None
    #: (scfg, ccfg, icfg, requested-quality) to re-key a result the
    #: replica served degraded (a sketched answer must never be
    #: replayed to exact-quality submissions)
    ckey_parts: "tuple | None" = None


@guarded_by("_lock", "_pending", "_retryq", "_outstanding", "_closed",
            "_burning", "_coalesce", "_cofollowers", "counters")
class NMFXRouter:
    """The front door: ``submit()`` with the ``NMFXServer`` surface,
    placed across a :class:`nmfx.replica.ReplicaPool` (see the module
    docstring for placement/failover/elasticity semantics)."""

    def __init__(self, pool, cfg: RouterConfig = RouterConfig(), *,
                 slo_engine=None, telemetry_dir: "str | None" = None,
                 own_pool: bool = True, result_cache=None):
        self.pool = pool
        self.cfg = cfg
        self._own_pool = own_pool
        self._lock = threading.Lock()
        self._pending: "dict[str, _Pending]" = {}
        self._retryq: "list[tuple[float, str]]" = []  # (due, rid)
        self._outstanding: "dict[str, int]" = {}  # per replica
        self._seq = itertools.count()
        self._closed = False
        self._burning: "list[str]" = []  # objectives in fast burn
        self._last_slo = 0.0
        self._idle_since: "float | None" = None
        self._wake = threading.Event()
        if result_cache is not None:
            self.result_cache = result_cache
        elif cfg.result_cache_dir is not None:
            from nmfx.result_cache import ResultCache

            self.result_cache = ResultCache(
                cache_dir=cfg.result_cache_dir, layer="router")
        else:
            self.result_cache = None
        # in-flight coalescing (ISSUE 16), guarded by self._lock:
        # result key → leader _Pending / attached follower rids.
        # Followers live in _pending (close()/stats see them) but
        # never forward — they resolve from the leader's fan-out
        self._coalesce: "dict[str, _Pending]" = {}
        self._cofollowers: "dict[str, list[str]]" = {}
        self.counters = {"submitted": 0, "completed": 0, "failed": 0,
                         "retried": 0, "shed": 0, "degraded": 0,
                         "readmitted": 0, "duplicates": 0,
                         "drained": 0, "recovered": 0,
                         "result_cache_hits": 0, "coalesced": 0}
        if slo_engine is not None:
            self._slo = slo_engine
        elif telemetry_dir is not None:
            # fleet-backed burn signal: process replicas book their
            # serve latency histograms in their OWN registries, so the
            # router must read them through the merged fleet view
            from nmfx.obs.aggregate import FleetCollector
            from nmfx.obs.slo import SLOEngine

            self._slo = SLOEngine(
                snapshot_fn=FleetCollector(
                    telemetry_dir,
                    stale_after_s=max(cfg.stale_after_s, 1.0)
                ).fleet_snapshot)
        else:
            from nmfx.obs.slo import SLOEngine

            self._slo = SLOEngine()
        self._maint = threading.Thread(target=self._run_maintenance,
                                       daemon=True, name="nmfx-router")
        self._maint.start()

    # -- lifecycle ---------------------------------------------------------
    def __enter__(self) -> "NMFXRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self, cancel_pending: bool = False,
              timeout: float = 600.0) -> None:
        """Stop accepting requests. Default: wait for every outstanding
        future to resolve (the pool keeps serving), then stop the
        maintenance thread and close the pool (when the router owns
        it). ``cancel_pending=True`` fails unresolved requests with a
        typed :class:`RouterClosed` instead of waiting."""
        from concurrent.futures import CancelledError
        from concurrent.futures import TimeoutError as FutTimeout

        with self._lock:
            if self._closed:
                pending = []
            else:
                self._closed = True
                pending = list(self._pending.values())
        if cancel_pending:
            for p in pending:
                self._resolve(p, error=RouterClosed(
                    "router closed with this request unresolved"))
        else:
            deadline = time.monotonic() + timeout
            for p in pending:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    p.future.exception(timeout=remaining)
                except (FutTimeout, CancelledError):
                    # nmfx: ignore[NMFX006] -- close() only WAITS; the
                    # request's outcome was already booked elsewhere
                    pass
            # anything still unresolved at the timeout fails typed —
            # the maintenance thread exits only when nothing is
            # pending, so leaving a stuck future would turn close()
            # into the hang it exists to prevent
            for p in pending:
                if not p.future.done():
                    self._resolve(p, error=RouterClosed(
                        f"router close() timed out after {timeout}s "
                        "with this request unresolved"))
        self._wake.set()
        self._maint.join()
        if self._own_pool:
            self.pool.close()

    # -- submission --------------------------------------------------------
    def submit(self, data, ks=(2, 3, 4, 5), restarts: int = 10, *,
               seed: int = 123, solver_cfg=None, init_cfg=None,
               label_rule: str = "argmax", linkage: str = "average",
               grid_slots: int = 48, grid_tail_slots="auto",
               min_restarts: int = 1, priority: int = 0,
               deadline: "float | None" = None,
               timeout: "float | None" = None) -> _RouterFuture:
        """Enqueue one consensus request against the fleet; returns a
        ``Future[ConsensusResult]`` immediately. Arguments mirror
        ``NMFXServer.submit`` (results are bit-identical to a direct
        submission — the serving exactness contract holds through the
        router, including across a failover readmission). Deadlines
        are enforced at the ROUTER (typed ``DeadlineExceeded``; a
        replica-side solve that outlives its deadline is discarded by
        request-id dedup)."""
        from nmfx.api import _as_matrix
        from nmfx.config import InitConfig, SolverConfig
        from nmfx.serve import NMFXServer, spill_meta

        with self._lock:
            if self._closed:
                raise RouterClosed("router is closed")
            n_out = len(self._pending)
            burning = list(self._burning)
        if n_out >= self.cfg.max_outstanding:
            self._note_shed("shed", "admission")
            raise RouterOverloaded(
                f"router outstanding bound reached "
                f"({self.cfg.max_outstanding})")
        scfg = solver_cfg if solver_cfg is not None else SolverConfig()
        icfg = init_cfg if init_cfg is not None else InitConfig()
        degraded_cause = None
        if burning and self.cfg.shed_on_burn:
            if self.cfg.quality_elastic \
                    and NMFXServer._sketch_eligible(scfg):
                # burn-pressure quality elasticity: serve the cheaper
                # engine instead of shedding — tagged end-to-end
                # (ConsensusResult.quality == "sketched"), never silent
                scfg = dataclasses.replace(scfg, backend="sketched")
                degraded_cause = "slo_burn"
                self._note_shed("degraded", "slo_burn")
            else:
                self._note_shed("shed", "slo_burn")
                raise RouterOverloaded(
                    "SLO fast burn on "
                    f"{'/'.join(burning)} — the router is shedding "
                    "load until the burn clears "
                    "(RouterConfig.shed_on_burn)")
        if deadline is not None and timeout is not None:
            raise ValueError("pass either deadline or timeout, not both")
        if timeout is not None:
            deadline = time.monotonic() + timeout
        arr, col_names = _as_matrix(data)
        arr = np.asarray(arr)
        rid = f"req-{os.getpid()}-{next(self._seq)}"
        meta = spill_meta(
            request_id=rid, ks=ks, restarts=restarts, seed=seed,
            scfg=scfg, icfg=icfg, label_rule=label_rule,
            linkage=linkage, grid_slots=grid_slots,
            grid_tail_slots=grid_tail_slots, min_restarts=min_restarts,
            priority=priority, col_names=col_names,
            router_request_id=rid)
        stats = RouterStats(request_id=rid,
                            degraded_cause=degraded_cause)
        # zero-copy content hash (the DataCache.key_for idiom):
        # ascontiguousarray is a no-op on the common contiguous case,
        # and the uint8 view hashes in place instead of materializing
        # a full tobytes() copy of the matrix per submission
        submitted_at = time.monotonic()
        chash = hashlib.sha256(
            np.ascontiguousarray(arr).view(np.uint8)
            .reshape(-1)).hexdigest()
        # request economics (ISSUE 16): the content-addressed result
        # key — shared verbatim with the server layer, so a router
        # cache directory and a replica cache directory interoperate.
        # Deadline'd requests bypass both the cache and coalescing
        # (a replayed/shared result cannot honor a latency contract
        # it never saw)
        ckey = ckey_parts = None
        if deadline is None and (self.result_cache is not None
                                 or self.cfg.coalesce_requests):
            from nmfx.config import ConsensusConfig
            from nmfx.result_cache import request_quality, result_key

            ccfg = ConsensusConfig(
                ks=tuple(ks), restarts=restarts, seed=seed,
                label_rule=label_rule, linkage=linkage,
                grid_slots=grid_slots,
                grid_tail_slots=grid_tail_slots,
                min_restarts=min_restarts)
            quality = request_quality(scfg)
            ckey_parts = (chash, tuple(arr.shape), arr.dtype.str,
                          scfg, ccfg, icfg, quality)
            ckey = result_key(*ckey_parts)
            if self.result_cache is not None:
                cached = self.result_cache.lookup(ckey)
                if cached is not None:
                    with self._lock:
                        if self._closed:
                            raise RouterClosed("router is closed")
                        self.counters["submitted"] += 1
                        self.counters["completed"] += 1
                        self.counters["result_cache_hits"] += 1
                    stats.latency_s = time.monotonic() - submitted_at
                    fut = _RouterFuture(stats)
                    fut.set_result(cached)
                    _router_e2e_hist.observe(stats.latency_s,
                                             outcome="completed")
                    return fut
        pending = _Pending(rid=rid, a=arr, meta=meta,
                           future=_RouterFuture(stats), chash=chash,
                           submitted=time.monotonic(),
                           deadline=deadline)
        with self._lock:
            # authoritative admission re-check at INSERTION: the cheap
            # pre-checks above ran in an earlier lock section, and a
            # close() (or a burst of submits) racing the hash/validate
            # work in between must not slip a request past the closed
            # flag — a post-close insert would hold the maintenance
            # thread (and close()'s join) hostage to a request nobody
            # will resolve
            if self._closed:
                raise RouterClosed("router is closed")
            if len(self._pending) >= self.cfg.max_outstanding:
                self.counters["shed"] += 1
                _shed_total.inc(action="shed", cause="admission")
                _flight.record("router.shed", action="shed",
                               cause="admission")
                raise RouterOverloaded(
                    f"router outstanding bound reached "
                    f"({self.cfg.max_outstanding})")
            leader = None
            if ckey is not None and self.cfg.coalesce_requests:
                cand = self._coalesce.get(ckey)
                if cand is not None and cand.rid in self._pending:
                    leader = cand
            if leader is not None:
                # attach as a follower: accounted in _pending (close()
                # and stats() must see it) but never forwarded — the
                # leader's fan-out resolves it, across re-forwards
                self._pending[rid] = pending
                self._cofollowers.setdefault(ckey, []).append(rid)
                _outstanding_gauge.set(len(self._pending))
                self.counters["submitted"] += 1
                self.counters["coalesced"] += 1
            else:
                self._pending[rid] = pending
                if ckey is not None and self.cfg.coalesce_requests:
                    # the key's in-flight leader (registered under the
                    # SAME lock section as admission — a raise above
                    # can never strand a registry entry)
                    self._coalesce[ckey] = pending
                pending.ckey = ckey
                pending.ckey_parts = ckey_parts
                _outstanding_gauge.set(len(self._pending))
                self.counters["submitted"] += 1
        if leader is not None:
            _coalesced_total.inc(layer="router")
            _flight.record("router.coalesce", request_id=rid,
                           leader=leader.rid, key=ckey[:12])
            return pending.future
        try:
            self._forward(pending)
        except RouterError as e:
            self._abort_leader(pending, e)
            raise
        return pending.future

    def _note_shed(self, action: str, cause: str) -> None:
        _shed_total.inc(action=action, cause=cause)
        _flight.record("router.shed", action=action, cause=cause)
        with self._lock:
            self.counters["degraded" if action == "degraded"
                          else "shed"] += 1

    # -- placement ---------------------------------------------------------
    @staticmethod
    def _hrw(chash: str, replica_id: str) -> int:
        return int.from_bytes(
            hashlib.sha256(f"{chash}:{replica_id}".encode())
            .digest()[:8], "big")

    @staticmethod
    def _capability_class(rep) -> int:
        """Devices behind one replica (1 = plain single-device)."""
        return int(getattr(rep, "n_devices", 1) or 1)

    def _price_placement(self, pending: _Pending, candidates: list,
                         routable: list) -> "tuple[list, list, dict]":
        """Cost-priced class selection (ISSUE 19): restrict placement
        to ONE capability class — the largest for atlas-shaped inputs
        (the hard rule the mesh-tier acceptance test pins: an atlas
        request never lands on a 1-chip replica while a mesh replica
        is routable), the smallest otherwise — and price the request
        against it from the analytic cost model."""
        classes = sorted({self._capability_class(rep)
                          for rep in candidates})
        atlas = int(pending.a.nbytes) >= self.cfg.atlas_floor_bytes
        chosen = classes[-1] if atlas else classes[0]
        candidates = [rep for rep in candidates
                      if self._capability_class(rep) == chosen]
        routable = [rep for rep in routable
                    if self._capability_class(rep) == chosen]
        inputs = {"bytes": int(pending.a.nbytes), "atlas": atlas,
                  "class": chosen, "classes": classes,
                  "flops_per_iter": None, "comm_bytes_per_iter": None}
        try:
            from nmfx.obs import costmodel

            meta = pending.meta
            alg = meta["solver_cfg"]["algorithm"]
            m, n = (int(d) for d in pending.a.shape)
            kmax = max(int(k) for k in meta["ks"])
            lanes = len(meta["ks"]) * int(meta["restarts"])
            fl = costmodel.iteration_flops(alg, "vmap", m, n, kmax)
            if fl is not None:
                inputs["flops_per_iter"] = fl * lanes
            if chosen > 1 and alg in \
                    costmodel.comm_covered_algorithms():
                spec = next((rep.mesh_spec for rep in candidates
                             if getattr(rep, "mesh_spec", None)
                             is not None), None)
                if spec is not None:
                    from nmfx.distributed import parse_mesh_spec

                    r_sh, f_sh, s_sh = parse_mesh_spec(spec)
                    cm = costmodel.comm_model(
                        alg, m, n, kmax, restart_shards=r_sh,
                        feature_shards=f_sh, sample_shards=s_sh,
                        restarts=int(meta["restarts"]))
                    inputs["comm_bytes_per_iter"] = \
                        cm["wire_bytes_per_iter"]
        except Exception:  # nmfx: ignore[NMFX006] -- pricing is an
            pass           # annotation; a model gap must never make a
        #                    request unroutable
        return candidates, routable, inputs

    def _place(self, pending: _Pending):
        """Pick the target replica: cost-priced capability-class
        selection first (``RouterConfig.price_placement``), then
        content-sticky by rendezvous hash WITHIN the class, yielding
        to least-loaded when the sticky choice is more than
        ``stickiness_slack`` outstanding requests busier."""
        routable = self.pool.routable()
        candidates = [rep for rep in routable
                      if rep.replica_id not in pending.exclude]
        if not candidates:
            raise NoRoutableReplicas(
                "no routable replica"
                + (f" outside {sorted(pending.exclude)}"
                   if pending.exclude else ""))
        inputs = None
        if self.cfg.price_placement:
            candidates, routable, inputs = self._price_placement(
                pending, candidates, routable)
        with self._lock:
            loads = {rep.replica_id:
                     self._outstanding.get(rep.replica_id, 0)
                     for rep in candidates}
        min_load = min(loads.values())
        ranked = sorted(candidates, reverse=True,
                        key=lambda rep: self._hrw(pending.chash,
                                                  rep.replica_id))
        # the sticky flag reports cache affinity, so it is judged
        # against the FULL routable set: a failover retry that lands
        # off the (excluded) preferred replica must read sticky=False
        # — it landed on a cold replica
        sticky_id = max((rep.replica_id for rep in routable),
                        key=lambda rid: self._hrw(pending.chash, rid))
        # the loop always returns: walking the rendezvous ranking, the
        # first replica within `stickiness_slack` of the least-loaded
        # wins, and the least-loaded replica itself always qualifies
        for rep in ranked:
            if loads[rep.replica_id] \
                    <= min_load + self.cfg.stickiness_slack:
                st = pending.future.stats
                st.sticky = rep.replica_id == sticky_id
                klass = self._capability_class(rep)
                st.placement_class = klass
                if inputs is not None:
                    inputs["queue_depth"] = loads[rep.replica_id]
                    st.placement_inputs = inputs
                _placement_total.inc(**{"class": str(klass)})
                return rep
        raise AssertionError("unreachable: the min-load candidate "
                             "always satisfies the slack bound")

    # -- forwarding --------------------------------------------------------
    def _forward(self, pending: _Pending) -> None:
        from nmfx import faults

        rep = self._place(pending)
        with self._lock:
            # an ATTEMPT is counted when tried, not when it succeeds —
            # a forward failing before it reaches the replica (the
            # armed router.forward site) must still burn one retry, or
            # a persistently failing path could loop forever
            pending.attempts += 1
        pending.future.stats.attempts = pending.attempts
        try:
            faults.inject("router.forward")
            inner = rep.forward(pending.rid, pending.a, pending.meta)
        except BaseException as e:  # nmfx: ignore[NMFX006] -- routed
            # to _schedule_retry, which re-forwards on another replica
            # or resolves the Future with a typed ForwardFailed
            self._schedule_retry(pending, e,
                                 failed_replica=rep.replica_id)
            return
        now = time.monotonic()
        with self._lock:
            pending.replica_id = rep.replica_id
            pending.inner = inner
            pending.forwarded_at = now
            pending.retry_due = None
            self._outstanding[rep.replica_id] = \
                self._outstanding.get(rep.replica_id, 0) + 1
        st = pending.future.stats
        st.replica = rep.replica_id
        st.attempts = pending.attempts
        _forwards_total.inc(replica=rep.replica_id)
        _flight.record("router.forward", request_id=pending.rid,
                       replica=rep.replica_id,
                       attempt=pending.attempts)
        inner.add_done_callback(
            lambda f, rid=pending.rid, inner_ref=inner:
            self._on_inner_done(rid, inner_ref))

    def _unassign_locked(self, pending: _Pending) -> None:
        if pending.replica_id is not None:
            n = self._outstanding.get(pending.replica_id, 1)
            self._outstanding[pending.replica_id] = max(n - 1, 0)
        pending.replica_id = None
        pending.inner = None

    def _schedule_retry(self, pending: _Pending, cause: BaseException,
                        failed_replica: "str | None" = None) -> None:
        """Book a failed forward and either queue a backoff re-forward
        on another replica or exhaust into a typed failure."""
        cause_name = cause.__class__.__name__
        with self._lock:
            if failed_replica is not None:
                pending.exclude.add(failed_replica)
            self._unassign_locked(pending)
            exhausted = pending.attempts > self.cfg.forward_retries
            if not exhausted:
                delay = (self.cfg.retry_backoff_s
                         * 2 ** max(pending.attempts - 1, 0))
                pending.retry_due = time.monotonic() + delay
                pending.retry_cause = cause
                heapq.heappush(self._retryq,
                               (pending.retry_due, pending.rid))
                self.counters["retried"] += 1
        pending.future.stats.retried.append(cause_name)
        _retries_total.inc(cause=cause_name)
        _flight.record("router.retry", request_id=pending.rid,
                       cause=cause_name, attempt=pending.attempts,
                       exhausted=exhausted)
        if exhausted:
            err = ForwardFailed(
                f"every forward attempt failed ({pending.attempts} "
                f"placement(s), {self.cfg.forward_retries} re-forwards "
                "allowed)")
            err.__cause__ = cause
            self._resolve(pending, error=err)
        else:
            self._wake.set()

    def _on_inner_done(self, rid: str, inner: Future) -> None:
        with self._lock:
            pending = self._pending.get(rid)
            if pending is None or pending.inner is not inner:
                # a late duplicate (stale forward after a re-place or
                # after resolution) — the dedup half of at-most-once
                self.counters["duplicates"] += 1
                return
        if inner.cancelled():
            return  # the router cancelled it (timeout/deadline);
            # the canceller booked the follow-up
        exc = inner.exception()
        if exc is None:
            self._resolve(pending, result=inner.result())
            return
        if isinstance(exc, _RETRYABLE):
            spill_path = getattr(exc, "spill_path", None)
            if spill_path is not None:
                self._consume_spill(pending, spill_path)
            self._schedule_retry(pending, exc,
                                 failed_replica=pending.replica_id)
            return
        self._resolve(pending, error=exc)

    def _consume_spill(self, pending: _Pending, path: str) -> None:
        """A drained replica spilled this request; the router owns the
        payload in memory, so claim the record and consume it — the
        re-forward is the re-admission (counted as one), and no other
        consumer can double-readmit it."""
        from nmfx.serve import claim_spill, release_spill_claim

        if claim_spill(path, f"router-{os.getpid()}"):
            try:
                os.unlink(path)
            except OSError:  # nmfx: ignore[NMFX006] -- already gone
                pass
            release_spill_claim(path)
            with self._lock:
                self.counters["readmitted"] += 1
            _readmits_total.inc()
            _flight.record("router.readmit", request_id=pending.rid,
                           source=path)

    def _release_coalesced_locked(self,
                                  pending: _Pending) -> "list[_Pending]":
        """Pop this leader's coalesce registration and return its
        still-pending followers. Caller holds the router lock. An
        identical submit arriving after the pop becomes the key's new
        leader — attach-after-pop never strands a request."""
        if pending.ckey is None \
                or self._coalesce.get(pending.ckey) is not pending:
            return []
        del self._coalesce[pending.ckey]
        rids = self._cofollowers.pop(pending.ckey, [])
        return [self._pending[r] for r in rids if r in self._pending]

    def _resolve(self, pending: _Pending, result=None,
                 error: "BaseException | None" = None) -> None:
        now = time.monotonic()
        with self._lock:
            if pending.rid not in self._pending:
                self.counters["duplicates"] += 1
                return
            del self._pending[pending.rid]
            self._unassign_locked(pending)
            followers = self._release_coalesced_locked(pending)
            _outstanding_gauge.set(len(self._pending))
            self.counters["completed" if error is None
                          else "failed"] += 1
        if error is None and result is not None \
                and self.result_cache is not None \
                and pending.ckey_parts is not None:
            # re-key a degraded answer at its ACTUAL served quality —
            # a sketched result must never be replayed to
            # exact-quality submissions
            chash, shape, dt, scfg, ccfg, icfg, quality = \
                pending.ckey_parts
            try:
                key = pending.ckey
                if result.quality != quality or key is None:
                    from nmfx.result_cache import result_key

                    key = result_key(chash, shape, dt, scfg, ccfg,
                                     icfg, result.quality)
                self.result_cache.put(key, result)
            except Exception:  # nmfx: ignore[NMFX006] -- cache trouble
                # must never fail a solved request
                pass
        pending.future.stats.latency_s = now - pending.submitted
        fut = pending.future
        self._fanout(pending, followers, result, error)
        if fut.done():
            return
        fut.set_running_or_notify_cancel()
        if fut.done():
            return
        from nmfx.serve import DeadlineExceeded

        if error is None:
            outcome = "completed"
            fut.set_result(result)
        else:
            outcome = ("deadline"
                       if isinstance(error, DeadlineExceeded)
                       else "failed")
            fut.set_exception(error)
        _router_e2e_hist.observe(pending.future.stats.latency_s,
                                 outcome=outcome)

    def _fanout(self, leader: _Pending, followers: "list[_Pending]",
                result, error: "BaseException | None") -> None:
        """Share the leader's outcome with its coalesced followers —
        through the ordinary `_resolve` path, so per-follower counters,
        latency spans, and the outstanding gauge stay exact. Followers
        have ``ckey=None``, so the recursion is one level deep."""
        if not followers:
            return
        _flight.record("router.coalesce_fanout", leader=leader.rid,
                       followers=len(followers),
                       outcome="error" if error is not None
                       else "result")
        for f in followers:
            self._resolve(f, result=result, error=error)

    def _abort_leader(self, pending: _Pending,
                      err: BaseException) -> None:
        """Unwind a submission whose INITIAL placement raised
        synchronously (`submit` re-raises to the caller): un-admit it
        and fail any followers that attached while `_forward` ran."""
        with self._lock:
            dropped = self._pending.pop(pending.rid, None)
            if dropped is not None:
                self._unassign_locked(pending)
                self.counters["submitted"] -= 1
            followers = self._release_coalesced_locked(pending)
            _outstanding_gauge.set(len(self._pending))
        self._fanout(pending, followers, None, err)

    # -- maintenance -------------------------------------------------------
    def _run_maintenance(self) -> None:
        while True:
            self._wake.wait(self.cfg.health_interval_s)
            self._wake.clear()
            with self._lock:
                closed = self._closed
                n_pending = len(self._pending)
            if closed and n_pending == 0:
                return
            try:
                self.pool.poll()
                self._dispatch_due_retries()
                self._check_deadlines_and_timeouts()
                self._check_health()
                self._check_slo()
                if self.cfg.autoscale and not closed:
                    self.autoscale_tick()
            except Exception as e:  # nmfx: ignore[NMFX006] -- the loop
                # must survive; warn-once + flight keep it loud
                from nmfx.faults import warn_once

                warn_once("router-maintenance-error",
                          f"router maintenance iteration failed "
                          f"({e!r}); continuing")

    def _dispatch_due_retries(self) -> None:
        now = time.monotonic()
        due = []
        with self._lock:
            while self._retryq and self._retryq[0][0] <= now:
                _, rid = heapq.heappop(self._retryq)
                pending = self._pending.get(rid)
                if pending is not None and pending.retry_due is not None:
                    pending.retry_due = None
                    due.append(pending)
        for pending in due:
            try:
                self._forward(pending)
            except NoRoutableReplicas as e:
                cause = pending.retry_cause or e
                err = NoRoutableReplicas(
                    "no routable replica left to re-forward to")
                err.__cause__ = cause
                self._resolve(pending, error=err)

    def _check_deadlines_and_timeouts(self) -> None:
        from nmfx.serve import DeadlineExceeded

        now = time.monotonic()
        with self._lock:
            snapshot = list(self._pending.values())
        for pending in snapshot:
            if pending.deadline is not None and now >= pending.deadline:
                inner = pending.inner
                if inner is not None:
                    inner.cancel()  # best-effort; a completed solve's
                    # late result is discarded by dedup
                self._resolve(pending, error=DeadlineExceeded(
                    "deadline expired at the router after "
                    f"{now - pending.submitted:.3f}s"))
                continue
            if (self.cfg.forward_timeout_s is not None
                    and pending.inner is not None
                    and pending.retry_due is None
                    and now - pending.forwarded_at
                    > self.cfg.forward_timeout_s):
                self._try_timeout_retry(pending)

    def _try_timeout_retry(self, pending: _Pending) -> None:
        """Forward timeout: re-place ONLY when the original provably
        never dispatched (thread: future still cancellable; process:
        the inbox record is still claimable by us). Otherwise keep
        waiting — at-most-once dispatch beats tail latency."""
        from nmfx.replica import ProcessReplica
        from nmfx.serve import claim_spill, release_spill_claim

        rep = self.pool.get(pending.replica_id)
        undispatched = False
        if rep is None:
            undispatched = True
        elif isinstance(rep, ProcessReplica):
            record = os.path.join(rep.inbox,
                                  f"spill_{pending.rid}.npz")
            if claim_spill(record, f"router-{os.getpid()}"):
                if os.path.exists(record):
                    # the worker never claimed it — safe to move
                    try:
                        os.unlink(record)
                    except OSError:  # nmfx: ignore[NMFX006] -- raced
                        pass
                    rep.forget(pending.rid)
                    undispatched = True
                # else: the record was already consumed (result
                # imminent or landed) — the claim was created against
                # nothing; drop it and keep waiting
                release_spill_claim(record)
        else:
            inner = pending.inner
            undispatched = inner is not None and inner.cancel()
        if undispatched:
            self._schedule_retry(
                pending,
                TimeoutError(f"forward timed out after "
                             f"{self.cfg.forward_timeout_s}s"),
                failed_replica=pending.replica_id)

    def _check_health(self) -> None:
        hb = self.pool.heartbeats(self.cfg.stale_after_s)
        now = time.monotonic()
        for rep in self.pool.all():
            if rep.state == "draining":
                if rep.kind != "process":
                    continue
                if not rep.alive():
                    # a SIGTERM'd worker exited: reclaim whatever it
                    # released (spill-migration's second half)
                    self._recover(rep)
                elif now - getattr(rep, "drained_at", now) \
                        > self.cfg.drain_kill_after_s:
                    # SIGTERM→SIGKILL escalation: an alive-but-wedged
                    # worker (stuck syscall, ignored signal) would
                    # otherwise hold its claimed records — and every
                    # request queued on it — forever
                    _flight.record("router.drain_escalated",
                                   replica=rep.replica_id)
                    rep.kill()
                continue
            if rep.state != "routable":
                continue
            if not rep.alive():
                self._recover(rep)
                continue
            payload = hb.get(rep.replica_id)
            if payload is None:
                # no heartbeat YET: a worker still importing its
                # runtime — grace-gated, while a dead process was
                # already caught by the alive() check above
                if now - rep.spawned_at > self.cfg.spawn_grace_s:
                    self._drain_async(rep.replica_id)
            elif payload.get("stale"):
                self._drain_async(rep.replica_id)

    # -- drain / recovery --------------------------------------------------
    def _drain_async(self, replica_id: str) -> None:
        """The maintenance loop's drain entry: claim the replica (state
        flip under the router lock, so racing health ticks drain once)
        and run the drain on its own short-lived thread — a thread
        replica's drain waits for its in-flight solves, and blocking
        the single maintenance thread on that would stall deadline
        enforcement, retries, and outbox polling fleet-wide."""
        if not self._claim_drain(replica_id):
            return
        threading.Thread(
            target=self._drain_claimed, args=(replica_id,),
            daemon=True, name=f"nmfx-router-drain-{replica_id}").start()

    def _claim_drain(self, replica_id: str) -> bool:
        rep = self.pool.get(replica_id)
        with self._lock:
            if rep is None or rep.state != "routable":
                return False
            rep.state = "draining"
            rep.drained_at = time.monotonic()
            self.counters["drained"] += 1
        return True

    def drain_replica(self, replica_id: str) -> None:
        """Stale ⇒ drain: mark unroutable, let in-flight work finish,
        and land its queued requests elsewhere — thread replicas spill
        through ``close(cancel_pending=True)`` (each ``ServerClosed``'s
        ``spill_path`` is claimed and the request re-forwarded),
        process replicas get SIGTERM (the worker releases queued
        claims; recovery reclaims them when the process exits; one
        that ignores the SIGTERM is SIGKILLed after
        ``drain_kill_after_s``). Synchronous — callers who must not
        block (the maintenance loop) go through the async wrapper."""
        if not self._claim_drain(replica_id):
            return
        self._drain_claimed(replica_id)

    def _drain_claimed(self, replica_id: str) -> None:
        from nmfx.faults import warn_once

        rep = self.pool.get(replica_id)
        if rep is None:
            return
        _flight.record("router.drain", replica=replica_id)
        warn_once(
            "router-drain",
            f"replica {replica_id} drained (stale heartbeat or "
            "scale-down); its queued requests are being readmitted on "
            "the surviving replicas")
        rep.drain()  # thread: synchronous spill; process: SIGTERM
        if rep.kind == "thread":
            self.pool.remove(replica_id)

    def _recover(self, rep) -> None:
        """A replica died (process gone / server down): reclaim its
        unfinished inbox records (breaking the dead owner's claims) and
        re-place every request the router still owes an answer for."""
        from nmfx.serve import (break_spill_claim, claim_spill,
                                list_spills, release_spill_claim,
                                spill_claimant)

        rep.state = "dead"
        dead_pid = getattr(rep, "pid", None)
        reclaimed = 0
        rep.poll()  # consume any results that DID land before death
        with self._lock:
            mine = [p for p in self._pending.values()
                    if p.replica_id == rep.replica_id
                    and p.retry_due is None]
        spill_dir = getattr(rep, "spill_dir", None)
        if spill_dir is not None:
            for path in list_spills(spill_dir):
                claim = spill_claimant(path)
                if claim is not None and not break_spill_claim(
                        path, owner_pid=dead_pid,
                        older_than_s=self.cfg.break_claims_after_s):
                    continue
                if not claim_spill(path, f"router-{os.getpid()}"):
                    continue
                try:
                    os.unlink(path)
                except OSError:  # nmfx: ignore[NMFX006] -- raced
                    pass
                release_spill_claim(path)
                reclaimed += 1
        for pending in mine:
            self._schedule_retry(
                pending,
                ServerCrashed(f"replica {rep.replica_id} died with "
                              "this request outstanding"),
                failed_replica=rep.replica_id)
        with self._lock:
            self.counters["recovered"] += 1
            self.counters["readmitted"] += len(mine)
        if mine:
            _readmits_total.inc(len(mine))
        _flight.record("router.recover", replica=rep.replica_id,
                       readmitted=len(mine), records_reclaimed=reclaimed)
        rep.retire()  # stop side threads (a crashed thread replica's
        # beater must not keep publishing a phantom live heartbeat)
        self.pool.remove(rep.replica_id)

    # -- SLO shedding ------------------------------------------------------
    def _check_slo(self) -> None:
        if not (self.cfg.shed_on_burn or self.cfg.autoscale):
            return
        now = time.monotonic()
        if now - self._last_slo < self.cfg.slo_interval_s:
            return
        self._last_slo = now
        try:
            status = self._slo.evaluate()
        except Exception as e:  # nmfx: ignore[NMFX006] -- a broken
            # burn signal degrades to no shedding, warn-once'd
            from nmfx.faults import warn_once

            warn_once("router-slo-error",
                      f"SLO evaluation failed ({e!r}); the router "
                      "stops shedding until it recovers")
            status = None
        burning = []
        if status is not None:
            for name in self.cfg.shed_objectives:
                obj = status["objectives"].get(name)
                if obj is not None and obj["state"] == "fast_burn":
                    burning.append(name)
        with self._lock:
            was = self._burning
            self._burning = burning
        if burning and not was:
            _flight.record("router.shed_signal", objectives=burning)

    # -- elasticity --------------------------------------------------------
    def scale_up(self):
        """Spawn one replica against the warm cache; a failed spawn
        (the ``replica.spawn`` chaos site) degrades warn-once — the
        fleet keeps serving at its current size."""
        from nmfx.faults import warn_once
        from nmfx.replica import SpawnFailed

        try:
            return self.pool.spawn()
        except SpawnFailed as e:
            warn_once("router-spawn-failed",
                      f"replica scale-up failed ({e}); continuing "
                      "with the current fleet")
            _flight.record("router.spawn_failed", error=e)
            return None

    def scale_down(self, replica_id: "str | None" = None, *,
                   wait: bool = True) -> bool:
        """Drain one replica (least-loaded by default) via
        spill-migration; refuses below ``min_replicas``.
        ``wait=False`` runs the drain on its own thread — the
        autoscaler's form, so a long in-flight solve on the draining
        replica cannot stall the maintenance loop."""
        routable = self.pool.routable()
        if len(routable) <= self.cfg.min_replicas:
            return False
        if replica_id is None:
            with self._lock:
                loads = {rep.replica_id:
                         self._outstanding.get(rep.replica_id, 0)
                         for rep in routable}
            replica_id = min(loads, key=loads.get)
        if wait:
            self.drain_replica(replica_id)
        else:
            self._drain_async(replica_id)
        return True

    def autoscale_tick(self) -> None:
        """One autoscale decision (called by the maintenance loop under
        ``RouterConfig.autoscale``; callable directly for deterministic
        tests): scale up on burn or deep mean outstanding, scale down
        after a sustained idle streak."""
        routable = self.pool.routable()
        n = len(routable)
        with self._lock:
            total = len(self._pending)
            burning = bool(self._burning)
        now = time.monotonic()
        if total > 0:
            self._idle_since = None
        elif self._idle_since is None:
            self._idle_since = now
        if n < self.cfg.max_replicas and (
                burning
                or total >= self.cfg.scale_up_outstanding * max(n, 1)):
            self.scale_up()
        elif (n > self.cfg.min_replicas and total == 0
                and self._idle_since is not None
                and now - self._idle_since
                >= self.cfg.scale_down_idle_s):
            self._idle_since = now  # one drain per idle period
            self.scale_down(wait=False)  # never stall the maintenance
            # loop on a drain (it owns deadlines/retries/polling)

    # -- introspection -----------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            c = dict(self.counters)
            c.update(outstanding=len(self._pending),
                     outstanding_per_replica=dict(self._outstanding),
                     routable_replicas=len(self.pool.routable()),
                     burning=list(self._burning))
        return c

    def slo_status(self, evaluate: bool = False) -> "dict | None":
        """The router SLO engine's most recent evaluation — None until
        something evaluated (the maintenance loop only does under
        ``shed_on_burn``/``autoscale``). ``evaluate=True`` forces a
        fresh evaluation first (the CLI's ``--slo`` report path)."""
        if evaluate:
            return self._slo.evaluate()
        return self._slo.status()
