"""Restart/k sweep: vmapped restarts, optionally sharded over a device mesh.

TPU-native replacement for the reference's job-grid layer (reference
``nmf.r:53-119``): where the reference expands a (k × restart) grid into
BatchJobs R worker processes communicating through a filesystem registry
(SURVEY.md §2c), here the restart axis is a vmapped batch dimension sharded
across TPU cores over ICI, and the per-k consensus reduction happens on-device
— only the n×n consensus matrix and per-restart stats are pulled to host.
"""

from __future__ import annotations

import logging
import time
from functools import lru_cache, partial
from typing import TYPE_CHECKING, Mapping, NamedTuple, Sequence, Union

if TYPE_CHECKING:  # api imports sweep; runtime import here would be a cycle
    from nmfx.api import ConsensusResult

    GridResults = Union[Mapping[int, "KSweepOutput"], "ConsensusResult"]

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nmfx._compat import shard_map
from nmfx.config import (PACKED_ALGORITHMS, ConsensusConfig,
                         InitConfig, SolverConfig)
from nmfx.consensus import labels_from_h
from nmfx.init import initialize, random_init
from nmfx.obs import metrics as _metrics
from nmfx.solvers.base import StopReason, solve

_log = logging.getLogger("nmfx")

#: pad-lane honesty (ISSUE 19): surplus restart lanes added so the pool
#: shards evenly over the mesh's restart axis are computed and
#: discarded — booked here so scaling numbers (bench `detail.mesh`)
#: can subtract them instead of crediting them as throughput
_pad_lanes_total = _metrics.counter(
    "nmfx_mesh_pad_lanes_total",
    "surplus restart lanes padded onto meshed sweeps (computed and "
    "discarded; subtract from restarts/s)")

#: mesh axis name for the restart batch dimension
RESTART_AXIS = "restarts"

#: mesh axis name for the feature (gene/row) dimension of A and W — this
#: workload's tensor-parallel axis (SURVEY.md §5: "shard A's rows across
#: devices ... the analogue of sequence parallelism for this workload").
#: Use when m is too large for one device's HBM; restarts×features compose
#: in one 2-D mesh (see feature_mesh)
FEATURE_AXIS = "features"

#: mesh axis name for the sample (column) dimension of A and H — the
#: sequence/context-parallel axis. Composable with both other axes into the
#: full 3-D restarts×features×samples mesh (see grid_mesh)
SAMPLE_AXIS = "samples"

#: solvers whose updates shard over the feature/sample grid axes through
#: the generic driver: their contracted terms psum along the tiled axes
#: (kl's quotient contractions; neals'/snmf's normal-equation Grams;
#: hals' shared GEMM precomputations). mu grids through its dedicated
#: packed path; als/pg/alspg have lstsq / line-search structures with no
#: collective formulation and stay restart-parallel only
GRID_SOLVERS = ("kl", "neals", "snmf", "hals")


class KSweepOutput(NamedTuple):
    consensus: jax.Array  # (n, n)
    iterations: jax.Array  # (restarts,)
    dnorms: jax.Array  # (restarts,)
    stop_reasons: jax.Array  # (restarts,)
    labels: jax.Array  # (restarts, n)
    best_w: jax.Array  # (m, k) factors of the lowest-residual restart
    best_h: jax.Array  # (k, n)
    #: every restart's factors, retained only under ``keep_factors=True``
    #: (the reference's registry keeps each job's full (W, H, iter),
    #: nmf.r:50; see also restart_factors for the recompute-by-key route)
    all_w: jax.Array | None = None  # (restarts, m, k) or None
    all_h: jax.Array | None = None  # (restarts, k, n) or None


class ChunkSweepOutput(NamedTuple):
    """One restart-chunk's per-lane results — the durable-sweep ledger's
    record payload (``nmfx/checkpoint.py``): everything the finalize
    step needs to rebuild a rank's ``KSweepOutput`` from records alone,
    in canonical restart order, regardless of completion order."""

    labels: jax.Array  # (chunk, n); quarantined lanes masked to -1
    iterations: jax.Array  # (chunk,)
    dnorms: jax.Array  # (chunk,) raw final residuals
    stop_reasons: jax.Array  # (chunk,)
    #: chunk-local index of the lowest-dnorm SURVIVING lane (ties break
    #: to the lowest index — the same first-min rule ``argmin`` applies
    #: globally, so the chunk holding the global best always nominates
    #: exactly that lane)
    best_local: jax.Array  # () i32
    best_w: jax.Array  # (m, k)
    best_h: jax.Array  # (k, n)


def _quarantine_lanes(labels, dnorm, stops):
    """Per-rank numeric-quarantine masking shared by every sweep
    epilogue: lanes that stopped with ``StopReason.NUMERIC_FAULT``
    (``SolverConfig.nonfinite_guard``) — or were screened out of the
    exact phase (``StopReason.SCREENED``, ``SolverConfig.screen``) —
    get their labels masked to -1 — ``one_hot`` then drops them from
    the consensus reduction exactly like pad lanes/columns — and their
    (possibly non-finite) dnorm masked to +inf so the best-restart
    argmin never selects them. Fault-free unscreened ranks pass through
    bit-identically (all-False selects).
    Returns ``(labels, dnorm_for_best, faulted)``."""
    faulted = ((stops == jnp.int32(StopReason.NUMERIC_FAULT))
               | (stops == jnp.int32(StopReason.SCREENED)))
    labels = jnp.where(faulted[:, None], -1, labels)
    dnorm_best = jnp.where(faulted, jnp.array(jnp.inf, dnorm.dtype), dnorm)
    return labels, dnorm_best, faulted


def _quarantined_consensus(labels, k: int, restarts: int, faulted):
    """Mean connectivity over the SURVIVING lanes: quarantined lanes
    contribute exact zeros to the one-hot einsum (labels -1), and the
    normalizer becomes the survivor count — so a rank with one diverged
    restart reports exactly the consensus of the same sweep without that
    restart. The fault-free branch keeps the original CONSTANT-divisor
    graph, so quarantine-off and quarantine-on runs of healthy data are
    bit-identical."""
    onehot = jax.nn.one_hot(labels, k, dtype=jnp.float32)
    raw = jnp.einsum("rik,rjk->ij", onehot, onehot)
    n_fault = jnp.sum(faulted, dtype=jnp.int32)
    survivors = jnp.maximum(restarts - n_fault, 1).astype(jnp.float32)
    return jnp.where(n_fault > 0, raw / survivors, raw / restarts)


def _poison_restart_lanes(w0, lane_idx: tuple) -> jax.Array:
    """Trace-time ``solve.nonfinite`` injection (``nmfx.faults``): set
    one entry of each selected lane's W0 to NaN. The armed spec is
    static at trace time — the builders' caches are keyed by
    ``faults.trace_token()`` — so the poison compiles in as constant
    indices and a lane is poisoned identically on every execution path
    (solo, whole-grid, bucketed, packed), which is what the
    quarantine-exactness tests pin."""
    if not lane_idx:
        return w0
    return w0.at[jnp.asarray(lane_idx), 0, 0].set(
        jnp.asarray(jnp.nan, w0.dtype))


def _pad_pool_lanes(w0, h0, job_ks: tuple, slots: int):
    """Pad a serving-tier job batch with inert all-zero lanes up to the
    full ``slots`` pool width — the composition-independent-geometry
    half of the packed==solo bit-identity contract (the other half is
    the fixed single-stage pool in the same builders).

    Why: the slot scheduler's data GEMMs fold the lane axis into one
    GEMM's free dimension (``grid_mu`` module docstring), and XLA's CPU
    backend picks its reduction partitioning per GEMM *shape* — under a
    constrained thread pool (the 8-virtual-device test platform) a
    36-lane pool's per-lane reductions differ from a 12-lane pool's by
    ~1 ulp/iteration. That drift is irrelevant inside one executable
    but violated the serve exactness contract: a ≥3-request packed
    dispatch (wider pool) drifted bitwise from each request's solo
    bucketed run (narrower pool) in dnorms/best_w/best_h while
    labels/consensus agreed (the PR-12-flagged pre-existing bug,
    reproduced at 120×48/maxiter 400). Padding every serving-tier
    dispatch to the same ``slots``-wide pool makes the GEMM shapes —
    and hence each lane's reduction order — independent of what else
    was packed alongside.

    The pad lanes are all-zero factors, which every packed-family block
    maps to zero with no non-finite intermediates (mu/hals: zero
    numerators; neals/snmf: zero Grams + the absolute-tiny jitter, zero
    rhs; als: min-norm lstsq of a zero matrix; kl: zero numerator
    contraction), so they TolX-stop at the first check and sit frozen in
    the pool thereafter. Their rows land past the real jobs and are
    sliced off by the epilogues. Cost: dispatches with fewer than
    ``slots`` lanes pay the full-width GEMMs (zero extra cost once a
    dispatch fills the pool, which the north-star shapes always do);
    see docs/serving.md "Serving front-end".

    No-op when the batch already fills the pool."""
    j = w0.shape[0]
    pad = slots - j
    if pad <= 0:
        return w0, h0, job_ks
    k_max = w0.shape[2]
    zw = jnp.zeros((pad,) + w0.shape[1:], w0.dtype)
    zh = jnp.zeros((pad,) + h0.shape[1:], h0.dtype)
    return (jnp.concatenate([w0, zw]), jnp.concatenate([h0, zh]),
            tuple(job_ks) + (k_max,) * pad)


def _pad_count(restarts: int, mesh: Mesh | None) -> int:
    """Round restarts up to a multiple of the mesh's restart-axis size so the
    batch shards evenly; surplus lanes are computed and discarded."""
    if mesh is None or RESTART_AXIS not in mesh.axis_names:
        return restarts
    size = mesh.shape[RESTART_AXIS]
    padded = -(-restarts // size) * size
    if padded > restarts:
        _pad_lanes_total.inc(padded - restarts)
    return padded


def _use_packed(solver_cfg: SolverConfig) -> bool:
    # a screened config's exact phase runs the vmapped generic driver
    # (the lane-independent engine its bit-identity contract rests on),
    # never the packed family; backend="sketched" is not in the tuple
    return (solver_cfg.algorithm == "mu" and not solver_cfg.screen
            and solver_cfg.backend in ("auto", "packed", "pallas"))


def grid_axes_active(mesh: Mesh | None) -> bool:
    """Whether the mesh shards single factorizations over feature/sample
    axes (vs a restart-only or absent mesh)."""
    return (mesh is not None
            and any(ax in mesh.axis_names and mesh.shape[ax] > 1
                    for ax in (FEATURE_AXIS, SAMPLE_AXIS)))


#: backends that route each algorithm into the slot-scheduled dense-grid
#: machinery. mu/hals: the packed family IS their default engine ("auto"
#: resolves there). neals/snmf (round 4): the dense-batched blocks exist
#: (grid_mu.BLOCKS) but "auto" deliberately stays on the vmapped generic
#: driver — their defaults' engine family (and checkpoint fingerprints)
#: are stable, and the whole-grid solve is an explicit backend="packed"
#: opt-in whose win is compile time (one jit vs one per rank), not
#: iteration throughput (they converge in ~14–21 iterations).
_GRID_EXEC_BACKENDS = {"mu": ("auto", "packed", "pallas"),
                       # hals pallas (ISSUE 20): the coordinate-sweep
                       # block kernel rides the same slot scheduler as
                       # mu — packed hals serving included
                       "hals": ("auto", "packed", "pallas"),
                       "neals": ("packed",),
                       # als (round 5): one whole-grid compile for the
                       # multi-rank sweep — its lstsq half-steps batch
                       # like neals' Gram solves (grid_mu.als_block);
                       # the win is compile time, ~14-iteration solves
                       # make iteration throughput a non-factor
                       "als": ("packed",),
                       "snmf": ("packed",),
                       # kl: the slot count bounds its (B, m, n) quotient
                       # working set — grid_slots plays restart_chunk's
                       # memory-bounding role on this path
                       "kl": ("packed",)}

# the routing table and the validation list must cover the same
# algorithms, or a backend="packed" config could validate but fall
# through to the vmapped driver (or vice versa)
assert set(_GRID_EXEC_BACKENDS) == set(PACKED_ALGORITHMS)


def resolve_engine_family(solver_cfg: SolverConfig,
                          mesh: Mesh | None = None) -> str:
    """The engine family a configuration actually executes — "pallas",
    "packed" (the batched/scheduled GEMM family), or "vmap" (the generic
    driver, including its grid-sharded form).

    Single source of truth shared by the sweep dispatch below and the
    registry fingerprint (nmfx/registry.py): families group matmul
    reductions differently and are not bit-identical, so checkpoints must
    never cross them — any routing change here invalidates exactly the
    right registries. hals auto/packed resolves to the packed family on
    restart-only meshes but to the grid-sharded generic driver when
    feature/sample axes are active (the GRID_SOLVERS branch of
    ``_build_sweep_fn``). backend="sketched" is its own family (the
    compressed engine is approximate by construction — see
    nmfx/solvers/sketched.py); a screened config resolves to "vmap",
    the engine its exact phase actually runs (the ``screen``/
    ``screen_keep`` fields themselves are hashed separately, so a
    screened registry never aliases an unscreened one)."""
    if solver_cfg.tile_rows is not None:
        # the out-of-core streaming engine (nmfx/tiles.py). Conservative
        # on purpose: a single-tile config that sweep() would delegate
        # to the dense path still fingerprints "tiled" when consulted
        # directly — splitting two identical numeric programs is safe,
        # aliasing two different ones is not (sweep() strips tile_rows
        # BEFORE the delegated path consults this, so the routed dense
        # run keeps its dense identity)
        return "tiled"
    if solver_cfg.backend == "sketched":
        return "sketched"
    if solver_cfg.screen:
        return "vmap"
    if solver_cfg.backend == "pallas":
        return "pallas"
    if _use_packed(solver_cfg):
        return "packed"
    # non-mu algorithms route into the batched/scheduled machinery
    # exactly when _GRID_EXEC_BACKENDS says so and no grid axes shard
    # single ranks — ONE table shared with grid_exec_ok and
    # _build_sweep_fn, so the fingerprint cannot desynchronize from the
    # execution routing
    if (solver_cfg.backend in _GRID_EXEC_BACKENDS.get(
            solver_cfg.algorithm, ())
            and not grid_axes_active(mesh)):
        return "packed"
    return "vmap"


@lru_cache(maxsize=64)
def _build_sweep_fn(k: int, restarts: int, solver_cfg: SolverConfig,
                    init_cfg: InitConfig, label_rule: str, mesh: Mesh | None,
                    keep_factors: bool = False, grid_slots: int = 48,
                    grid_tail_slots="auto", fault_token=None):
    # fault_token = faults.trace_token(): keys this cache (and every
    # builder below) by the armed trace-affecting fault state, so
    # arming/disarming solve.nonfinite or sched.stale_reload can never
    # silently serve a previously built clean function; None (nothing
    # armed) keys identically to the pre-fault-registry world
    grid = grid_axes_active(mesh)
    if solver_cfg.backend == "sketched" or solver_cfg.screen:
        if grid:
            raise ValueError(
                "the sketched engine and restart screening are restart-"
                "parallel only (their per-restart projections have no "
                "feature/sample-sharded formulation); drop the grid "
                "mesh axes")
        if solver_cfg.backend == "sketched":
            return _build_sketched_sweep_fn(k, restarts, solver_cfg,
                                            init_cfg, label_rule,
                                            keep_factors)
        return _build_screened_sweep_fn(k, restarts, solver_cfg,
                                        init_cfg, label_rule,
                                        keep_factors)
    if grid:
        grid_ok = ((_use_packed(solver_cfg)
                    and solver_cfg.backend != "pallas")
                   or solver_cfg.algorithm in GRID_SOLVERS)
        if not grid_ok:
            raise ValueError(
                "feature/sample-axis sharding requires the packed mu "
                "backend (algorithm='mu', backend='packed'/'auto') or a "
                f"Gram/quotient-sharded solver {GRID_SOLVERS}; got "
                f"algorithm={solver_cfg.algorithm!r}, "
                f"backend={solver_cfg.backend!r}")
        if keep_factors:
            # the point of grid axes is that no device ever holds a full
            # factor; gathering every restart's W would defeat it. The
            # recompute-by-key route (api.restart_factors) still works.
            raise ValueError(
                "keep_factors is not supported on feature/sample-sharded "
                "meshes (it would gather every restart's full factors onto "
                "each device); use nmfx.restart_factors to recompute any "
                "restart's factors from its key instead")
        return _build_grid_sharded_sweep_fn(
            k, restarts, solver_cfg, init_cfg, label_rule, mesh)
    if _use_packed(solver_cfg):
        return _build_packed_sweep_fn(k, restarts, solver_cfg, init_cfg,
                                      label_rule, mesh, keep_factors)
    if (solver_cfg.algorithm != "mu"
            and solver_cfg.backend in _GRID_EXEC_BACKENDS.get(
                solver_cfg.algorithm, ())):
        # the batched backend IS the dense grid machinery at one rank:
        # shared-GEMM lanes through the slot scheduler (hals' two big
        # GEMMs are mu-shaped — ref libnmf/nmf_mu.c:174-216; neals/snmf
        # batch their Gram solves, ref nmf_neals.c:200-306; als batches
        # its lstsq half-steps, ref nmf_als.c:209-360). For hals,
        # "auto" resolves here too so its execution family is the same
        # on every sweep path (the checkpoint fingerprint hashes that
        # family; vmap is the explicit backend="vmap" choice); for
        # neals/als/snmf the grid engine is the explicit "packed" opt-in
        # (_GRID_EXEC_BACKENDS)
        grid_fn = _build_grid_exec_sweep_fn(
            (k,), restarts, solver_cfg, init_cfg, label_rule, mesh,
            keep_factors, grid_slots, grid_tail_slots, fold_keys=False,
            fault_token=fault_token)

        def impl(a: jax.Array, key: jax.Array) -> KSweepOutput:
            return grid_fn(a, key)[k]

        return impl
    padded = _pad_count(restarts, mesh)
    dtype = jnp.dtype(solver_cfg.dtype)
    mesh_size = (mesh.shape[RESTART_AXIS]
                 if mesh is not None and RESTART_AXIS in mesh.axis_names
                 else 1)
    # effective chunk: rounded up to the mesh's restart-axis size so every
    # chunk still shards evenly across devices (per-device concurrency
    # becomes chunk_eff / mesh_size)
    chunk_eff = None
    if solver_cfg.restart_chunk is not None:
        chunk_eff = -(-solver_cfg.restart_chunk // mesh_size) * mesh_size
    use_chunks = chunk_eff is not None and chunk_eff < padded
    from nmfx import faults

    poison = faults.poison_restarts(k, restarts)
    if poison and use_chunks:
        raise ValueError(
            "solve.nonfinite fault injection does not compose with "
            "restart_chunk (chunked batches lose the global lane index); "
            "disarm the site or drop restart_chunk for the chaos run")

    def _solve_batch(a: jax.Array, keys: jax.Array):
        """Init + solve + labels for one concurrent batch of restarts."""
        w0s, h0s = jax.vmap(
            lambda kk: initialize(kk, a, k, init_cfg, dtype))(keys)
        w0s = _poison_restart_lanes(w0s, poison)
        if mesh_size > 1:
            shard = NamedSharding(mesh, P(RESTART_AXIS))
            w0s = lax.with_sharding_constraint(w0s, shard)
            h0s = lax.with_sharding_constraint(h0s, shard)
        res = jax.vmap(lambda w0, h0: solve(a, w0, h0, solver_cfg))(w0s, h0s)
        labels = jax.vmap(partial(labels_from_h, rule=label_rule))(res.h)
        return res, labels

    def impl(a: jax.Array, key: jax.Array) -> KSweepOutput:
        a = jnp.asarray(a, dtype)
        keys = jax.random.split(key, padded)
        if use_chunks:
            # bound peak memory for solvers with O(m·n) per-lane
            # intermediates (kl's A/(WH) quotient): chunks of chunk_eff
            # restarts run sequentially (lax.map over full chunks, one
            # smaller batch for the remainder — no wasted solves); only the
            # small per-restart outputs persist across chunks
            n_full = padded // chunk_eff
            split_at = n_full * chunk_eff
            parts = []
            if n_full:
                full = lax.map(lambda kc: _solve_batch(a, kc),
                               keys[:split_at].reshape(n_full, chunk_eff))
                parts.append(jax.tree.map(
                    lambda x: x.reshape((split_at,) + x.shape[2:]), full))
            if split_at < padded:
                parts.append(_solve_batch(a, keys[split_at:]))
            res, labels = jax.tree.map(
                lambda *xs: jnp.concatenate(xs, axis=0), *parts)
        else:
            res, labels = _solve_batch(a, keys)
        labels = labels[:restarts]  # drop padding lanes before the reduction
        labels, dnorm_best, faulted = _quarantine_lanes(
            labels, res.dnorm[:restarts], res.stop_reason[:restarts])
        cons = _quarantined_consensus(labels, k, restarts, faulted)
        best = jnp.argmin(dnorm_best)
        all_w = all_h = None
        if keep_factors:
            all_w, all_h = res.w, res.h  # padded; sliced after replication
            if mesh is not None and RESTART_AXIS in mesh.axis_names:
                # replicate BEFORE slicing off the padding lanes: slicing
                # the restart-sharded (padded, m, k) stack to an uneven
                # prefix and then re-constraining trips XLA's SPMD
                # partitioner (shape mismatch after partitioning); the
                # gather-then-slice order is also the natural collective
                rep = NamedSharding(mesh, P())
                all_w = lax.with_sharding_constraint(all_w, rep)
                all_h = lax.with_sharding_constraint(all_h, rep)
            all_w, all_h = all_w[:restarts], all_h[:restarts]
        out = KSweepOutput(cons, res.iterations[:restarts],
                           res.dnorm[:restarts],
                           res.stop_reason[:restarts], labels,
                           res.w[best], res.h[best], all_w, all_h)
        if mesh is not None and RESTART_AXIS in mesh.axis_names:
            # replicate every output across the mesh (XLA all_gathers over
            # ICI/DCN): under multi-process execution this makes each field
            # fully addressable on every host, so the host-side pipeline
            # (rank selection, checkpointing, file outputs) needs no
            # process-level gather — the collective rode the interconnect
            rep = NamedSharding(mesh, P())
            out = jax.tree.map(
                lambda x: lax.with_sharding_constraint(x, rep), out)
        return out

    return jax.jit(impl)


def _sharded_rank_output(k: int, labels, iters, dnorm, stops, wk, hk,
                         valid, restarts: int,
                         keep_factors: bool) -> KSweepOutput:
    """Replicated KSweepOutput for ONE rank from restart-sharded per-lane
    results (inside ``shard_map`` over RESTART_AXIS) — shared epilogue of
    the packed per-k and whole-grid builders. ``valid`` masks this shard's
    padding lanes. Masked one-hot consensus reduction: invalid lanes
    contribute 0 and one psum over ICI yields the replicated n×n mean
    connectivity; per-restart stats gather the padded axis (pad sliced off
    after); best restart = local argmin candidate per shard, then a tiny
    gathered argmin across shards."""
    # numeric quarantine: faulted lanes mask out of the reduction
    # exactly like the pad lanes `valid` already masks; the normalizer
    # becomes the global survivor count (constant-divisor graph kept on
    # the fault-free branch — see _quarantined_consensus)
    labels, dnorm_masked, faulted = _quarantine_lanes(labels, dnorm, stops)
    onehot = (jax.nn.one_hot(labels, k, dtype=jnp.float32)
              * valid[:, None, None])
    raw = lax.psum(jnp.einsum("rik,rjk->ij", onehot, onehot),
                   RESTART_AXIS)
    n_fault = lax.psum(jnp.sum(faulted & valid, dtype=jnp.int32),
                       RESTART_AXIS)
    survivors = jnp.maximum(restarts - n_fault, 1).astype(jnp.float32)
    cons = jnp.where(n_fault > 0, raw / survivors, raw / restarts)
    iters_g = lax.all_gather(iters, RESTART_AXIS, tiled=True)
    dnorm_g = lax.all_gather(dnorm, RESTART_AXIS, tiled=True)
    stop_g = lax.all_gather(stops, RESTART_AXIS, tiled=True)
    labels_g = lax.all_gather(labels, RESTART_AXIS, tiled=True)
    masked = jnp.where(valid, dnorm_masked, jnp.inf)
    best = jnp.argmin(masked)
    bws = lax.all_gather(wk[best], RESTART_AXIS)
    bhs = lax.all_gather(hk[best], RESTART_AXIS)
    bds = lax.all_gather(masked[best], RESTART_AXIS)
    gbest = jnp.argmin(bds)
    extra = (None, None)
    if keep_factors:
        # every restart's factors, replicated on each device — fine at
        # restart-mesh scale (factors are small); grid meshes refuse
        # keep_factors upstream precisely because this gather would
        # defeat their memory bound
        extra = (lax.all_gather(wk, RESTART_AXIS, tiled=True)[:restarts],
                 lax.all_gather(hk, RESTART_AXIS, tiled=True)[:restarts])
    return KSweepOutput(cons, iters_g[:restarts], dnorm_g[:restarts],
                        stop_g[:restarts], labels_g[:restarts],
                        bws[gbest], bhs[gbest], *extra)


def _build_packed_sweep_fn(k: int, restarts: int, solver_cfg: SolverConfig,
                           init_cfg: InitConfig, label_rule: str,
                           mesh: Mesh | None, keep_factors: bool = False):
    """Sweep builder for the restart-packed GEMM path (nmfx.ops.packed_mu).

    Without a mesh the whole batch runs as one packed solve. With a mesh the
    batch is laid out SPMD via ``shard_map``: each device packs and solves
    only its restart shard (so the packed Grams stay device-local — no
    cross-device blocks, no per-iteration collectives, and devices exit
    their while_loops independently); one ``psum`` reduces the consensus
    matrix over ICI and small ``all_gather``s replicate the per-restart
    stats, mirroring the replicated-output contract of the vmap path.
    """
    from nmfx import faults
    from nmfx.ops.packed_mu import mu_packed, unpack_w

    padded = _pad_count(restarts, mesh)
    dtype = jnp.dtype(solver_cfg.dtype)
    poison = faults.poison_restarts(k, restarts)
    if poison and mesh is not None and RESTART_AXIS in mesh.axis_names:
        raise ValueError(
            "solve.nonfinite fault injection is not supported on a "
            "restart-sharded mesh (per-shard lane indices); disarm the "
            "site or run unmeshed for the chaos run")

    def _solve_local(a: jax.Array, keys: jax.Array,
                     varying_axes: tuple[str, ...] = ()):
        """Init + packed solve + labels for a (local) block of restarts."""
        r_local = keys.shape[0]
        w0s, h0s = jax.vmap(
            lambda kk: initialize(kk, a, k, init_cfg, dtype))(keys)
        w0s = _poison_restart_lanes(w0s, poison)
        res = mu_packed(a, w0s, h0s, solver_cfg, varying_axes=varying_axes)
        hs = res.hp.reshape(r_local, k, -1)
        labels = jax.vmap(partial(labels_from_h, rule=label_rule))(hs)
        return res, hs, labels

    def _best(res, hs, dnorm_masked, r_local):
        best = jnp.argmin(dnorm_masked)
        return (unpack_w(res.wp, r_local)[best], hs[best],
                dnorm_masked[best])

    if mesh is None or RESTART_AXIS not in mesh.axis_names:

        def impl(a: jax.Array, key: jax.Array) -> KSweepOutput:
            a = jnp.asarray(a, dtype)
            keys = jax.random.split(key, padded)
            res, hs, labels = _solve_local(a, keys)
            labels = labels[:restarts]
            labels, _, faulted = _quarantine_lanes(
                labels, res.dnorm[:restarts], res.stop_reason[:restarts])
            cons = _quarantined_consensus(labels, k, restarts, faulted)
            masked = jnp.where(jnp.arange(padded) < restarts, res.dnorm,
                               jnp.inf)
            masked = jnp.where(jnp.pad(faulted, (0, padded - restarts)),
                               jnp.inf, masked)
            best_w, best_h, _ = _best(res, hs, masked, padded)
            extra = ((unpack_w(res.wp, padded)[:restarts], hs[:restarts])
                     if keep_factors else (None, None))
            return KSweepOutput(cons, res.iterations[:restarts],
                                res.dnorm[:restarts],
                                res.stop_reason[:restarts], labels,
                                best_w, best_h, *extra)

        return jax.jit(impl)

    n_shards = mesh.shape[RESTART_AXIS]

    def shard_body(a: jax.Array, keys: jax.Array) -> KSweepOutput:
        r_local = padded // n_shards
        res, hs, labels = _solve_local(a, keys,
                                       varying_axes=(RESTART_AXIS,))
        gidx = (lax.axis_index(RESTART_AXIS) * r_local
                + jnp.arange(r_local))
        valid = gidx < restarts
        return _sharded_rank_output(k, labels, res.iterations, res.dnorm,
                                    res.stop_reason,
                                    unpack_w(res.wp, r_local), hs, valid,
                                    restarts, keep_factors)

    # check_vma=False: every output IS replicated (psum for the consensus,
    # all_gather + identical replicated epilogues for the rest), but the
    # varying-manual-axes checker cannot infer that through the argmin-
    # over-gathered-candidates pattern, and no varying→invariant pcast
    # exists to assert it
    sharded = shard_map(shard_body, mesh=mesh,
                        in_specs=(P(), P(RESTART_AXIS)),
                        out_specs=P(), check_vma=False)

    def impl(a: jax.Array, key: jax.Array) -> KSweepOutput:
        a = jnp.asarray(a, dtype)
        keys = jax.random.split(key, padded)
        return sharded(a, keys)

    return jax.jit(impl)


def _build_sketched_sweep_fn(k: int, restarts: int,
                             solver_cfg: SolverConfig,
                             init_cfg: InitConfig, label_rule: str,
                             keep_factors: bool = False):
    """Sweep builder for ``backend="sketched"`` (ISSUE 12): the
    random-projection compressed engine (``nmfx/solvers/sketched.py``),
    vmapped over the restart axis like the generic driver — so it rides
    the per-k sweep path, the streamed harvest, and the serve solo
    dispatch unchanged. Init draws the canonical per-(seed, k, restart)
    key chain; each lane's projections fold deterministically off its
    restart key, so a given (seed, k, restart) factorizes identically
    on every batch composition. Restart-parallel only (no mesh
    sharding — the sweep layer routes grid meshes away upstream);
    quarantine/labels/best-restart epilogue identical to the vmap
    path's."""
    from nmfx import faults
    from nmfx.solvers import sketched as sk

    dtype = jnp.dtype(solver_cfg.dtype)
    poison = faults.poison_restarts(k, restarts)

    def impl(a: jax.Array, key: jax.Array) -> KSweepOutput:
        a = jnp.asarray(a, dtype)
        keys = jax.random.split(key, restarts)
        w0s, h0s = jax.vmap(
            lambda kk: initialize(kk, a, k, init_cfg, dtype))(keys)
        w0s = _poison_restart_lanes(w0s, poison)
        res = sk.sweep_lanes(a, w0s, h0s, keys, solver_cfg)
        labels = jax.vmap(partial(labels_from_h, rule=label_rule))(res.h)
        labels, dnorm_best, faulted = _quarantine_lanes(
            labels, res.dnorm, res.stop_reason)
        cons = _quarantined_consensus(labels, k, restarts, faulted)
        best = jnp.argmin(dnorm_best)
        extra = (res.w, res.h) if keep_factors else (None, None)
        return KSweepOutput(cons, res.iterations, res.dnorm,
                            res.stop_reason, labels,
                            res.w[best], res.h[best], *extra)

    return jax.jit(impl)


def _build_screened_sweep_fn(k: int, restarts: int,
                             solver_cfg: SolverConfig,
                             init_cfg: InitConfig, label_rule: str,
                             keep_factors: bool = False):
    """Sweep builder for restart screening (``SolverConfig.screen``):
    a cheap sketched pass (``sketch.screen_iters`` compressed
    iterations, ``nmfx.solvers.sketched.screen_pass``) scores the FULL
    restart pool by compressed objective; only the ``screen_keep``
    best-scoring lanes then receive exact iterations, through the
    vmapped generic driver from their canonical per-restart keys.

    Exactness contract (pinned by tests/test_screening.py): batched
    dot_generals evaluate each lane independently, so a survivor
    lane's results are BIT-IDENTICAL to a solo exact run of that lane
    (``initialize(key_i)`` + ``solve``) — screening changes which lanes
    are solved, never their numbers. Survivor indices are sorted
    ascending so the exact batch composition is a deterministic
    function of the survivor set. Screened-out lanes are masked from
    the consensus exactly like pad lanes (labels -1,
    ``StopReason.SCREENED``, dnorm +inf) and count as non-survivors
    under the ``min_restarts`` floor; ``keep_factors`` is refused (a
    screened-out lane has no exact factors to keep)."""
    from nmfx import faults
    from nmfx.solvers import sketched as sk

    keep = solver_cfg.screen_keep
    if keep is None or not 1 <= keep <= restarts:
        raise ValueError(
            f"screen_keep must be in [1, restarts={restarts}], got "
            f"{keep!r}")
    if keep_factors:
        raise ValueError(
            "keep_factors does not compose with screening: screened-out "
            "lanes never receive exact iterations, so there is no full "
            "factor grid to keep (use nmfx.restart_factors on survivor "
            "lanes instead)")
    if faults.poison_restarts(k, restarts):
        raise ValueError(
            "solve.nonfinite fault injection does not compose with "
            "screening (the screening pass reorders which lanes the "
            "exact engine sees); disarm the site for screened sweeps")
    import dataclasses as _dc

    # the exact phase runs the PLAIN exact solve — the same config a
    # solo run of a survivor lane uses (solve() refuses screening
    # fields by design; restart_factors strips them identically, which
    # is what keeps the survivor bit-identity contract one-config-deep)
    exact_cfg = _dc.replace(solver_cfg, screen=False, screen_keep=None)
    dtype = jnp.dtype(solver_cfg.dtype)

    def impl(a: jax.Array, key: jax.Array) -> KSweepOutput:
        a = jnp.asarray(a, dtype)
        n = a.shape[1]
        keys = jax.random.split(key, restarts)
        w0s, h0s = jax.vmap(
            lambda kk: initialize(kk, a, k, init_cfg, dtype))(keys)
        scores = jax.vmap(
            lambda w0, h0, kk: sk.screen_pass(a, w0, h0, kk,
                                              solver_cfg))(w0s, h0s,
                                                           keys)
        # lowest compressed objective wins; jnp.argsort is stable, so
        # ties break to the lower restart index — deterministic. The
        # survivor set is re-sorted ascending so the exact batch's lane
        # order is index order regardless of the scores' permutation.
        surv = jnp.sort(jnp.argsort(scores)[:keep])
        res = jax.vmap(lambda w0, h0: solve(a, w0, h0, exact_cfg))(
            w0s[surv], h0s[surv])
        labels_s = jax.vmap(partial(labels_from_h,
                                    rule=label_rule))(res.h)
        # scatter survivors back to full (restarts,)-shaped records;
        # screened-out lanes read exactly like pad lanes downstream
        labels = jnp.full((restarts, n), -1, jnp.int32).at[surv].set(
            labels_s)
        iters = jnp.full((restarts,),
                         solver_cfg.sketch.screen_iters,
                         jnp.int32).at[surv].set(res.iterations)
        dnorms = jnp.full((restarts,), jnp.inf,
                          res.dnorm.dtype).at[surv].set(res.dnorm)
        stops = jnp.full((restarts,), int(StopReason.SCREENED),
                         jnp.int32).at[surv].set(res.stop_reason)
        labels, dnorm_best, faulted = _quarantine_lanes(labels, dnorms,
                                                        stops)
        cons = _quarantined_consensus(labels, k, restarts, faulted)
        # best restart among the survivors (their own numeric faults
        # masked); index into the survivor batch, where factors exist
        surv_masked = jnp.where(
            res.stop_reason == jnp.int32(StopReason.NUMERIC_FAULT),
            jnp.array(jnp.inf, res.dnorm.dtype), res.dnorm)
        bi = jnp.argmin(surv_masked)
        return KSweepOutput(cons, iters, dnorms, stops, labels,
                            res.w[bi], res.h[bi])

    return jax.jit(impl)


@lru_cache(maxsize=64)
def _build_chunk_sweep_fn(k: int, n_chunk: int, solver_cfg: SolverConfig,
                          init_cfg: InitConfig, label_rule: str,
                          poison: tuple = (), fault_token=None,
                          mesh: "Mesh | None" = None):
    """Sweep builder for the durable-checkpoint chunk executor
    (``nmfx/checkpoint.py``): solve ``n_chunk`` restarts of rank ``k``
    from EXPLICIT per-restart keys (a slice of the canonical
    ``split(fold_in(root, k), restarts)`` chain) and return the
    per-lane :class:`ChunkSweepOutput` a completion record persists.

    Keyed by the chunk SIZE, not its offset, so every same-sized chunk
    of a rank shares one compiled executable; ``poison`` carries the
    chunk-LOCAL ``solve.nonfinite`` lane indices (the global spec is
    offset-dependent, so the checkpoint layer translates before the
    build — ``fault_token`` keys the cache as everywhere else).

    Engine routing: the packed-family mu backends run ``mu_packed``
    (their per-k engine); everything else runs the vmapped generic
    driver. Non-mu whole-grid opt-ins (hals "auto", neals/als/snmf/kl
    ``backend="packed"``) therefore checkpoint through the vmapped
    driver — the manifest hashes this resolution
    (``checkpoint.engine_family``), so a ledger can never be resumed
    under a different engine, and resumed-vs-uninterrupted parity holds
    because BOTH checkpointed runs execute the identical chunk plan
    through the identical engine (per-chunk batch composition included:
    resume re-runs whole plan chunks, never partial ones).
    """
    dtype = jnp.dtype(solver_cfg.dtype)
    packed = _use_packed(solver_cfg)
    if packed:
        from nmfx.ops.packed_mu import mu_packed, unpack_w
    if mesh is not None:
        return _build_meshed_chunk_sweep_fn(k, n_chunk, solver_cfg,
                                            init_cfg, label_rule, poison,
                                            mesh, packed)

    def impl(a: jax.Array, keys: jax.Array) -> ChunkSweepOutput:
        a = jnp.asarray(a, dtype)
        w0s, h0s = jax.vmap(
            lambda kk: initialize(kk, a, k, init_cfg, dtype))(keys)
        w0s = _poison_restart_lanes(w0s, poison)
        if packed:
            res = mu_packed(a, w0s, h0s, solver_cfg)
            hs = res.hp.reshape(n_chunk, k, -1)
            ws = unpack_w(res.wp, n_chunk)
        else:
            res = jax.vmap(
                lambda w0, h0: solve(a, w0, h0, solver_cfg))(w0s, h0s)
            hs, ws = res.h, res.w
        labels = jax.vmap(partial(labels_from_h, rule=label_rule))(hs)
        labels, dnorm_best, _ = _quarantine_lanes(labels, res.dnorm,
                                                  res.stop_reason)
        best = jnp.argmin(dnorm_best)
        return ChunkSweepOutput(labels, res.iterations, res.dnorm,
                                res.stop_reason,
                                best.astype(jnp.int32), ws[best], hs[best])

    return jax.jit(impl)


def _build_meshed_chunk_sweep_fn(k: int, n_chunk: int,
                                 solver_cfg: SolverConfig,
                                 init_cfg: InitConfig, label_rule: str,
                                 poison: tuple, mesh: Mesh,
                                 packed: bool):
    """The durable chunk executor over a restart-only sub-mesh
    (``ElasticShardRunner`` meshed mode, ISSUE 19: a shard is a device
    *set*, not a device).

    The chunk's lanes shard over the sub-mesh's restart axis — the
    communication-avoiding layout: zero per-iteration collectives, one
    tiled all_gather of the per-lane stats plus the masked-psum
    best-restart selection in the epilogue. Each lane's math is the
    same vmapped generic driver the unmeshed executor runs, so the
    persisted record stays bit-identical to a single-device run of the
    same chunk plan (the elastic exactness contract; pinned in
    tests/test_distributed.py).

    The packed family is refused: its pool geometry (and therefore its
    GEMM reduction shapes) is composition-dependent, so sharding a
    chunk's pool would break record parity with the unmeshed executor.
    """
    if any(ax != RESTART_AXIS and mesh.shape[ax] > 1
           for ax in mesh.axis_names):
        raise ValueError(
            "meshed chunk execution shards the restart axis only; got "
            f"mesh axes {dict(mesh.shape)}")
    if packed or RESTART_AXIS not in mesh.axis_names:
        raise ValueError(
            "meshed chunk execution supports the vmapped generic "
            "driver only (the packed family's pool geometry is "
            "composition-dependent; ledger records must stay "
            "bit-identical to the unmeshed chunk executor)")
    dtype = jnp.dtype(solver_cfg.dtype)
    rsize = mesh.shape[RESTART_AXIS]
    r_loc = -(-n_chunk // rsize)
    n_pad = r_loc * rsize

    def shard_body(a: jax.Array, keys_loc: jax.Array):
        ridx = lax.axis_index(RESTART_AXIS)
        gidx = ridx * r_loc + jnp.arange(r_loc)
        w0s, h0s = jax.vmap(
            lambda kk: initialize(kk, a, k, init_cfg, dtype))(keys_loc)
        if poison:
            pmask = jnp.isin(gidx, jnp.asarray(poison))
            w0s = w0s.at[:, 0, 0].set(jnp.where(
                pmask, jnp.asarray(jnp.nan, w0s.dtype), w0s[:, 0, 0]))
        res = jax.vmap(
            lambda w0, h0: solve(a, w0, h0, solver_cfg))(w0s, h0s)
        labels = jax.vmap(
            partial(labels_from_h, rule=label_rule))(res.h)
        labels, dnorm_best, _ = _quarantine_lanes(labels, res.dnorm,
                                                  res.stop_reason)
        # global first-min argmin over the canonical lane order: gather
        # the (tiny) per-lane dnorms, mask the pad lanes to +inf, and
        # psum-select the owning shard's factors — the same masked-psum
        # idiom as the grid driver's best-restart epilogue
        dn_all = lax.all_gather(dnorm_best, RESTART_AXIS, tiled=True)
        dn_all = jnp.where(jnp.arange(n_pad) < n_chunk, dn_all, jnp.inf)
        best = jnp.argmin(dn_all).astype(jnp.int32)
        loc = best - ridx * r_loc
        mine = (loc >= 0) & (loc < r_loc)
        sel = jnp.where(mine, jnp.asarray(1, res.w.dtype), 0)
        locc = jnp.clip(loc, 0, r_loc - 1)
        wb = lax.psum(sel * res.w[locc], RESTART_AXIS)
        hb = lax.psum(sel * res.h[locc], RESTART_AXIS)
        return (labels, res.iterations, res.dnorm, res.stop_reason,
                best, wb, hb)

    sharded = shard_map(
        shard_body, mesh=mesh,
        in_specs=(P(), P(RESTART_AXIS)),
        out_specs=(P(RESTART_AXIS), P(RESTART_AXIS), P(RESTART_AXIS),
                   P(RESTART_AXIS), P(), P(), P()),
        check_vma=False)

    def impl(a: jax.Array, keys: jax.Array) -> ChunkSweepOutput:
        a = jnp.asarray(a, dtype)
        if n_pad != n_chunk:
            reps = -(-n_pad // n_chunk)
            keys = jnp.concatenate([keys] * reps)[:n_pad]
        labels, iters, dnorm, stop, best, wb, hb = sharded(a, keys)
        return ChunkSweepOutput(labels[:n_chunk], iters[:n_chunk],
                                dnorm[:n_chunk], stop[:n_chunk],
                                best, wb, hb)

    return jax.jit(impl)


def _build_grid_sharded_sweep_fn(k: int, restarts: int,
                                 solver_cfg: SolverConfig,
                                 init_cfg: InitConfig, label_rule: str,
                                 mesh: Mesh):
    """Sweep builder for a mesh with feature (row) and/or sample (column)
    axes, optionally composed with the restart axis — up to the full 3-D
    ``restarts×features×samples`` (data × tensor × sequence) mesh.

    SPMD layout: A is tiled over (FEATURE_AXIS, SAMPLE_AXIS); W is
    row-sharded over features (replicated over samples); H is
    column-sharded over samples (replicated over features). Per iteration
    the solver psums its m-contracted terms over features and its
    n-contracted terms over samples (SUMMA-style): the packed mu path's
    Gram pairs (see ``mu_packed``), kl's quotient contractions — the
    solver whose O(m·n) per-restart intermediate makes these axes a
    *necessity* at scale (``solvers/kl.py``; its quotient block is purely
    local under this layout) — or the neals/snmf/hals Gram-family
    contractions (``GRID_SOLVERS``). Labels are computed on local columns with the
    class-stability AND reduced by one tiny psum. The consensus reduction
    psums over the restart axis as in the 1-D path.

    Init: random W0/H0 are drawn from the canonical per-restart keys and
    then row/column-sliced, so a given (seed, k, restart) yields the same
    factorization on any mesh shape (modulo float reduction order). NNDSVD
    (deterministic in A, so every restart is identical — as in the
    reference, generatematrix.c:145) is computed once from the full matrix
    at the jit level and handed to the shards pre-sliced — the "host-side
    SVD, broadcast factors" scheme: the transient full factors exist only
    outside the solver loop, never per restart.
    """
    from nmfx.ops.packed_mu import mu_packed, unpack_w
    from nmfx.solvers import SOLVERS, base

    grid_mod = (SOLVERS[solver_cfg.algorithm]
                if solver_cfg.algorithm in GRID_SOLVERS else None)
    use_nndsvd = init_cfg.method == "nndsvd"

    def axis_size(name):
        return mesh.shape[name] if name in mesh.axis_names else 1

    has_restart = axis_size(RESTART_AXIS) > 1
    has_feature = axis_size(FEATURE_AXIS) > 1
    has_sample = axis_size(SAMPLE_AXIS) > 1
    n_rshards = axis_size(RESTART_AXIS) if has_restart else 1
    f_shards = axis_size(FEATURE_AXIS)
    s_shards = axis_size(SAMPLE_AXIS)
    padded = _pad_count(restarts, mesh)
    r_local = padded // n_rshards
    dtype = jnp.dtype(solver_cfg.dtype)
    vary_axes = tuple(ax for ax, has in
                      ((RESTART_AXIS, has_restart),
                       (FEATURE_AXIS, has_feature),
                       (SAMPLE_AXIS, has_sample)) if has)

    def shard_body(a_loc: jax.Array, keys: jax.Array, w0_init: jax.Array,
                   h0_init: jax.Array, m_true: int,
                   n_true: int) -> KSweepOutput:
        m_loc, n_loc = a_loc.shape
        m_pad = m_loc * f_shards
        n_pad = n_loc * s_shards
        fidx = lax.axis_index(FEATURE_AXIS) if has_feature else 0
        sidx = lax.axis_index(SAMPLE_AXIS) if has_sample else 0
        f_ax = FEATURE_AXIS if has_feature else None
        s_ax = SAMPLE_AXIS if has_sample else None

        # full W0/H0 from the canonical per-restart keys (identical draws on
        # every mesh shape), immediately sliced to this shard's row/column
        # blocks so peak transient memory is one restart's m×k + k×n, not
        # r_local times that. Rows/columns past the true dims (padding) are
        # zeroed and stay exactly zero by each grid solver's own argument —
        # multiplicative short-circuit for mu/kl, zero right-hand-side
        # columns solving to zero for neals/snmf, zero numerators and zero
        # AXPY contributions for hals (their docstrings) — so they
        # contribute nothing to the psummed contractions; any NEW grid
        # solver must establish the same invariant
        def init_one(kk):
            w0, h0 = random_init(kk, m_true, n_true, k, init_cfg, dtype)
            w0 = jnp.pad(w0, ((0, m_pad - m_true), (0, 0)))
            h0 = jnp.pad(h0, ((0, 0), (0, n_pad - n_true)))
            return (lax.dynamic_slice_in_dim(w0, fidx * m_loc, m_loc,
                                             axis=0),
                    lax.dynamic_slice_in_dim(h0, sidx * n_loc, n_loc,
                                             axis=1))

        if use_nndsvd:
            # deterministic init, identical for every restart (reference
            # generatematrix.c:145); already sliced to this shard's blocks
            # at the jit level, so just broadcast over the restart lanes
            w0s_loc = jnp.broadcast_to(w0_init,
                                       (r_local,) + w0_init.shape)
            h0s_loc = jnp.broadcast_to(h0_init,
                                       (r_local,) + h0_init.shape)
        else:
            w0s_loc, h0s_loc = lax.map(init_one, keys)
        if grid_mod is not None:
            shard_info = base.ShardInfo(f_ax, s_ax, m_true, n_true)
            step_fn = partial(grid_mod.step, shard=shard_info)

            def solve_lanes(w0s, h0s):
                with base.matmul_precision_ctx(solver_cfg.matmul_precision):
                    return jax.vmap(
                        lambda w0, h0: base.run_loop(
                            a_loc, w0, h0, solver_cfg, step_fn,
                            grid_mod.init_aux(a_loc, w0, h0, solver_cfg,
                                              shard=shard_info),
                            shard_info))(w0s, h0s)

            # restart_chunk composes with the grid mesh exactly as with the
            # restart mesh (config.py): it bounds the lanes solved
            # concurrently PER DEVICE — kl's (m_loc × n_loc) quotient is
            # the per-lane intermediate that needs it — with chunks running
            # sequentially via lax.map (in lockstep across the grid group:
            # every chunk's convergence decisions are global psums/pmaxes)
            chunk = solver_cfg.restart_chunk
            c_loc = (max(1, -(-chunk // n_rshards))
                     if chunk is not None else None)
            if c_loc is not None and c_loc < r_local:
                n_full = r_local // c_loc
                split_at = n_full * c_loc
                parts = []
                if n_full:
                    full = lax.map(
                        lambda wh: solve_lanes(*wh),
                        (w0s_loc[:split_at].reshape(
                            (n_full, c_loc) + w0s_loc.shape[1:]),
                         h0s_loc[:split_at].reshape(
                            (n_full, c_loc) + h0s_loc.shape[1:])))
                    parts.append(jax.tree.map(
                        lambda x: x.reshape((split_at,) + x.shape[2:]),
                        full))
                if split_at < r_local:
                    parts.append(solve_lanes(w0s_loc[split_at:],
                                             h0s_loc[split_at:]))
                res = jax.tree.map(lambda *xs: jnp.concatenate(xs, axis=0),
                                   *parts)
            else:
                res = solve_lanes(w0s_loc, h0s_loc)
            hs_loc = res.h  # (r_local, k, n_loc)
            w_all_loc = res.w  # (r_local, m_loc, k)
        else:
            res = mu_packed(a_loc, w0s_loc, h0s_loc, solver_cfg,
                            varying_axes=vary_axes,
                            feature_axis=f_ax, m_total=m_true,
                            sample_axis=s_ax, n_total=n_true)
            hs_loc = res.hp.reshape(r_local, k, -1)
            w_all_loc = unpack_w(res.wp, r_local)
        labels = jax.vmap(partial(labels_from_h, rule=label_rule))(hs_loc)
        if has_sample:
            labels = lax.all_gather(labels, SAMPLE_AXIS, tiled=True,
                                    axis=1)  # (r_local, n_pad)
        labels = labels[:, :n_true]

        gidx = ((lax.axis_index(RESTART_AXIS) if has_restart else 0)
                * r_local + jnp.arange(r_local))
        valid = gidx < restarts
        labels, dnorm_q, faulted = _quarantine_lanes(labels, res.dnorm,
                                                     res.stop_reason)
        onehot = (jax.nn.one_hot(labels, k, dtype=jnp.float32)
                  * valid[:, None, None])
        cons = jnp.einsum("rik,rjk->ij", onehot, onehot)
        n_fault = jnp.sum(faulted & valid, dtype=jnp.int32)
        if has_restart:
            cons = lax.psum(cons, RESTART_AXIS)
            n_fault = lax.psum(n_fault, RESTART_AXIS)
        survivors = jnp.maximum(restarts - n_fault, 1).astype(jnp.float32)
        cons = jnp.where(n_fault > 0, cons / survivors, cons / restarts)

        def rgather(x, tiled=True):
            return (lax.all_gather(x, RESTART_AXIS, tiled=tiled)
                    if has_restart else x)

        iters_g = rgather(res.iterations)
        dnorm_g = rgather(res.dnorm)
        stop_g = rgather(res.stop_reason)
        labels_g = rgather(labels)
        # best restart: local candidate per restart shard; pick the global
        # winner from gathered *scalars* only, select its (still sharded)
        # factors with a masked psum, then one feature/sample gather into
        # the full factors — at no point does any device hold more than one
        # full-size factor matrix
        masked_dnorm = jnp.where(valid, dnorm_q, jnp.inf)
        best = jnp.argmin(masked_dnorm)
        bw_loc = w_all_loc[best]  # (m_loc, k)
        bh_loc = hs_loc[best]  # (k, n_loc)
        bd = masked_dnorm[best]
        if has_restart:
            bds = lax.all_gather(bd, RESTART_AXIS)
            gbest = jnp.argmin(bds)
            win = (lax.axis_index(RESTART_AXIS) == gbest)
            bw_loc = lax.psum(bw_loc * win.astype(bw_loc.dtype),
                              RESTART_AXIS)
            bh_loc = lax.psum(bh_loc * win.astype(bh_loc.dtype),
                              RESTART_AXIS)
        bw = bw_loc
        if has_feature:
            bw = lax.all_gather(bw, FEATURE_AXIS, tiled=True, axis=0)
        bw = bw[:m_true]
        bh = bh_loc
        if has_sample:
            bh = lax.all_gather(bh, SAMPLE_AXIS, tiled=True, axis=1)
        bh = bh[:, :n_true]
        return KSweepOutput(cons, iters_g[:restarts], dnorm_g[:restarts],
                            stop_g[:restarts], labels_g[:restarts], bw, bh)

    a_specs = P(FEATURE_AXIS if has_feature else None,
                SAMPLE_AXIS if has_sample else None)
    key_specs = P(RESTART_AXIS) if has_restart else P()
    w0_specs = P(FEATURE_AXIS if has_feature else None, None)
    h0_specs = P(None, SAMPLE_AXIS if has_sample else None)

    def impl(a: jax.Array, key: jax.Array) -> KSweepOutput:
        a = jnp.asarray(a, dtype)
        m_true, n_true = a.shape
        m_pad = -(-m_true // f_shards) * f_shards
        n_pad = -(-n_true // s_shards) * s_shards
        if use_nndsvd:
            # one deterministic init from the full (unpadded) matrix, then
            # zero-pad to the shard grid — the factors enter shard_map
            # already row/column-sharded; XLA inserts whatever resharding
            # of A the SVD needs, outside the solver loop
            from nmfx.init import nndsvd_init

            w0f, h0f = nndsvd_init(a, k, dtype=dtype,
                                   svd_method=init_cfg.svd_method,
                                   ncv=init_cfg.ncv)
            w0f = jnp.pad(w0f, ((0, m_pad - m_true), (0, 0)))
            h0f = jnp.pad(h0f, ((0, 0), (0, n_pad - n_true)))
        else:  # dummies: shard_map wants a fixed arg structure
            w0f = jnp.zeros((m_pad, k), dtype)
            h0f = jnp.zeros((k, n_pad), dtype)
        if (m_pad, n_pad) != (m_true, n_true):
            a = jnp.pad(a, ((0, m_pad - m_true), (0, n_pad - n_true)))
        keys = jax.random.split(key, padded)
        sharded = shard_map(
            partial(shard_body, m_true=m_true, n_true=n_true),
            mesh=mesh, in_specs=(a_specs, key_specs, w0_specs, h0_specs),
            out_specs=P(), check_vma=False)
        return sharded(a, keys, w0f, h0f)

    return jax.jit(impl)


def grid_exec_ok(solver_cfg: SolverConfig, mesh: Mesh | None) -> bool:
    """Whether the whole-grid slot-scheduled solve (``nmfx.ops.sched_mu``)
    can run this configuration: an algorithm with a dense-batched block
    (grid_mu.BLOCKS: mu, hals, neals, als, snmf, kl) under the backend
    that routes
    it there (``_GRID_EXEC_BACKENDS`` — including the fused pallas
    kernels for mu; the scheduler keeps its slot state in the packed
    column layout those kernels consume) — with no feature/sample mesh
    axes (those shard single ranks; the grid layout composes with the
    restart axis only)."""
    if solver_cfg.backend == "sketched" or solver_cfg.screen:
        # the compressed engine and the screening two-phase dispatch
        # have no slot-scheduled form (and the exec-cache's bit-exact
        # serving contract excludes them by construction — cacheable()
        # reads this predicate)
        return False
    if solver_cfg.tile_rows is not None:
        # the out-of-core streaming engine holds A on host; the slot
        # scheduler (and the exec-cache serving contract built on this
        # predicate) assumes a device-resident A
        return False
    backends = _GRID_EXEC_BACKENDS.get(solver_cfg.algorithm, ())
    if solver_cfg.backend not in backends:
        return False
    return not grid_axes_active(mesh)


@lru_cache(maxsize=64)
def _build_grid_exec_sweep_fn(ks: tuple[int, ...], restarts: int,
                              solver_cfg: SolverConfig,
                              init_cfg: InitConfig, label_rule: str,
                              mesh: Mesh | None,
                              keep_factors: bool = False,
                              slots: int = 48,
                              tail_slots="auto",
                              fold_keys: bool = True,
                              fault_token=None):
    """Sweep builder for the whole-grid path (``nmfx.ops.sched_mu``):
    EVERY (k, restart) cell solves through one jit'd slot-scheduled
    while_loop — the reference's whole-grid-concurrent job array with
    workers picking up queued jobs (nmf.r:64-68, nmf.r:111-113) — instead
    of one compile + dispatch per rank.

    Jobs dispatch rank-DESCENDING (longest-expected-first, the LPT rule;
    iteration counts grow with k). Per-rank consensus/stats come from
    static lane slices of the per-job results (rank-major). With a restart
    mesh each device schedules its own restart shard of every rank
    independently (no collectives inside the loop); per rank, one psum
    reduces the consensus and small all_gathers replicate the stats — the
    same replicated-output contract as the per-k builders.
    """
    from nmfx import faults
    from nmfx.ops.sched_mu import mu_sched

    if not fold_keys and len(ks) != 1:
        raise ValueError("fold_keys=False is the single-rank (pre-folded "
                         "key) mode; got multiple ks")
    ks = tuple(sorted(ks, reverse=True))  # LPT dispatch order
    k_max = max(ks)
    padded = _pad_count(restarts, mesh)
    dtype = jnp.dtype(solver_cfg.dtype)
    # solve.nonfinite injection (trace-time constant — fault_token keys
    # this cache): global lane index of each poisoned (k, restart) cell
    # in the rank-major lane stack
    poison = tuple(g * padded + r for g, k in enumerate(ks)
                   for r in faults.poison_restarts(k, restarts))
    if poison and mesh is not None and RESTART_AXIS in mesh.axis_names \
            and mesh.shape[RESTART_AXIS] > 1:
        raise ValueError(
            "solve.nonfinite fault injection is not supported on a "
            "restart-sharded mesh (per-shard lane indices); disarm the "
            "site or run unmeshed for the chaos run")

    def _init_lanes(a, rank_keys):
        """[(k, (r,) keys)] → zero-padded dense (B, m, k_max), (B, k_max, n)
        lane batch, rank-major. Padding is exactly invariant under the MU
        epilogue (see grid_mu module docstring)."""
        w0l, h0l = [], []
        for k, keys in rank_keys:
            w0s, h0s = jax.vmap(
                lambda kk, k=k: initialize(kk, a, k, init_cfg, dtype))(keys)
            w0l.append(jnp.pad(w0s, ((0, 0), (0, 0), (0, k_max - k))))
            h0l.append(jnp.pad(h0s, ((0, 0), (0, k_max - k), (0, 0))))
        return jnp.concatenate(w0l), jnp.concatenate(h0l)

    if (mesh is None or RESTART_AXIS not in mesh.axis_names
            or mesh.shape[RESTART_AXIS] == 1):

        def impl(a: jax.Array, root_key: jax.Array) -> dict[int,
                                                            KSweepOutput]:
            a = jnp.asarray(a, dtype)
            # the canonical per-(k, restart) keys of the per-k path
            # (sweep: fold_in(root, k), then split) — a given (seed, k,
            # restart) yields the same initial factors on either execution
            rank_keys = [
                (k, jax.random.split(
                    jax.random.fold_in(root_key, k) if fold_keys
                    else root_key, padded))
                for k in ks]
            w0, h0 = _init_lanes(a, rank_keys)
            w0 = _poison_restart_lanes(w0, poison)
            res = mu_sched(a, w0, h0, solver_cfg, slots=slots,
                           tail_slots=tail_slots,
                           job_ks=tuple(k for k in ks
                                        for _ in range(padded)))
            out: dict[int, KSweepOutput] = {}
            for g, k in enumerate(ks):
                sl = slice(g * padded, g * padded + restarts)
                hk = res.h[sl, :k, :]  # true rows only: correct under
                wk = res.w[sl, :, :k]  # both label rules
                labels = jax.vmap(partial(labels_from_h,
                                          rule=label_rule))(hk)
                labels, dnorm_best, faulted = _quarantine_lanes(
                    labels, res.dnorm[sl], res.stop_reason[sl])
                cons = _quarantined_consensus(labels, k, restarts, faulted)
                best = jnp.argmin(dnorm_best)
                extra = (wk, hk) if keep_factors else (None, None)
                out[k] = KSweepOutput(cons, res.iterations[sl],
                                      res.dnorm[sl], res.stop_reason[sl],
                                      labels, wk[best], hk[best], *extra)
            return out

        return jax.jit(impl)

    n_shards = mesh.shape[RESTART_AXIS]
    r_local = padded // n_shards

    def shard_body(a: jax.Array, keys: jax.Array) -> dict[int, KSweepOutput]:
        rank_keys = [(k, keys[g]) for g, k in enumerate(ks)]
        w0, h0 = _init_lanes(a, rank_keys)
        res = mu_sched(a, w0, h0, solver_cfg, slots=slots,
                       varying_axes=(RESTART_AXIS,), tail_slots=tail_slots,
                       job_ks=tuple(k for k in ks
                                    for _ in range(r_local)))
        gidx = (lax.axis_index(RESTART_AXIS) * r_local
                + jnp.arange(r_local))
        valid = gidx < restarts
        out: dict[int, KSweepOutput] = {}
        for g, k in enumerate(ks):
            sl = slice(g * r_local, (g + 1) * r_local)
            hk = res.h[sl, :k, :]
            wk = res.w[sl, :, :k]
            labels = jax.vmap(partial(labels_from_h, rule=label_rule))(hk)
            out[k] = _sharded_rank_output(k, labels, res.iterations[sl],
                                          res.dnorm[sl],
                                          res.stop_reason[sl], wk, hk,
                                          valid, restarts, keep_factors)
        return out

    # check_vma=False for the same reason as the per-k packed builder: the
    # outputs ARE replicated but the checker can't see it through the
    # argmin-over-gathered-candidates pattern
    sharded = shard_map(shard_body, mesh=mesh,
                        in_specs=(P(), P(None, RESTART_AXIS)),
                        out_specs=P(), check_vma=False)

    def impl(a: jax.Array, root_key: jax.Array) -> dict[int, KSweepOutput]:
        a = jnp.asarray(a, dtype)
        keys = jnp.stack([
            jax.random.split(jax.random.fold_in(root_key, k) if fold_keys
                             else root_key, padded) for k in ks])
        return sharded(a, keys)

    return jax.jit(impl)


@lru_cache(maxsize=128)
def bucketed_lane_init_fn(true_shape: tuple[int, int], ks: tuple[int, ...],
                          padded_restarts: int, init_cfg: InitConfig,
                          dtype_str: str, bucket_shape: tuple[int, int]):
    """Jitted lane-initializer for the shape-bucketed executables
    (``nmfx/exec_cache.py``): draws every (k, restart) cell's W0/H0 at the
    TRUE shape from the canonical keys — ``fold_in(root, k)`` split over
    the restart axis, exactly the per-k/grid paths' chain — then
    zero-pads to the bucket lattice, rank-major, rank-descending.

    Init happens OUTSIDE the cached sweep executable on purpose: random
    draws are shape-keyed (drawing at the padded shape would change every
    restart vs the exact-shape sweep) and NNDSVD factors the true matrix.
    The per-true-shape compile this costs is the cheap one — a vmapped
    draw or one SVD — while the 20-odd-second sweep compile stays keyed
    by bucket. Padding rows/columns start exactly zero and stay exactly
    zero under every grid solver (``grid_mu`` module docstring), the same
    invariant the feature/sample sharding relies on.
    """
    m_true, n_true = true_shape
    m_pad, n_pad = bucket_shape
    ks = tuple(sorted(ks, reverse=True))  # LPT dispatch order
    k_max = max(ks)
    dtype = jnp.dtype(dtype_str)

    def build(a_true: jax.Array, root_key: jax.Array):
        w0l, h0l = [], []
        for k in ks:
            keys = jax.random.split(jax.random.fold_in(root_key, k),
                                    padded_restarts)
            w0s, h0s = jax.vmap(
                lambda kk, k=k: initialize(kk, a_true, k, init_cfg,
                                           dtype))(keys)
            w0l.append(jnp.pad(w0s, ((0, 0), (0, m_pad - m_true),
                                     (0, k_max - k))))
            h0l.append(jnp.pad(h0s, ((0, 0), (0, k_max - k),
                                     (0, n_pad - n_true))))
        return jnp.concatenate(w0l), jnp.concatenate(h0l)

    return jax.jit(build)


def _dyn_lane_init(init_cfg: InitConfig, dtype, n_pad: int, m_pad: int,
                   k_max: int):
    """Lane initializer with DYNAMIC true dims for the bucketed
    executables: reproduces ``random_init``'s exact (m_true, n_true)
    draws from inside a bucket-shaped jit, zero-padded to the lattice.

    Exactness rests on two properties of the partitionable threefry PRNG
    (enforced by ``nmfx._compat``; pinned by
    tests/test_exec_cache.py::test_threefry_flat_index_properties):
    draws are counter-based per FLAT element index, so (a) a draw with
    the same trailing column count is row-prefix-stable —
    ``uniform(kw, (m_pad, k))[:m_true]`` equals the true W0 draw — and
    (b) a 1-D draw gathered at ``i·n_true + j`` equals element (i, j) of
    the true 2-D H0 draw. Pad entries are masked to exact zero, the
    padding invariant every grid solver preserves."""
    minval, maxval = init_cfg.minval, init_cfg.maxval

    def init_one(kk, k, m_true, n_true):
        kw, kh = jax.random.split(kk)
        w = jax.random.uniform(kw, (m_pad, k), dtype, minval, maxval)
        w = jnp.where(jnp.arange(m_pad)[:, None] < m_true, w, 0.0)
        hu = jax.random.uniform(kh, (k * n_pad,), dtype, minval, maxval)
        i = jnp.arange(k)[:, None]
        j = jnp.arange(n_pad)[None, :]
        # max gather index (k-1)·n_true + n_pad-1 < k·n_pad: in bounds
        h = jnp.where(j < n_true, hu[i * n_true + j], 0.0)
        return w, h

    def build(rank_keys, m_true, n_true):
        """[(k, (r,) keys)] → padded (B, m_pad, k_max) / (B, k_max, n_pad)
        lane stacks, rank-major (the ``_init_lanes`` layout)."""
        w0l, h0l = [], []
        for k, keys in rank_keys:
            w0s, h0s = jax.vmap(
                lambda kk, k=k: init_one(kk, k, m_true, n_true))(keys)
            w0l.append(jnp.pad(w0s, ((0, 0), (0, 0), (0, k_max - k))))
            h0l.append(jnp.pad(h0s, ((0, 0), (0, k_max - k), (0, 0))))
        return jnp.concatenate(w0l), jnp.concatenate(h0l)

    return build


@lru_cache(maxsize=32)
def _build_bucketed_sweep_fn(ks: tuple[int, ...], restarts: int,
                             solver_cfg: SolverConfig, label_rule: str,
                             mesh: Mesh | None, keep_factors: bool,
                             grid_slots: int, grid_tail_slots,
                             bucket_shape: tuple[int, int],
                             donate_inits: bool = False,
                             init_cfg: InitConfig | None = None,
                             fault_token=None):
    """Sweep builder for the shape-bucketed executable-reuse layer
    (``nmfx/exec_cache.py``): the whole-grid slot-scheduled solve of
    ``_build_grid_exec_sweep_fn``, restructured so ONE compiled
    executable serves every dataset whose shape rounds up to
    ``bucket_shape``.

    With ``init_cfg`` (random init only) the built function is

        fn(a_pad, root_key, m_true, n_true, flip_floor) -> {k: KSweepOutput}

    — initialization happens INSIDE the executable with dynamic true
    dims (``_dyn_lane_init``), so a new true shape in a warm bucket
    costs literally zero compilation. Without it (the NNDSVD route,
    whose SVD factors the true matrix) the signature is

        fn(a_pad, w0, h0, m_true, n_true, flip_floor)

    with the lane batch pre-built per true shape by
    ``bucketed_lane_init_fn`` (a small per-shape jit — the one compile
    NNDSVD requests still pay).

    ``a_pad`` is the zero-padded (m_pad, n_pad) matrix and ``m_true``/
    ``n_true``/``flip_floor`` are DYNAMIC i32 scalars: the executable
    masks pad columns out of labels (-1) and hence the one-hot consensus
    reduction, rescales the RMS dnorms from the padded to the true
    normalizer (the residual sums themselves get exact-zero pad
    contributions), and threads the true sample count's class-stability
    flip budget into the scheduler (``mu_sched(flip_floor=...)``) — so
    nothing user-visible depends on the bucket, only on the data.
    Outputs keep padded extents (the cache's host layer slices them);
    per-restart stats are exact.

    ``donate_inits`` donates the external lane-batch buffers to the
    executable (they are rebuilt per request; ignored for the
    inside-init signature, which has none).
    """
    from nmfx import faults
    from nmfx.ops.sched_mu import mu_sched

    ks = tuple(sorted(ks, reverse=True))
    k_max = max(ks)
    m_pad, n_pad = bucket_shape
    padded = _pad_count(restarts, mesh)
    dtype = jnp.dtype(solver_cfg.dtype)
    inside_init = init_cfg is not None
    poison = tuple(g * padded + r for g, k in enumerate(ks)
                   for r in faults.poison_restarts(k, restarts))
    if poison and (not inside_init or mesh is not None):
        raise ValueError(
            "solve.nonfinite fault injection on the bucketed executables "
            "needs the random-init unmeshed route (init inside the "
            "executable); disarm the site for NNDSVD/meshed runs")
    if inside_init and init_cfg.method != "random":
        raise ValueError(
            "inside-executable init is the random-init fast path; NNDSVD "
            "lane batches are built per true shape (pass init_cfg=None)")
    dyn_init = (_dyn_lane_init(init_cfg, dtype, n_pad, m_pad, k_max)
                if inside_init else None)
    donate = (1, 2) if donate_inits and not inside_init else ()

    def _true_scale(m_true, n_true, ref_dtype):
        # pad entries contribute exact zeros to the Frobenius sums, so
        # only the √(mn) normalizer differs; float math — i32 m·n can
        # overflow at large shapes
        true_mn = (m_true.astype(jnp.float32)
                   * n_true.astype(jnp.float32))
        return jnp.sqrt(float(m_pad * n_pad) / true_mn).astype(ref_dtype)

    def _rank_keys(root_key, r):
        """The canonical per-(k, restart) key chain of the per-k/grid
        paths: fold_in(root, k), split over the (padded) restart axis."""
        return [(k, jax.random.split(jax.random.fold_in(root_key, k), r))
                for k in ks]

    if (mesh is None or RESTART_AXIS not in mesh.axis_names
            or mesh.shape[RESTART_AXIS] == 1):
        job_ks = tuple(k for k in ks for _ in range(padded))

        def run(a_pad, w0, h0, m_true, n_true,
                flip_floor) -> dict[int, KSweepOutput]:
            a_pad = jnp.asarray(a_pad, dtype)
            # composition-independent pool geometry (the serve-layer
            # bit-identity contract): pad the batch to the full slot
            # width and run ONE fixed-width stage — the straggler-tail
            # cascade would move surviving lanes into narrower pools at
            # composition-dependent times, re-introducing exactly the
            # shape-dependent reduction drift _pad_pool_lanes exists to
            # remove, so the serving-tier builders pin it off
            # (grid_tail_slots is honored everywhere else)
            w0p, h0p, jks = _pad_pool_lanes(w0, h0, job_ks, grid_slots)
            res = mu_sched(a_pad, w0p, h0p, solver_cfg, slots=grid_slots,
                           tail_slots=0, job_ks=jks,
                           flip_floor=flip_floor)
            scale = _true_scale(m_true, n_true, res.dnorm.dtype)
            valid = jnp.arange(n_pad) < n_true
            out: dict[int, KSweepOutput] = {}
            for g, k in enumerate(ks):
                sl = slice(g * padded, g * padded + restarts)
                hk = res.h[sl, :k, :]
                wk = res.w[sl, :, :k]
                labels = jax.vmap(partial(labels_from_h,
                                          rule=label_rule))(hk)
                # pad columns → -1: one_hot drops them from the
                # consensus reduction and the host layer slices them off
                labels = jnp.where(valid[None, :], labels, -1)
                dnorm = res.dnorm[sl] * scale
                labels, dnorm_best, faulted = _quarantine_lanes(
                    labels, dnorm, res.stop_reason[sl])
                cons = _quarantined_consensus(labels, k, restarts, faulted)
                best = jnp.argmin(dnorm_best)
                extra = (wk, hk) if keep_factors else (None, None)
                out[k] = KSweepOutput(cons, res.iterations[sl], dnorm,
                                      res.stop_reason[sl], labels,
                                      wk[best], hk[best], *extra)
            return out

        if inside_init:

            def impl(a_pad, root_key, m_true, n_true, flip_floor):
                w0, h0 = dyn_init(_rank_keys(root_key, padded),
                                  m_true, n_true)
                w0 = _poison_restart_lanes(w0, poison)
                return run(a_pad, w0, h0, m_true, n_true, flip_floor)

            return jax.jit(impl)

        return jax.jit(run, donate_argnums=donate)

    n_shards = mesh.shape[RESTART_AXIS]
    r_local = padded // n_shards
    job_ks_loc = tuple(k for k in ks for _ in range(r_local))

    def shard_core(a_pad, w0, h0, m_true, n_true,
                   flip_floor) -> dict[int, KSweepOutput]:
        res = mu_sched(a_pad, w0, h0, solver_cfg, slots=grid_slots,
                       varying_axes=(RESTART_AXIS,),
                       tail_slots=grid_tail_slots, job_ks=job_ks_loc,
                       flip_floor=flip_floor)
        scale = _true_scale(m_true, n_true, res.dnorm.dtype)
        valid_col = jnp.arange(n_pad) < n_true
        gidx = (lax.axis_index(RESTART_AXIS) * r_local
                + jnp.arange(r_local))
        valid_lane = gidx < restarts
        out: dict[int, KSweepOutput] = {}
        for g, k in enumerate(ks):
            sl = slice(g * r_local, (g + 1) * r_local)
            hk = res.h[sl, :k, :]
            wk = res.w[sl, :, :k]
            labels = jax.vmap(partial(labels_from_h, rule=label_rule))(hk)
            labels = jnp.where(valid_col[None, :], labels, -1)
            out[k] = _sharded_rank_output(k, labels, res.iterations[sl],
                                          res.dnorm[sl] * scale,
                                          res.stop_reason[sl], wk, hk,
                                          valid_lane, restarts,
                                          keep_factors)
        return out

    if inside_init:

        def shard_body_keys(a_pad, keys, m_true, n_true, flip_floor):
            # keys: this shard's (n_ks, r_local) key block — same
            # canonical chain, just sharded before the per-lane draws
            w0, h0 = dyn_init([(k, keys[g]) for g, k in enumerate(ks)],
                              m_true, n_true)
            return shard_core(a_pad, w0, h0, m_true, n_true, flip_floor)

        sharded = shard_map(shard_body_keys, mesh=mesh,
                            in_specs=(P(), P(None, RESTART_AXIS),
                                      P(), P(), P()),
                            out_specs=P(), check_vma=False)

        def impl(a_pad, root_key, m_true, n_true, flip_floor):
            a_pad = jnp.asarray(a_pad, dtype)
            keys = jnp.stack([kk for _, kk in _rank_keys(root_key,
                                                         padded)])
            return sharded(a_pad, keys, m_true, n_true, flip_floor)

        return jax.jit(impl)

    def shard_body(a_pad, w0s, h0s, m_true, n_true, flip_floor):
        w0 = w0s.reshape(len(ks) * r_local, m_pad, k_max)
        h0 = h0s.reshape(len(ks) * r_local, k_max, n_pad)
        return shard_core(a_pad, w0, h0, m_true, n_true, flip_floor)

    sharded = shard_map(shard_body, mesh=mesh,
                        in_specs=(P(), P(None, RESTART_AXIS),
                                  P(None, RESTART_AXIS), P(), P(), P()),
                        out_specs=P(), check_vma=False)

    def impl(a_pad, w0, h0, m_true, n_true,
             flip_floor) -> dict[int, KSweepOutput]:
        a_pad = jnp.asarray(a_pad, dtype)
        w0s = w0.reshape(len(ks), padded, m_pad, k_max)
        h0s = h0.reshape(len(ks), padded, k_max, n_pad)
        return sharded(a_pad, w0s, h0s, m_true, n_true, flip_floor)

    return jax.jit(impl, donate_argnums=donate)


@lru_cache(maxsize=32)
def _build_packed_serve_fn(layout: tuple, solver_cfg: SolverConfig,
                           label_rule: str, grid_slots: int,
                           grid_tail_slots,
                           bucket_shape: tuple[int, int],
                           init_cfg: InitConfig,
                           fault_token=None):
    """Sweep builder for CROSS-REQUEST lane packing (``nmfx/serve.py``):
    one slot-scheduled dispatch whose lanes come from SEVERAL serve
    requests — the token-level-batching analogue for consensus NMF.

    ``layout`` is the static pack shape, a tuple of ``(k, restarts)``
    groups sorted rank-descending (LPT dispatch order, request-arrival
    ties preserved by the caller); each group is one request's rank-k
    restart block. The built function is

        fn(a_pad, group_roots, m_true, n_true, flip_floor)
            -> tuple[KSweepOutput, ...]   # one per group, layout order

    ``group_roots`` is a stacked ``(G,)`` key array: group g's root is
    ``fold_in(key(seed_g), k_g)`` computed host-side by the serve
    scheduler, so each group draws EXACTLY the canonical per-(seed, k,
    restart) key chain of the solo paths — a request's lanes are
    initialized identically whether it solves alone or packed.

    Exactness contract (the load-bearing property, pinned by
    tests/test_serve.py): each lane's trajectory through ``mu_sched``
    is independent of the dispatch composition — batched GEMMs evaluate
    each lane independently, padding a lane's factors to a larger
    ``k_max`` only adds exact-zero terms to its contractions (the
    ``grid_mu`` invariant), and per-lane budgets/stop decisions are
    per-lane state — so a request's packed results are bit-identical to
    its solo bucketed sweep on the XLA engines, the same class as the
    whole-grid/per-k and streamed/sequential parities. The epilogue
    below mirrors ``_build_bucketed_sweep_fn``'s per-rank block
    field-for-field for the same reason. Lane independence additionally
    requires composition-independent GEMM *shapes*: XLA picks reduction
    partitionings per shape, and on a thread-constrained CPU platform a
    wider pool's per-lane reductions drift ~1 ulp/iteration from a
    narrower one's (the PR-12-flagged ≥3-request violation) — so this
    builder and the solo bucketed builder both pad their batch to the
    full ``grid_slots``-wide pool and pin the straggler-tail cascade
    off (``_pad_pool_lanes``).

    Packing therefore REQUIRES (enforced by the serve scheduler's
    compatibility key, never here): one shared padded matrix, one true
    shape (the masks/dnorm rescale/flip budget are shared scalars), one
    SolverConfig/InitConfig(random)/label-rule/slot-pool setting, and
    no mesh (the serve scheduler owns a single device).

    Compile cost (a known, documented tradeoff — docs/serving.md): the
    executable is keyed by the exact pack ``layout``, so the FIRST
    occurrence of a novel batch composition pays a synchronous compile
    on the scheduler thread, cached only in this in-process
    ``lru_cache`` (no ``ExecCacheConfig.cache_dir`` persistence, no
    ``compile_count`` accounting). Steady-state serving with stable
    request shapes converges to a handful of layouts; deployments with
    highly variable compositions should bound them via
    ``ServeConfig.max_batch_requests``/``batch_linger_s`` or disable
    packing.
    """
    from nmfx.ops.sched_mu import mu_sched

    if init_cfg.method != "random":
        raise ValueError(
            "cross-request packing draws lanes inside the executable "
            "(the random-init fast path); NNDSVD requests must dispatch "
            "solo")
    if any(layout[i][0] < layout[i + 1][0] for i in range(len(layout) - 1)):
        raise ValueError(
            f"layout must be sorted rank-descending (LPT), got {layout}")
    k_max = max(k for k, _ in layout)
    m_pad, n_pad = bucket_shape
    dtype = jnp.dtype(solver_cfg.dtype)
    dyn_init = _dyn_lane_init(init_cfg, dtype, n_pad, m_pad, k_max)
    job_ks = tuple(k for k, r in layout for _ in range(r))
    # solve.nonfinite injection: each group poisons the SAME per-(k,
    # restart) lanes its solo bucketed run would (lane selection is
    # (k, restart)-keyed, not request-keyed), so packed == solo parity
    # holds under injection too
    from nmfx import faults

    poison, _off = [], 0
    for k, r in layout:
        poison.extend(_off + rr for rr in faults.poison_restarts(k, r))
        _off += r
    poison = tuple(poison)

    def impl(a_pad, group_roots, m_true, n_true,
             flip_floor) -> tuple[KSweepOutput, ...]:
        a_pad = jnp.asarray(a_pad, dtype)
        rank_keys = [(k, jax.random.split(group_roots[g], r))
                     for g, (k, r) in enumerate(layout)]
        w0, h0 = dyn_init(rank_keys, m_true, n_true)
        w0 = _poison_restart_lanes(w0, poison)
        # same fixed pool geometry as the solo bucketed builder (padded
        # to the full slot width, tail cascade pinned off): per-lane
        # GEMM shapes — and so each lane's reduction order — must not
        # depend on what else packed into this dispatch, or packed
        # results drift bitwise from the solo runs they are contracted
        # to equal (see _pad_pool_lanes)
        w0, h0, jks = _pad_pool_lanes(w0, h0, job_ks, grid_slots)
        res = mu_sched(a_pad, w0, h0, solver_cfg, slots=grid_slots,
                       tail_slots=0, job_ks=jks,
                       flip_floor=flip_floor)
        # pad-masking epilogue: identical math to the solo bucketed
        # executable's per-rank block (labels -> -1 pad columns ->
        # one-hot consensus; dnorm rescaled from the padded to the true
        # normalizer) so packed == solo is slicing, not re-derivation
        true_mn = (m_true.astype(jnp.float32)
                   * n_true.astype(jnp.float32))
        scale = jnp.sqrt(float(m_pad * n_pad) / true_mn).astype(
            res.dnorm.dtype)
        valid = jnp.arange(n_pad) < n_true
        out: list[KSweepOutput] = []
        start = 0
        for k, r in layout:
            sl = slice(start, start + r)
            start += r
            hk = res.h[sl, :k, :]
            wk = res.w[sl, :, :k]
            labels = jax.vmap(partial(labels_from_h,
                                      rule=label_rule))(hk)
            labels = jnp.where(valid[None, :], labels, -1)
            dnorm = res.dnorm[sl] * scale
            labels, dnorm_best, faulted = _quarantine_lanes(
                labels, dnorm, res.stop_reason[sl])
            cons = _quarantined_consensus(labels, k, r, faulted)
            best = jnp.argmin(dnorm_best)
            out.append(KSweepOutput(cons, res.iterations[sl], dnorm,
                                    res.stop_reason[sl], labels,
                                    wk[best], hk[best]))
        return tuple(out)

    return jax.jit(impl)


def grid_mesh(restart_shards: int | None = None,
              feature_shards: int = 1,
              sample_shards: int = 1,
              devices=None) -> Mesh:
    """A mesh over ``devices`` (default: the local devices) with up to
    three axes: ``restarts`` (data parallel) × ``features`` (tensor
    parallel, rows of A/W) × ``samples`` (sequence parallel, columns of
    A/H).

    ``restart_shards=None`` uses all remaining devices on the restart axis.
    Any axis of size 1 is effectively off; (R,1,1) is the default restart
    mesh, (1,F,S) is pure SUMMA-style 2-D parallelism for one huge
    factorization.
    """
    if feature_shards < 1 or sample_shards < 1:
        raise ValueError(
            f"shard counts must be >= 1, got features={feature_shards}, "
            f"samples={sample_shards}")
    devices = list(jax.devices() if devices is None else devices)
    auto = restart_shards is None
    if auto:
        restart_shards = len(devices) // (feature_shards * sample_shards)
    n = restart_shards * feature_shards * sample_shards
    if restart_shards < 1:
        why = (f"features×samples={feature_shards * sample_shards} exceeds "
               f"the {len(devices)} available devices" if auto
               else "restart_shards must be >= 1")
        raise ValueError(
            f"mesh {restart_shards}x{feature_shards}x{sample_shards}: {why}")
    if n > len(devices):
        raise ValueError(
            f"mesh {restart_shards}x{feature_shards}x{sample_shards} needs "
            f"{n} devices, have {len(devices)}")
    return Mesh(
        np.array(devices[:n]).reshape(restart_shards, feature_shards,
                                      sample_shards),
        (RESTART_AXIS, FEATURE_AXIS, SAMPLE_AXIS))


def feature_mesh(restart_shards: int | None = None,
                 feature_shards: int = 1) -> Mesh:
    """A 2-D ``restarts×features`` mesh: ``grid_mesh`` without a sample
    axis (kept for the common tall-matrix case)."""
    if restart_shards is None:
        restart_shards = len(jax.devices()) // feature_shards
    mesh = grid_mesh(restart_shards, feature_shards, 1)
    return Mesh(mesh.devices.reshape(restart_shards, feature_shards),
                (RESTART_AXIS, FEATURE_AXIS))


def sweep_one_k(a, key, k: int, restarts: int,
                solver_cfg: SolverConfig = SolverConfig(),
                init_cfg: InitConfig = InitConfig(),
                label_rule: str = "argmax",
                mesh: Mesh | None = None,
                keep_factors: bool = False,
                grid_slots: int = 48,
                grid_tail_slots="auto") -> KSweepOutput:
    """Run `restarts` independent factorizations at rank k and reduce them to
    one consensus matrix, entirely on-device.

    ``keep_factors=True`` additionally returns every restart's (W, H) in
    ``all_w``/``all_h`` — the reference registry's per-job retention
    (nmf.r:50) — enabling restart-level analyses and custom ``reduce_grid``
    reductions without re-solving. ``grid_slots`` bounds the concurrent
    lanes of the slot-scheduled backends (hals backend='packed';
    ConsensusConfig.grid_slots at the sweep level)."""
    if solver_cfg.tile_rows is not None:
        # sweep() owns the out-of-core routing (single-tile delegation
        # included) because it runs BEFORE A is placed on device; by the
        # time this per-k entry runs, a tiled config should have been
        # delegated or routed — reaching here means a direct caller
        # skipped that
        raise ValueError(
            "tile_rows is routed by sweep() (which delegates one-tile "
            "dense configs to this in-core path and streams the rest "
            "through nmfx.tiles); call sweep(), or "
            "nmfx.tiles.sweep_one_k_tiled for the streaming engine "
            "directly")
    if (solver_cfg.algorithm == "mu" or solver_cfg.backend
            not in _GRID_EXEC_BACKENDS.get(solver_cfg.algorithm, ())
            or grid_axes_active(mesh)):
        # only the slot-scheduled branch consumes the grid knobs (any
        # non-mu algorithm routed there by _GRID_EXEC_BACKENDS — the mu
        # per-k path uses the packed driver, not the scheduler, and a
        # feature/sample-sharded mesh takes the grid-sharded builder,
        # which has no slot pool); normalize so a different value cannot
        # force a re-trace of unrelated builders
        grid_slots = 48
        grid_tail_slots = "auto"
    from nmfx import faults

    fn = _build_sweep_fn(k, restarts, solver_cfg, init_cfg, label_rule, mesh,
                         keep_factors, grid_slots, grid_tail_slots,
                         fault_token=faults.trace_token())
    return fn(jnp.asarray(a), key)


def _sweep_tiled(a, plan, cfg: ConsensusConfig,
                 solver_cfg: SolverConfig, init_cfg: InitConfig, *,
                 mesh=None, registry=None, profiler=None, on_rank=None,
                 checkpoint=None) -> "dict[int, KSweepOutput]":
    """The out-of-core arm of :func:`sweep`: per-k sequential solves
    through the streaming tiled engine (``nmfx/tiles.py``), sharing the
    canonical per-k key chain (``fold_in(root, k)``) and the on_rank
    streaming hook. A stays HOST-side — the stream owns all transfers —
    so the in-core path's ``place_resilient`` first-touch, grid
    execution, and the exec-cache (device-resident A by contract,
    ``grid_exec_ok``) do not apply here."""
    from nmfx import tiles as _tiles
    from nmfx.sparse import SparseMatrix

    if mesh is not None and any(
            mesh.shape[ax] > 1 for ax in mesh.axis_names):
        raise ValueError(
            "out-of-core (tiled/sparse) sweeps stream tiles through the "
            "default device; drop the mesh (the tile budget, not the "
            "device count, bounds the working set)")
    if registry is not None:
        raise ValueError(
            "out-of-core sweeps checkpoint mid-matrix through the "
            "durable chunk ledger (pass checkpoint=CheckpointConfig()); "
            "the legacy per-rank registry has no partial-pass records")
    if cfg.grid_exec == "grid":
        raise ValueError(
            "grid_exec='grid' is the in-core whole-grid solve; "
            "tiled/sparse sweeps run the streaming engine per rank "
            "(use grid_exec='auto')")
    if checkpoint is not None:
        from nmfx.checkpoint import run_checkpointed_sweep

        return run_checkpointed_sweep(a, cfg, solver_cfg, init_cfg,
                                      checkpoint, profiler=profiler,
                                      on_rank=on_rank)
    if isinstance(a, SparseMatrix):
        from nmfx.obs import costmodel

        costmodel.set_sparse_density(a.density)
    root = jax.random.key(cfg.seed)
    out: dict[int, KSweepOutput] = {}
    for k in cfg.ks:
        key = jax.random.fold_in(root, k)
        t0 = time.perf_counter()
        with profiler.phase(f"solve.k={k}") as sync:
            out[k] = sync(_tiles.sweep_one_k_tiled(
                a, key, k, cfg.restarts, solver_cfg, init_cfg,
                cfg.label_rule, cfg.keep_factors, profiler))
        on_rank(k, out[k])
        if 0 < _log.level <= logging.INFO:
            iters = np.asarray(out[k].iterations)
            _log.info(
                "k=%d (tiled, %d tiles): %d restarts in %.2fs "
                "(mean %.0f iters)", k, plan.n_tiles, cfg.restarts,
                time.perf_counter() - t0, float(iters.mean()))
    return {k: out[k] for k in cfg.ks}


def sweep(a, cfg: ConsensusConfig = ConsensusConfig(),
          solver_cfg: SolverConfig = SolverConfig(),
          init_cfg: InitConfig = InitConfig(),
          mesh: Mesh | None = None,
          registry=None, profiler=None,
          exec_cache=None, on_rank=None,
          checkpoint=None) -> dict[int, KSweepOutput]:
    """Full (k × restart) grid — by default as ONE whole-grid solve.

    Under ``cfg.grid_exec`` "grid"/"auto" (and an eligible config, see
    :func:`grid_exec_ok`) every remaining (k, restart) cell runs in one
    dense-batched jit'd solve: the TPU analogue of the reference's
    whole-grid-concurrent job array (nmf.r:64-68, shuffled chunks
    nmf.r:111) — one compile for the sweep instead of one per rank, and
    the chip contracts over every grid cell at once. Otherwise k values
    run sequentially, each using every device via the sharded restart
    batch.

    With a ``registry`` (nmfx.registry.SweepRegistry), each finished rank is
    checkpointed and a re-run resumes from the completed ranks instead of
    recomputing them (SURVEY.md §5 checkpoint/resume); under grid
    execution the still-missing ranks form one (smaller) grid solve.

    ``exec_cache`` (nmfx.exec_cache.ExecCache): serve the sweep through
    the shape-bucketed executable-reuse layer when the configuration is
    cacheable (:meth:`ExecCache.cacheable`) — repeat requests whose
    shapes land in an already-compiled bucket skip the trace+compile
    entirely, and with a persistent ``cache_dir`` a fresh process
    deserializes the bucket's executable from disk instead of
    recompiling it. Falls back to the normal path for non-cacheable
    configs and for checkpointed (``registry``) runs.

    ``on_rank(k, KSweepOutput)``: streaming hook, invoked the moment
    rank k's device output EXISTS (dispatched, not completed — the
    arrays are async futures). The harvest pipeline
    (``nmfx/harvest.py``) uses it to overlap per-rank device→host
    copies and host rank selection with the remaining ranks' device
    solve; checkpoint-loaded ranks are streamed too. The callback must
    not block (it runs on the dispatching thread).

    ``checkpoint`` (nmfx.config.CheckpointConfig): run through the
    durable sweep ledger (``nmfx/checkpoint.py``) — per-(k,
    restart-chunk) completion records with atomic writes, resume of
    only the missing chunks, results bit-identical to an uninterrupted
    checkpointed run. Mutually exclusive with ``registry`` (the legacy
    per-rank path) and ``mesh`` (the chunk executor owns its execution
    plan; see ``nmfx.distributed`` for elastic multi-device durable
    sweeps)."""
    if profiler is None:
        from nmfx.profiling import NullProfiler

        profiler = NullProfiler()
    if on_rank is None:
        on_rank = _noop_rank
    # Out-of-core routing (nmfx/tiles.py) decides FIRST, before the
    # checkpoint/exec-cache/registry branches consult the config: a
    # dense input whose plan resolves to ONE tile is delegated to the
    # in-core path with tile_rows stripped — bit-identical by
    # construction (the same jit graph runs), so aliasing the dense
    # identity everywhere downstream (fingerprints, cache keys,
    # manifests) is correct, not a collision. Multi-tile dense and all
    # sparse inputs run the streaming "tiled" engine family.
    from nmfx.sparse import SparseMatrix

    sparse_input = isinstance(a, SparseMatrix)
    if solver_cfg.tile_rows is not None or sparse_input:
        import dataclasses

        from nmfx import tiles as _tiles

        plan = _tiles.plan_for(a, solver_cfg)
        if plan.n_tiles == 1 and not sparse_input:
            solver_cfg = dataclasses.replace(solver_cfg, tile_rows=None)
        else:
            return _sweep_tiled(a, plan, cfg, solver_cfg, init_cfg,
                                mesh=mesh, registry=registry,
                                profiler=profiler, on_rank=on_rank,
                                checkpoint=checkpoint)
    # Block-shape autotune resolves HERE — before the checkpoint /
    # exec-cache / registry branches — so every downstream key
    # (fingerprint, bucket key, ledger manifest, jit static args) sees
    # the RESOLVED kernel schedule; a warm process resolves to the
    # identical config (nmfx/autotune.py's key discipline), so
    # artifacts written by a cold run are served to warm ones.
    if solver_cfg.experimental.autotune == "on":
        import os as _os

        from nmfx import autotune as _autotune

        m_a, n_a = a.shape
        k_hi = int(max(cfg.ks))
        at_slots = 1
        if solver_cfg.backend == "pallas":
            from nmfx.ops.sched_mu import _pallas_slot_clamp

            at_slots = _pallas_slot_clamp(
                cfg.grid_slots, k_hi, m_a, n_a, solver_cfg,
                solver_cfg.experimental.factor_dtype)
        at_dir = None
        if exec_cache is not None and exec_cache.cfg.cache_dir:
            at_dir = _os.path.join(exec_cache.cfg.cache_dir, "autotune")
        solver_cfg = _autotune.resolve(solver_cfg, m_a, n_a, k_hi,
                                       at_slots, cache_dir=at_dir)
    if checkpoint is not None:
        if registry is not None:
            raise ValueError(
                "pass either checkpoint (the durable chunked ledger) or "
                "registry (the legacy per-rank SweepRegistry), not both")
        if mesh is not None and any(
                mesh.shape[ax] > 1 for ax in mesh.axis_names):
            raise ValueError(
                "checkpointed sweeps execute per-(k, restart-chunk) on "
                "the default device (the chunk plan is the durability "
                "unit); drop the mesh, or use nmfx.distributed's "
                "elastic shard runner for multi-device durable sweeps")
        from nmfx.checkpoint import run_checkpointed_sweep

        return run_checkpointed_sweep(a, cfg, solver_cfg, init_cfg,
                                      checkpoint, profiler=profiler,
                                      on_rank=on_rank)
    if (exec_cache is not None and registry is None
            and exec_cache.cacheable(cfg, solver_cfg, mesh)):
        return exec_cache.run_sweep(a, cfg, solver_cfg, init_cfg, mesh,
                                    profiler=profiler, on_rank=on_rank)
    # Multi-host discipline: every process must take the same compute-vs-skip
    # branch for each k, or the skippers never join the collectives compiled
    # into the sharded sweep and the job deadlocks. The coordinator (the only
    # process expected to hold a registry — see distributed.consensus) decides
    # and broadcasts; loaded results are broadcast to the other hosts.
    multi = jax.process_count() > 1
    root = jax.random.key(cfg.seed)
    out: dict[int, KSweepOutput] = {}
    needed: list[int] = []
    for k in cfg.ks:
        loaded = registry.try_load(k) if registry is not None else None
        have = loaded is not None
        if multi:
            from jax.experimental import multihost_utils

            have = bool(multihost_utils.broadcast_one_to_all(
                np.asarray(have)))
        if have:
            if loaded is None:  # registry-less host joining the broadcast
                loaded = _template(a, k, cfg.restarts, solver_cfg,
                                   cfg.keep_factors)
            if multi:
                loaded = KSweepOutput(*(
                    None if x is None else np.asarray(x) for x in
                    multihost_utils.broadcast_one_to_all(tuple(loaded))))
            out[k] = loaded
            on_rank(k, loaded)
        else:
            needed.append(k)
    if not needed:  # fully-checkpointed re-run: A never transfers
        return out
    # place A on device once, replicated over the mesh, THROUGH the
    # device-resident input cache: a repeat sweep over the same matrix
    # (serving traffic, re-runs at new ks) transfers ZERO bytes, and a
    # first touch dispatches a chunked async copy that overlaps the
    # first rank's trace/compile instead of blocking here —
    # re-transferring the matrix for every rank costs more than a
    # rank's whole solve at small sizes (~0.14 s/call through the TPU
    # tunnel for a 10 MB matrix). place_resilient: a cache-layer
    # placement failure degrades to a direct uncached transfer instead
    # of failing the sweep (docs/serving.md "Failure model")
    from nmfx.data_cache import place_resilient

    a_dev = place_resilient(a, solver_cfg, mesh, profiler=profiler)

    eligible = grid_exec_ok(solver_cfg, mesh)
    if cfg.grid_exec == "grid" and not eligible:
        raise ValueError(
            "grid_exec='grid' needs an algorithm/backend pair that routes "
            "into the slot scheduler — mu (backend "
            "'auto'/'packed'/'pallas'), hals ('auto'/'packed'/'pallas'), "
            "or "
            "neals/snmf/kl (explicit 'packed') — and no feature/sample "
            "mesh "
            f"axes; got algorithm={solver_cfg.algorithm!r}, "
            f"backend={solver_cfg.backend!r} (use grid_exec='auto' to "
            "fall back per configuration)")
    use_grid = eligible and (cfg.grid_exec == "grid"
                             or (cfg.grid_exec == "auto" and len(needed) > 1))
    coord = not multi or jax.process_index() == 0
    if use_grid:
        from nmfx import faults

        fn = _build_grid_exec_sweep_fn(tuple(needed), cfg.restarts,
                                       solver_cfg, init_cfg, cfg.label_rule,
                                       mesh, cfg.keep_factors,
                                       cfg.grid_slots, cfg.grid_tail_slots,
                                       fault_token=faults.trace_token())
        t0 = time.perf_counter()
        with profiler.phase("solve.grid") as sync:
            solved = sync(fn(a_dev, root))
        from nmfx.exec_cache import start_host_fetch

        with profiler.phase("xfer.overlap"):
            # begin non-blocking device→host copies NOW: by the time the
            # pipeline's batched device_get runs (after rank-selection
            # dispatch), the results are already streaming/resident
            start_host_fetch(solved)
        out.update(solved)
        for k in needed:
            # stream: one executable produced every rank, but each
            # rank's arrays complete (and harvest) independently
            on_rank(k, solved[k])
        _attribute_dispatch("sweep.grid", solver_cfg, a_dev.shape,
                            solved, time.perf_counter() - t0, mesh,
                            profiler)
        if 0 < _log.level <= logging.INFO and coord:
            iters = {k: float(np.asarray(v.iterations).mean())
                     for k, v in solved.items()}
            _log.info("grid: %d ranks x %d restarts in one solve, %.2fs "
                      "(mean iters %s)", len(needed), cfg.restarts,
                      time.perf_counter() - t0,
                      {k: round(v) for k, v in iters.items()})
        if registry is not None and coord:
            with profiler.phase("checkpoint"):
                for k in needed:
                    registry.save(k, out[k])
        return {k: out[k] for k in cfg.ks}
    for k in needed:
        # fold in k itself (not its position) so a given (seed, k) always
        # yields the same factorizations regardless of sweep composition
        key = jax.random.fold_in(root, k)
        t0 = time.perf_counter()
        with profiler.phase(f"solve.k={k}") as sync:
            out[k] = sync(sweep_one_k(a_dev, key, k, cfg.restarts,
                                      solver_cfg, init_cfg, cfg.label_rule,
                                      mesh, cfg.keep_factors,
                                      cfg.grid_slots, cfg.grid_tail_slots))
        from nmfx.exec_cache import start_host_fetch

        with profiler.phase("xfer.overlap"):
            # non-blocking: rank k's results stream to host while rank
            # k+1 compiles/solves, instead of all ranks paying one end
            # barrier at the pipeline's device_get
            start_host_fetch(out[k])
        on_rank(k, out[k])
        _attribute_dispatch("sweep.k", solver_cfg, a_dev.shape,
                            {k: out[k]}, time.perf_counter() - t0,
                            mesh, profiler)
        if 0 < _log.level <= logging.INFO and coord:
            # reading the stats forces a device sync, trading the k-grid's
            # async dispatch pipelining for live progress. Gated on a level
            # set explicitly on the "nmfx" logger (CLI --verbose does this)
            # — inherited app-wide INFO must not silently serialize the
            # sweep; coordinator-only under multi-host
            iters = np.asarray(out[k].iterations)
            _log.info("k=%d: %d restarts in %.2fs (mean %.0f iters)",
                      k, cfg.restarts, time.perf_counter() - t0,
                      float(iters.mean()))
        if registry is not None and coord:
            with profiler.phase("checkpoint"):
                registry.save(k, out[k])
    return {k: out[k] for k in cfg.ks}


def _noop_rank(k: int, out: KSweepOutput) -> None:
    """Default ``on_rank`` hook: no streaming consumer attached."""


def _attribute_dispatch(kind: str, solver_cfg: SolverConfig,
                        shape: tuple, outs: dict, wall_s: float,
                        mesh, profiler) -> None:
    """Per-dispatch roofline attribution (``nmfx.obs.costmodel``,
    ISSUE 13): annotate a just-measured solve dispatch with its model
    FLOPs/bytes and export the ``nmfx_perf_*`` gauges. Runs only on
    PROFILED dispatches — a real ``Profiler`` already blocked on the
    phase (so the wall is honest and the iteration counts are
    computed), while the NullProfiler paths (the serve scheduler, fully
    async callers) must never gain a device sync they didn't have; the
    serving engine attributes its own requests at harvest time instead
    (``nmfx/serve.py``). Note a cold ``sweep()`` dispatch's phase wall
    includes trace+compile — its attribution lands in the histograms'
    low-MFU tail (the exec-cache path's dispatches are compile-free by
    construction and attribute cleanly)."""
    from nmfx.profiling import NullProfiler

    if isinstance(profiler, NullProfiler):
        return
    from nmfx.obs import costmodel

    if not costmodel.attribution_enabled() or not outs:
        return
    devices = int(mesh.size) if mesh is not None else 1
    iters = {k: np.asarray(v.iterations) for k, v in outs.items()}
    costmodel.attribute_dispatch(kind, solver_cfg, shape[0], shape[1],
                                 iters, wall_s, mesh=mesh,
                                 devices=devices)


def place_input(a, solver_cfg: SolverConfig, mesh: Mesh | None) -> jax.Array:
    """Transfer A to device in the solver dtype: replicated across a
    restart-only mesh, *tiled* over any feature/sample axes — so an A whose
    m or n outgrows one device's HBM is never materialized whole on any
    single device (the point of the grid axes). Host arrays are dtype-cast
    host-side before placement for the same reason.

    Idempotent: an already-placed array passes through untouched, so callers
    that loop over ranks (``sweep``) pay the host→device transfer exactly
    once instead of once per rank.
    """
    dtype = jnp.dtype(solver_cfg.dtype)
    if mesh is None:
        return jnp.asarray(a, dtype)

    def ax(name):
        on = name in mesh.axis_names and mesh.shape[name] > 1
        return name if on else None

    spec = P(ax(FEATURE_AXIS), ax(SAMPLE_AXIS))
    if not isinstance(a, jax.Array):
        a = np.asarray(a, dtype)
    elif a.dtype != dtype:
        a = jnp.asarray(a, dtype)
    return jax.device_put(a, NamedSharding(mesh, spec))


def _template(a, k: int, restarts: int, solver_cfg: SolverConfig,
              keep_factors: bool = False) -> KSweepOutput:
    """Zero-valued KSweepOutput with the exact shapes/dtypes sweep_one_k
    produces — the broadcast skeleton a registry-less host contributes when
    the coordinator resumes a rank from checkpoint (structures must match on
    every process for broadcast_one_to_all)."""
    m, n = a.shape  # numpy or jax array; only the shape is needed
    f = jnp.dtype(solver_cfg.dtype)
    return KSweepOutput(
        consensus=np.zeros((n, n), np.float32),
        iterations=np.zeros((restarts,), np.int32),
        dnorms=np.zeros((restarts,), f),
        stop_reasons=np.zeros((restarts,), np.int32),
        labels=np.zeros((restarts, n), np.int32),
        best_w=np.zeros((m, k), f),
        best_h=np.zeros((k, n), f),
        all_w=np.zeros((restarts, m, k), f) if keep_factors else None,
        all_h=np.zeros((restarts, k, n), f) if keep_factors else None,
    )


class RestartResult(NamedTuple):
    """One grid cell's full result — the reference's per-job
    ``list(W, H, iter)`` (nmf.r:50), plus the residual and stop reason the
    reference never surfaces."""

    k: int
    restart: int
    w: np.ndarray  # (m, k)
    h: np.ndarray  # (k, n)
    iterations: int
    dnorm: float
    stop_reason: int


def grid_cells(results: "GridResults") -> list[RestartResult]:
    """Flatten a ``keep_factors=True`` sweep into the (k × restart) grid of
    per-job results the reference's registry holds. Accepts either the raw
    ``sweep`` output (``{k: KSweepOutput}``) or a ``ConsensusResult`` from
    ``nmfconsensus`` (its per-k records carry the same per-restart
    fields)."""
    if hasattr(results, "per_k"):  # ConsensusResult
        results = results.per_k
    cells: list[RestartResult] = []
    for k in sorted(results):
        out = results[k]
        if out.all_w is None or out.all_h is None:
            raise ValueError(
                f"per-restart factors for k={k} were not retained; run the "
                "sweep with keep_factors=True (or recompute a single "
                "restart with nmfx.restart_factors)")
        all_w = np.asarray(out.all_w)
        all_h = np.asarray(out.all_h)
        iters = np.asarray(out.iterations)
        dnorms = np.asarray(out.dnorms)
        stops = np.asarray(out.stop_reasons)
        for r in range(all_w.shape[0]):
            cells.append(RestartResult(k, r, all_w[r], all_h[r],
                                       int(iters[r]), float(dnorms[r]),
                                       int(stops[r])))
    return cells


def reduce_grid(results: "GridResults", fun=None,
                by: str = "k") -> dict[int, object]:
    """Generic axis-grouped reduction over the (k × restart) job grid — the
    reference's ``reduceGridBy`` (nmf.r:72-98), which groups job results by
    the kept grid axis and applies ``fun`` to each group's list of per-job
    results. ``fun=None`` uses the reference's own reduction,
    :func:`consensus_from_cells` (the default ``fun`` in ``runNMFinJobs``,
    nmf.r:117).

    ``by="k"``: ``fun`` receives all restarts at one rank (the reference's
    only actual use, ``by="k"`` with the consensus reduction, nmf.r:117);
    ``by="restart"``: the transpose grouping — one restart index across all
    ranks (the reference's ``num.clusterings`` axis). ``results`` is the
    raw ``sweep`` output or a ``ConsensusResult`` (see ``grid_cells``).
    Returns ``{axis_value: fun(cells)}`` sorted by axis value. Host-side by
    design: this is the flexibility hook for custom analyses; the
    performance path is the on-device consensus reduction inside
    ``sweep_one_k``.
    """
    if fun is None:
        fun = consensus_from_cells
    axes = {"k": 0, "restart": 1}
    if by not in axes:
        raise ValueError(f"by must be 'k' or 'restart', got {by!r}")
    groups: dict[int, list[RestartResult]] = {}
    for cell in grid_cells(results):
        groups.setdefault(cell[axes[by]], []).append(cell)
    return {g: fun(groups[g]) for g in sorted(groups)}


def consensus_from_cells(cells: Sequence[RestartResult],
                         label_rule: str = "argmax") -> np.ndarray:
    """Host-numpy ``computeConsensusMatrixFromClusterings`` (nmf.r:121-144)
    over a group of grid cells — the reference's default reduction, used by
    :func:`reduce_grid` when no ``fun`` is given. The on-device einsum in
    ``nmfx.consensus`` is the performance path; this one exists so custom
    grid reductions have the reference reduction to compose with."""
    if label_rule not in ("argmax", "argmin"):
        raise ValueError(
            f"label_rule must be 'argmax' or 'argmin', got {label_rule!r}")
    pick = np.argmax if label_rule == "argmax" else np.argmin
    labels = np.stack([pick(c.h, axis=0) for c in cells])  # (R, n)
    return (labels[:, :, None] == labels[:, None, :]).mean(axis=0)


def default_mesh() -> Mesh | None:
    """A 1-D mesh over all local devices for the restart axis; None if only
    one device is visible (plain vmap is already optimal there)."""
    devices = jax.devices()
    if len(devices) <= 1:
        return None
    return Mesh(np.array(devices), (RESTART_AXIS,))
