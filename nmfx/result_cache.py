"""Finished-result cache: content-addressed ``ConsensusResult`` reuse.

At service scale the dominant waste is not slow solves but REPEATED
ones: the same atlas resubmitted under the same configuration re-solves
from scratch even though the input is already content-hashed
(``data_cache.DataKey``), the router already places by that hash, and
the result is fully deterministic given (data, config, seed). This
module closes the loop — the caching/memoization analogue of the
communication-avoiding reuse arguments in MPI-FAUN (arxiv 1609.09154)
and the batch-streaming decomposition of Distributed Out-of-Memory NMF
(arxiv 2202.09518): never recompute or re-move bytes you already have.

* **Content-addressed key.** :func:`result_key` digests (input content
  fingerprint + shape + source dtype, every result-affecting
  SolverConfig/ConsensusConfig field, the init config, the quality tag,
  a format version). Coverage is declared by :func:`cache_key_fields`
  and built FROM the existing introspection hooks — the solver side is
  ``checkpoint.manifest_key_fields()['solver']`` (all fields minus the
  declared execution-strategy-only ``NON_NUMERICS_FIELDS``), the
  consensus side is every ``ConsensusConfig`` field minus the
  (deliberately empty) ``RESULT_CACHE_EXEMPT_FIELDS`` — so lint rule
  NMFX011 cross-references the key against the live dataclasses and a
  field can never silently drop out (the stale-serve class: one cached
  result served to two configurations that must differ).
* **Quality separation.** The key INCLUDES the result's quality tag, so
  an approximate (``"sketched"``) result — including a serve request
  quality-DEGRADED there mid-flight — is cached under its own address
  and can never be served to an ``"exact"`` lookup. Callers derive the
  lookup quality from the request config (:func:`request_quality`).
* **Two tiers.** An in-memory LRU (``OrderedDict``, the exec-cache
  discipline) over an optional disk tier of ``ConsensusResult.save``
  archives written atomically (mkstemp ``.part`` + ``os.replace``) with
  an embedded key/format verification record — corrupt, truncated or
  key-mismatched entries are dropped with one warning and treated as
  misses, never served. The disk tier is byte-capped by an mtime-LRU
  (every hit touches its entry); evicting from memory never deletes a
  disk entry.
* **Honesty counters.** ``nmfx_result_cache_{hits,misses}_total``
  (labeled by serving layer) plus the coalescing/extension counters
  declared here for the whole request-economics surface; a warm hit is
  additionally gated by ``nmfx_serve_dispatches_total`` and the
  ``data_cache`` transfer counters staying FLAT (zero solve dispatches,
  zero host-to-device bytes — tests/test_result_cache.py).

See docs/serving.md "Request economics".
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import tempfile
import threading
import time
import warnings
import zipfile
from collections import OrderedDict

import numpy as np

from nmfx.api import ConsensusResult
from nmfx.config import (ConsensusConfig, InitConfig, ResultCacheConfig,
                         SolverConfig)
from nmfx.obs import flight as _flight
from nmfx.obs import metrics as _metrics

__all__ = ["ResultCache", "cache_key_fields", "cacheable", "result_key",
           "key_for_array", "request_quality"]

#: on-disk entry format version; bumped on any serialization OR key
#: layout change so old entries fail the embedded-record check (one
#: warning, clean re-solve) instead of deserializing a stale result
_DISK_FORMAT = 1
#: suffix of persisted result entries (the eviction scan and tests key
#: on it; atomic-write temp files use ``.part`` so a crashed writer's
#: leftovers are never mistaken for entries)
_DISK_SUFFIX = ".nmfxres"
#: zip member holding the embedded verification record — npz archives
#: are zips, and ``ConsensusResult.load`` reads only its own member
#: names, so the record rides INSIDE the entry (single-file atomicity)
#: without touching the result serialization format
_META_MEMBER = "nmfxres_meta.json"
#: age after which an orphaned ``.part`` temp file (a writer killed
#: between mkstemp and the rename) is swept by the eviction scan
_PART_MAX_AGE_S = 3600.0

# -- the request-economics counter block (ISSUE 16) ----------------------
# Declared once here; serve/router/checkpoint re-declare by name where
# importing this module would cycle (MetricsRegistry._declare is an
# idempotent get-or-create, so every declaration site shares one series).
_hits_total = _metrics.counter(
    "nmfx_result_cache_hits_total",
    "requests served a finished ConsensusResult straight from the "
    "content-addressed result cache (zero solve dispatches, zero h2d "
    "transfers)", labelnames=("layer",))
_misses_total = _metrics.counter(
    "nmfx_result_cache_misses_total",
    "result-cache lookups that found no finished result and fell "
    "through to a solve", labelnames=("layer",))
_coalesced_total = _metrics.counter(
    "nmfx_result_cache_coalesced_total",
    "requests attached as followers to an identical in-flight solve "
    "instead of dispatching their own", labelnames=("layer",))
_extended_total = _metrics.counter(
    "nmfx_result_cache_extended_total",
    "checkpointed sweeps that resumed a compatible ledger under a "
    "widened budget (more restarts / more ranks) and solved only the "
    "delta chunks")


def cache_key_fields() -> "dict[str, frozenset]":
    """The SolverConfig/ConsensusConfig fields the result-cache key
    covers — the introspection hook lint rule NMFX011 cross-references
    (the ``manifest_key_fields`` pattern).

    Built FROM the existing authoritative hooks rather than a parallel
    list: the solver side is exactly the checkpoint manifest's solver
    coverage (every field minus the declared execution-strategy-only
    ``SolverConfig.NON_NUMERICS_FIELDS`` — those change scheduling,
    never numbers); the consensus side is every ``ConsensusConfig``
    field minus ``ConsensusConfig.RESULT_CACHE_EXEMPT_FIELDS``, which
    is deliberately EMPTY: unlike the checkpoint ledger (whose unit is
    a per-(k, chunk) record, making ``ks``/``restarts`` resumable
    deltas), this cache stores the FINISHED result, and every
    ConsensusConfig field — including finalize-time ones like
    ``linkage`` — shapes that result."""
    from nmfx.checkpoint import manifest_key_fields

    consensus = frozenset(
        f.name for f in dataclasses.fields(ConsensusConfig)
    ) - frozenset(ConsensusConfig.RESULT_CACHE_EXEMPT_FIELDS)
    return {"solver": manifest_key_fields()["solver"],
            "consensus": consensus}


def cacheable(ccfg: ConsensusConfig) -> bool:
    """Whether a request's finished result may enter the cache.

    ``keep_factors=True`` results carry every restart's full (W, H)
    stacks — restarts×(m·k + k·n) values per rank — which would blow
    the byte budget for a retention mode that exists for interactive
    analysis, not serving; the recompute-by-key route
    (``nmfx.restart_factors``) reconstructs any restart exactly, so
    those requests solve through. Everything else is cacheable —
    approximate results included, under their own quality address."""
    return not ccfg.keep_factors


def request_quality(scfg: SolverConfig) -> str:
    """The quality tag a request's finished result will carry if served
    at its CONFIGURED fidelity — the tag lookups must use. (A request
    quality-DEGRADED mid-flight produces a different tag and therefore
    a different cache address; followers of a degraded leader share the
    leader's tagged outcome — see docs/serving.md.)"""
    return "sketched" if scfg.backend == "sketched" else "exact"


def _jsonable(v):
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        return dataclasses.asdict(v)
    return v


def result_key(fingerprint: str, shape: tuple, src_dtype: str,
               scfg: SolverConfig = SolverConfig(),
               ccfg: ConsensusConfig = ConsensusConfig(),
               icfg: InitConfig = InitConfig(),
               quality: str = "exact") -> str:
    """The content-addressed key: sha256 over a canonical JSON payload
    of (input content identity, every covered config field, init
    config, quality tag, format version).

    ``fingerprint`` is the sha256 of the raw host bytes — the same
    content digest ``data_cache.DataKey`` carries, so serving layers
    that already hashed the input (the placement pass) reuse it for
    free. ``shape``/``src_dtype`` disambiguate byte-identical buffers
    interpreted differently (the DataKey discipline). The raw
    ``scfg.backend`` is covered (not the coarser checkpoint
    engine-family): different backends produce float-different results,
    and one address must never serve both."""
    covered = cache_key_fields()
    payload = {
        "format": _DISK_FORMAT,
        "data": {"fingerprint": str(fingerprint),
                 "shape": [int(x) for x in shape],
                 "src_dtype": str(src_dtype)},
        "solver": {name: _jsonable(getattr(scfg, name))
                   for name in sorted(covered["solver"])},
        "consensus": {name: _jsonable(getattr(ccfg, name))
                      for name in sorted(covered["consensus"])},
        "init": dataclasses.asdict(icfg),
        "quality": str(quality),
    }
    blob = json.dumps(payload, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()


def key_for_array(a, scfg: SolverConfig = SolverConfig(),
                  ccfg: ConsensusConfig = ConsensusConfig(),
                  icfg: InitConfig = InitConfig(),
                  quality: str = "exact") -> str:
    """Convenience wrapper: content-hash a host matrix and key it.
    Costs one sha256 pass over the host bytes — serving layers that
    already placed the input through ``data_cache`` should pass the
    DataKey's fingerprint to :func:`result_key` instead. Sparse inputs
    (:class:`nmfx.sparse.SparseMatrix`) hash their canonical triplets,
    never a densified copy."""
    from nmfx.sparse import SparseMatrix

    if isinstance(a, SparseMatrix):
        return result_key(a.fingerprint(), tuple(a.shape),
                          a.data.dtype.str, scfg, ccfg, icfg, quality)
    arr = np.ascontiguousarray(a)
    digest = hashlib.sha256(arr.view(np.uint8).reshape(-1)).hexdigest()
    return result_key(digest, tuple(a.shape), arr.dtype.str,
                      scfg, ccfg, icfg, quality)


class ResultCache:
    """Two-tier finished-result store: in-memory LRU over an atomic
    tmp+rename disk tier (the exec-cache persistence idioms).

    Thread-safe; one instance can back a whole serving process (the
    server and router layers construct their own against a shared
    directory — entries are content-addressed, so concurrent writers
    last-win a complete file and readers never see a partial one).
    """

    def __init__(self, cfg: "ResultCacheConfig | None" = None, *,
                 cache_dir: "str | None" = None, layer: str = "server"):
        if cfg is None:
            cfg = ResultCacheConfig(cache_dir=cache_dir)
        elif cache_dir is not None and cfg.cache_dir != cache_dir:
            cfg = dataclasses.replace(cfg, cache_dir=cache_dir)
        self.cfg = cfg
        self.layer = str(layer)
        self._lock = threading.Lock()
        self._mem: "OrderedDict[str, ConsensusResult]" = OrderedDict()
        self._warned: set = set()
        # per-instance mirrors of the registry counters (tests and the
        # bench economics rung read these without snapshot plumbing)
        self.hits = 0
        self.misses = 0
        self.puts = 0
        self.mem_evictions = 0
        self.disk_evictions = 0

    # -- lookup ------------------------------------------------------------
    def lookup(self, key: str) -> "ConsensusResult | None":
        """O(1) lookup: memory first, then the disk tier (a disk hit is
        re-admitted to memory and touches its entry's mtime). Counts
        one hit or one miss on the registry counters per call."""
        with self._lock:
            res = self._mem.get(key)
            if res is not None:
                self._mem.move_to_end(key)
                self.hits += 1
        if res is not None:
            _hits_total.inc(layer=self.layer)
            _flight.record("result_cache.hit", layer=self.layer,
                           key=key[:12], tier="memory")
            return res
        res = self._disk_load(key)
        if res is not None:
            self._admit(key, res)
            with self._lock:
                self.hits += 1
            _hits_total.inc(layer=self.layer)
            _flight.record("result_cache.hit", layer=self.layer,
                           key=key[:12], tier="disk")
            return res
        with self._lock:
            self.misses += 1
        _misses_total.inc(layer=self.layer)
        return None

    def put(self, key: str, result: ConsensusResult,
            ccfg: "ConsensusConfig | None" = None) -> bool:
        """Admit a finished result under ``key``; refuses uncacheable
        requests (``ccfg`` with ``keep_factors``) and results that
        carry retained factor stacks. Returns whether the result is now
        addressable (memory at least; disk best-effort)."""
        if ccfg is not None and not cacheable(ccfg):
            return False
        if any(result.per_k[k].all_w is not None for k in result.ks):
            return False  # retained factor stacks: never cached
        self._admit(key, result)
        with self._lock:
            self.puts += 1
        if self.cfg.cache_dir:
            self._disk_store(key, result)
        _flight.record("result_cache.put", layer=self.layer,
                       key=key[:12], quality=result.quality)
        return True

    def _admit(self, key: str, result: ConsensusResult) -> None:
        with self._lock:
            self._mem[key] = result
            self._mem.move_to_end(key)
            while len(self._mem) > self.cfg.max_entries:
                self._mem.popitem(last=False)
                self.mem_evictions += 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._mem)

    @property
    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._mem), "hits": self.hits,
                    "misses": self.misses, "puts": self.puts,
                    "mem_evictions": self.mem_evictions,
                    "disk_evictions": self.disk_evictions}

    # -- the persistent tier ----------------------------------------------
    def _disk_path(self, key: str) -> str:
        return os.path.join(self.cfg.cache_dir, key[:40] + _DISK_SUFFIX)

    def _warn_once(self, category: str, msg: str) -> None:
        with self._lock:
            if category in self._warned:
                return
            self._warned.add(category)
        warnings.warn(f"nmfx result cache: {msg}", RuntimeWarning,
                      stacklevel=4)

    def _disk_load(self, key: str) -> "ConsensusResult | None":
        if not self.cfg.cache_dir:
            return None
        path = self._disk_path(key)
        try:
            # the embedded record first: an entry written under a
            # different key (hash-prefix collision, a hand-moved file)
            # or format version must never deserialize as a result
            with zipfile.ZipFile(path) as zf:
                # bound-method alias: a literal ``zf.read(...)`` would
                # alias every project ``read`` in the lint name-graph
                # (ast_scan's over-approximate method fallback) and drag
                # checkpoint/registry's ``open``/``_fingerprint`` into
                # the traced closure through this cache's ``get``
                read_member = zf.read
                meta = json.loads(read_member(_META_MEMBER))
        except FileNotFoundError:
            return None
        except OSError as e:
            # transient read problem — leave the entry for the other
            # processes sharing this directory, re-solve here
            self._warn_once("disk-read",
                            f"could not read cache entry ({e}); solving")
            return None
        except Exception:  # nmfx: ignore[NMFX006] -- truncated or
            # corrupt zip: fall through to the drop-and-resolve path
            meta = None
        try:
            if not (isinstance(meta, dict)
                    and meta.get("format") == _DISK_FORMAT
                    and meta.get("key") == key):
                raise ValueError(
                    f"unrecognized or mismatched cache record in {path}")
            res = ConsensusResult.load(path)
            try:
                os.utime(path)  # mtime-LRU: a hit refreshes the entry
            except OSError:
                pass
            return res
        except Exception as e:
            # content failure — the entry itself is unusable: drop it,
            # warn once, re-solve (always exact: a fresh solve is the
            # ground truth the cache was built from)
            self._warn_once(
                "disk-read",
                f"discarding unusable cache entry and solving ({e})")
            try:
                os.remove(path)
            except OSError:
                pass
            return None

    def _disk_store(self, key: str, result: ConsensusResult) -> bool:
        path = self._disk_path(key)
        try:
            d = os.path.dirname(path) or "."
            os.makedirs(d, exist_ok=True)
            # atomic publish: write a COMPLETE temp file (the result
            # archive plus the embedded verification record appended as
            # an extra zip member — npz archives are zips and the
            # loader reads only its own member names), then rename onto
            # the entry path. Concurrent writers last-win; readers
            # never see a partial file.
            fd, tmp = tempfile.mkstemp(dir=d, prefix="write-",
                                       suffix=".part")
            os.close(fd)
            try:
                result.save(tmp)
                with zipfile.ZipFile(tmp, "a") as zf:
                    zf.writestr(_META_MEMBER, json.dumps(
                        {"format": _DISK_FORMAT, "key": key,
                         "quality": result.quality}))
                os.replace(tmp, path)
            except BaseException:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                raise
            self._evict_disk(keep=path)
            return True
        except Exception as e:
            self._warn_once(
                "disk-write",
                f"could not persist result ({e}); this process caches "
                "in memory only")
            return False

    def _evict_disk(self, keep: "str | None" = None) -> None:
        """Byte-capped mtime-LRU over the cache directory (the
        exec-cache discipline): evict oldest-touched entries until the
        directory fits ``max_disk_bytes``; the just-written entry
        survives even when it alone exceeds the cap; orphaned ``.part``
        files old enough that no live writer can own them are swept."""
        d = self.cfg.cache_dir
        try:
            stats = []
            now = time.time()
            for name in os.listdir(d):
                p = os.path.join(d, name)
                if name.endswith(".part"):
                    try:
                        if now - os.stat(p).st_mtime > _PART_MAX_AGE_S:
                            os.remove(p)
                    except OSError:
                        pass
                    continue
                if not name.endswith(_DISK_SUFFIX):
                    continue
                try:
                    st = os.stat(p)
                except OSError:
                    continue  # concurrently evicted by another process
                stats.append((st.st_mtime, st.st_size, p))
            total = sum(size for _, size, _ in stats)
            keep_abs = os.path.abspath(keep) if keep is not None else None
            for _, size, p in sorted(stats):
                if total <= self.cfg.max_disk_bytes:
                    break
                if os.path.abspath(p) == keep_abs:
                    continue
                try:
                    os.remove(p)
                except OSError:
                    continue
                total -= size
                with self._lock:
                    self.disk_evictions += 1
        except OSError as e:
            self._warn_once("disk-evict",
                            f"disk eviction scan failed ({e})")
