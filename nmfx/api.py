"""Top-level API: single factorization and the full consensus pipeline.

The public surface a user of the reference lands on:

* ``nmf(...)``          ≈ one ``doNMF`` call (reference ``nmf.r:23-51``),
  with all eight solvers wired instead of only mu (the reference's
  five plus the BROAD original's Brunet ``kl`` rule and Kim & Park
  ``snmf``).
* ``nmfconsensus(...)`` ≈ ``runNMFinJobs`` + ``computeConsensusAndSaveFiles``
  (reference ``nmf.r:106-119, 146-253``): the (k × restart) sweep, consensus
  matrices, cophenetic rank selection, memberships, and optional file/plot
  outputs.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Mapping, Sequence

import jax
import numpy as np

from nmfx import cophenetic as coph
from nmfx.config import ConsensusConfig, InitConfig, OutputConfig, SolverConfig
from nmfx.io import Dataset, read_dataset, write_gct
from nmfx.solvers.base import SolverResult, solve
from nmfx.init import initialize
from nmfx.sweep import default_mesh, sweep


@dataclasses.dataclass(frozen=True)
class KResult:
    """Everything the pipeline derives at one rank k."""

    k: int
    consensus: np.ndarray  # (n, n) mean connectivity
    rho: float  # cophenetic correlation
    dispersion: float  # Kim & Park (2007): mean (2C-1)^2, 1.0 = crisp
    membership: np.ndarray  # (n,) labels 1..k from cutree
    order: np.ndarray  # (n,) dendrogram leaf order
    iterations: np.ndarray  # (restarts,)
    dnorms: np.ndarray  # (restarts,) final RMS residuals
    stop_reasons: np.ndarray  # (restarts,)
    best_w: np.ndarray  # (m, k) factors of the lowest-residual restart
    best_h: np.ndarray  # (k, n) — the "metagenes" (reference H, nmf.r:50)
    #: every restart's factors — populated only under ``keep_factors=True``
    #: (the reference registry's per-job retention, nmf.r:50)
    all_w: np.ndarray | None = None  # (restarts, m, k)
    all_h: np.ndarray | None = None  # (restarts, k, n)

    @property
    def ordered_consensus(self) -> np.ndarray:
        """Consensus matrix reordered by the dendrogram (reference
        ``connect.matrix[HC$order, HC$order]``, nmf.r:174)."""
        return self.consensus[np.ix_(self.order, self.order)]


#: KResult fields that may legitimately be absent from a saved result (their
#: dataclass default is None); every other field missing from a file is
#: corruption / a version mismatch and must fail fast on load
_OPTIONAL_KRESULT = frozenset(("all_w", "all_h"))


@dataclasses.dataclass(frozen=True)
class ConsensusResult:
    ks: tuple[int, ...]
    per_k: Mapping[int, KResult]
    col_names: tuple[str, ...]
    #: solver-quality tag (ISSUE 12): "exact" for the bit-exact engine
    #: families, "sketched" when the factorizations ran the random-
    #: projection compressed engine (``backend="sketched"`` — including
    #: a serve request DEGRADED there by quality-elastic scheduling,
    #: ``ServeConfig.quality_elastic``). The tag is set by every
    #: producing path (``nmfconsensus``, the serve completion workers —
    #: a lint fixture in tests/test_serve_quality.py pins that no
    #: construction site can omit it), so an approximate result can
    #: never reach a caller untyped.
    quality: str = "exact"

    @property
    def rhos(self) -> np.ndarray:
        return np.array([self.per_k[k].rho for k in self.ks])

    @property
    def dispersions(self) -> np.ndarray:
        """Kim & Park (2007) dispersion per k — a secondary rank-selection
        signal alongside the reference's cophenetic rho (1.0 = every
        consensus entry is 0 or 1, i.e. perfectly stable clustering)."""
        return np.array([self.per_k[k].dispersion for k in self.ks])

    @property
    def best_k(self) -> int:
        """Rank with the highest cophenetic correlation; exact rho ties
        (common on clean designs, where several ranks hit 1.0 after the
        reference's signif-4 rounding) break toward the higher dispersion —
        the crisper consensus. The reference computes no best_k (it writes
        the table for the user to eyeball), so the tie-break is free to be
        the sensible one."""
        return max(self.ks,
                   key=lambda k: (self.per_k[k].rho,
                                  self.per_k[k].dispersion))

    def summary(self) -> str:
        lines = ["k\trho\tdispersion\tmean_iters"]
        for k in self.ks:
            r = self.per_k[k]
            lines.append(f"{k}\t{r.rho:.4f}\t{r.dispersion:.4f}"
                         f"\t{r.iterations.mean():.1f}")
        lines.append(f"best k = {self.best_k}")
        if self.quality != "exact":
            lines.append(f"quality = {self.quality} (approximate engine; "
                         "statistical accuracy contract)")
        return "\n".join(lines)

    def save(self, path: str) -> None:
        """Persist the whole result as one compressed ``.npz`` so analyses
        (plots, rank comparisons, factor inspection) can resume later
        without rerunning the sweep — the reference keeps results only as
        transient BatchJobs registry files plus rendered outputs."""
        arrays: dict[str, np.ndarray] = {
            "ks": np.asarray(self.ks, np.int64),
            "col_names": np.asarray(self.col_names, np.str_),
            "quality": np.asarray(self.quality, np.str_),
        }
        for k in self.ks:
            r = self.per_k[k]
            for f in dataclasses.fields(KResult):
                v = getattr(r, f.name)
                if v is not None:  # optional all_w/all_h: absent = None
                    arrays[f"k{k}_{f.name}"] = np.asarray(v)
        # write through a handle (savez would append .npz to a bare path,
        # breaking load's path symmetry) into a tmp file, then atomically
        # replace — a crash mid-write never leaves a truncated result
        tmp = f"{path}.tmp"
        with open(tmp, "wb") as fh:
            np.savez_compressed(fh, **arrays)
        os.replace(tmp, path)

    @staticmethod
    def load(path: str) -> "ConsensusResult":
        """Inverse of :meth:`save`."""
        with np.load(path, allow_pickle=False) as z:
            ks = tuple(int(k) for k in z["ks"])
            per_k = {}
            for k in ks:
                kwargs = {}
                for f in dataclasses.fields(KResult):
                    name = f"k{k}_{f.name}"
                    if name not in z.files and f.name in _OPTIONAL_KRESULT:
                        kwargs[f.name] = None  # optional field not retained
                        continue
                    v = z[name]  # missing REQUIRED field: fail fast
                    if f.type == "int":
                        v = int(v)
                    elif f.type == "float":
                        v = float(v)
                    kwargs[f.name] = v
                per_k[k] = KResult(**kwargs)
            return ConsensusResult(ks=ks, per_k=per_k,
                                   col_names=tuple(str(c)
                                                   for c in z["col_names"]),
                                   # absent in pre-ISSUE-12 files, which
                                   # could only have been exact
                                   quality=(str(z["quality"])
                                            if "quality" in z.files
                                            else "exact"))


def _build_k_result(k: int, out, linkage: str,
                    selection=None, min_restarts: int = 1) -> KResult:
    """One rank's host-side assembly — the SINGLE implementation both
    the sequential loop and the streaming harvest workers
    (``nmfx/harvest.py``) call, so the two paths are bit-identical by
    construction. ``out`` is a host-materialized ``KSweepOutput``;
    ``selection`` injects a precomputed (rho, membership, order) (the
    device rank-selection path), else the host
    hclust/cophenetic/cutree runs here.

    ``min_restarts``: the numeric-quarantine survivor floor
    (``ConsensusConfig.min_restarts``) — enforced HERE, the one funnel
    every consumer's per-rank assembly passes through (sequential,
    streamed, served), so a rank whose surviving restarts fell below it
    raises a typed :class:`nmfx.faults.InsufficientRestarts` on every
    path instead of silently serving a thin consensus."""
    from nmfx.faults import InsufficientRestarts
    from nmfx.solvers.base import StopReason

    stops = np.asarray(out.stop_reasons)
    masked = ((stops == int(StopReason.NUMERIC_FAULT))
              | (stops == int(StopReason.SCREENED)))
    survivors = int((~masked).sum())
    if survivors < min_restarts:
        n_fault = int((stops == int(StopReason.NUMERIC_FAULT)).sum())
        n_screen = int((stops == int(StopReason.SCREENED)).sum())
        raise InsufficientRestarts(
            f"rank k={k}: only {survivors} of {stops.size} restarts "
            "survived the numeric quarantine / screening cut "
            f"(NUMERIC_FAULT on {n_fault}, SCREENED on {n_screen}), "
            f"below the configured floor min_restarts={min_restarts} — "
            "the consensus for this rank is not trustworthy. Inspect "
            "the input conditioning / solver settings (or raise "
            "screen_keep), or lower min_restarts to accept thinner "
            "consensus")
    cons = np.asarray(out.consensus, dtype=np.float64)
    if selection is not None:
        rho, membership, order = selection
        rho = float(rho)
        membership = np.asarray(membership)
        order = np.asarray(order)
    else:
        rho, membership, order = coph.rank_selection(cons, k, linkage)
    rho = float(np.format_float_positional(
        rho, precision=4, fractional=False))  # signif(rho,4) nmf.r:172
    return KResult(
        k=k, consensus=cons, rho=rho,
        dispersion=float(np.mean((2.0 * cons - 1.0) ** 2)),
        membership=membership, order=order,
        iterations=out.iterations,
        dnorms=out.dnorms,
        stop_reasons=out.stop_reasons,
        best_w=out.best_w,
        best_h=out.best_h,
        all_w=out.all_w,
        all_h=out.all_h,
    )


def run_example(outdir: str | None = "./nmfx_out", **kwargs):
    """The reference's ``runExample`` entry (nmf.r:6-14) on equivalent
    synthetic data: a 1000x40 two-group expression matrix (the bundled
    ``20+20x1000.gct`` design), swept at the reference defaults —
    k=2..5, 10 restarts, maxiter 10000, seed 123. Returns the
    ConsensusResult; pass ``outdir=None`` to skip file outputs."""
    from nmfx.datasets import two_group_matrix

    a = two_group_matrix(n_genes=1000, n_per_group=20, seed=123)
    output = None if outdir is None else OutputConfig(directory=outdir)
    defaults = dict(ks=(2, 3, 4, 5), restarts=10, seed=123, output=output)
    defaults.update(kwargs)
    return nmfconsensus(a, **defaults)


def _as_matrix(data) -> tuple:
    from nmfx.sparse import SparseMatrix

    if isinstance(data, str):
        data = read_dataset(data)
    if isinstance(data, Dataset):
        return np.asarray(data.values), list(data.col_names)
    if isinstance(data, SparseMatrix):
        # stays sparse end to end: sweep() streams it through the
        # out-of-core tile pipeline without densifying
        return data, [str(i + 1) for i in range(data.shape[1])]
    arr = np.asarray(data)
    return arr, [str(i + 1) for i in range(arr.shape[1])]


def _resolve_cfgs(algorithm, max_iter, init, solver_cfg, init_cfg):
    """Merge convenience args with config objects; reject silent conflicts."""
    if solver_cfg is not None:
        if algorithm is not None or max_iter is not None:
            raise ValueError(
                "pass either solver_cfg or algorithm/max_iter, not both — "
                "set them on the SolverConfig instead")
        scfg = solver_cfg
    else:
        scfg = SolverConfig(algorithm=algorithm or "mu",
                            max_iter=max_iter or 10000)
    if init_cfg is not None:
        if init is not None:
            raise ValueError("pass either init_cfg or init, not both")
        icfg = init_cfg
    else:
        icfg = InitConfig(method=init or "random")
    return scfg, icfg


def nmf(a, k: int, *, seed: int = 0, algorithm: str | None = None,
        max_iter: int | None = None, init: str | None = None,
        solver_cfg: SolverConfig | None = None,
        init_cfg: InitConfig | None = None,
        w0=None, h0=None) -> SolverResult:
    """One non-negative factorization A ≈ W·H at rank k.

    ``w0``/``h0``: explicit initial factors (both or neither) — warm-start
    from a previous solve or a custom scheme; otherwise initialization
    follows ``init``/``init_cfg`` with the given ``seed``.
    """
    arr, _ = _as_matrix(a)
    if not np.isfinite(arr).all():
        raise ValueError("input matrix contains non-finite values")
    if (arr < 0).any():
        # reference-side validation lives in dead C code (checkmatrices.c:43-81);
        # here it is a real error
        raise ValueError("input matrix must be non-negative")
    scfg, icfg = _resolve_cfgs(algorithm, max_iter, init, solver_cfg, init_cfg)
    import jax.numpy as jnp

    dtype = jnp.dtype(scfg.dtype)
    if (w0 is None) != (h0 is None):
        raise ValueError("pass both w0 and h0, or neither")
    if w0 is None:
        w0, h0 = initialize(jax.random.key(seed), jnp.asarray(arr, dtype),
                            k, icfg, dtype)
    else:
        if init is not None or init_cfg is not None:
            raise ValueError(
                "pass either explicit w0/h0 or an init scheme, not both")
        w0 = np.asarray(w0)
        h0 = np.asarray(h0)
        m, n = arr.shape
        if w0.shape != (m, k) or h0.shape != (k, n):
            raise ValueError(
                f"w0/h0 shapes {w0.shape}/{h0.shape} don't match "
                f"({m}, {k})/({k}, {n})")
        if not (np.isfinite(w0).all() and np.isfinite(h0).all()):
            raise ValueError("initial factors contain non-finite values")
        if (w0 < 0).any() or (h0 < 0).any():
            raise ValueError("initial factors must be non-negative")
    if scfg.screen:
        raise ValueError(
            "screen=True is a sweep-pool concept (it ranks RESTARTS); "
            "a single factorization has no pool to screen")
    if scfg.backend == "sketched":
        # the compressed engine: projections fold off the same seed key
        # the init drew from, so nmf(seed=s) is deterministic end to end
        from nmfx.solvers.sketched import solve_sketched

        return solve_sketched(jnp.asarray(arr, dtype),
                              jnp.asarray(w0, dtype),
                              jnp.asarray(h0, dtype),
                              jax.random.key(seed), scfg)
    return solve(arr, w0, h0, scfg)


def restart_factors(a, k: int, restart: int, *, restarts: int,
                    seed: int = 123, algorithm: str | None = None,
                    max_iter: int | None = None, init: str | None = None,
                    solver_cfg: SolverConfig | None = None,
                    init_cfg: InitConfig | None = None) -> SolverResult:
    """Recompute one sweep restart's full (W, H, iterations) from its key.

    The sweep derives every restart's PRNG key deterministically —
    ``fold_in(key(seed), k)`` split over the restart axis — so any single
    job of a ``nmfconsensus(seed=..., restarts=...)`` run is exactly
    reproducible in isolation, without the sweep having retained its
    factors. This is the bounded-memory counterpart to
    ``keep_factors=True``: the reference keeps every job's ``list(W, H,
    iter)`` on disk in its BatchJobs registry (nmf.r:50) and hands the full
    list to ``reduceGridBy`` (nmf.r:72-98); here retention is opt-in and
    recomputation is the always-available fallback (restarts are
    seconds-long; a re-solve is cheaper than holding every factor of a
    large sweep resident).

    Key-chain note: the sweep may split the restart axis to a padded
    multiple of the device mesh, but ``jax.random.split`` is prefix-stable
    (split(key, n)[:r] == split(key, r') prefixes agree), so restart r's
    key — and therefore its factors — is independent of mesh shape and
    padding. Guarded by tests/test_grid.py.
    """
    if not 0 <= restart < restarts:
        raise ValueError(
            f"restart index {restart} outside [0, {restarts})")
    arr, _ = _as_matrix(a)
    scfg, icfg = _resolve_cfgs(algorithm, max_iter, init, solver_cfg,
                               init_cfg)
    import jax.numpy as jnp

    dtype = jnp.dtype(scfg.dtype)
    key = jax.random.fold_in(jax.random.key(seed), k)
    kk = jax.random.split(key, restarts)[restart]
    w0, h0 = initialize(kk, jnp.asarray(arr, dtype), k, icfg, dtype)
    if scfg.backend == "sketched":
        # the sketched sweep's projections fold off this same canonical
        # restart key, so the recompute reproduces the sweep lane —
        # same draws, same trajectory, equivalent within float
        # tolerance (solo vs vmapped GEMM tilings reorder reductions;
        # the whole-grid/per-k equivalence class). The engine's
        # contract is statistical anyway — bit-exact recompute is an
        # exact-engine property.
        from nmfx.solvers.sketched import solve_sketched

        return solve_sketched(jnp.asarray(arr, dtype), w0, h0, kk, scfg)
    if scfg.screen:
        # a screened sweep's SURVIVOR lanes ran the plain exact solve
        # from these keys; recomputing with the screening fields
        # stripped reproduces them bit-for-bit (and yields the
        # would-have-been exact result for screened-out lanes)
        scfg = dataclasses.replace(scfg, screen=False, screen_keep=None)
    return solve(arr, w0, h0, scfg)


def nmfconsensus(
    data,
    ks: Sequence[int] = (2, 3, 4, 5),
    restarts: int = 10,
    *,
    seed: int = 123,
    algorithm: str | None = None,
    max_iter: int | None = None,
    init: str | None = None,
    label_rule: str = "argmax",
    linkage: str = "average",
    solver_cfg: SolverConfig | None = None,
    init_cfg: InitConfig | None = None,
    mesh=None,
    use_mesh: bool = True,
    rank_selection: str = "host",
    harvest: str = "streamed",
    keep_factors: bool = False,
    grid_exec: str = "auto",
    grid_slots: int = 48,
    grid_tail_slots: "int | None | str | tuple" = "auto",
    min_restarts: int = 1,
    output: OutputConfig | None = None,
    checkpoint_dir: str | None = None,
    checkpoint=None,
    profiler=None,
    exec_cache=None,
    result_cache=None,
) -> ConsensusResult:
    """Full consensus-NMF rank sweep (the reference's ``runExample`` pipeline,
    nmf.r:6-14, minus the hardcoded paths).

    Runs `restarts` factorizations per rank in `ks`, reduces each rank's runs
    to a consensus matrix on-device, selects ranks by cophenetic correlation,
    and (optionally) writes GCT/plot outputs.

    ``checkpoint_dir``: persist each finished rank there and resume an
    interrupted sweep from the ranks already on disk (guarded by a fingerprint
    of the data + configs, so a registry never serves a different run).

    ``checkpoint`` (an ``nmfx.CheckpointConfig``, or a directory path):
    the DURABLE sweep ledger (``nmfx/checkpoint.py``, docs/serving.md
    "Durability model") — finer-grained than ``checkpoint_dir``:
    per-(rank, restart-chunk) completion records with atomic writes and
    torn-record tolerance, so a preempted/killed process loses at most
    the chunk in flight and a re-run recomputes ONLY the missing
    chunks, bit-identical to an uninterrupted checkpointed run. A
    manifest mismatch (different data/config/env/plan) triggers a clean
    cold start, never a wrong resume. Raises on combination with
    ``checkpoint_dir``, ``keep_factors``, an explicit ``mesh``, or
    ``exec_cache`` (the chunk executor owns its execution plan; see
    ``nmfx.distributed`` for elastic multi-device durable sweeps).

    ``rank_selection``: "host" (default) runs hclust/cophenetic/cutree in
    host numpy or native C++ (``nmfx/cophenetic.py``); "device" keeps the
    clustering itself on the accelerator (``nmfx/ops/hclust_jax.py``) —
    the consensus matrix still comes to host once, for the returned
    ``KResult``, overlapped with the device clustering.

    ``harvest``: how per-rank results cross to host under host rank
    selection — "streamed" (default) pipelines each rank's
    device→host copy AND its hclust/cophenetic/cutree through worker
    threads the moment that rank's device output exists, so the host
    tail overlaps the remaining ranks' device solve
    (``nmfx/harvest.py``; results are bit-identical to the sequential
    path — same transfers, same host math, pinned by
    tests/test_harvest.py); "sequential" restores the strictly
    phase-ordered path (one end-of-sweep batched transfer, then rank
    selection) — the reference's shape (nmf.r:146-253) and the
    measurement baseline the streamed path is audited against.
    ``rank_selection="device"`` implies the sequential assembly (the
    clustering already overlaps the transfer on-device).

    ``keep_factors``: retain every restart's (W, H) in each ``KResult``
    (``all_w``/``all_h``) — the reference registry's per-job retention
    (nmf.r:50). Off by default; any single restart is also recomputable
    exactly via :func:`restart_factors`.

    ``grid_exec``: how the (k × restart) grid executes —
    ``ConsensusConfig.grid_exec``. The default "auto" solves ALL ranks in
    one dense-batched compile when eligible (the reference's whole-grid
    job-array concurrency, nmf.r:64-68); "per_k" forces the sequential
    per-rank path; "grid" demands the whole-grid path (error when the
    config can't run it). ``grid_slots`` is the scheduler's per-device
    slot-pool width (``ConsensusConfig.grid_slots``); ``grid_tail_slots``
    its straggler-tail cascade — an int or decreasing tuple of pool
    widths (``ConsensusConfig.grid_tail_slots``; "auto"/0-to-disable;
    per-job stop decisions identical in every case).

    ``min_restarts``: floor on the restarts that must survive the
    numeric quarantine (``SolverConfig.nonfinite_guard``) at each rank
    — below it the rank raises a typed
    ``nmfx.faults.InsufficientRestarts`` instead of serving a consensus
    averaged over too few runs (``ConsensusConfig.min_restarts``).

    ``exec_cache``: an ``nmfx.exec_cache.ExecCache`` serving this and
    future calls — repeat requests whose dataset shapes land in an
    already-compiled bucket skip the sweep's trace+compile entirely
    (results are shape-exact: the bucket only pads the execution). With
    ``ExecCacheConfig(cache_dir=...)`` the compiled executables persist
    on disk, so a FRESH process deserializes instead of recompiling
    (cold start becomes deserialize-and-dispatch), and
    ``ExecCache.warm(shapes, ..., background=True)`` pre-compiles
    buckets off-thread — a request arriving mid-warm waits on the
    in-flight compile rather than duplicating it. Ignored for
    non-cacheable configurations and checkpointed runs; see
    ``docs/serving.md``.

    ``result_cache``: an ``nmfx.result_cache.ResultCache`` (or a cache
    directory path) of FINISHED ``ConsensusResult``s, keyed by input
    content + every result-affecting config field + quality tag
    (docs/serving.md "Request economics"). A warm hit returns in O(1)
    with zero solve dispatches; a miss solves normally and populates
    the cache on the way out. ``keep_factors=True`` requests solve
    through uncached (the full factor stacks would blow the byte
    budget; ``restart_factors`` recomputes any restart exactly).
    """
    if rank_selection not in ("host", "device"):
        raise ValueError("rank_selection must be 'host' or 'device', got "
                         f"{rank_selection!r}")
    if harvest not in ("streamed", "sequential"):
        raise ValueError("harvest must be 'streamed' or 'sequential', got "
                         f"{harvest!r}")
    from nmfx.sparse import SparseMatrix

    arr, col_names = _as_matrix(data)
    # sparse inputs validate their stored nonzeros (the implicit zeros
    # are finite and non-negative by construction)
    vals = arr.data if isinstance(arr, SparseMatrix) else arr
    if not np.isfinite(vals).all():
        raise ValueError("input matrix contains non-finite values")
    if (vals < 0).any():
        raise ValueError("input matrix must be non-negative")
    ks = tuple(ks)
    if not ks:
        raise ValueError("ks must be non-empty")
    n_samples = arr.shape[1]
    if max(ks) > n_samples:
        # cutree cannot yield more clusters than samples; fail clearly here
        # instead of deep inside the clustering (reference guards only k>=2,
        # nmf.r:107-108)
        raise ValueError(
            f"k={max(ks)} exceeds the number of samples ({n_samples})")
    ccfg = ConsensusConfig(ks=tuple(ks), restarts=restarts, seed=seed,
                           label_rule=label_rule, linkage=linkage,
                           keep_factors=keep_factors, grid_exec=grid_exec,
                           grid_slots=grid_slots,
                           grid_tail_slots=grid_tail_slots,
                           min_restarts=min_restarts)
    scfg, icfg = _resolve_cfgs(algorithm, max_iter, init, solver_cfg, init_cfg)
    rcache = rkey = None
    if result_cache is not None:
        from nmfx.result_cache import (ResultCache, cacheable,
                                       key_for_array, request_quality)

        if cacheable(ccfg):
            rcache = (result_cache
                      if isinstance(result_cache, ResultCache)
                      else ResultCache(cache_dir=os.fspath(result_cache),
                                       layer="api"))
            rkey = key_for_array(arr, scfg, ccfg, icfg,
                                 request_quality(scfg))
            cached = rcache.lookup(rkey)
            if cached is not None:
                if output is not None:
                    save_results(cached, output)
                return cached
    if checkpoint is not None:
        from nmfx.config import CheckpointConfig

        if isinstance(checkpoint, (str, os.PathLike)):
            checkpoint = CheckpointConfig(directory=os.fspath(checkpoint))
        if checkpoint_dir is not None:
            raise ValueError(
                "pass either checkpoint (the durable chunked ledger) or "
                "checkpoint_dir (the legacy per-rank registry), not both")
        if mesh is not None:
            raise ValueError(
                "checkpoint does not compose with an explicit mesh: the "
                "chunk executor owns its per-(k, restart-chunk) "
                "execution plan on the default device (use "
                "nmfx.distributed's elastic shard runner for "
                "multi-device durable sweeps)")
        if exec_cache is not None:
            # erroring beats silently discarding a cache the caller may
            # have paid warmup compiles into (the CLI guard's rationale)
            raise ValueError(
                "checkpoint does not compose with exec_cache: "
                "checkpointed sweeps dispatch per (rank, restart-chunk) "
                "through the durable ledger, which bypasses the "
                "bucketed executable cache")
        use_mesh = False  # the chunk plan is the parallelism unit
    if mesh is None and use_mesh:
        mesh = default_mesh()

    registry = None
    if checkpoint_dir is not None:
        from nmfx.registry import SweepRegistry

        if isinstance(arr, SparseMatrix) or scfg.tile_rows is not None:
            raise ValueError(
                "checkpoint_dir (the legacy per-rank registry) does not "
                "support sparse/tiled inputs; pass checkpoint= (the "
                "durable chunked ledger) for out-of-core resume")
        registry = SweepRegistry.open(checkpoint_dir, arr, scfg, icfg,
                                      restarts, seed, label_rule,
                                      keep_factors, mesh)
    if profiler is None:
        from nmfx.profiling import NullProfiler

        profiler = NullProfiler()

    streamed = harvest == "streamed" and rank_selection == "host"
    if streamed:
        # streaming harvest: the sweep layer hands each rank's device
        # output to the pipeline the moment it EXISTS (async dispatch —
        # arrays are futures), so its device→host copy and its host
        # rank selection run in worker threads while later ranks still
        # solve on device. results() joins; per-rank host math is the
        # shared _build_k_result, so this path is bit-identical to the
        # sequential one below.
        from nmfx.harvest import HarvestPipeline

        pipeline = HarvestPipeline(linkage=ccfg.linkage, profiler=profiler,
                                   min_restarts=ccfg.min_restarts)
        try:
            sweep(arr, ccfg, scfg, icfg, mesh, registry=registry,
                  profiler=profiler, exec_cache=exec_cache,
                  on_rank=pipeline.submit, checkpoint=checkpoint)
            per_k = pipeline.results()
        finally:
            pipeline.close()
        # results() yields submission order (checkpoint-loaded ranks
        # stream first); normalize to ks order like the sequential path
        per_k = {k: per_k[k] for k in ccfg.ks}
    else:
        raw = sweep(arr, ccfg, scfg, icfg, mesh, registry=registry,
                    profiler=profiler, exec_cache=exec_cache,
                    checkpoint=checkpoint)

        # Device-path rank selection is dispatched for every k BEFORE
        # anything is pulled to host, so the clustering overlaps the
        # transfer below.
        dev_sel = None
        if rank_selection == "device":
            import jax.numpy as jnp

            from nmfx.ops.hclust_jax import rank_selection_jax

            # its own phase so per-k trace/compile cost (synchronous,
            # host-side) isn't silently charged to device_to_host or to
            # no phase at all
            with profiler.phase("rank_selection_dispatch"):
                dev_sel = {k: rank_selection_jax(
                    jnp.asarray(out.consensus), k, ccfg.linkage)
                    for k, out in raw.items()}
        # ONE batched device→host transfer for every rank's outputs
        # (labels are never read here — keep them out of the transfer):
        # a per-field np.asarray pays one round trip per array,
        # ~50–150 ms each through a remote-attached chip — 0.4–1.4 s of
        # pure latency measured on the 9-rank north star (same
        # reasoning as registry.save)
        with profiler.phase("device_to_host"):
            host, dev_sel = jax.device_get(
                ({k: out._replace(labels=None) for k, out in raw.items()},
                 dev_sel))

        per_k = {}
        for k, out in host.items():
            with profiler.phase("rank_selection"):
                per_k[k] = _build_k_result(
                    k, out, ccfg.linkage,
                    selection=None if dev_sel is None else dev_sel[k],
                    min_restarts=ccfg.min_restarts)

    result = ConsensusResult(ks=ccfg.ks, per_k=per_k,
                             col_names=tuple(col_names),
                             # an approximate engine's result is typed,
                             # never silently exact-shaped (ISSUE 12)
                             quality=("sketched"
                                      if scfg.backend == "sketched"
                                      else "exact"))
    if rcache is not None and rkey is not None:
        try:
            rcache.put(rkey, result, ccfg=ccfg)
        except Exception:  # nmfx: ignore[NMFX006] -- cache trouble
            # must never fail a solved request
            pass
    if output is not None:
        with profiler.phase("write_outputs"):
            save_results(result, output)
    return result


def save_results(result: ConsensusResult, out: OutputConfig) -> list[str]:
    """Write the reference's output set (nmf.r:195-252) under a configurable
    directory — per-k ordered membership GCTs, the all-k membership matrix,
    `cophenetic.txt`, per-k consensus-matrix GCTs, optional plots — plus
    per-k metagene GCTs and the `rank_metrics.txt` companion table."""
    os.makedirs(out.directory, exist_ok=True)
    doc = out.doc_string
    prefix = os.path.join(out.directory, f"{doc}." if doc else "")
    written: list[str] = []
    names = np.asarray(result.col_names)

    if out.write_gcts:
        for k in result.ks:
            r = result.per_k[k]
            ordered_names = names[r.order]
            path = f"{prefix}consensus.k.{k}.gct"
            write_gct(r.membership[r.order].reshape(-1, 1), path,
                      row_names=list(ordered_names), col_names=["membership"])
            written.append(path)
            path = f"{prefix}consensus.matrix.k.{k}.gct"
            write_gct(r.consensus, path, row_names=list(names),
                      col_names=list(names))
            written.append(path)
            # metagenes of the lowest-residual restart (the H the reference
            # returns per job, nmf.r:50, but never exports) — k × samples
            path = f"{prefix}metagenes.k.{k}.gct"
            write_gct(r.best_h, path,
                      row_names=[f"metagene.{i + 1}" for i in range(k)],
                      col_names=list(names))
            written.append(path)
        all_membership = np.stack(
            [result.per_k[k].membership for k in result.ks], axis=1)
        path = f"{prefix}membership.gct"
        write_gct(all_membership, path, row_names=list(names),
                  col_names=[f"k={k}" for k in result.ks])
        written.append(path)

    path = f"{prefix}cophenetic.txt"
    with open(path, "wt") as f:
        for k in result.ks:
            f.write(f"{k}\t{result.per_k[k].rho}\n")
    written.append(path)

    # richer companion table (cophenetic.txt keeps the reference's exact
    # two-column format, nmf.r:251-252)
    path = f"{prefix}rank_metrics.txt"
    with open(path, "wt") as f:
        f.write("k\trho\tdispersion\tmean_iters\tmean_dnorm\n")
        for k in result.ks:
            r = result.per_k[k]
            f.write(f"{k}\t{r.rho}\t{r.dispersion:.6f}"
                    f"\t{r.iterations.mean():.1f}\t{r.dnorms.mean():.6g}\n")
    written.append(path)

    if out.write_plots:
        try:
            from nmfx import plots
        except ImportError:  # matplotlib absent: GCT outputs still complete
            return written
        written += plots.save_all(result, prefix)
    return written
