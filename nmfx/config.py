"""Typed configuration for the whole framework.

One config object per concern, replacing the reference's scatter of compile-time
macros (``ZERO_THRESHOLD``/``DEBUG_LEVEL``, reference ``libnmf/include/common.h:15-25``),
the ``options_t`` struct defaults (reference ``libnmf/setdefaultopts.c:38-52``), and
R-level function arguments (reference ``nmf.r:106``).
"""

from __future__ import annotations

import dataclasses
from typing import ClassVar, Sequence

ALGORITHMS = ("mu", "als", "neals", "pg", "alspg", "kl", "snmf", "hals")
#: algorithms with a dense-batched block (nmfx.ops.grid_mu.BLOCKS) that
#: backend="packed" can route through the batched/scheduled machinery —
#: the single list shared by SolverConfig validation, the CLI/bench
#: guards, and (as the keys of sweep._GRID_EXEC_BACKENDS) the routing
#: table itself
PACKED_ALGORITHMS = ("mu", "hals", "neals", "als", "snmf", "kl")
#: algorithms with a Gram-accumulation formulation the out-of-core tile
#: pipeline (nmfx/tiles.py) can stream: per-tile contributions reduce
#: into k×k / k×n Gram terms, so A never needs to exist on device at
#: once (MPI-FAUN, arxiv 1609.09154). Shared by SolverConfig validation,
#: the sweep routing, and the costmodel universe (NMFX009).
TILED_ALGORITHMS = ("mu", "hals")
INIT_METHODS = ("random", "nndsvd")
LINKAGE_METHODS = ("average", "complete", "single")

#: canonical package version (lives here so light importers — the CLI's
#: --help/--version path — don't pull the full jax-importing package)
VERSION = "0.1.0"


@dataclasses.dataclass(frozen=True)
class ExperimentalConfig:
    """Measured-but-not-default opt-ins, grouped so the primary surfaces
    (``SolverConfig``, ``mu_sched``) stay small.

    Every knob here was BUILT AND MEASURED on real hardware and lost to
    the shipping configuration for a documented reason (see
    benchmarks/RESULTS.md round 5 and the per-field notes below), or is
    a numerics experiment whose hardware verdict is still pending. They
    are kept so the measurements are reproducible and so workloads
    unlike the north star can opt in.

    Keep/remove policy: a knob stays while its rejection rationale is
    workload-shaped (it may win elsewhere: ``ragged`` for extreme
    padding mixes, ``evict_batch`` for heavy evict traffic) or while a
    round's measurement plan names it; a knob whose rejection is
    *arithmetic* (cannot win anywhere) is removed outright — rejections
    by construction are recorded in RESULTS.md, not kept as code. Each
    knob must keep a regression test pinning its semantics for as long
    as it ships.
    """

    #: ragged class-blocked slot pool (pallas block-kernel route only):
    #: eliminates ALL packed-column padding. Measured NET SLOWER at the
    #: north star (tail trips triple, multi-class bookkeeping ~1.5x per
    #: trip — RESULTS.md round 5); kept for mixes with extreme padding
    #: waste (k_max >> typical k)
    ragged: bool = False
    #: per-class expected-iteration overrides for the ragged layout's
    #: greedy-minimax slot allocation, as a hashable tuple of
    #: (k, expected_iters) pairs — derive from a previous run's
    #: ``SchedMUResult.iterations`` via
    #: ``nmfx.ops.sched_mu.ragged_estimates_from_iterations``. None uses
    #: the built-in north-star model (``_ragged_iters_est``), which WARNs
    #: when the job mix departs its calibrated profile. Only schedule
    #: quality depends on these; results never do.
    ragged_iters_est: "tuple[tuple[int, float], ...] | None" = None
    #: harvest hysteresis: batch the heavy half of slot eviction until
    #: this many slots are pending. Recorded per-job results are exactly
    #: invariant; measured no clear win at the north star (round 5)
    evict_batch: int = 1
    #: slot-pool factor storage (pallas block-kernel route only):
    #: None = the solve dtype; "bfloat16" = both factors bf16 (round-5
    #: experiment, REJECTED as a default: quantized labels hit a bf16
    #: fixed point and the class-stability counter coasts to the floor);
    #: "bfloat16_w" = W stored bf16 with H kept at the solve dtype (the
    #: round-6 variant: the label-bearing factor never quantizes, so the
    #: round-5 freeze cannot start from the labels, while W — 10 of the
    #: ~11 MB of per-launch factor round-trip at the north star — still
    #: moves at half the bytes). An f32-master/error-feedback variant
    #: was analyzed and rejected by arithmetic: a residual accumulator
    #: must either live in bf16 storage (where sub-ulp residuals round
    #: away — a no-op) or round-trip alongside the bf16 factors (f32
    #: traffic parity — no win); see RESULTS.md round 6.
    factor_dtype: "str | None" = None
    #: donate the block kernel's input buffers as outputs. Bit-exact at
    #: every bisect level (the explicit step-0 DMA is the data path) but
    #: measured ~8% SLOWER than the while-carry copies it targets
    #: (round 5, probe_alias_io.py)
    alias_io: bool = False
    #: kl + backend="packed" only — stream A as one-time-truncated bf16
    #: through the slot scheduler, halving A's HBM reread traffic like
    #: the GEMM families get by default. Measured-REJECTED (round 5,
    #: probe_kl_ab.py): slower than the f32 quotient AND +7-11%
    #: iterations at k>=5 — kl consumes A in an ELEMENTWISE division
    #: where bf16 truncation is a real ~0.4% input perturbation, and the
    #: quotient upcasts to f32 before dividing anyway (kl is
    #: quotient-FLOP-bound, not A-bandwidth-bound)
    kl_bf16_quotient: bool = False
    #: on-first-run pallas block-shape autotuner (round 7,
    #: ``nmfx.autotune``): "on" times a small (block_m, check_block,
    #: fused-vs-phased) candidate grid on the real device at this
    #: (m, n, k, slots) bucket on first contact and persists the winner
    #: next to the exec cache (keyed by bucket + device kind + jax/PJRT
    #: versions — a second process pays zero search); "off" (default)
    #: never searches and never reads the store. Explicit ``block_m``/
    #: ``fused_updates``/``check_block`` settings always win over a
    #: tuned entry — the tuner only fills what was left on "auto".
    autotune: str = "off"
    #: pallas block-kernel tile rows override (None = the built-in
    #: ~512-row 16-aligned geometry, ``sched_mu._pallas_block_geometry``).
    #: Must be a positive multiple of 16; set by hand or by the
    #: autotuner. Changes kernel numerics only through Mosaic tile-order
    #: accumulation (the gate-checkable float-tolerance class)
    block_m: "int | None" = None
    #: mu block-kernel schedule: "auto" (default — resolves to the
    #: phased two-pass kernel, byte-identical numerics to round 6),
    #: "phased", or "fused" (the round-7 PL-NMF join-the-updates kernel:
    #: A read once per iteration instead of twice, bit-exact vs phased —
    #: tests/test_fused_kernel.py pins the equivalence)
    fused_updates: str = "auto"

    def __post_init__(self):
        if self.factor_dtype not in (None, "bfloat16", "bfloat16_w"):
            raise ValueError(
                "experimental.factor_dtype must be None, 'bfloat16' or "
                f"'bfloat16_w', got {self.factor_dtype!r}")
        if self.evict_batch < 1:
            raise ValueError("experimental.evict_batch must be >= 1")
        if self.autotune not in ("off", "on"):
            raise ValueError(
                "experimental.autotune must be 'off' or 'on', got "
                f"{self.autotune!r}")
        if self.block_m is not None and (
                self.block_m <= 0 or self.block_m % 16):
            raise ValueError(
                "experimental.block_m must be a positive multiple of 16 "
                f"(the TPU sublane tiling), got {self.block_m!r}")
        if self.fused_updates not in ("auto", "phased", "fused"):
            raise ValueError(
                "experimental.fused_updates must be 'auto', 'phased' or "
                f"'fused', got {self.fused_updates!r}")
        if self.ragged_iters_est is not None:
            est = tuple((int(k), float(v))
                        for k, v in self.ragged_iters_est)
            if any(v <= 0 for _, v in est):
                raise ValueError(
                    "experimental.ragged_iters_est iteration estimates "
                    "must be positive")
            object.__setattr__(self, "ragged_iters_est", est)


@dataclasses.dataclass(frozen=True)
class SketchConfig:
    """Random-projection compression of the solver updates
    (``nmfx/solvers/sketched.py`` — the "Faster-than-fast NMF" engine,
    arxiv 1812.04315).

    The sketched engine keeps both factors at FULL size and compresses
    only the update *computations*: per restart, two random projections
    L (r_l × m) and R (n × r_c) are drawn from the canonical
    per-(seed, k, restart) key chain, and every Gram-family term of the
    MU/HALS updates contracts against the sketched matrices L·A / A·R
    instead of A — cutting the per-iteration FLOPs from ~4mnk to
    ~4rk(m+n) (the four m/n-sized sketched GEMMs; see
    ``nmfx.solvers.sketched.sketched_model_flops``). Labels and the final residual are computed from the
    full (uncompressed) factors, so the consensus layer consumes exact
    labels of approximate factorizations — which is why the accuracy
    contract is STATISTICAL at the consensus level (membership
    agreement / ARI vs the exact engine, ``nmfx/agreement.py``), never
    bit-exact. The same machinery powers restart screening
    (``SolverConfig.screen``) and quality-elastic serving
    (``ServeConfig.quality_elastic``).
    """

    #: sketch dimension r (both projections): "auto" resolves per rank
    #: to ``max(4k + 8, 40)`` clamped to the matrix dims — the usual
    #: randomized-sketching oversampling regime (r ≪ min(m, n), r > k)
    #: with a measured absolute floor (see
    #: ``nmfx.solvers.sketched.resolve_dim``); an int pins it (clamped
    #: to the matrix dims at build time)
    dim: "int | str" = "auto"
    #: Nesterov momentum on the factor iterates (the acceleration half
    #: of arxiv 1812.04315): updates evaluate at the extrapolated point
    #: ``X + beta_t (X - X_prev)`` clamped to >= 0, with the standard
    #: t-sequence beta. Off = plain compressed MU/HALS.
    momentum: bool = True
    #: iteration budget of the cheap screening pass
    #: (``SolverConfig.screen``): each restart runs this many sketched
    #: iterations before the compressed objective ranks the pool
    screen_iters: int = 40
    #: final UNCOMPRESSED polish: after the compressed loop stops, run
    #: this many exact update iterations (the full mu/hals rule against
    #: A itself) before the labels/residual are read — snaps the
    #: sketch-noise-rattled factors to an exact-update neighborhood, so
    #: long compressed budgets cannot wander the final labels (measured:
    #: without it, consensus ARI vs exact dropped to ~0.34 on harsh
    #: seeds at max_iter=3000; with 3 polish steps it holds >= 0.9).
    #: O(polish · mnk) per restart — amortized over the hundreds of
    #: compressed iterations it replaces
    polish_iters: int = 3

    def __post_init__(self):
        d = self.dim
        if not (d == "auto" or (isinstance(d, int)
                                and not isinstance(d, bool) and d >= 1)):
            raise ValueError(
                f"sketch.dim must be 'auto' or an int >= 1, got {d!r}")
        if self.screen_iters < 1:
            raise ValueError("sketch.screen_iters must be >= 1")
        if self.polish_iters < 0:
            raise ValueError("sketch.polish_iters must be >= 0")


#: algorithms with a compressed (sketched) update formulation —
#: backend="sketched" and SolverConfig.screen are limited to these
#: (their updates are Gram-family GEMM chains the projections contract)
SKETCHED_ALGORITHMS = ("mu", "hals")


@dataclasses.dataclass(frozen=True)
class SolverConfig:
    """Per-factorization solver settings.

    Defaults mirror the reference's observed defaults: ``TolX = TolFun = 1e-4``
    and projected-gradient ``tol = 2e-16`` (reference ``libnmf/setdefaultopts.c:47-51``),
    ``maxiter = 10000`` (reference ``nmf.r:13``), division guard ``1e-9``
    (reference ``libnmf/nmf_mu.c:56``), class-stability stop after 200 stable
    checks performed every 2nd iteration (reference ``libnmf/nmf_mu.c:253-282``).

    Intentional divergences from observed reference behavior (SURVEY.md §3.2):

    * Q1 — the stability check reads per-column argmax of H with correct
      indexing (the reference indexes out of bounds for n > k).
    * Q2 — ``tol_x``/``tol_fun`` are live: the reference passes them to C where
      the checks are commented out; here ``use_tol_checks`` enables the
      *documented* semantics (delta < TolX, or relative residual decrease
      below TolFun). Note the reference's non-mu solvers compare
      ``dnorm <= TolFun * dnorm0`` *after* assigning ``dnorm0 = dnorm``
      (reference ``libnmf/nmf_als.c:330-352``) — a self-comparison that can
      never fire for TolFun < 1; we test against the previous iteration's
      residual instead.
    """

    #: AUTHORITATIVE declaration of the fields that are EXECUTION
    #: STRATEGY only — they change how the solve is scheduled or
    #: batched, never the numbers it produces — and are therefore the
    #: ONLY fields the registry fingerprint may exclude
    #: (``registry.FINGERPRINT_SOLVER_EXCLUDED``). The static analyzer
    #: (``nmfx.analysis`` rule NMFX001) cross-references the two lists
    #: and errors on any fingerprint exclusion not declared here, so
    #: adding a numerics-affecting field while forgetting the
    #: fingerprint fails lint instead of silently resuming stale
    #: checkpoints. A new field earns a place here only with a
    #: bit-identity argument on record (restart_chunk: prefix-stable
    #: PRNG keys make chunked and unchunked sweeps bit-identical —
    #: tests/test_solvers.py).
    NON_NUMERICS_FIELDS: ClassVar[tuple] = ("restart_chunk",)

    algorithm: str = "mu"
    max_iter: int = 10000
    tol_x: float = 1e-4
    tol_fun: float = 1e-4
    #: relative projected-gradient tolerance for pg/alspg. The reference's
    #: (dead) driver default is ``opts->tol = 2E-16`` (libnmf/setdefaultopts.c:51),
    #: which disables the stop in practice; we default to Lin (2007)'s usual
    #: 1e-4 so pg/alspg terminate — set 2e-16 for reference-default parity.
    tol_pg: float = 1e-4
    #: check convergence every `check_every` iterations (reference: even iters)
    check_every: int = 2
    #: how many ``check_every``-iteration check blocks one scheduler trip
    #: (or batched-solver loop body) executes back-to-back before the
    #: per-trip machinery — while-carry copies, the evict/reload
    #: ``lax.cond``, host-side bookkeeping — runs once for all of them.
    #: The CHECK CADENCE never changes: convergence is still evaluated
    #: at every ``check_every`` boundary (the pallas block kernel exports
    #: per-boundary label snapshots and TolX stats from its VMEM-resident
    #: factors; the XLA engines interleave the checks between sub-blocks
    #: exactly), so stop decisions are preserved — on the XLA engines
    #: exactly, on the pallas engine up to the gate-checkable slot-drift
    #: class (a job that stops at an interior boundary keeps iterating to
    #: the end of its in-flight launch, so its recorded factors carry up
    #: to ``(check_block-1)*check_every`` post-stop iterations — the same
    #: benign class as slot-count drift; iteration counts and stop
    #: reasons are exact). "auto" resolves to 4 on the pallas
    #: block-kernel slot scheduler (where the round-5 trace put ~47 us of
    #: per-trip non-kernel overhead against a 136 us kernel, and the
    #: longer VMEM residency also amortizes the W round-trip) and to 1
    #: everywhere else. See docs/design.md "Check cadence".
    check_block: "int | str" = "auto"
    #: consecutive stable class checks before stopping (mu only)
    stable_checks: int = 200
    #: enable class-stability early stop (mu; the only live stop in the reference)
    use_class_stop: bool = True
    #: class-stability noise tolerance, as a fraction of the sample count: a
    #: check counts as "stable" when at most ``floor(class_flip_tol * n)``
    #: sample labels differ from a held reference labeling (the snapshot
    #: updates only when the tolerance is exceeded, so slow genuine drift
    #: accumulates against a fixed reference and still resets the counter —
    #: only bounded oscillation around one labeling passes). 0.0 reproduces
    #: the reference's exact-match semantics (nmf_mu.c:253-282) bit-for-bit:
    #: with zero tolerance the snapshot always equals the previous check's
    #: labels. The nonzero default exists because low-precision (bfloat16)
    #: matmul noise perpetually flips a few boundary-sample labels at larger
    #: k, which keeps the exact-match counter at zero and burns every restart
    #: to max_iter — measured at k=10 on 5000x500: ~0.46 flips/check forever,
    #: so only 6% of restarts ever stopped. floor() keeps small fixtures
    #: (n < 1/class_flip_tol) on the exact reference rule automatically.
    #: Default 0.02 measured on the north-star sweep (k=2..10 x 50 restarts,
    #: 5000x500): every restart stops by ~3000 iterations (vs 45% burning to
    #: max_iter=10000 strict), cophenetic rho per k within 0.003 of the
    #: strict rule and identical rank selection.
    class_flip_tol: float = 0.02
    #: enable the documented TolX/TolFun stops (dead code in reference nmf_mu)
    use_tol_checks: bool = True
    #: values below this are clamped to zero after updates (reference
    #: ZERO_THRESHOLD, common.h:15; effective value 0.0 in the shipped build)
    zero_threshold: float = 0.0
    #: additive guard on denominators (reference DIV_BY_ZERO_AVOIDANCE)
    div_eps: float = 1e-9
    #: max inner line-search steps for pg/alspg (reference pg_subprob_h.c:113)
    ls_max_steps: int = 20
    #: line-search step shrink factor (reference factor_b = 0.1)
    ls_beta: float = 0.1
    #: sufficient-decrease constant (reference 0.99 / 0.01 tests)
    ls_sigma: float = 0.01
    #: max iterations for pg subproblems inside alspg (reference nmf_alspg.c:218)
    sub_max_iter: int = 1000
    #: computation dtype: "float32" (TPU default) or "float64" (parity testing
    #: vs the reference's f64 BLAS; requires jax_enable_x64)
    dtype: str = "float32"
    #: TPU matmul precision for the solver's dot ops: "default", "bfloat16"
    #: (fastest, 1-pass MXU; measured ~20% faster with an identical
    #: convergence path on the north-star config), or "highest" (3-pass f32;
    #: ~2.6x slower per iteration but stabilizes class labels in ~3x fewer
    #: iterations — matmul noise resets the stability counter)
    matmul_precision: str = "default"
    #: restart-batch execution strategy for the sweep layer:
    #: "auto" picks the restart-packed GEMM formulation (nmfx.ops.packed_mu)
    #: where it exists (mu), else the vmapped generic driver; "packed" forces
    #: it (error for other algorithms); "pallas" runs the packed iteration
    #: through the fused Pallas TPU kernels (nmfx.ops.pallas_mu); "vmap"
    #: forces the generic driver; "sketched" runs the random-projection
    #: compressed engine (nmfx/solvers/sketched.py, SKETCHED_ALGORITHMS
    #: only — see ``SketchConfig`` and the STATISTICAL accuracy contract
    #: documented there). Measured ~3.5x faster per iteration at
    #: k=10 on the north-star config (packed vs vmap).
    #: Engine-parity note for kl + backend="packed" (the whole-grid
    #: opt-in): at high k relative to the data's structure (k=5/6 on the
    #: 4-group north-star benchmark matrix) the packed-grid engine's
    #: consensus drifts from the vmapped default by up to
    #: max|dC|*R ~ 5 restart-equivalents on a handful of boundary
    #: samples (round 5 measured max|dC| <= 0.25 at R=20, rho identical,
    #: iteration ratios 0.95-0.97) — surplus-cluster near-ties split
    #: differently between the engines' reduction orders, the same
    #: over-clustering drift class the hardware gate bounds;
    #: tests/test_kl_drift.py pins the band. At k <= 4 the engines agree
    #: exactly.
    backend: str = "auto"
    #: random-projection compression knobs for backend="sketched" and
    #: the screening pass (``screen``); inert on the exact engines
    sketch: SketchConfig = SketchConfig()
    #: restart screening (ISSUE 12): run a cheap sketched pass
    #: (``sketch.screen_iters`` compressed iterations) over the FULL
    #: restart pool, rank restarts by compressed objective, and spend
    #: exact iterations only on the top-``screen_keep`` survivors.
    #: Screened-out lanes are masked from the consensus exactly like
    #: pad/quarantined lanes (``StopReason.SCREENED``; the
    #: ``ConsensusConfig.min_restarts`` floor counts them as
    #: non-survivors), and survivor-lane results are bit-identical to
    #: solo exact runs of those lanes (the exact phase runs the vmapped
    #: generic driver — lane-independent batched GEMMs; pinned by
    #: tests/test_screening.py). Requires an algorithm in
    #: ``SKETCHED_ALGORITHMS`` and backend "auto"/"vmap".
    screen: bool = False
    #: survivors of the screening pass per rank (required with
    #: ``screen=True``; must be <= the sweep's restart count — checked
    #: where the restart count is known)
    screen_keep: "int | None" = None
    #: measured-rejected / still-experimental opt-ins, grouped behind one
    #: documented surface (see ExperimentalConfig for the keep/remove
    #: policy): the ragged pool, evict hysteresis, slot-pool factor
    #: dtypes, kernel buffer donation, and the kl bf16 quotient
    experimental: ExperimentalConfig = ExperimentalConfig()
    #: snmf only — Kim & Park L1 penalty on H's columns (larger = sparser)
    sparsity_beta: float = 0.01
    #: snmf only — ridge on W; None = max(A)^2 (the Kim & Park default)
    ridge_eta: float | None = None
    #: in-kernel numeric quarantine (ISSUE 7): at every convergence
    #: check, a lane whose factors contain a non-finite value stops with
    #: ``StopReason.NUMERIC_FAULT`` and is masked out of the
    #: consensus/labels/best-restart reductions exactly like a pad lane
    #: — one diverged restart can no longer poison a rank's consensus
    #: matrix (the sweep layer fails the rank loudly, typed
    #: ``InsufficientRestarts``, only when survivors drop below
    #: ``ConsensusConfig.min_restarts``). On the batched dense engines
    #: the guard costs one isfinite reduction per lane per check; the
    #: packed-column mu engine additionally screens every iteration so
    #: a non-finite lane is frozen before its NaN can cross the shared
    #: Grams to its batch-mates. Fault-free runs are bit-identical with
    #: the guard on or off; disabling it restores the pre-quarantine
    #: behavior (a non-finite lane burns to max_iter and poisons the
    #: consensus mean).
    nonfinite_guard: bool = True
    #: cap on restarts solved concurrently in the vmapped driver (chunks run
    #: sequentially). Bounds peak memory for solvers with O(m·n) per-restart
    #: intermediates — kl materializes the A/(WH) quotient per lane, so an
    #: unchunked 200-restart sweep on a large matrix OOMs where chunks of 16
    #: sail through. Composes with a restart-sharded mesh (the chunk rounds
    #: up to a mesh-size multiple; per-device concurrency = chunk / #devices).
    #: None = all restarts at once; ignored by the packed/pallas mu backends
    #: (no m·n intermediates)
    restart_chunk: int | None = None
    #: out-of-core tile pipeline (ISSUE 17): partition A into
    #: feature-axis (row) blocks of at most ``tile_rows`` rows and stream
    #: them through the device while W/H and the vmapped restart pool
    #: stay resident — per-tile contributions reduce into k×k / k×n Gram
    #: terms (MPI-FAUN, arxiv 1609.09154), with the next tile's
    #: ``device_put`` overlapped against the current tile's update.
    #: "auto" sizes tiles to the device budget
    #: (``nmfx.tiles.tile_budget_bytes``; env NMFX_TILE_BUDGET_BYTES) and
    #: resolves to NO tiling when A fits in-core, so the default path
    #: costs nothing. A plan with one tile delegates to the dense
    #: in-core engines verbatim (bit-identical by construction); a
    #: multi-tile plan runs the streamed Gram engine, whose fixed
    #: tile-order f32 reduction is its own engine family ("tiled") —
    #: deliberately NOT in NON_NUMERICS_FIELDS, because a multi-tile
    #: reduction order is a different (bit-level) numeric program than
    #:  the in-core one. TILED_ALGORITHMS only; requires init "random".
    tile_rows: "int | str | None" = None

    def __post_init__(self):
        if self.backend not in ("auto", "vmap", "packed", "pallas",
                                "sketched"):
            raise ValueError(
                f"backend must be 'auto', 'vmap', 'packed', 'pallas' or "
                f"'sketched', got {self.backend!r}")
        if self.backend == "pallas" and self.algorithm not in ("mu",
                                                               "hals"):
            raise ValueError(
                "backend='pallas' is only implemented for algorithm='mu' "
                "and 'hals'; use 'auto' to fall back per algorithm")
        if (self.backend == "sketched"
                and self.algorithm not in SKETCHED_ALGORITHMS):
            raise ValueError(
                "backend='sketched' is only implemented for the Gram-"
                f"family algorithms {SKETCHED_ALGORITHMS}; use 'auto' "
                "for an exact engine")
        if self.screen:
            if self.algorithm not in SKETCHED_ALGORITHMS:
                raise ValueError(
                    "screen=True needs a sketched screening pass, which "
                    f"only the algorithms {SKETCHED_ALGORITHMS} have")
            if self.backend not in ("auto", "vmap"):
                raise ValueError(
                    "screen=True runs its exact phase through the "
                    "vmapped generic driver (the lane-independent "
                    "engine the survivor bit-identity contract rests "
                    "on); use backend 'auto' or 'vmap', got "
                    f"{self.backend!r}")
            if self.screen_keep is None:
                raise ValueError(
                    "screen=True requires screen_keep (how many "
                    "survivors get exact iterations)")
        if self.screen_keep is not None and self.screen_keep < 1:
            raise ValueError("screen_keep must be >= 1 or None")
        if (self.backend == "packed"
                and self.algorithm not in PACKED_ALGORITHMS):
            raise ValueError(
                "backend='packed' is only implemented for algorithms with "
                f"a dense-batched block {PACKED_ALGORITHMS}; use "
                "'auto' to fall back per algorithm")
        if self.algorithm not in ALGORITHMS:
            raise ValueError(
                f"algorithm must be one of {ALGORITHMS}, got {self.algorithm!r}"
            )
        if self.max_iter < 1:
            raise ValueError("max_iter must be >= 1")
        if self.check_every < 1:
            raise ValueError("check_every must be >= 1")
        cb = self.check_block
        if not (cb == "auto" or (isinstance(cb, int)
                                 and not isinstance(cb, bool) and cb >= 1)):
            raise ValueError(
                f"check_block must be 'auto' or an int >= 1, got {cb!r}")
        if self.matmul_precision not in ("default", "bfloat16", "highest"):
            raise ValueError(
                "matmul_precision must be 'default', 'bfloat16' or 'highest',"
                f" got {self.matmul_precision!r}")
        if self.restart_chunk is not None and self.restart_chunk < 1:
            raise ValueError("restart_chunk must be >= 1 or None")
        tr = self.tile_rows
        if not (tr is None or tr == "auto"
                or (isinstance(tr, int) and not isinstance(tr, bool)
                    and tr >= 1)):
            raise ValueError(
                f"tile_rows must be None, 'auto' or an int >= 1, got {tr!r}")
        if tr is not None and self.algorithm not in TILED_ALGORITHMS:
            raise ValueError(
                "tile_rows is only implemented for the Gram-accumulation "
                f"algorithms {TILED_ALGORITHMS}, got "
                f"algorithm={self.algorithm!r}")
        if tr is not None and self.backend in ("pallas", "sketched"):
            raise ValueError(
                "tile_rows streams A through the XLA Gram engines; it "
                f"cannot combine with backend={self.backend!r}")
        if tr is not None and self.screen:
            raise ValueError(
                "tile_rows cannot combine with screen=True (the "
                "screening pass needs in-core A)")
        if not 0.0 <= self.class_flip_tol < 1.0:
            raise ValueError(
                f"class_flip_tol must be in [0, 1), got {self.class_flip_tol}")
        if self.sparsity_beta < 0:
            # a negative beta makes the H Gram indefinite -> NaNs from the
            # Cholesky under jit instead of an error
            raise ValueError("sparsity_beta must be >= 0")
        if self.ridge_eta is not None and self.ridge_eta < 0:
            raise ValueError("ridge_eta must be >= 0 or None")


@dataclasses.dataclass(frozen=True)
class InitConfig:
    """W0/H0 initialization (reference ``libnmf/generatematrix.c:59-250``).

    ``random`` draws uniform [minval, maxval) with explicit, splittable PRNG
    keys — fixing the reference's non-reproducible libc ``rand()`` self-seeded
    with wall-clock time (reference ``libnmf/randnumber.c:27-35``).
    ``nndsvd`` is the Boutsidis NNDSVD scheme (reference
    ``libnmf/generatematrix.c:145-247``).
    """

    method: str = "random"
    minval: float = 0.0
    maxval: float = 1.0
    #: how NNDSVD obtains its rank-k SVD: "dense" (jnp.linalg.svd — fine at
    #: consensus-NMF sizes) or "lanczos" (on-device Lanczos on the Gram
    #: operator, the analogue of the reference's ARPACK path,
    #: libnmf/calculatesvd.c:38-267 — for k ≪ min(m, n) at scale)
    svd_method: str = "dense"
    #: Lanczos subspace size; None = 2k+1 with a floor of 20, capped to the
    #: operator dimension (cf. the reference's ncv defaulting,
    #: generatematrix.c:107-120; the floor is ours — full
    #: reorthogonalization in one restart wants a small cushion)
    ncv: int | None = None

    def __post_init__(self):
        if self.method not in INIT_METHODS:
            raise ValueError(
                f"init method must be one of {INIT_METHODS}, got {self.method!r}"
            )
        if self.svd_method not in ("dense", "lanczos"):
            raise ValueError(
                f"svd_method must be 'dense' or 'lanczos', got "
                f"{self.svd_method!r}")


@dataclasses.dataclass(frozen=True)
class ConsensusConfig:
    """Consensus sweep settings (reference ``nmf.r:106-119``)."""

    #: AUTHORITATIVE declaration of the ConsensusConfig fields that may
    #: legitimately be absent from the durable-sweep checkpoint manifest
    #: (``nmfx.checkpoint.manifest_key_fields``) — the fields that
    #: cannot change a persisted per-restart record's numbers. The
    #: static analyzer (``nmfx.analysis`` rule NMFX007) cross-references
    #: this list against ``checkpoint.MANIFEST_CONSENSUS_EXCLUDED``, so
    #: a result-affecting field can never silently drop out of the
    #: manifest (the stale-resume class). Rationale per field:
    #: ``ks`` — records are keyed per rank, widening a sweep reuses
    #: finished ranks by design (the SweepRegistry precedent);
    #: ``linkage``/``min_restarts`` — finalize-time only: rank selection
    #: and the quarantine floor are recomputed from the records at every
    #: finalize, never persisted; ``keep_factors`` — checkpointed sweeps
    #: refuse it (recompute via ``nmfx.restart_factors``);
    #: ``grid_exec``/``grid_slots``/``grid_tail_slots`` — inert under
    #: checkpointing (the chunk executor is its own per-(k, chunk)
    #: execution plan; the manifest hashes the checkpoint engine family
    #: instead); ``restarts`` — per-chunk records are restart-BUDGET
    #: independent: chunk ``[r0, r1)`` solves under keys
    #: ``split(fold_in(key(seed), k), R)[r0:r1]`` and counter-mode
    #: threefry makes ``split(key, R)[i]`` depend only on ``(key, i)``,
    #: never on ``R`` — so raising the budget from 50 to 100 restarts
    #: leaves every finished chunk byte-identical and the ledger resumes
    #: by solving only the delta chunks (the manifest pins the chunk
    #: PLAN separately; extension reuses only records whose exact
    #: boundaries appear in the new plan — see
    #: ``checkpoint.SweepCheckpoint``).
    CHECKPOINT_EXEMPT_FIELDS: ClassVar[tuple] = (
        "ks", "linkage", "min_restarts", "keep_factors", "grid_exec",
        "grid_slots", "grid_tail_slots", "restarts")

    #: AUTHORITATIVE declaration of the ConsensusConfig fields the
    #: finished-result cache key (``nmfx.result_cache.cache_key_fields``)
    #: may exclude. Deliberately EMPTY: unlike the checkpoint ledger —
    #: whose unit is a per-(k, chunk) record, making ``ks``/``restarts``
    #: resumable deltas — the result cache stores the FINISHED
    #: ``ConsensusResult``, and every ConsensusConfig field (including
    #: finalize-time ones like ``linkage``) shapes that result. The
    #: static analyzer (rule NMFX011) cross-references this list against
    #: the live key so a field can never silently drop out.
    RESULT_CACHE_EXEMPT_FIELDS: ClassVar[tuple] = ()

    ks: Sequence[int] = (2, 3, 4, 5)
    restarts: int = 10
    seed: int = 123
    #: cluster label rule. "argmax" is the intended BROAD semantics (largest
    #: H-loading; matches the C early-stop's biggestInRow, nmf_mu.c:258-261);
    #: "argmin" reproduces the reference R layer's observed behavior
    #: (`apply(H, 2, order)[1,]` picks the SMALLEST loading, nmf.r:128 — Q3).
    label_rule: str = "argmax"
    #: hierarchical clustering linkage for rank selection: "average" (the
    #: reference's hclust method, nmf.r:166), "complete", or "single"
    linkage: str = "average"
    #: retain every restart's (W, H) in the sweep output (the reference
    #: registry keeps each job's full result, nmf.r:50) — enables
    #: ``reduce_grid`` custom reductions and restart-level analyses at the
    #: cost of holding restarts×(m·k + k·n) extra values. Off by default:
    #: the recompute-by-key route (``nmfx.restart_factors``) reconstructs
    #: any single restart exactly without retention
    keep_factors: bool = False
    #: how the (k × restart) grid executes — the analogue of the
    #: reference's whole-grid job array (every |k|·R job concurrent,
    #: nmf.r:64-68). "grid" packs ALL ranks into one dense-batched solve
    #: (nmfx.ops.grid_mu): ONE jit compile for the sweep and the chip
    #: contracts over every grid cell at once; "per_k" runs ranks
    #: sequentially, each through its own backend (one compile per rank).
    #: "auto" picks "grid" when eligible — algorithm="mu" with the
    #: packed-family backend, >1 rank to solve, no feature/sample mesh
    #: axes — else "per_k". Results agree with per_k to float tolerance
    #: (GEMM reduction orders differ between the layouts).
    grid_exec: str = "auto"
    #: slot-pool width of the whole-grid scheduler (nmfx.ops.sched_mu):
    #: how many grid cells iterate concurrently per device; freed slots
    #: reload queued jobs. Wall ≈ max(longest job, total-iters/slots) ×
    #: per-iteration cost(slots) — 48 measured best at the north-star
    #: sweep (450 jobs on one v5e chip); larger pools help only when the
    #: grid is iteration-rich relative to its stragglers
    grid_slots: int = 48
    #: floor on the restarts that must SURVIVE the numeric quarantine
    #: (``SolverConfig.nonfinite_guard``) at each rank: a rank whose
    #: non-quarantined restart count drops below this raises a typed
    #: ``nmfx.faults.InsufficientRestarts`` at harvest instead of
    #: serving a consensus averaged over too few runs. The default (1)
    #: errors only when EVERY restart diverged — the loud floor under
    #: graceful degradation.
    min_restarts: int = 1
    #: straggler-tail cascade of the whole-grid scheduler: an int or a
    #: decreasing tuple of pool widths. Once the job queue drains and at
    #: most the next width's worth of jobs are live, the survivors
    #: compact into that narrower pool and finish at its cheaper
    #: per-iteration cost (the straggler tail dominates the sweep wall —
    #: see nmfx/ops/sched_mu.py). "auto" = measured default; 0/None
    #: disables. The knob targets wall-clock only: per-job stop decisions
    #: were identical on every tested workload, and factors stay within
    #: float tolerance (batch-width changes re-tile GEMMs, ~1e-6 factor
    #: drift, so a near-tie stop could in principle flip an iteration);
    #: each stage costs one extra compiled loop.
    grid_tail_slots: "int | None | str | tuple" = "auto"

    def __post_init__(self):
        # dedupe preserving order: a duplicated rank would be solved twice
        # and reported twice for an identical result (same (seed, k) keys)
        ks = tuple(dict.fromkeys(int(k) for k in self.ks))
        object.__setattr__(self, "ks", ks)
        if any(k < 2 for k in ks):
            # reference guard: "Need at least two clusters" (nmf.r:107-108)
            raise ValueError("all k must be >= 2")
        if self.restarts < 1:
            raise ValueError("restarts must be >= 1")
        if not 1 <= self.min_restarts <= self.restarts:
            raise ValueError(
                f"min_restarts must be in [1, restarts={self.restarts}], "
                f"got {self.min_restarts}")
        if self.label_rule not in ("argmax", "argmin"):
            raise ValueError("label_rule must be 'argmax' or 'argmin'")
        if self.grid_exec not in ("auto", "grid", "per_k"):
            raise ValueError(
                f"grid_exec must be 'auto', 'grid' or 'per_k', got "
                f"{self.grid_exec!r}")
        if self.grid_slots < 1:
            raise ValueError("grid_slots must be >= 1")
        ts = self.grid_tail_slots
        if isinstance(ts, (list, tuple)):
            ok = all(isinstance(t, int) and not isinstance(t, bool)
                     and t >= 1 for t in ts)
            if ok:
                # normalize to a tuple: the value keys jit/builder caches
                object.__setattr__(self, "grid_tail_slots", tuple(ts))
        else:
            ok = (ts is None or ts == "auto"
                  or (isinstance(ts, int) and not isinstance(ts, bool)
                      and ts >= 0))
        if not ok:
            raise ValueError(
                f"grid_tail_slots must be 'auto', None, an int >= 0, or "
                f"a tuple of int widths >= 1, got {self.grid_tail_slots!r}")
        if self.linkage not in LINKAGE_METHODS:
            raise ValueError(
                f"linkage must be one of {LINKAGE_METHODS}, got "
                f"{self.linkage!r}")


@dataclasses.dataclass(frozen=True)
class ExecCacheConfig:
    """Executable-reuse policy for the serving layer (``nmfx/exec_cache.py``).

    The sweep's trace+compile dwarfs a warm solve (measured 22.3 s compile
    against 1.85 s solve at the north star, BENCH_r05), and XLA keys
    executables by EXACT shape — so serving datasets of nearby shapes
    recompiles from scratch every time. The cache instead rounds incoming
    ``(m, n)`` up to a coarse padded-shape lattice and reuses one compiled
    executable per bucket; zero padding is exactly invariant under every
    grid solver (the invariant the feature/sample sharding already relies
    on — see ``nmfx/ops/grid_mu.py``), and pad rows/columns are masked out
    of consensus/labels/dnorms inside the executable.
    """

    #: lattice quanta: shapes round up to a multiple of a step that starts
    #: at the quantum and doubles once the dimension exceeds
    #: ``growth_steps`` steps — relative padding overhead stays below
    #: 2/growth_steps while the bucket count stays logarithmic. The
    #: defaults land the north-star 5000×500 on 5120×512 (the
    #: hardware-probed VMEM boundary shape): m steps are multiples of the
    #: pallas block row alignment, n steps of the 128-lane tile
    m_quantum: int = 256
    n_quantum: int = 64
    growth_steps: int = 8
    #: LRU bound on LIVE compiled executables (each holds device buffers
    #: for its constants and its compiled program — evicting drops the
    #: reference so a re-request recompiles, or re-deserializes under
    #: ``cache_dir``). The ``pipeline_ranks`` mode raises the EFFECTIVE
    #: bound to the largest request's rank count, so one sweep's
    #: per-rank executables can never thrash the LRU against themselves
    #: (ks=2..10 is 9 co-resident entries). The NNDSVD route's small
    #: per-true-shape lane-init jits live in a separate module-level
    #: pool (``sweep.bucketed_lane_init_fn``, lru_cache(128)) outside
    #: this bound — orders of magnitude smaller than a sweep executable
    #: each; the random-init fast path allocates none
    max_entries: int = 8
    #: donate the per-request initial-factor stacks to the executable
    #: (they are rebuilt per request, so aliasing them away is safe;
    #: applied only on backends where XLA honors donation)
    donate_inits: bool = True
    #: persistent executable cache directory (None = in-memory only).
    #: Compiled executables are SERIALIZED here (atomic tmp+rename
    #: writes), keyed by the bucket key extended with the device kind and
    #: jax/jaxlib/platform versions, so a FRESH process deserializes and
    #: dispatches instead of re-tracing and re-compiling — the cold-start
    #: path collapses to a disk read. Corrupt or version-mismatched
    #: entries fall back to a clean recompile with one warning. See
    #: docs/serving.md "Cold start".
    cache_dir: "str | None" = None
    #: byte cap on the disk cache: once the directory's entries exceed
    #: it, oldest-mtime entries are evicted (every disk hit touches its
    #: entry's mtime — an mtime-LRU). Independent of the in-memory LRU:
    #: evicting a live executable from memory NEVER deletes its disk
    #: entry, and re-admission from disk is a (persist) hit, not a
    #: recompile.
    max_disk_bytes: int = 2 << 30  # 2 GiB
    #: serve each rank through its OWN bucketed executable: on a cold
    #: start the per-rank executables compile concurrently in a thread
    #: pool (XLA compilation releases the GIL) and dispatch
    #: lowest-rank-first, so the k=2 solve is already running on device
    #: while higher ranks are still compiling. Each rank's results are
    #: exactly those of a single-rank grid sweep (ks=(k,)); the grid
    #: COMPOSITION differs from the whole-grid default, so cross-mode
    #: results agree only to float tolerance — which is why this is an
    #: opt-in rather than the default cold path.
    pipeline_ranks: bool = False
    #: thread-pool width for parallel compilation (ExecCache.warm and the
    #: pipeline_ranks cold path); 0 = auto (one thread per pending
    #: executable, capped at the CPU count)
    compile_workers: int = 0

    def __post_init__(self):
        if self.m_quantum < 1 or self.n_quantum < 1:
            raise ValueError("bucket quanta must be >= 1")
        if self.growth_steps < 1:
            raise ValueError("growth_steps must be >= 1")
        if self.max_entries < 1:
            raise ValueError("max_entries must be >= 1")
        if self.max_disk_bytes < 1:
            raise ValueError("max_disk_bytes must be >= 1")
        if self.compile_workers < 0:
            raise ValueError("compile_workers must be >= 0")


@dataclasses.dataclass(frozen=True)
class CheckpointConfig:
    """Durable-sweep checkpoint policy (``nmfx/checkpoint.py``).

    A sweep run with a CheckpointConfig persists a content-addressed
    manifest (input + config fingerprint + jax/device env) plus one
    completion record per (rank, restart-chunk) under ``directory``,
    with atomic tmp+rename writes — a preempted/killed process loses at
    most the chunk in flight, and a re-run with ``resume=True``
    recomputes ONLY the missing chunks, producing a result bit-identical
    to an uninterrupted checkpointed run (the consensus is accumulated
    from the per-restart records in canonical restart order at finalize
    time, in exact integer arithmetic, so completion order can never
    matter). See docs/serving.md "Durability model".
    """

    #: ledger directory (manifest + per-(k, chunk) records)
    directory: str = "./nmfx_ckpt"
    #: restarts per completion record — the durability granularity AND
    #: the chunk execution plan (deterministic boundaries
    #: ``[0,c), [c,2c), …`` per rank, recorded in the manifest so a
    #: resume re-runs exactly the missing plan chunks with identical
    #: batch composition). None = one chunk per rank (the SweepRegistry
    #: granularity).
    every_n_restarts: "int | None" = None
    #: time-batched persistence: completed records are buffered in
    #: memory and flushed to disk at most every this many seconds (and
    #: always at rank boundaries, on ``flush()``, and from the
    #: SIGTERM/SIGINT flush hook — ``nmfx.checkpoint
    #: .install_signal_flush``). None = every record is written the
    #: moment its chunk completes (maximum durability, the default).
    every_s: "float | None" = None
    #: resume from records already in ``directory`` (guarded by the
    #: manifest: a fingerprint/env/plan mismatch triggers a clean cold
    #: start — warn + recompute — never a wrong resume). False clears
    #: the ledger and starts fresh.
    resume: bool = True

    def __post_init__(self):
        if not self.directory:
            raise ValueError("directory must be a non-empty path")
        if self.every_n_restarts is not None and self.every_n_restarts < 1:
            raise ValueError("every_n_restarts must be >= 1 or None")
        if self.every_s is not None and self.every_s <= 0:
            raise ValueError("every_s must be positive or None")


@dataclasses.dataclass(frozen=True)
class ResultCacheConfig:
    """Finished-result cache policy (``nmfx/result_cache.py``).

    At service scale the dominant waste is REPEATED solves: the same
    atlas resubmitted under the same configuration re-solves from
    scratch even though the input is already content-hashed
    (``data_cache.DataKey``) and the result is deterministic given
    (data, config, seed). The result cache closes that loop: finished
    ``ConsensusResult``s are stored content-addressed by (input
    fingerprint, result-affecting config fingerprint, quality tag) in
    an in-memory LRU over an atomic tmp+rename disk tier, so a warm
    resubmission is served in O(1) with ZERO solve dispatches and ZERO
    host-to-device transfers. See docs/serving.md "Request economics".
    """

    #: persistent cache directory (None = in-memory only). Entries are
    #: ``ConsensusResult.save`` archives written atomically
    #: (tmp + ``os.replace``), named by the content-addressed key
    #: digest; corrupt or key-mismatched entries are treated as misses
    #: with one warning, never served.
    cache_dir: "str | None" = None
    #: LRU bound on in-memory results (each holds its per-k consensus
    #: matrices — n×n float64 per rank — so the default stays modest;
    #: evicting from memory never deletes a disk entry)
    max_entries: int = 32
    #: byte cap on the disk tier: oldest-mtime entries are evicted once
    #: the directory exceeds it (every disk hit touches its entry's
    #: mtime — an mtime-LRU, the exec-cache discipline)
    max_disk_bytes: int = 4 << 30  # 4 GiB

    def __post_init__(self):
        if self.max_entries < 1:
            raise ValueError("max_entries must be >= 1")
        if self.max_disk_bytes < 1:
            raise ValueError("max_disk_bytes must be >= 1")


@dataclasses.dataclass(frozen=True)
class OutputConfig:
    """File outputs (reference writes to hardcoded './temp*', nmf.r:157-159)."""

    directory: str = "./nmfx_out"
    doc_string: str = ""
    write_gcts: bool = True
    write_plots: bool = True
