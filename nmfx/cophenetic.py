"""Hierarchical clustering (average/complete/single linkage), cophenetic
correlation, cut-tree.

Framework-owned host implementation of the rank-selection step the reference
delegates to base R: ``hclust(as.dist(1-C), method="average")`` →
``cophenetic`` → ``cor`` → ``cutree`` (reference ``nmf.r:165-177``). n is the
number of samples (tiny next to the NMF work), so this runs on host numpy;
the heavy consensus reduction stays on-device (see consensus.py). Validated
against scipy.cluster.hierarchy in tests.

``average_linkage`` and ``cut_tree`` dispatch to the native C++ library
(nmfx/native, the framework's host-side analogue of the reference's
libnmf.so) when it is available, and fall back to the pure-numpy
implementations (``average_linkage_numpy`` / ``cut_tree_numpy``) otherwise;
set NMFX_NATIVE=0 to force the fallback. Both paths share one contract and
are cross-tested in tests/test_native.py.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np


class HClust(NamedTuple):
    """Result of average-linkage clustering of an n×n distance matrix."""

    linkage: np.ndarray  # (n-1, 4) scipy-style: id_a, id_b, height, size
    coph: np.ndarray  # (n, n) cophenetic distances
    order: np.ndarray  # (n,) dendrogram leaf order


def hierarchical_linkage(dist: np.ndarray,
                         method: str = "average") -> HClust:
    """Agglomerative clustering of a distance matrix. ``method`` is the
    Lance-Williams rule: "average" (UPGMA — the reference's
    hclust(method="average"), nmf.r:166), "complete", or "single". The
    native C++ path implements average only; other methods use the numpy
    implementation (n is tiny here)."""
    from nmfx import native

    if method == "average" and native.available():
        nat = native.average_linkage(dist)
        return HClust(nat.linkage, nat.coph, nat.order)
    return linkage_numpy(dist, method)


def average_linkage(dist: np.ndarray) -> HClust:
    """UPGMA agglomerative clustering (native C++ when available)."""
    return hierarchical_linkage(dist, "average")


def cut_tree(linkage: np.ndarray, n: int, k: int) -> np.ndarray:
    """Memberships 1..k from the first n-k merges (reference ``cutree``,
    nmf.r:177); native C++ when available."""
    from nmfx import native

    if native.available():
        return native.cut_tree(linkage, n, k)
    return cut_tree_numpy(linkage, n, k)


def average_linkage_numpy(dist: np.ndarray) -> HClust:
    """UPGMA clustering, pure numpy (kept as the named entry the native
    path is cross-tested against)."""
    return linkage_numpy(dist, "average")


def linkage_numpy(dist: np.ndarray, method: str = "average") -> HClust:
    """Agglomerative clustering (pure-numpy reference implementation) under
    the "average", "complete", or "single" Lance-Williams update.

    Cluster ids follow the scipy convention: leaves are 0..n-1, the cluster
    created at merge t is n+t. Cophenetic distance of a cross pair = height
    of the merge that first joins them.
    """
    from nmfx.config import LINKAGE_METHODS

    if method not in LINKAGE_METHODS:
        raise ValueError(
            f"linkage must be one of {LINKAGE_METHODS}, got {method!r}")
    d = np.array(dist, dtype=np.float64, copy=True)
    n = d.shape[0]
    if d.shape != (n, n):
        raise ValueError("dist must be square")
    np.fill_diagonal(d, np.inf)
    active = np.ones(n, dtype=bool)
    size = np.ones(n)
    cid = np.arange(n)  # cluster id currently held in each slot
    members: list[list[int]] = [[i] for i in range(n)]
    linkage = np.zeros((n - 1, 4))
    coph = np.zeros((n, n))
    children: dict[int, tuple[int, int]] = {}

    for t in range(n - 1):
        masked = np.where(active[:, None] & active[None, :], d, np.inf)
        i, j = np.unravel_index(np.argmin(masked), masked.shape)
        if i > j:
            i, j = j, i
        height = masked[i, j]
        a, b = sorted((cid[i], cid[j]))
        new_size = size[i] + size[j]
        linkage[t] = (a, b, height, new_size)
        mi, mj = members[i], members[j]
        coph[np.ix_(mi, mj)] = height
        coph[np.ix_(mj, mi)] = height
        # Lance-Williams update of the merged cluster's distances
        if method == "average":
            merged = (size[i] * d[i] + size[j] * d[j]) / new_size
        elif method == "complete":
            merged = np.maximum(d[i], d[j])
        else:  # single
            merged = np.minimum(d[i], d[j])
        d[i] = merged
        d[:, i] = merged
        d[i, i] = np.inf
        active[j] = False
        children[n + t] = (a, b)
        members[i] = mi + mj
        size[i] = new_size
        cid[i] = n + t

    # dendrogram leaf order: depth-first, left child first
    order: list[int] = []
    stack = [2 * n - 2] if n > 1 else [0]
    while stack:
        node = stack.pop()
        if node < n:
            order.append(node)
        else:
            left, right = children[node]
            stack.append(right)
            stack.append(left)
    return HClust(linkage, coph, np.asarray(order))


def condensed(mat: np.ndarray) -> np.ndarray:
    """Upper-triangle (off-diagonal) entries, row-major."""
    iu = np.triu_indices(mat.shape[0], k=1)
    return np.asarray(mat)[iu]


def cophenetic_rho(dist: np.ndarray, coph: np.ndarray) -> float:
    """Pearson correlation between the condensed distance and cophenetic
    matrices (reference ``cor(dist.matrix, dist.coph)``, nmf.r:171)."""
    x = condensed(dist)
    y = condensed(coph)
    xc = x - x.mean()
    yc = y - y.mean()
    denom = np.sqrt((xc @ xc) * (yc @ yc))
    if denom == 0:
        return 1.0  # degenerate: all restarts agree perfectly
    return float((xc @ yc) / denom)


def cut_tree_numpy(linkage: np.ndarray, n: int, k: int) -> np.ndarray:
    """Memberships 1..k from the first n-k merges (pure-numpy; labels
    numbered by first appearance in leaf index order, as R's cutree does)."""
    if not 1 <= k <= n:
        raise ValueError(f"k must be in [1, {n}]")
    parent = np.arange(2 * n - 1)

    def find(x: int) -> int:
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for t in range(n - k):
        a, b, _, _ = linkage[t]
        new = n + t
        parent[find(int(a))] = new
        parent[find(int(b))] = new

    labels = np.zeros(n, dtype=np.int64)
    seen: dict[int, int] = {}
    for i in range(n):
        root = find(i)
        if root not in seen:
            seen[root] = len(seen) + 1
        labels[i] = seen[root]
    return labels


def rank_selection(consensus: np.ndarray, k: int,
                   linkage: str = "average"):
    """Full per-k rank-selection step on one consensus matrix: returns
    (rho, memberships, leaf order), mirroring reference nmf.r:165-177."""
    dist = 1.0 - np.asarray(consensus)
    np.fill_diagonal(dist, 0.0)
    hc = hierarchical_linkage(dist, linkage)
    rho = cophenetic_rho(dist, hc.coph)
    membership = cut_tree(hc.linkage, dist.shape[0], k)
    return rho, membership, hc.order
