"""W0/H0 initialization: uniform random and NNDSVD.

TPU-native re-design of reference ``libnmf/generatematrix.c:59-250``.

* ``random``: uniform [minval, maxval) with explicit, splittable
  ``jax.random`` keys. This deliberately fixes the reference's
  reproducibility hole — its C RNG self-seeds from wall-clock time and
  ignores every caller-provided seed (``libnmf/randnumber.c:27-35``, quirk
  Q2 in SURVEY.md), while its R-layer init draws from R's global RNG
  (``nmf.r:37-38``). Here a seed fully determines every restart.

* ``nndsvd``: Boutsidis & Gallopoulos NNDSVD (reference
  ``generatematrix.c:145-247``): rank-k SVD, leading pair from
  √σ₀·|u₀|,|v₀|, remaining pairs split into ± parts keeping the dominant
  side scaled by √(σⱼ·‖side_u‖·‖side_v‖), final zero-threshold clamp. The
  reference pulls the SVD from ARPACK Lanczos reverse communication
  (``calculatesvd.c:141-224``); at consensus-NMF sizes a dense
  ``jnp.linalg.svd`` on-device is both simpler and faster on the MXU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from nmfx.config import InitConfig


def random_init(key: jax.Array, m: int, n: int, k: int,
                cfg: InitConfig = InitConfig(),
                dtype=jnp.float32) -> tuple[jax.Array, jax.Array]:
    """Uniform random W0 (m×k), H0 (k×n) (reference generatematrix.c:94-100;
    R-layer equivalent runif in (0,1), nmf.r:37-38)."""
    kw, kh = jax.random.split(key)
    w0 = jax.random.uniform(kw, (m, k), dtype, cfg.minval, cfg.maxval)
    h0 = jax.random.uniform(kh, (k, n), dtype, cfg.minval, cfg.maxval)
    return w0, h0


def nndsvd_init(a: jax.Array, k: int, zero_threshold: float = 0.0,
                dtype=jnp.float32, svd_method: str = "dense",
                ncv: int | None = None) -> tuple[jax.Array, jax.Array]:
    """NNDSVD initialization (deterministic in A)."""
    a = jnp.asarray(a, dtype)
    if svd_method == "lanczos":
        from nmfx.ops.lanczos_svd import truncated_svd

        u, s, vt = truncated_svd(a, k, ncv)
    elif svd_method == "dense":
        u, s, vt = jnp.linalg.svd(a, full_matrices=False)
        u, s, vt = u[:, :k], s[:k], vt[:k, :]
    else:
        raise ValueError(
            f"svd_method must be 'dense' or 'lanczos', got {svd_method!r}")

    # leading pair: W[:,0] = sqrt(s0)*|u0|, H[0,:] = sqrt(s0)*|v0|
    # (generatematrix.c:172-175; sign-ambiguous SVD made non-negative by abs)
    w0 = jnp.sqrt(s[0]) * jnp.abs(u[:, :1])
    h0 = jnp.sqrt(s[0]) * jnp.abs(vt[:1, :])

    if k > 1:
        uj = u[:, 1:]  # (m, k-1)
        vj = vt[1:, :].T  # (n, k-1)
        up, un = jnp.maximum(uj, 0), jnp.maximum(-uj, 0)
        vp, vn = jnp.maximum(vj, 0), jnp.maximum(-vj, 0)
        nup = jnp.linalg.norm(up, axis=0)
        nun = jnp.linalg.norm(un, axis=0)
        nvp = jnp.linalg.norm(vp, axis=0)
        nvn = jnp.linalg.norm(vn, axis=0)
        termp = nup * nvp
        termn = nun * nvn
        use_p = termp >= termn
        term = jnp.where(use_p, termp, termn)
        scale = jnp.sqrt(s[1:] * term)
        tiny = jnp.finfo(dtype).tiny
        wcols = scale * jnp.where(use_p, up / jnp.maximum(nup, tiny),
                                  un / jnp.maximum(nun, tiny))
        hrows = scale * jnp.where(use_p, vp / jnp.maximum(nvp, tiny),
                                  vn / jnp.maximum(nvn, tiny))
        w0 = jnp.concatenate([w0, wcols], axis=1)
        h0 = jnp.concatenate([h0, hrows.T], axis=0)

    # final clamp (generatematrix.c:229-247)
    w0 = jnp.where(w0 <= zero_threshold, 0.0, w0)
    h0 = jnp.where(h0 <= zero_threshold, 0.0, h0)
    return w0, h0


def initialize(key: jax.Array, a: jax.Array, k: int, cfg: InitConfig,
               dtype=jnp.float32) -> tuple[jax.Array, jax.Array]:
    """Dispatch on cfg.method; NNDSVD ignores the key (deterministic in A,
    as in the reference — restarts only differ under random init)."""
    m, n = a.shape
    if cfg.method == "random":
        return random_init(key, m, n, k, cfg, dtype)
    return nndsvd_init(a, k, dtype=dtype, svd_method=cfg.svd_method,
                       ncv=cfg.ncv)
