"""Command-line entry point.

Mirrors the reference's ``runNMFinJobs`` arguments (reference ``nmf.r:106``)
plus the knobs its C layer kept behind compile flags: solver choice, init
scheme, tolerances, output directory.

    python -m nmfx data.gct --ks 2-5 --restarts 10 --algorithm mu
"""

from __future__ import annotations

import argparse
import sys

import os

from nmfx.config import (ALGORITHMS, INIT_METHODS, LINKAGE_METHODS,
                         PACKED_ALGORITHMS, VERSION, OutputConfig,
                         SolverConfig)

#: default persistent XLA compilation-cache location (XDG-style, overridable
#: via --compile-cache/--no-compile-cache). The reference pays no compile
#: cost anywhere — its workers start solving the moment they spawn
#: (nmf.r:112) — so first-compile latency is OUR artifact to hide: with a
#: warm cache a cold process recovers compiled executables instead of
#: re-lowering the sweep.
_DEFAULT_COMPILE_CACHE = os.path.join(
    os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
    "nmfx", "xla")


def parse_ks(spec: str) -> tuple[int, ...]:
    """'2-5' or '2,3,4,5' or '3' -> tuple of ranks."""
    ks: list[int] = []
    for part in spec.split(","):
        part = part.strip()
        if "-" in part[1:]:
            lo, hi = part.split("-")
            ks.extend(range(int(lo), int(hi) + 1))
        else:
            ks.append(int(part))
    return tuple(ks)


def _tail_slots_arg(value: str):
    """'auto', a non-negative int, or a comma-separated decreasing
    cascade like '24,8' — validated at parse time so a bad value is a
    usage error, not a late ValueError traceback."""
    if value == "auto":
        return value
    try:
        widths = tuple(int(part) for part in value.split(","))
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"expected 'auto', a non-negative integer, or a "
            f"comma-separated cascade (e.g. '24,8'), got {value!r}")
    if len(widths) == 1:
        if widths[0] < 0:
            raise argparse.ArgumentTypeError(
                f"expected a non-negative integer, got {value!r}")
        return widths[0]
    if any(w < 1 for w in widths):
        raise argparse.ArgumentTypeError(
            f"cascade widths must be >= 1, got {value!r}")
    if any(b >= a for a, b in zip(widths, widths[1:])):
        raise argparse.ArgumentTypeError(
            f"cascade widths must be strictly decreasing, got {value!r}")
    return widths


def _check_block_arg(value: str):
    """'auto' or a positive int — validated at parse time."""
    if value == "auto":
        return value
    try:
        n = int(value)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"expected 'auto' or a positive integer, got {value!r}")
    if n < 1:
        raise argparse.ArgumentTypeError(
            f"check-block must be >= 1, got {value!r}")
    return n


def _tile_rows_arg(value: str):
    """'auto' or a positive int — validated at parse time."""
    if value == "auto":
        return value
    try:
        n = int(value)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"expected 'auto' or a positive integer, got {value!r}")
    if n < 1:
        raise argparse.ArgumentTypeError(
            f"tile-rows must be >= 1, got {value!r}")
    return n


def _warm_shapes_arg(value: str) -> tuple[tuple[int, int], ...]:
    """'5000x500,20000x1000' -> ((5000, 500), (20000, 1000)); validated
    at parse time so a bad spec is a usage error."""
    shapes = []
    for part in value.split(","):
        try:
            m, n = part.strip().lower().split("x")
            shapes.append((int(m), int(n)))
        except ValueError:
            raise argparse.ArgumentTypeError(
                f"expected comma-separated MxN shapes (e.g. "
                f"'5000x500,20000x1000'), got {value!r}")
        if shapes[-1][0] < 1 or shapes[-1][1] < 1:
            raise argparse.ArgumentTypeError(
                f"shape dims must be >= 1, got {part.strip()!r}")
    return tuple(shapes)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="nmfx",
        description="TPU-native consensus NMF (capabilities of "
                    "mschubert/NMFconsensus, re-designed for JAX/XLA).")
    p.add_argument("dataset",
                   help="input .gct or .res file (dense), or a sparse "
                        ".mtx / .csr.npz matrix — sparse inputs stream "
                        "through the out-of-core tile pipeline without "
                        "densifying")
    p.add_argument("--ks", default="2-5", type=parse_ks,
                   help="ranks to sweep, e.g. '2-5' or '2,4,8' (default 2-5)")
    p.add_argument("--restarts", type=int, default=10,
                   help="random restarts per rank (default 10)")
    p.add_argument("--maxiter", type=int, default=10000,
                   help="max solver iterations (default 10000)")
    p.add_argument("--seed", type=int, default=123)
    p.add_argument("--algorithm", choices=ALGORITHMS, default="mu")
    p.add_argument("--precision", default="default",
                   choices=("default", "bfloat16", "highest"),
                   help="TPU matmul precision for solver dots")
    p.add_argument("--backend", default="auto",
                   choices=("auto", "vmap", "packed", "pallas",
                            "sketched"),
                   help="restart-batch execution strategy (auto = packed "
                        "GEMMs for mu, vmapped driver otherwise; "
                        "'sketched' = the random-projection compressed "
                        "engine — approximate, statistical accuracy "
                        "contract at the consensus level, result tagged "
                        "quality='sketched'; mu/hals only)")
    p.add_argument("--sketch-dim", type=int, default=None, metavar="R",
                   help="sketch dimension of the compressed engine / "
                        "screening pass (SketchConfig.dim; default "
                        "'auto' = 4k+8 per rank, clamped to the matrix "
                        "dims). Requires --backend sketched or --screen")
    p.add_argument("--screen", action="store_true",
                   help="restart screening (SolverConfig.screen): a "
                        "cheap sketched pass scores the full restart "
                        "pool and only the --screen-keep best lanes "
                        "get exact iterations — survivor results are "
                        "bit-identical to solo exact runs; screened-out "
                        "lanes are masked from the consensus like pad "
                        "lanes (the min_restarts floor counts them as "
                        "non-survivors). mu/hals with --backend "
                        "auto/vmap")
    p.add_argument("--screen-keep", type=int, default=None, metavar="K",
                   help="survivors of the screening pass per rank "
                        "(required with --screen; must be <= "
                        "--restarts)")
    p.add_argument("--restart-chunk", type=int, default=None,
                   help="cap on restarts solved concurrently in the vmapped "
                        "driver (bounds peak memory for kl's m*n "
                        "intermediates; results are identical)")
    p.add_argument("--tile-rows", default=None, type=_tile_rows_arg,
                   metavar="N|auto",
                   help="out-of-core tile pipeline "
                        "(SolverConfig.tile_rows): stream A from host in "
                        "N-row feature blocks instead of pinning it "
                        "device-resident — for matrices larger than "
                        "device memory. 'auto' sizes tiles to the "
                        "device budget (--tile-budget-bytes). mu/hals; "
                        "where A fits in-core the tiled sweep is "
                        "bit-identical to the dense one. Sparse .mtx/"
                        ".csr.npz inputs stream regardless of this flag")
    p.add_argument("--tile-budget-bytes", type=int, default=None,
                   metavar="BYTES",
                   help="device-memory budget the 'auto' tile size is "
                        "derived from (default: NMFX_TILE_BUDGET_BYTES "
                        "env or 256 MiB; two tile buffers live at once "
                        "— current + prefetched)")
    p.add_argument("--check-block", default="auto", type=_check_block_arg,
                   help="check blocks batched per scheduler trip "
                        "(SolverConfig.check_block): convergence is still "
                        "evaluated every check-every iterations, but the "
                        "per-trip machinery fires once per N checks. "
                        "'auto' (default) = 4 on the pallas block-kernel "
                        "scheduler, 1 elsewhere; see docs/design.md "
                        "'Check cadence'")
    p.add_argument("--autotune", action="store_true",
                   help="measure-don't-model kernel scheduling on the "
                        "pallas backend (ExperimentalConfig.autotune): "
                        "the first solve at a shape bucket times a small "
                        "(block_m, check_block, fused-vs-phased) "
                        "candidate grid on the real device and persists "
                        "the winner next to the exec cache (under "
                        "--cache-dir when given), so later processes "
                        "resolve with zero search; explicit "
                        "--check-block still wins. No-op off the pallas "
                        "backend")
    p.add_argument("--rank-selection", default="host",
                   choices=("host", "device"),
                   help="where hclust/cophenetic/cutree run: host numpy/C++ "
                        "or fully on the accelerator")
    p.add_argument("--init", choices=INIT_METHODS, default="random")
    p.add_argument("--linkage", choices=LINKAGE_METHODS,
                   default="average",
                   help="hclust linkage for rank selection (reference: "
                        "average)")
    p.add_argument("--label-rule", choices=("argmax", "argmin"),
                   default="argmax",
                   help="cluster label rule; argmin reproduces the reference "
                        "R layer's observed (buggy) assignment")
    p.add_argument("--verbose", action="store_true",
                   help="log per-rank progress while the sweep runs (turns "
                        "off async dispatch pipelining across ranks)")
    p.add_argument("--save-result", default=None, metavar="PATH",
                   help="also persist the full ConsensusResult as one npz "
                        "(reload with nmfx.ConsensusResult.load)")
    p.add_argument("--version", action="version",
                   version="%(prog)s " + VERSION)
    p.add_argument("--outdir", default="./nmfx_out")
    p.add_argument("--no-plots", action="store_true")
    p.add_argument("--no-files", action="store_true",
                   help="print the summary only, write nothing")
    p.add_argument("--no-mesh", action="store_true",
                   help="disable sharding over the local device mesh")
    p.add_argument("--feature-shards", type=int, default=1,
                   help="tile each factorization's rows (A, W) across this "
                        "many devices — tensor parallelism for m too large "
                        "for one device (default 1 = off)")
    p.add_argument("--sample-shards", type=int, default=1,
                   help="tile each factorization's columns (A, H) across "
                        "this many devices — sequence parallelism for huge "
                        "n (default 1 = off)")
    p.add_argument("--restart-shards", type=int, default=None,
                   metavar="N",
                   help="pin the restart axis to exactly N devices "
                        "(communication-avoiding data parallelism: zero "
                        "per-iteration collectives). Default: auto — all "
                        "local devices. Composes with --feature-shards/"
                        "--sample-shards into an N x F x S grid mesh")
    p.add_argument("--checkpoint-dir", default=None,
                   help="durable sweep ledger (docs/serving.md "
                        "'Durability model'): persist per-(rank, "
                        "restart-chunk) completion records here — a "
                        "preempted/killed run loses at most the chunk "
                        "in flight, and a re-run resumes bit-identical "
                        "to an uninterrupted checkpointed run, "
                        "recomputing only the missing chunks. A "
                        "manifest mismatch (different data/config/"
                        "environment) cold-starts cleanly, never a "
                        "wrong resume")
    p.add_argument("--checkpoint-every", type=int, default=None,
                   metavar="N",
                   help="restarts per completion record (the durability "
                        "granularity; default: one record per rank). "
                        "Requires --checkpoint-dir")
    p.add_argument("--resume", action=argparse.BooleanOptionalAction,
                   default=None,
                   help="with --checkpoint-dir: resume from records "
                        "already in the ledger (the default); "
                        "--no-resume clears them and recomputes from "
                        "scratch")
    p.add_argument("--keep-factors", action="store_true",
                   help="retain every restart's (W, H) in the result "
                        "(the reference registry's per-job retention); "
                        "pairs with --save-result for offline "
                        "restart-level analysis via nmfx.reduce_grid")
    p.add_argument("--grid-exec", default="auto",
                   choices=("auto", "grid", "per_k"),
                   help="(k x restart) grid execution: 'auto' solves every "
                        "rank in ONE compiled whole-grid slot-scheduled "
                        "batch when eligible (mu/hals with the packed "
                        "backend family, or neals/als/snmf/kl with "
                        "--backend packed; no grid shards) — the reference's "
                        "whole-grid job-array concurrency; 'per_k' forces "
                        "sequential ranks (one compile each); 'grid' "
                        "demands the whole-grid path")
    p.add_argument("--grid-slots", type=int, default=48,
                   help="slot-pool width of the whole-grid scheduler: how "
                        "many grid cells iterate concurrently per device "
                        "(freed slots reload queued jobs); 48 measured "
                        "best at the north-star sweep")
    p.add_argument("--grid-tail-slots", default="auto",
                   type=_tail_slots_arg,
                   help="straggler-tail cascade of the whole-grid "
                        "scheduler: an int or comma-separated decreasing "
                        "widths (e.g. '24,8'). Once the queue drains, "
                        "surviving stragglers compact into progressively "
                        "narrower pools with cheaper per-iteration cost. "
                        "'auto' (default) = measured default; 0 disables. "
                        "Affects wall-clock only (stop decisions "
                        "identical on all tested workloads)")
    p.add_argument("--exec-cache", action="store_true",
                   help="serve the sweep through the shape-bucketed "
                        "executable-reuse layer (nmfx.exec_cache): one "
                        "AOT-compiled executable per padded-shape bucket, "
                        "reused across datasets of nearby shapes — "
                        "results are shape-exact (see docs/serving.md)")
    p.add_argument("--warm-shapes", default=None, metavar="MxN[,MxN...]",
                   type=_warm_shapes_arg,
                   help="pre-compile the exec-cache executables for these "
                        "dataset shapes' buckets before the run (e.g. "
                        "'5000x500,20000x1000') — makes warmup explicit "
                        "and batchable at startup instead of paying the "
                        "20-odd-second sweep compile on first traffic; "
                        "implies --exec-cache")
    p.add_argument("--cache-dir", default=None, metavar="DIR",
                   help="persistent EXECUTABLE cache for the serving "
                        "layer: compiled sweep executables are serialized "
                        "here and a fresh process DESERIALIZES instead of "
                        "re-tracing and re-compiling — cold start becomes "
                        "deserialize-and-dispatch (docs/serving.md 'Cold "
                        "start'). Implies --exec-cache; independent of "
                        "--compile-cache (which caches XLA's intermediate "
                        "compilation products, not loaded executables)")
    p.add_argument("--result-cache-dir", default=None, metavar="DIR",
                   help="content-addressed FINISHED-RESULT cache "
                        "(nmfx.result_cache): completed ConsensusResults "
                        "are stored here keyed by the input bytes plus "
                        "every result-affecting config field, and a "
                        "repeat invocation is served in O(1) with zero "
                        "solve dispatches (docs/serving.md 'Request "
                        "economics'). Composes with --serve-smoke (the "
                        "server's own cache tier), --replicas (the "
                        "router front door's tier), and "
                        "--checkpoint-dir (a miss resumes the durable "
                        "ledger as usual, then the finished result is "
                        "cached). Independent of --cache-dir, which "
                        "caches compiled EXECUTABLES, not results")
    p.add_argument("--pipeline-ranks", action="store_true",
                   help="serve each rank through its OWN bucketed "
                        "executable (ExecCacheConfig.pipeline_ranks): "
                        "cold compiles run concurrently and dispatch is "
                        "lowest-k-first, so k=2 solves while k=10 still "
                        "compiles, and the streamed harvest consumes "
                        "each rank as it lands. Implies --exec-cache. "
                        "Exactness caveat (docs/serving.md): each "
                        "rank's results are exactly a single-rank grid "
                        "sweep's, but the grid COMPOSITION differs from "
                        "the whole-grid default, so cross-mode results "
                        "agree only to float tolerance")
    p.add_argument("--input-cache-bytes", type=int, default=None,
                   metavar="N",
                   help="byte cap for the device-resident input cache "
                        "(repeat sweeps over the same matrix transfer "
                        "zero bytes; default 2 GiB of live device "
                        "buffers). 0 disables retention — every request "
                        "transfers — for accelerators where resident "
                        "inputs would crowd solver working memory")
    p.add_argument("--warm-cache", action="store_true",
                   help="run the --warm-shapes warmup in the BACKGROUND "
                        "(compiles overlap dataset loading and run setup; "
                        "the sweep waits only for its own bucket's "
                        "executable, de-duplicated against the in-flight "
                        "warm). Requires --warm-shapes; pairs with "
                        "--cache-dir so warmed executables persist for "
                        "future processes")
    p.add_argument("--serve-smoke", action="store_true",
                   help="route the run through the multi-tenant serving "
                        "engine (nmfx.serve.NMFXServer): submit this "
                        "request to the async queue, await its future, "
                        "and report the serve counters and per-request "
                        "spans (queue-wait, pack, solve, harvest) to "
                        "stderr. Results are bit-identical to the "
                        "direct path — the serving exactness contract "
                        "(docs/serving.md 'Serving front-end'). Implies "
                        "--exec-cache; single-device (no shard flags)")
    p.add_argument("--replicas", type=int, default=None, metavar="N",
                   help="with --serve-smoke: route the request through "
                        "the resilient service tier instead of one "
                        "server — an NMFXRouter over N in-process "
                        "replica servers (nmfx.replica.ReplicaPool, "
                        "thread mode; docs/serving.md 'Service tier'). "
                        "Results stay bit-identical to the direct "
                        "path; the router stats (placement, retries, "
                        "readmissions) are reported to stderr")
    p.add_argument("--router-spill-dir", default=None, metavar="DIR",
                   help="with --replicas: root directory of the "
                        "replica pool's spill/heartbeat ledger (spill-"
                        "migration records and replica_<id>.json "
                        "heartbeats live here; default: a temporary "
                        "directory)")
    p.add_argument("--replica-mesh", default=None, metavar="SPECS",
                   help="with --replicas: comma-separated per-replica "
                        "mesh specs making the fleet HETEROGENEOUS — "
                        "each entry is R, RxF, or RxFxS (that replica "
                        "owns a carved block of r*f*s local devices) "
                        "or '-' for a plain 1-device replica. Must "
                        "name one spec per replica, e.g. "
                        "--replicas 2 --replica-mesh -,4. The router "
                        "prices placement across the classes "
                        "(docs/serving.md 'Mesh tier')")
    p.add_argument("--compile-cache", default=_DEFAULT_COMPILE_CACHE,
                   metavar="DIR",
                   help="persistent XLA compilation cache directory: "
                        "re-runs of the same (shape, config) skip the "
                        "first-compile cost (equivalent to setting "
                        "JAX_COMPILATION_CACHE_DIR). ON by default "
                        f"(at {_DEFAULT_COMPILE_CACHE}) — the reference "
                        "has no compile step at all, its workers start "
                        "solving immediately (nmf.r:112); "
                        "--no-compile-cache opts out")
    p.add_argument("--no-compile-cache", action="store_true",
                   help="disable the persistent compilation cache")
    p.add_argument("--profile", action="store_true",
                   help="print a per-phase wall-clock breakdown (replaces "
                        "the reference's rebuild-to-instrument PROFILE_* "
                        "macros, libnmf common.h:27-45)")
    p.add_argument("--trace-dir", default=None,
                   help="with --profile: also capture a jax.profiler device "
                        "trace (TensorBoard/Perfetto) into this directory")
    p.add_argument("--trace-out", default=None, metavar="PATH",
                   help="record the run through the structured span "
                        "tracer (nmfx.obs.trace — every profiler phase "
                        "plus the serving spans, per thread) and write "
                        "Chrome trace-event JSON here; load it in "
                        "Perfetto (ui.perfetto.dev) or chrome://tracing "
                        "(docs/observability.md). Independent of "
                        "--trace-dir, which captures XLA's op-level "
                        "device trace")
    p.add_argument("--metrics-out", default=None, metavar="PATH",
                   help="write the process-wide metrics registry "
                        "(nmfx.obs.metrics — compile/transfer/dispatch "
                        "counters, serve latency histograms) as "
                        "Prometheus text exposition after the run; the "
                        "serving engine exposes the same payload live "
                        "via NMFXServer.metrics_text()")
    p.add_argument("--perf-report", action="store_true",
                   help="print the per-dispatch roofline attribution "
                        "report after the run (nmfx.obs.costmodel): "
                        "model FLOPs and bytes moved per solve "
                        "dispatch, achieved FLOP/s, MFU vs the device "
                        "peak, arithmetic intensity, and the "
                        "compute-bound vs bandwidth-bound verdict "
                        "(docs/observability.md 'Performance "
                        "attribution'). Runs the sweep with phase "
                        "timing enabled (the --profile discipline) so "
                        "the attributed walls are honest")
    p.add_argument("--telemetry-dir", default=None, metavar="DIR",
                   help="with --serve-smoke: publish this process's "
                        "telemetry snapshots (registry + instance "
                        "identity + heartbeat) into DIR every "
                        "couple of seconds (ServeConfig.telemetry_dir "
                        "— the fleet-observatory ledger; merge N "
                        "processes with nmfx.obs.aggregate, watch "
                        "them live with nmfx-top DIR; "
                        "docs/observability.md 'Fleet telemetry')")
    p.add_argument("--metrics-port", type=int, default=None,
                   metavar="PORT",
                   help="with --serve-smoke: also serve the registry's "
                        "Prometheus exposition over HTTP on PORT "
                        "(0 = ephemeral, printed to stderr) for "
                        "scraper-based deployments "
                        "(ServeConfig.metrics_port)")
    p.add_argument("--slo", action="store_true",
                   help="with --serve-smoke: print the server's SLO "
                        "burn-rate status (nmfx.obs.slo — "
                        "availability, p99 latency bound, "
                        "goodput/MFU floors as multi-window burn "
                        "rates) to stderr after the run")
    p.add_argument("--flight-dir", default=None, metavar="DIR",
                   help="arm the crash flight recorder's disk dump: on "
                        "a serve scheduler crash or SIGTERM the last "
                        "~4096 structured events (dispatches, retries, "
                        "degradations, fault fires, evictions, "
                        "checkpoint commits) are written here as a "
                        "redacted JSON postmortem "
                        "(docs/observability.md). Recording is always "
                        "on in-process; this only enables writing")
    return p


#: one SIGTERM flight-dump hook per process: repeated in-process
#: main() calls with --flight-dir must not chain a handler per run
_signal_dump_installed = False


def main(argv: list[str] | None = None) -> int:
    """CLI entry. Wraps the run so the process-wide structured tracer
    can never outlive this invocation's ``--trace-out`` — a usage
    error or failing sweep after enable() would otherwise leave every
    later in-process caller silently recording spans."""
    from nmfx.obs import trace as obs_trace

    enabled_before = obs_trace.default_tracer().enabled
    try:
        return _run_cli(argv)
    finally:
        obs_trace.default_tracer().enabled = enabled_before


def _run_cli(argv: list[str] | None = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if not os.path.isfile(args.dataset):
        # a clean, instant usage error instead of a FileNotFoundError /
        # IsADirectoryError traceback from deep inside the reader — and
        # before the jax import and cache-dir creation below
        parser.error(f"dataset not found: {args.dataset}")
    if args.trace_dir and not args.profile:
        parser.error("--trace-dir requires --profile")
    if not args.ks:
        # e.g. a descending range '5-3' parses to no ranks at all
        parser.error("--ks selects no ranks (use e.g. '2-5', '2,3,4' "
                     "or '3')")
    if min(args.ks) < 2:
        # instant usage error instead of the ValueError traceback the API
        # raises for the same input (reference guard: nmf.r:107-108)
        parser.error(f"--ks must all be >= 2, got {min(args.ks)}")
    if args.backend == "pallas" and args.algorithm != "mu":
        parser.error("--backend pallas is only implemented for "
                     "--algorithm mu (use auto)")
    if (args.backend == "packed"
            and args.algorithm not in PACKED_ALGORITHMS):
        parser.error("--backend packed is only implemented for "
                     f"--algorithm {'/'.join(PACKED_ALGORITHMS)} "
                     "(use auto)")
    from nmfx.config import SKETCHED_ALGORITHMS

    if (args.backend == "sketched"
            and args.algorithm not in SKETCHED_ALGORITHMS):
        parser.error("--backend sketched is only implemented for "
                     f"--algorithm {'/'.join(SKETCHED_ALGORITHMS)} "
                     "(the Gram-family updates the projections "
                     "compress)")
    if args.screen:
        if args.algorithm not in SKETCHED_ALGORITHMS:
            parser.error("--screen needs a sketched screening pass, "
                         "which only --algorithm "
                         f"{'/'.join(SKETCHED_ALGORITHMS)} has")
        if args.backend not in ("auto", "vmap"):
            parser.error("--screen runs its exact phase through the "
                         "vmapped driver (the survivor bit-identity "
                         "contract); use --backend auto or vmap")
        if args.screen_keep is None:
            parser.error("--screen requires --screen-keep (how many "
                         "survivors get exact iterations)")
        if not 1 <= args.screen_keep <= args.restarts:
            parser.error(f"--screen-keep must be in [1, --restarts="
                         f"{args.restarts}], got {args.screen_keep}")
        if args.keep_factors:
            parser.error("--screen does not compose with "
                         "--keep-factors (screened-out lanes never "
                         "receive exact iterations, so there is no "
                         "full factor grid to keep)")
    elif args.screen_keep is not None:
        parser.error("--screen-keep requires --screen")
    if args.sketch_dim is not None:
        if args.sketch_dim < 1:
            parser.error("--sketch-dim must be >= 1")
        if args.backend != "sketched" and not args.screen:
            parser.error("--sketch-dim only applies to the compressed "
                         "paths; pass --backend sketched or --screen")
    sparse_input = args.dataset.lower().endswith((".mtx", ".csr.npz"))
    if args.tile_rows is not None or sparse_input:
        from nmfx.config import TILED_ALGORITHMS

        what = ("--tile-rows" if args.tile_rows is not None
                else "sparse inputs")
        if args.algorithm not in TILED_ALGORITHMS:
            parser.error(f"{what} require(s) the Gram-accumulating "
                         f"update family: --algorithm "
                         f"{'/'.join(TILED_ALGORITHMS)}")
        if args.backend in ("pallas", "sketched") or args.screen:
            parser.error(f"{what} stream(s) A tile-by-tile through the "
                         "out-of-core engine; --backend pallas/sketched "
                         "and --screen need the whole matrix device-"
                         "resident — use --backend auto")
        if args.feature_shards > 1 or args.sample_shards > 1 \
                or args.restart_shards is not None:
            parser.error(f"{what} do(es) not compose with --restart-"
                         "shards/--feature-shards/--sample-shards (the "
                         "tile stream owns one device; shard across "
                         "processes with nmfx.distributed instead)")
        if args.exec_cache or args.warm_shapes or args.cache_dir \
                or args.pipeline_ranks:
            parser.error(f"{what} do(es) not compose with --exec-cache/"
                         "--warm-shapes/--cache-dir/--pipeline-ranks "
                         "(the bucketed executable cache dispatches "
                         "whole-matrix device solves)")
        if args.serve_smoke:
            parser.error(f"{what} do(es) not compose with --serve-smoke "
                         "(served requests dispatch through the "
                         "executable cache)")
        if args.grid_exec == "grid":
            parser.error(f"{what} solve(s) per rank over the tile "
                         "stream; --grid-exec grid demands the whole-"
                         "grid scheduler — use auto")
    elif args.tile_budget_bytes is not None:
        parser.error("--tile-budget-bytes requires --tile-rows (or a "
                     "sparse .mtx/.csr.npz input)")
    if args.tile_budget_bytes is not None:
        from nmfx import tiles

        try:
            tiles.set_tile_budget_bytes(args.tile_budget_bytes)
        except ValueError as e:
            parser.error(str(e))
    if args.backend == "sketched" or args.screen:
        # compose-guards for the statistical-contract paths: every
        # surface whose contract is BIT-EXACT (or whose resume replays
        # exact records) refuses the approximate engine loudly instead
        # of silently serving it
        if args.rank_selection == "device":
            parser.error("--backend sketched/--screen carry a "
                         "STATISTICAL accuracy contract; "
                         "--rank-selection device exists for bit-exact "
                         "pipelines — use the host path")
        if args.checkpoint_dir is not None:
            parser.error("--backend sketched/--screen do not compose "
                         "with --checkpoint-dir (the durable ledger "
                         "replays per-chunk records bit-identically; "
                         "the sketched/screened paths are whole-pool "
                         "and statistical)")
        if args.serve_smoke:
            parser.error("--serve-smoke gates served results "
                         "bit-identical to the direct path; the "
                         "sketched/screened engines are statistical — "
                         "drop --backend sketched/--screen")
        if (args.exec_cache or args.warm_shapes or args.cache_dir
                or args.pipeline_ranks):
            parser.error("--backend sketched/--screen are not exec-"
                         "cacheable (no slot-scheduled form; see "
                         "ExecCache.cacheable) — drop --exec-cache/"
                         "--warm-shapes/--cache-dir/--pipeline-ranks")
        if args.grid_exec == "grid":
            parser.error("--grid-exec grid demands the whole-grid slot "
                         "scheduler, which has no sketched/screened "
                         "form; use auto (falls back per-k)")
        if args.feature_shards > 1 or args.sample_shards > 1:
            parser.error("--backend sketched/--screen are restart-"
                         "parallel only (per-restart projections have "
                         "no feature/sample-sharded formulation)")
    if args.verbose:
        import logging

        logging.basicConfig(format="%(message)s")
        logging.getLogger("nmfx").setLevel(logging.INFO)
    if args.compile_cache and not args.no_compile_cache:
        # must precede the first compile; config-level set works even if
        # jax was already imported (unlike the env var). Also drop the
        # min-compile-time gate so the small per-rank executables cache too.
        # Best-effort: an unwritable cache path (read-only HOME in a
        # container) degrades to no caching, never blocks solving
        try:
            os.makedirs(args.compile_cache, exist_ok=True)
        except OSError as e:
            print(f"nmfx: compilation cache disabled ({e})", file=sys.stderr)
        else:
            import jax

            jax.config.update("jax_compilation_cache_dir",
                              args.compile_cache)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.1)
    from nmfx.api import nmfconsensus  # deferred: keeps --help fast

    output = None
    if not args.no_files:
        output = OutputConfig(directory=args.outdir,
                              write_plots=not args.no_plots)
    from nmfx.config import SolverConfig
    from nmfx.profiling import NullProfiler, Profiler

    # --perf-report needs the profiled (phase-synced) run: attribution
    # only annotates dispatches whose walls a real Profiler measured
    profiler = (Profiler(trace_dir=args.trace_dir)
                if args.profile or args.perf_report else NullProfiler())
    if args.flight_dir:
        from nmfx.obs import flight

        flight.configure(args.flight_dir)
        global _signal_dump_installed
        if not _signal_dump_installed:
            flight.install_signal_dump()
            _signal_dump_installed = True
    if args.trace_out:
        from nmfx.obs import trace as obs_trace

        # fresh ring: an earlier in-process run's spans must not leak
        # into this run's exported trace
        obs_trace.default_tracer().clear()
        obs_trace.enable()
    if args.feature_shards < 1 or args.sample_shards < 1:
        parser.error("--feature-shards/--sample-shards must be >= 1")
    if args.restart_shards is not None and args.restart_shards < 1:
        parser.error("--restart-shards must be >= 1")
    mesh = None
    if args.feature_shards > 1 or args.sample_shards > 1:
        if args.no_mesh:
            parser.error("--feature-shards/--sample-shards conflict with "
                         "--no-mesh")
        from nmfx.sweep import GRID_SOLVERS, grid_mesh

        grid_ok = (args.algorithm == "mu"
                   and args.backend in ("auto", "packed")) \
            or args.algorithm in GRID_SOLVERS
        if not grid_ok:
            parser.error("--feature-shards/--sample-shards require "
                         "--algorithm mu with --backend auto or packed, "
                         f"or one of {'/'.join(GRID_SOLVERS)}")

        if args.keep_factors:
            parser.error("--keep-factors is not supported with grid shards "
                         "(gathering every restart's full factors would "
                         "defeat the memory bound; use nmfx.restart_factors "
                         "to recompute single restarts)")
        try:
            mesh = grid_mesh(args.restart_shards, args.feature_shards,
                             args.sample_shards)
        except ValueError as e:
            parser.error(str(e))
    elif args.restart_shards is not None:
        # restart-only mesh: communication-avoiding data parallelism
        # over exactly N devices (auto mesh uses ALL devices; pinning N
        # is the reproducible-placement / benchmark-protocol knob)
        if args.no_mesh:
            parser.error("--restart-shards conflicts with --no-mesh")
        from nmfx.sweep import grid_mesh

        try:
            mesh = grid_mesh(args.restart_shards, 1, 1)
        except ValueError as e:
            parser.error(str(e))
    # ONE SolverConfig for warmup and the run: the exec-cache key hashes
    # it, so warming with a copy that could drift from the run's config
    # would silently compile a never-hit executable
    from nmfx.config import ExperimentalConfig, SketchConfig

    run_scfg = SolverConfig(algorithm=args.algorithm,
                            max_iter=args.maxiter,
                            matmul_precision=args.precision,
                            backend=args.backend,
                            restart_chunk=args.restart_chunk,
                            check_block=args.check_block,
                            sketch=(SketchConfig(dim=args.sketch_dim)
                                    if args.sketch_dim is not None
                                    else SketchConfig()),
                            screen=args.screen,
                            screen_keep=args.screen_keep,
                            tile_rows=args.tile_rows,
                            experimental=ExperimentalConfig(
                                autotune=("on" if args.autotune
                                          else "off")))
    ckpt_cfg = None
    if args.checkpoint_every is not None and args.checkpoint_every < 1:
        parser.error("--checkpoint-every must be >= 1")
    if args.checkpoint_dir is not None:
        # compose-guards mirror the --cache-dir discipline: reject
        # combinations the durable engine cannot honor instead of
        # silently dropping a flag
        if args.keep_factors:
            parser.error("--checkpoint-dir does not compose with "
                         "--keep-factors (the ledger persists per-"
                         "restart stats and best candidates, not every "
                         "factor stack; use nmfx.restart_factors to "
                         "recompute any restart exactly)")
        if mesh is not None:
            parser.error("--checkpoint-dir does not compose with "
                         "--feature-shards/--sample-shards (the chunk "
                         "executor owns its execution plan; use "
                         "nmfx.distributed's elastic shard runner for "
                         "multi-device durable sweeps)")
        from nmfx.config import CheckpointConfig

        ckpt_cfg = CheckpointConfig(directory=args.checkpoint_dir,
                                    every_n_restarts=args.checkpoint_every,
                                    resume=(True if args.resume is None
                                            else args.resume))
    elif args.checkpoint_every is not None:
        parser.error("--checkpoint-every requires --checkpoint-dir")
    elif args.resume is not None:
        # reject-don't-drop, like --checkpoint-every above: a silently
        # ignored --no-resume would leave the user believing the ledger
        # was cleared
        parser.error("--resume/--no-resume require --checkpoint-dir")
    if args.result_cache_dir is not None and args.keep_factors:
        # reject-don't-drop: the result cache refuses factor-retaining
        # results (result_cache.cacheable), so the flag would be
        # silently inert
        parser.error("--result-cache-dir does not compose with "
                     "--keep-factors (results retaining every "
                     "restart's factor stacks are not admitted to the "
                     "result cache; drop one of the flags)")
    exec_cache = None
    warm_task = None
    if args.input_cache_bytes is not None:
        if args.input_cache_bytes < 0:
            parser.error("--input-cache-bytes must be >= 0 "
                         "(0 disables retention)")
        from nmfx.data_cache import default_cache

        default_cache().resize(max_bytes=args.input_cache_bytes)
    if args.warm_cache and not args.warm_shapes:
        parser.error("--warm-cache backgrounds the --warm-shapes warmup; "
                     "pass --warm-shapes with the shapes to pre-compile")
    # fleet-telemetry flags ride the serving engine's config: without
    # a server there is no publisher/endpoint/SLO engine to configure
    # — reject-don't-drop, the compose-guard discipline
    if args.telemetry_dir is not None and not args.serve_smoke:
        parser.error("--telemetry-dir configures the serving engine's "
                     "telemetry publisher (ServeConfig.telemetry_dir); "
                     "pass --serve-smoke")
    if args.metrics_port is not None and not args.serve_smoke:
        parser.error("--metrics-port configures the serving engine's "
                     "Prometheus endpoint (ServeConfig.metrics_port); "
                     "pass --serve-smoke")
    if args.metrics_port is not None \
            and not 0 <= args.metrics_port <= 65535:
        parser.error("--metrics-port must be in [0, 65535]")
    if args.slo and not args.serve_smoke:
        parser.error("--slo reports the serving engine's SLO burn "
                     "status; pass --serve-smoke")
    if args.replicas is not None:
        # service-tier compose-guards (reject-don't-drop)
        if not args.serve_smoke:
            parser.error("--replicas runs the serving engine behind "
                         "the router front door; pass --serve-smoke")
        if args.replicas < 1:
            parser.error("--replicas must be >= 1")
        if args.metrics_port is not None:
            parser.error("--metrics-port does not compose with "
                         "--replicas (N in-process replica servers "
                         "cannot share one HTTP port; scrape the "
                         "merged fleet via --telemetry-dir + "
                         "nmfx.obs.aggregate instead)")
        if args.replica_mesh is not None:
            specs = [s.strip() for s in args.replica_mesh.split(",")]
            if len(specs) != args.replicas:
                parser.error(f"--replica-mesh names {len(specs)} "
                             f"spec(s) for --replicas {args.replicas} "
                             "— one entry per replica ('-' = plain "
                             "1-device)")
            from nmfx.distributed import MeshSpecError, parse_mesh_spec

            for spec in specs:
                if spec in ("-", ""):
                    continue
                try:
                    parse_mesh_spec(spec)
                except MeshSpecError as e:
                    parser.error(f"--replica-mesh: {e}")
            args.replica_mesh_specs = tuple(
                None if s in ("-", "") else s for s in specs)
        else:
            args.replica_mesh_specs = None
    elif args.router_spill_dir is not None:
        parser.error("--router-spill-dir configures the replica "
                     "pool's ledger; pass --replicas")
    elif args.replica_mesh is not None:
        parser.error("--replica-mesh shapes the replica pool's device "
                     "ownership; pass --serve-smoke --replicas N")
    if args.serve_smoke:
        if mesh is not None:
            parser.error("--serve-smoke owns ONE device (the serving "
                         "scheduler's contract); drop "
                         "--restart-shards/--feature-shards/"
                         "--sample-shards (mesh-tier serving is "
                         "per-REPLICA: --replicas N --replica-mesh ...)")
        if args.checkpoint_dir is not None:
            parser.error("--serve-smoke does not compose with "
                         "--checkpoint-dir (served requests dispatch "
                         "through the executable cache, which bypasses "
                         "the durable-ledger resume path)")
        if args.keep_factors:
            parser.error("--serve-smoke does not compose with "
                         "--keep-factors (served results carry the best "
                         "restart's factors only)")
        if args.rank_selection == "device":
            parser.error("--serve-smoke harvests on the host (the "
                         "completion workers run hclust/cophenetic "
                         "there); drop --rank-selection device")
        if args.grid_exec == "per_k":
            parser.error("--serve-smoke does not compose with "
                         "--grid-exec per_k (served requests dispatch "
                         "through the whole-grid scheduler; per-k "
                         "outputs differ by float tolerance, which "
                         "would break the serve exactness contract)")
    if (args.exec_cache or args.warm_shapes or args.cache_dir
            or args.pipeline_ranks or args.serve_smoke):
        from nmfx.config import ConsensusConfig, ExecCacheConfig, InitConfig
        from nmfx.exec_cache import ExecCache
        from nmfx.sweep import default_mesh

        if mesh is not None:
            parser.error("--exec-cache does not compose with "
                         "--restart-shards/--feature-shards/"
                         "--sample-shards (the grid builders do their "
                         "own shape padding, and the cache tier "
                         "already restart-shards over all devices)")
        if args.checkpoint_dir is not None:
            # sweep() routes checkpointed runs past the cache — erroring
            # here beats silently paying the warmup compile twice
            parser.error("--exec-cache/--warm-shapes do not compose with "
                         "--checkpoint-dir (checkpointed sweeps dispatch "
                         "per (rank, restart-chunk) through the durable "
                         "ledger, which bypasses the bucketed "
                         "executable cache)")
        ecfg = ExecCacheConfig(cache_dir=args.cache_dir,
                               pipeline_ranks=args.pipeline_ranks)
        exec_cache = ExecCache(ecfg)
        if args.warm_shapes:
            cache_mesh = None if args.no_mesh else default_mesh()
            # must mirror nmfconsensus' own ConsensusConfig construction
            # field-for-field (same key requirement as run_scfg above) —
            # wire any new sweep-shaping CLI flag into BOTH
            warm_ccfg = ConsensusConfig(
                ks=args.ks, restarts=args.restarts, seed=args.seed,
                label_rule=args.label_rule, linkage=args.linkage,
                keep_factors=args.keep_factors,
                grid_exec=args.grid_exec, grid_slots=args.grid_slots,
                grid_tail_slots=args.grid_tail_slots)
            if not exec_cache.cacheable(warm_ccfg, run_scfg, cache_mesh):
                parser.error(
                    "--warm-shapes needs an exec-cacheable configuration "
                    "(an algorithm/backend the whole-grid scheduler runs "
                    "— see ExecCache.cacheable)")
            if args.warm_cache:
                # background: compiles overlap dataset loading; the run's
                # own bucket de-duplicates against the in-flight warm
                warm_task = exec_cache.warm(
                    args.warm_shapes, warm_ccfg, run_scfg,
                    InitConfig(method=args.init), cache_mesh,
                    background=True)
                print(f"nmfx: warming {len(args.warm_shapes)} shape(s) "
                      "in the background", file=sys.stderr)
            else:
                for rec in exec_cache.warm(args.warm_shapes, warm_ccfg,
                                           run_scfg,
                                           InitConfig(method=args.init),
                                           cache_mesh):
                    print(_warm_line(rec), file=sys.stderr)
    with profiler:
        if args.serve_smoke:
            result = _serve_smoke(args, run_scfg, exec_cache, output,
                                  profiler)
        else:
            result = nmfconsensus(
                args.dataset,
                ks=args.ks,
                restarts=args.restarts,
                seed=args.seed,
                solver_cfg=run_scfg,
                init=args.init,
                label_rule=args.label_rule,
                linkage=args.linkage,
                mesh=mesh,
                use_mesh=not args.no_mesh,
                rank_selection=args.rank_selection,
                keep_factors=args.keep_factors,
                grid_exec=args.grid_exec,
                grid_slots=args.grid_slots,
                grid_tail_slots=args.grid_tail_slots,
                output=output,
                checkpoint=ckpt_cfg,
                profiler=profiler,
                exec_cache=exec_cache,
                result_cache=args.result_cache_dir,
            )
    if warm_task is not None and args.cache_dir:
        # with a persistent cache dir, joining is worth the wait: every
        # warmed bucket lands on disk for FUTURE processes. Without one
        # the daemon warm dies with the process (nothing to keep). The
        # warm is best-effort — a failure must not discard the completed
        # run's results below
        try:
            for rec in warm_task.result():
                print(_warm_line(rec), file=sys.stderr)
        except Exception as e:
            from nmfx.faults import warn_once

            warn_once("cli-background-warm",
                      f"background warmup failed ({e}); the run "
                      "itself is unaffected")
    if args.save_result:
        result.save(args.save_result)
    print(result.summary())
    if args.profile:
        print(profiler.report())
    if args.perf_report:
        from nmfx.obs import costmodel as obs_costmodel

        # --profile already embeds the same table in its report; avoid
        # printing it twice
        if not args.profile:
            print(obs_costmodel.perf_report())
    if args.trace_out:
        tracer = obs_trace.default_tracer()
        obs_trace.disable()  # also restored on error paths by main()
        tracer.export(args.trace_out)
        print(f"nmfx: structured trace ({tracer.event_count()} events"
              + (f", {tracer.dropped} dropped" if tracer.dropped
                 else "")
              + f") written to {args.trace_out} — load in Perfetto "
              "(ui.perfetto.dev) or chrome://tracing", file=sys.stderr)
    if args.metrics_out:
        from nmfx.obs import metrics as obs_metrics

        with open(args.metrics_out, "w") as f:
            f.write(obs_metrics.registry().prometheus_text())
        print(f"nmfx: metrics written to {args.metrics_out} "
              "(Prometheus text exposition)", file=sys.stderr)
    return 0


def _serve_smoke(args, run_scfg, exec_cache, output, profiler):
    """Route the run through the multi-tenant serving engine: ONE
    request down the same queue → pack → dispatch → harvest path
    concurrent tenants share (nmfx/serve.py), then report the serve
    counters and this request's spans. Results are bit-identical to the
    direct path — the serving exactness contract (docs/serving.md
    "Serving front-end") — which is exactly what makes this a smoke
    test: same output, with the serving machinery in the loop."""
    from nmfx.api import save_results
    from nmfx.config import InitConfig
    from nmfx.serve import NMFXServer, ServeConfig

    if args.replicas is not None:
        return _serve_smoke_router(args, run_scfg, exec_cache, output,
                                   profiler)
    serve_cfg = ServeConfig(telemetry_dir=args.telemetry_dir,
                            metrics_port=args.metrics_port,
                            result_cache_dir=args.result_cache_dir)
    with NMFXServer(serve_cfg, exec_cache=exec_cache,
                    profiler=profiler) as srv:
        if srv.metrics_port is not None:
            print(f"nmfx: serving /metrics on 127.0.0.1:"
                  f"{srv.metrics_port}", file=sys.stderr)
        fut = srv.submit(args.dataset, ks=args.ks,
                         restarts=args.restarts, seed=args.seed,
                         solver_cfg=run_scfg,
                         init_cfg=InitConfig(method=args.init),
                         label_rule=args.label_rule,
                         linkage=args.linkage,
                         grid_slots=args.grid_slots,
                         grid_tail_slots=args.grid_tail_slots)
        result = fut.result()
        if args.slo:
            slo_status = srv.stats_snapshot()["slo"]
            for name, obj in sorted(slo_status["objectives"].items()):
                burns = " ".join(
                    f"{w}={'n/a' if b is None else round(b, 3)}"
                    for w, b in obj["burn"].items())
                print(f"nmfx: slo {name}: state={obj['state']} "
                      f"burn[{burns}]", file=sys.stderr)
    if args.telemetry_dir is not None:
        print(f"nmfx: telemetry published to {args.telemetry_dir} "
              f"(fleet view: nmfx-top {args.telemetry_dir})",
              file=sys.stderr)
    s = srv.stats()
    st = fut.stats

    def fmt(v):
        return "n/a" if v is None else f"{v:.3f}s"

    print("nmfx: serve-smoke: submitted="
          f"{s['submitted']} completed={s['completed']} "
          f"dispatches={s['dispatches']} "
          f"packed_dispatches={s['packed_dispatches']} "
          f"packing_efficiency={s['packing_efficiency']}"
          + (f" result_cache_hits={s['result_cache_hits']}"
             f" coalesced={s['coalesced']}"
             if args.result_cache_dir is not None else ""),
          file=sys.stderr)
    print("nmfx: serve-smoke spans: "
          f"queue-wait={fmt(st.queue_wait_s)} pack={fmt(st.pack_s)} "
          f"solve={fmt(st.solve_s)} harvest={fmt(st.harvest_s)} "
          f"latency={fmt(st.latency_s)}", file=sys.stderr)
    if output is not None:
        with profiler.phase("write_outputs"):
            save_results(result, output)
    return result


def _serve_smoke_router(args, run_scfg, exec_cache, output, profiler):
    """The service-tier smoke: the same single request through an
    ``NMFXRouter`` over ``--replicas`` in-process replica servers —
    results stay bit-identical to the direct path (the serving
    exactness contract holds THROUGH the router), and the router's
    placement/failover books are reported."""
    import tempfile

    from nmfx.api import save_results
    from nmfx.config import InitConfig
    from nmfx.replica import ReplicaPool
    from nmfx.router import NMFXRouter, RouterConfig
    from nmfx.serve import ServeConfig

    import shutil

    ephemeral = args.router_spill_dir is None
    root = args.router_spill_dir if not ephemeral \
        else tempfile.mkdtemp(prefix="nmfx-router-")
    pool = ReplicaPool(
        args.replicas, root=root, mode="thread",
        serve_cfg=ServeConfig(),
        exec_cache=exec_cache, telemetry_dir=args.telemetry_dir,
        mesh_specs=getattr(args, "replica_mesh_specs", None))
    try:
        with NMFXRouter(pool, RouterConfig(
                result_cache_dir=args.result_cache_dir)) as router:
            fut = router.submit(args.dataset, ks=args.ks,
                                restarts=args.restarts, seed=args.seed,
                                solver_cfg=run_scfg,
                                init_cfg=InitConfig(method=args.init),
                                label_rule=args.label_rule,
                                linkage=args.linkage,
                                grid_slots=args.grid_slots,
                                grid_tail_slots=args.grid_tail_slots)
            result = fut.result()
            s = router.stats()
            if args.slo:
                slo_status = router.slo_status(evaluate=True)
                for name, obj in sorted(
                        slo_status["objectives"].items()):
                    burns = " ".join(
                        f"{w}={'n/a' if b is None else round(b, 3)}"
                        for w, b in obj["burn"].items())
                    print(f"nmfx: slo {name}: state={obj['state']} "
                          f"burn[{burns}]", file=sys.stderr)
    finally:
        if ephemeral:
            # an unnamed pool root is run-scoped scratch — don't
            # litter the temp dir with heartbeats/spill subdirs
            shutil.rmtree(root, ignore_errors=True)
    st = fut.stats
    print("nmfx: serve-smoke (router): replicas="
          f"{args.replicas} submitted={s['submitted']} "
          f"completed={s['completed']} retried={s['retried']} "
          f"readmitted={s['readmitted']} "
          f"replica={st.replica} sticky={st.sticky} "
          f"class={st.placement_class} attempts={st.attempts} "
          f"latency={'n/a' if st.latency_s is None else f'{st.latency_s:.3f}s'}",
          file=sys.stderr)
    if args.telemetry_dir is not None:
        print(f"nmfx: telemetry published to {args.telemetry_dir} "
              f"(fleet view: nmfx-top {args.telemetry_dir})",
              file=sys.stderr)
    if output is not None:
        with profiler.phase("write_outputs"):
            save_results(result, output)
    return result


def router_main(argv: "list[str] | None" = None) -> int:
    """``nmfx-router`` — run a dataset's consensus requests through the
    resilient service tier (router + replica pool) and report the
    routing books. The operational entrypoint for the service tier:
    thread replicas for one-process/multi-request serving, subprocess
    replicas (``--mode process``) for the production shape — each
    worker cold-starts against the warm persistent executable cache
    (``--cache-dir``), which is what makes scale-up ~1 s instead of
    ~22 s (docs/serving.md 'Service tier')."""
    import argparse
    import tempfile

    p = argparse.ArgumentParser(
        prog="nmfx-router",
        description="Route consensus requests through the resilient "
                    "service tier: an NMFXRouter front door over N "
                    "replica servers with health-checked failover, "
                    "spill-migration, and SLO-driven shedding.")
    p.add_argument("dataset", help="input .gct or .res file")
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--mode", choices=("thread", "process"),
                   default="thread",
                   help="replica kind: in-process servers (thread) or "
                        "subprocess workers (process)")
    p.add_argument("--requests", type=int, default=1, metavar="R",
                   help="submit R copies of the request with distinct "
                        "seeds (seed, seed+1, ...) — a small traffic "
                        "sample through the tier")
    p.add_argument("--ks", default="2-5", type=parse_ks)
    p.add_argument("--restarts", type=int, default=10)
    p.add_argument("--maxiter", type=int, default=10000)
    p.add_argument("--seed", type=int, default=123)
    p.add_argument("--algorithm", choices=ALGORITHMS, default="mu")
    p.add_argument("--spill-root", default=None, metavar="DIR",
                   help="pool root (spill records + heartbeat ledger; "
                        "default: a temporary directory)")
    p.add_argument("--cache-dir", default=None, metavar="DIR",
                   help="persistent executable cache replicas start "
                        "against (process mode: what makes spawn "
                        "warm)")
    p.add_argument("--telemetry-dir", default=None, metavar="DIR",
                   help="fleet telemetry ledger (watch live with "
                        "nmfx-top DIR)")
    p.add_argument("--autoscale", action="store_true",
                   help="enable the router's metrics-driven "
                        "autoscaler (RouterConfig.autoscale)")
    args = p.parse_args(argv)
    if not os.path.isfile(args.dataset):
        p.error(f"dataset not found: {args.dataset}")
    if args.replicas < 1:
        p.error("--replicas must be >= 1")
    if args.requests < 1:
        p.error("--requests must be >= 1")
    from nmfx.config import ExecCacheConfig, SolverConfig
    from nmfx.replica import ReplicaPool
    from nmfx.router import NMFXRouter, RouterConfig

    exec_cache = None
    if args.cache_dir is not None and args.mode == "thread":
        from nmfx.exec_cache import ExecCache

        exec_cache = ExecCache(ExecCacheConfig(cache_dir=args.cache_dir))
    import shutil

    ephemeral = args.spill_root is None
    root = args.spill_root if not ephemeral \
        else tempfile.mkdtemp(prefix="nmfx-router-")
    pool = ReplicaPool(args.replicas, root=root, mode=args.mode,
                       exec_cache=exec_cache, cache_dir=args.cache_dir,
                       telemetry_dir=args.telemetry_dir)
    scfg = SolverConfig(algorithm=args.algorithm, max_iter=args.maxiter)
    try:
        with NMFXRouter(pool, RouterConfig(
                autoscale=args.autoscale)) as router:
            futs = [router.submit(args.dataset, ks=args.ks,
                                  restarts=args.restarts,
                                  seed=args.seed + i, solver_cfg=scfg)
                    for i in range(args.requests)]
            failed = 0
            for fut in futs:
                try:
                    result = fut.result()
                except Exception as e:  # nmfx: ignore[NMFX006] -- each
                    # outcome is REPORTED per request; the command's
                    # exit code carries the failure
                    failed += 1
                    print(f"nmfx-router: request "
                          f"{fut.stats.request_id} FAILED: {e!r}",
                          file=sys.stderr)
                else:
                    print(f"nmfx-router: request "
                          f"{fut.stats.request_id} "
                          f"ok on {fut.stats.replica} "
                          f"(attempts={fut.stats.attempts})",
                          file=sys.stderr)
                    print(result.summary())
            s = router.stats()
    finally:
        if ephemeral:
            shutil.rmtree(root, ignore_errors=True)
    print("nmfx-router: "
          + " ".join(f"{k}={s[k]}" for k in
                     ("submitted", "completed", "failed", "retried",
                      "readmitted", "drained", "recovered",
                      "routable_replicas")), file=sys.stderr)
    return 1 if failed else 0


def _warm_line(rec: dict) -> str:
    # for disk-served entries report the seconds THIS process paid
    # (deserialize), not the original compile cost stored in the record
    if rec["cache_hit"] and rec.get("source") == "disk":
        return (f"nmfx: warmed bucket {rec['bucket']} for shape "
                f"{rec['shape']} in {rec['deserialize_s']}s "
                "(deserialized from disk cache)")
    note = " (already warm)" if rec["cache_hit"] else ""
    return (f"nmfx: warmed bucket {rec['bucket']} for shape "
            f"{rec['shape']} in {rec['compile_s']}s{note}")


if __name__ == "__main__":
    sys.exit(main())
