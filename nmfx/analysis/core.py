"""nmfx-lint core: findings, the rule registry, suppressions, baselines.

The framework's correctness rests on contracts that crashes never
enforce — a numerics-affecting config field missing from the registry
fingerprint serves stale checkpoints silently (``nmfx/registry.py``), a
trace-time env read bakes a test hook into production executables (the
``NMFX_FAULT_INJECT_STALE_RELOAD`` class, ADVICE.md round 5), a buffer
read after donation returns garbage only on backends that honor
donation (the round-3 ``alias_io`` hazard), and a reused PRNG key
correlates restarts without any numerical signature. Each shipped rule
(``nmfx/analysis/rules_*.py``) encodes one of these observed failure
classes; this module is the machinery they share.

Suppression syntax, on the offending line::

    something_flagged()  # nmfx: ignore[NMFX002] -- why this is safe

The rule id list is comma-separated; the ``-- reason`` is REQUIRED (a
suppression without a recorded justification is itself a finding,
``NMFX000`` — unexplained suppressions rot into "nobody knows why").

Baselines are JSON lists of ``{file, rule, line}`` records
(``--baseline FILE``): findings matching a record are reported as
baselined and do not fail the run. The shipped policy is an EMPTY
baseline — the tree stays clean and the file exists only to adopt the
linter on a dirty branch without blocking it.
"""

from __future__ import annotations

import dataclasses
import json
import re
from typing import Callable, Iterable

#: severity levels: only "error" findings fail the run (exit code /
#: test assertion); "warning" is advisory output
SEVERITIES = ("error", "warning")

#: suppression comment: ``# nmfx: ignore[ID1, ID2] -- reason``
_SUPPRESS_RE = re.compile(
    r"#\s*nmfx:\s*ignore\[(?P<ids>[A-Za-z0-9_,\s]*)\]"
    r"(?:\s*--\s*(?P<reason>.*\S))?")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation, anchored to ``file:line``."""

    file: str
    line: int
    rule_id: str
    message: str
    severity: str = "error"
    col: int = 0
    #: set by the suppression/baseline pass, not by rules
    suppressed: bool = False
    baselined: bool = False

    def render(self) -> str:
        tag = ("" if not (self.suppressed or self.baselined)
               else (" [suppressed]" if self.suppressed else " [baselined]"))
        return (f"{self.file}:{self.line}: {self.rule_id} "
                f"{self.severity}: {self.message}{tag}")

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


class Rule:
    """Base class: one contract class per rule.

    ``check(project)`` yields Findings over a :class:`Project`
    (``nmfx.analysis.ast_scan``). Cross-file rules (NMFX001's
    config/fingerprint cross-reference, the jaxpr layer) see the whole
    project; per-file rules iterate ``project.modules``.
    """

    rule_id: str = "NMFX000"
    title: str = ""
    #: default severity for this rule's findings
    severity: str = "error"

    def check(self, project) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(self, file: str, line: int, message: str,
                severity: "str | None" = None, col: int = 0) -> Finding:
        return Finding(file=file, line=line, rule_id=self.rule_id,
                       message=message, col=col,
                       severity=severity or self.severity)


#: rule_id -> Rule instance. Population happens at import of
#: ``nmfx.analysis`` (each rules_* module registers its rules); the
#: registry is ordered by registration so output is deterministic.
RULES: "dict[str, Rule]" = {}


def register(rule: "Rule | Callable[[], Rule]") -> Rule:
    """Register a rule instance (or zero-arg factory). Usable as a class
    decorator: ``@register`` on a Rule subclass registers an instance."""
    inst = rule() if isinstance(rule, type) else rule
    if inst.rule_id in RULES:
        raise ValueError(f"duplicate rule id {inst.rule_id}")
    RULES[inst.rule_id] = inst
    return rule


def _comment_tokens(path: str, text: str):
    """(lineno, comment_text) for every COMMENT token — suppression
    syntax quoted inside a string literal or docstring must neither
    suppress nor trip NMFX000. Falls back to whole lines on tokenize
    errors (a file broken enough to fail tokenize fails ast.parse too,
    so this path only covers encoding oddities)."""
    import io
    import tokenize

    try:
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type == tokenize.COMMENT:
                yield tok.start[0], tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        for lineno, line in enumerate(text.splitlines(), start=1):
            yield lineno, line


def parse_suppressions(path: str, text: str):
    """``line -> set of suppressed rule ids`` for one source file, plus
    NMFX000 findings for malformed suppressions (missing reason or empty
    id list — those do NOT suppress anything)."""
    by_line: dict[int, set[str]] = {}
    bad: list[Finding] = []
    for lineno, line in _comment_tokens(path, text):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        ids = {s.strip() for s in m.group("ids").split(",") if s.strip()}
        reason = m.group("reason")
        if not ids or not reason:
            bad.append(Finding(
                file=path, line=lineno, rule_id="NMFX000",
                message=("malformed suppression: use '# nmfx: "
                         "ignore[RULE-ID] -- reason' (the reason is "
                         "required; this comment suppresses nothing)"),
                severity="error"))
            continue
        by_line.setdefault(lineno, set()).update(ids)
    return by_line, bad


def load_baseline(path: "str | None") -> "list[dict]":
    if path is None:
        return []
    with open(path) as f:
        records = json.load(f)
    if not isinstance(records, list):
        raise ValueError(f"baseline {path!r} must be a JSON list of "
                         "{file, rule, line} records")
    return records


def apply_baseline(findings: "list[Finding]",
                   baseline: "list[dict]") -> "list[Finding]":
    """Mark findings matching a baseline record. Matching is by
    (file, rule, line) — a moved finding resurfaces, which is the
    point: baselines tolerate known debt, not a file's whole future.
    File paths normalize to absolute before comparing, so a baseline
    written from a relative invocation still applies to an
    absolute-path run (and vice versa) as long as the cwd is the same
    project root."""
    import os

    keys = {(os.path.abspath(str(r.get("file"))), r.get("rule"),
             r.get("line"))
            for r in baseline}
    return [dataclasses.replace(f, baselined=True)
            if (os.path.abspath(f.file), f.rule_id, f.line) in keys
            else f
            for f in findings]


def active(findings: "Iterable[Finding]",
           severity: str = "error") -> "list[Finding]":
    """The findings that fail a run: given severity, not suppressed,
    not baselined."""
    return [f for f in findings
            if f.severity == severity
            and not f.suppressed and not f.baselined]
