"""NMFX013 — static lock-order / deadlock-cycle detection.

Incident class: PR 7's done-callback deadlock — a Future done-callback
running on a thread that still held the scheduler lock called back
into a path that took a second lock, while another thread took the two
in the opposite order. And PR 10's FlightRecorder SIGTERM
self-deadlock: a signal handler re-entering ``record()`` on the same
thread through a non-reentrant lock (fixed by making it an RLock —
whose reentrancy this rule's exemption encodes).

The shared concurrency model extracts the static lock-acquisition
graph: every nested ``with``/``acquire`` (with ``Condition`` aliasing
onto its underlying lock), plus edges through TYPED call-graph edges —
holding lock A while calling a method known to acquire lock B adds
A -> B. Findings:

* a cycle among distinct locks is a potential deadlock (two threads
  walking the cycle from different entry points);
* a self-edge on a NON-reentrant lock is a guaranteed self-deadlock
  (re-acquiring a held ``threading.Lock`` blocks forever); RLock and
  bare-``Condition`` self-edges are exempt — reentrancy is the point.

Resolution is deliberately under-approximate (no by-name fallback — a
false edge would invent deadlocks the code cannot execute); the
runtime witness (``nmfx/analysis/witness.py``) records the orders the
threaded suites ACTUALLY exercise and a completeness test asserts the
static graph covers them.
"""

from __future__ import annotations

from typing import Iterable

from nmfx.analysis.core import Finding, Rule, register
from nmfx.analysis.ast_scan import Project
from nmfx.analysis.concurrency.model import concurrency_model


def _cycles(graph: "dict[str, set]") -> "list[list[str]]":
    """Elementary cycles, one representative per strongly connected
    component (Tarjan, then a shortest closed walk from the smallest
    node) — enough to NAME the deadlock without enumerating every
    rotation of it."""
    index: "dict[str, int]" = {}
    low: "dict[str, int]" = {}
    on: "set[str]" = set()
    stack: "list[str]" = []
    sccs: "list[list[str]]" = []
    counter = [0]

    def strongconnect(v: str) -> None:
        # iterative Tarjan — the lock graph is small, but recursion
        # depth must not depend on it
        work = [(v, iter(sorted(graph.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                elif w in on:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)

    out = []
    for comp in sccs:
        members = set(comp)
        start = comp[0]
        # BFS for the shortest closed walk start -> ... -> start
        frontier = [[start]]
        found = None
        while frontier and found is None:
            nxt = []
            for path in frontier:
                for w in sorted(graph.get(path[-1], ())):
                    if w == start and len(path) > 1:
                        found = path
                        break
                    if w in members and w not in path:
                        nxt.append(path + [w])
                if found:
                    break
            frontier = nxt
        out.append((found or [start]) + [start])
    return out


@register
class LockOrderRule(Rule):
    rule_id = "NMFX013"
    title = "static lock-acquisition graph stays cycle-free"

    def check(self, project: Project) -> "Iterable[Finding]":
        model = concurrency_model(project)
        graph: "dict[str, set]" = {}
        for (a, b), (path, line) in sorted(model.order_edges.items()):
            if a == b:
                li = model.lock_index.get(a)
                if li is not None and not li.reentrant:
                    yield Finding(
                        file=path, line=line, rule_id=self.rule_id,
                        message=(f"non-reentrant lock {a} is acquired "
                                 "while already held on this path — "
                                 "guaranteed self-deadlock (RLock if "
                                 "re-entry is intended)"))
                continue
            graph.setdefault(a, set()).add(b)
        for cycle in _cycles(graph):
            a, b = cycle[0], cycle[1]
            path, line = model.order_edges[(a, b)]
            order = " -> ".join(cycle)
            yield Finding(
                file=path, line=line, rule_id=self.rule_id,
                message=(f"lock-order cycle {order}: two threads "
                         "entering this cycle at different points can "
                         "deadlock; pick one global order and make "
                         "every path follow it"))
