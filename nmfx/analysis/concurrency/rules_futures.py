"""NMFX014 — future-resolution completeness.

Incident class: the stranded-Future family the serve watchdog exists
to mop up — a ``Future`` handed to a waiter whose producer died
between registering it and completing the hand-off protocol. The PR-7
scheduler death left every queued future hanging forever; the
ProcessReplica forward path writes a spill record AFTER registering
the future, and a failed write without the unregister-and-reraise
would strand the waiter just as silently.

The rule checks every function that constructs a ``Future`` (or an
in-module subclass — ``_ServeFuture``/``_RouterFuture``):

* **dead future** — a constructed future that is never resolved
  (``set_result``/``set_exception``), returned, stored, or passed
  anywhere strands its waiter by construction;
* **unprotected publication gap** — once the future is PUBLISHED into
  an instance attribute (a pending map, a queue the scheduler drains),
  the publisher still owns the hand-off until the consumer can see a
  complete record; any later statement that can raise must sit under a
  handler that resolves the future or unpublishes it (references the
  future or the published container). Lock/condition operations and
  calls on the future itself are exempt — they are the hand-off.

The gap check is lexical (line-ordered, nested ``def`` bodies
excluded — they run later); a branch-exclusive path the analysis
cannot see is exactly what an inline suppression with a reason is
for.
"""

from __future__ import annotations

import ast
from typing import Iterable

from nmfx.analysis.core import Finding, Rule, register
from nmfx.analysis.ast_scan import Project, _attr_tail, own_nodes
from nmfx.analysis.concurrency.model import concurrency_model

#: calls that cannot meaningfully fail mid-hand-off: lock/condition
#: protocol ops and queue/container inserts (the hand-off itself), and
#: the observability layer (counters, gauges, flight-recorder events —
#: designed to never raise into the serving path)
_SAFE_TAILS = {"notify", "notify_all", "acquire", "release", "wait",
               "locked", "append", "appendleft", "add", "setdefault",
               "put", "put_nowait", "inc", "set", "observe", "record",
               "mark",
               # non-raising builtins on in-memory values
               "len", "str", "int", "float", "bool", "repr", "sorted",
               "list", "tuple", "dict", "min", "max", "isinstance"}


def _names_in(node: ast.AST) -> "set[str]":
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _attrs_in(node: ast.AST) -> "set[str]":
    return {n.attr for n in ast.walk(node) if isinstance(n, ast.Attribute)}


def _own_statements(fn: ast.AST) -> "list[tuple[ast.stmt, list]]":
    """(statement, ancestor chain) for every statement in the function
    body, nested function bodies EXCLUDED (they run later, on another
    thread — their exceptions are not this function's exception
    paths)."""
    out: "list[tuple[ast.stmt, list]]" = []

    def walk(body, chain):
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            out.append((stmt, chain))
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if sub:
                    walk(sub, chain + [stmt])
            for handler in getattr(stmt, "handlers", []) or []:
                walk(handler.body, chain + [stmt])

    walk(fn.body, [])
    return out


def _protecting_try(chain: "list[ast.stmt]", fname: str,
                    published_attr: "str | None") -> bool:
    """Is the statement under a handler/finally that disposes the
    future — resolves ``fname`` or touches the published container?"""
    for anc in chain:
        if not isinstance(anc, ast.Try):
            continue
        bodies = [h.body for h in anc.handlers]
        if anc.finalbody:
            bodies.append(anc.finalbody)
        for body in bodies:
            for stmt in body:
                if fname in _names_in(stmt):
                    return True
                if (published_attr is not None
                        and published_attr in _attrs_in(stmt)):
                    return True
    return False


def _check_function(mod_path: str, qual: str, fn: ast.AST,
                    creations, rule_id: str) -> "Iterable[Finding]":
    stmts = _own_statements(fn)
    for crt in creations:
        fname = crt.name
        if fname is None:
            continue
        resolved_line = None
        published = None  # (line, attr name of the container)
        disposed = False
        for stmt, chain in stmts:
            if stmt.lineno < crt.line:
                continue
            names = _names_in(stmt)
            if fname not in names:
                continue
            # resolution: f.set_result(...) / futs[k].set_exception(...)
            for node in ast.walk(stmt):
                if (isinstance(node, ast.Call)
                        and _attr_tail(node.func) in ("set_result",
                                                      "set_exception")
                        and fname in _names_in(node.func)):
                    disposed = True
                    if resolved_line is None:
                        resolved_line = stmt.lineno
            if isinstance(stmt, (ast.Return, ast.Expr)) and isinstance(
                    getattr(stmt, "value", None), ast.AST):
                if fname in _names_in(stmt.value):
                    disposed = True
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call):
                    argnames = set()
                    for a in list(node.args) + [kw.value
                                                for kw in node.keywords]:
                        argnames |= _names_in(a)
                    if fname in argnames:
                        disposed = True  # ownership passed along
            if isinstance(stmt, ast.Assign) and fname in _names_in(
                    stmt.value):
                for tgt in stmt.targets:
                    if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                        disposed = True
                        attrs = _attrs_in(tgt)
                        if attrs and published is None:
                            published = (stmt.lineno,
                                         sorted(attrs - {fname})[0]
                                         if sorted(attrs - {fname})
                                         else None)
        # the constructor call may itself be the transfer:
        # _Pending(future=_ServeFuture(...)) hands the future to the
        # wrapper the moment it exists
        if not disposed:
            yield Finding(
                file=mod_path, line=crt.line, rule_id=rule_id,
                message=(f"{qual} constructs a Future bound to "
                         f"{fname!r} but never resolves, returns, "
                         "stores, or passes it — its waiter can only "
                         "hang"))
            continue
        if published is None or resolved_line is not None:
            continue
        pub_line, pub_attr = published
        # gap scan: risky statements after publication
        for stmt, chain in stmts:
            if stmt.lineno <= pub_line:
                continue
            risky = None
            for node in own_nodes(stmt):
                if not isinstance(node, ast.Call):
                    continue
                tail = _attr_tail(node.func)
                if tail in _SAFE_TAILS or tail in ("set_result",
                                                   "set_exception"):
                    continue
                if fname in _names_in(node):
                    continue
                risky = node
                break
            if risky is None:
                continue
            if _protecting_try(chain, fname, pub_attr):
                continue
            yield Finding(
                file=mod_path, line=pub_line, rule_id=rule_id,
                message=(f"{qual} publishes Future {fname!r} into "
                         f"self.{pub_attr} and then calls "
                         f"{_attr_tail(risky.func) or 'a function'}() "
                         f"at line {stmt.lineno} with no handler that "
                         "resolves or unpublishes it — an exception "
                         "there strands the waiter"))
            break


@register
class FutureResolutionRule(Rule):
    rule_id = "NMFX014"
    title = "every owned Future resolves on every path"

    def check(self, project: Project) -> "Iterable[Finding]":
        model = concurrency_model(project)
        for (mod_path, qual), mm in sorted(model.functions.items()):
            if not mm.futures:
                continue
            yield from _check_function(mod_path, qual, mm.node,
                                       mm.futures, self.rule_id)
