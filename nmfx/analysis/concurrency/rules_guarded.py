"""NMFX012 — guarded-state discipline.

Incident class: the close()-vs-submit admission race and the PR-15
spill-claim / stale-read-breaker races — shared mutable state of a
threaded class touched outside its owning lock. The locking discipline
used to live in comments ("guarded by _lock"); a comment cannot fail a
build. ``@guarded_by("_lock", "_queue", ...)`` (``nmfx/guards.py``)
turns the comment into a declaration, and this rule turns every access
to a declared attribute outside a ``with self._lock`` scope into a
finding.

The analysis is statement-ordered and scope-aware through the shared
concurrency model: ``Condition(self._lock)`` aliases collapse onto the
underlying lock, ``l = self._lock`` local aliases are followed,
``acquire()``/``release()`` pairs extend the region linearly, a nested
``def`` (done-callbacks) resets the held set to nothing (it runs later
on an unknown thread), and a PRIVATE helper called exclusively from
lock-holding sites inherits the intersection of its callers' held sets
(the ``_expire_locked`` convention, checked instead of trusted).
``__init__`` is exempt: publication of ``self`` happens-after
construction. Module-level state declared via ``module_guarded()`` is
checked the same way against its module-level lock.
"""

from __future__ import annotations

from typing import Iterable

from nmfx.analysis.core import Finding, Rule, register
from nmfx.analysis.ast_scan import Project
from nmfx.analysis.concurrency.model import concurrency_model


@register
class GuardedStateRule(Rule):
    rule_id = "NMFX012"
    title = "guarded attributes accessed only under their lock"

    def check(self, project: Project) -> "Iterable[Finding]":
        model = concurrency_model(project)
        for cm in model.classes.values():
            if not cm.guarded:
                continue
            # stale declarations: a guard lock that is never created is
            # a discipline the rule cannot check — loudly, not silently
            for lock_attr in sorted(set(cm.guarded.values())):
                if lock_attr not in cm.locks:
                    yield Finding(
                        file=cm.module.path, line=cm.node.lineno,
                        rule_id=self.rule_id,
                        message=(f"{cm.name} declares attributes "
                                 f"guarded by self.{lock_attr}, but no "
                                 f"method ever creates that lock "
                                 "(threading.Lock/RLock/Condition)"))
            for name in sorted(cm.methods):
                if name == "__init__":
                    continue
                mm = model.functions.get(
                    (cm.module.path, f"{cm.name}.{name}"))
                if mm is None:
                    continue
                for attr, line, held, nested in mm.accesses:
                    lock_attr = cm.guarded.get(attr)
                    key = cm.lock_key(lock_attr) if lock_attr else None
                    if key is None or key in held:
                        continue
                    where = (f"{cm.name}.{name}"
                             + (" (nested callback — locks held at the"
                                " definition site are NOT held when it"
                                " runs)" if nested else ""))
                    yield Finding(
                        file=cm.module.path, line=line,
                        rule_id=self.rule_id,
                        message=(f"self.{attr} is guarded by "
                                 f"self.{lock_attr} but accessed "
                                 f"without it in {where}"))
        for mod in project.modules:
            guarded = model.module_guarded.get(mod.path)
            if not guarded:
                continue
            locks = model.module_locks.get(mod.path, {})
            owner = {name: lock for lock, names in guarded.items()
                     for name in names}
            for lock in guarded:
                if lock not in locks:
                    yield Finding(
                        file=mod.path, line=1, rule_id=self.rule_id,
                        message=(f"module_guarded({lock!r}, ...) names "
                                 "a module-level lock that is never "
                                 "created"))
            for (path, qual), mm in sorted(model.functions.items()):
                if path != mod.path:
                    continue
                for name, line, held, nested in mm.global_accesses:
                    lock = owner[name]
                    li = locks.get(lock)
                    if li is None or li.key in held:
                        continue
                    yield Finding(
                        file=mod.path, line=line, rule_id=self.rule_id,
                        message=(f"module global {name} is guarded by "
                                 f"{lock} but accessed without it in "
                                 f"{qual}"))
