"""Shared concurrency model for the NMFX012-015 rules.

One pass over the project builds everything the four concurrency rules
need — threaded classes with their lock inventory, ``@guarded_by``
declarations, per-method lock-scope events (statement-ordered, with
``Condition`` aliasing, local lock aliases, and ``acquire``/``release``
tracking), a typed cross-class call graph, the interprocedural
held-at-entry fixpoint for private helpers, and the static
lock-acquisition order graph. The model is memoized on the
:class:`~nmfx.analysis.ast_scan.Project` so the rules share it (the
ISSUE 18 satellite: build the graph once per run, not once per rule).

Resolution policy: the lock graph uses TYPED call edges only —
``self.m()``, ``self.attr.m()``/``name.m()`` where the receiver's class
is known from a constructor assignment, an ``AnnAssign`` annotation, or
an annotated parameter, and bare/imported module-level functions. No
by-name fallback: a false lock edge would invent deadlock cycles the
code cannot execute, and the runtime witness
(``nmfx/analysis/witness.py``) covers the under-approximation by
feeding observed acquisition orders back into a completeness test.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Iterable

from nmfx.analysis.ast_scan import ModuleInfo, Project, _attr_tail

#: constructors that create a lock object
_LOCK_CTORS = {"Lock": False, "RLock": True, "Condition": True,
               "Semaphore": False, "BoundedSemaphore": False}


def _mod_stem(mod: ModuleInfo) -> str:
    base = os.path.basename(mod.path)
    return base[:-3] if base.endswith(".py") else base


def _const_str(node: ast.AST) -> "str | None":
    return node.value if (isinstance(node, ast.Constant)
                          and isinstance(node.value, str)) else None


def _self_attr(node: ast.AST) -> "str | None":
    """``self.x`` -> "x", else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


@dataclasses.dataclass
class LockInfo:
    """One lock object: an instance attribute of a class, or a
    module-level global."""

    key: str                 # graph node id, e.g. "serve.NMFXServer._lock"
    attr: str                # attribute / global name
    reentrant: bool
    site: "tuple[str, int]"  # (path, lineno) of the creation call
    #: a Condition built on another declared lock IS that lock — its
    #: key aliases the underlying one and this records the surface name
    alias_of: "str | None" = None


@dataclasses.dataclass
class FutureCreation:
    """One ``Future()`` (or Future-subclass) construction site."""

    line: int
    name: "str | None"       # local name it is bound to (None = unbound)
    published_line: "int | None" = None  # first store into attr/subscript
    disposed: bool = False   # returned / stored / passed / resolved
    gap_line: "int | None" = None  # risky stmt in a published-unresolved gap


@dataclasses.dataclass
class ThreadStart:
    """One ``threading.Thread(...)`` / ``Timer(...)`` construction."""

    line: int
    kind: str                # "Thread" | "Timer"
    daemon: bool
    name: "str | None"       # local binding, if any
    stored_attr: "str | None" = None   # self.<attr> = t / self.<attr>.append(t)
    container: bool = False  # stored via .append / subscript
    joined: bool = False


@dataclasses.dataclass
class MethodModel:
    """Per-function lock-scope analysis results."""

    qual: str                # "ClassName.meth" or "func"
    node: ast.AST
    #: guarded-attr accesses: (attr, line, frozenset(held keys), nested)
    accesses: "list[tuple]" = dataclasses.field(default_factory=list)
    #: module_guarded() global accesses: (name, line, held keys, nested)
    global_accesses: "list[tuple]" = dataclasses.field(
        default_factory=list)
    #: lock acquisitions: (frozenset(held keys), key, line)
    acquisitions: "list[tuple]" = dataclasses.field(default_factory=list)
    #: typed call events: (frozenset(held keys), callee function id, line)
    calls: "list[tuple]" = dataclasses.field(default_factory=list)
    #: class-internal self.m() sites: (callee name, frozenset(held ATTR
    #: names of this class's locks))
    self_calls: "list[tuple]" = dataclasses.field(default_factory=list)
    #: self.m references without a call (callback positions)
    self_refs: "set[str]" = dataclasses.field(default_factory=set)
    futures: "list[FutureCreation]" = dataclasses.field(
        default_factory=list)
    threads: "list[ThreadStart]" = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ClassModel:
    name: str
    module: ModuleInfo
    node: ast.ClassDef
    methods: "dict[str, ast.FunctionDef]" = dataclasses.field(
        default_factory=dict)
    locks: "dict[str, LockInfo]" = dataclasses.field(default_factory=dict)
    #: guarded attr -> owning lock attr (from @guarded_by decorators)
    guarded: "dict[str, str]" = dataclasses.field(default_factory=dict)
    #: self.<attr> -> ClassModel, inferred from constructor assignments
    #: and annotations
    attr_types: "dict[str, 'ClassModel']" = dataclasses.field(
        default_factory=dict)
    #: method -> lock ATTR names provably held at entry (private-helper
    #: fixpoint over in-class call sites)
    entry_held: "dict[str, frozenset]" = dataclasses.field(
        default_factory=dict)
    #: join()/cancel() receivers seen anywhere in the class:
    #: self.<attr> names whose threads are joined on some path
    joined_attrs: "set[str]" = dataclasses.field(default_factory=set)
    #: method names called from OUTSIDE the class through a typed
    #: receiver — their entry-held answer must stay empty
    external_calls: "set[str]" = dataclasses.field(default_factory=set)

    @property
    def key_prefix(self) -> str:
        return f"{_mod_stem(self.module)}.{self.name}"

    def lock_key(self, attr: str) -> "str | None":
        li = self.locks.get(attr)
        if li is None:
            return None
        return li.key


@dataclasses.dataclass
class ConcurrencyModel:
    project: Project
    classes: "dict[tuple, ClassModel]" = dataclasses.field(
        default_factory=dict)   # (module path, class name) -> model
    by_class_name: "dict[str, list]" = dataclasses.field(
        default_factory=dict)
    #: module path -> {global name -> LockInfo}
    module_locks: "dict[str, dict]" = dataclasses.field(
        default_factory=dict)
    #: module path -> {lock global -> guarded global names} from
    #: module_guarded(...) top-level calls
    module_guarded: "dict[str, dict]" = dataclasses.field(
        default_factory=dict)
    #: function id (module path, qual) -> MethodModel
    functions: "dict[tuple, MethodModel]" = dataclasses.field(
        default_factory=dict)
    #: function id -> transitively acquired lock keys
    acquires: "dict[tuple, frozenset]" = dataclasses.field(
        default_factory=dict)
    #: lock key -> LockInfo
    lock_index: "dict[str, LockInfo]" = dataclasses.field(
        default_factory=dict)
    #: directed order edges: (held key, acquired key) -> (path, line)
    #: of the first acquisition/call site that creates the edge
    order_edges: "dict[tuple, tuple]" = dataclasses.field(
        default_factory=dict)

    #: memoized module-level singleton types, keyed by module path
    inst_types: "dict[str, dict]" = dataclasses.field(
        default_factory=dict)

    def _instance_type(self, mod: ModuleInfo,
                       name: str) -> "ClassModel | None":
        """Type of a module-level singleton (``_flight =
        FlightRecorder(...)``), followed through ``from X import``."""
        types = self.inst_types.get(mod.path)
        if types is None:
            types = _module_instance_types(self, mod)
            self.inst_types[mod.path] = types
        if name in types:
            return types[name]
        if name in mod.from_imports:
            src, orig = mod.from_imports[name]
            target = self.project._module_for(src)
            if target is not None and target.path != mod.path:
                return self._instance_type(target, orig)
        return None

    def class_of(self, mod: ModuleInfo, name: str) -> "ClassModel | None":
        """Resolve a class name seen in ``mod`` — local definition
        first, then through ``from X import name``."""
        cm = self.classes.get((mod.path, name))
        if cm is not None:
            return cm
        if name in mod.from_imports:
            src, orig = mod.from_imports[name]
            target = self.project._module_for(src)
            if target is not None:
                return self.classes.get((target.path, orig))
        return None


# ---------------------------------------------------------------------------
# collection

def _guarded_from_decorators(cls: ast.ClassDef) -> "dict[str, str]":
    """Read stacked ``@guarded_by("_lock", "a", "b")`` decorators
    syntactically (no import needed in fixture files)."""
    guarded: "dict[str, str]" = {}
    for dec in cls.decorator_list:
        if not (isinstance(dec, ast.Call)
                and _attr_tail(dec.func) == "guarded_by"
                and dec.args):
            continue
        lock = _const_str(dec.args[0])
        if lock is None:
            continue
        for arg in dec.args[1:]:
            attr = _const_str(arg)
            if attr is not None:
                guarded[attr] = lock
    return guarded


def _lock_ctor(call: ast.AST) -> "tuple[str, bool] | None":
    """``threading.Lock()`` / ``Lock()`` etc -> (ctor name, reentrant)."""
    if not isinstance(call, ast.Call):
        return None
    tail = _attr_tail(call.func)
    if tail in _LOCK_CTORS:
        return tail, _LOCK_CTORS[tail]
    return None


def _collect_class(model: ConcurrencyModel, mod: ModuleInfo,
                   node: ast.ClassDef) -> ClassModel:
    cm = ClassModel(name=node.name, module=mod, node=node,
                    guarded=_guarded_from_decorators(node))
    for item in node.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            cm.methods[item.name] = item
    # lock inventory + attr types, from every method (locks are almost
    # always created in __init__, but a lazy _ensure_started counts too)
    for meth in cm.methods.values():
        for stmt in ast.walk(meth):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                attr = _self_attr(stmt.targets[0])
                if attr is None:
                    continue
                ctor = _lock_ctor(stmt.value)
                if ctor is not None:
                    name, reentrant = ctor
                    alias = None
                    if name == "Condition" and stmt.value.args:
                        alias = _self_attr(stmt.value.args[0])
                    cm.locks[attr] = LockInfo(
                        key=f"{cm.key_prefix}.{attr}", attr=attr,
                        reentrant=reentrant,
                        site=(mod.path, stmt.lineno), alias_of=alias)
            elif isinstance(stmt, ast.AnnAssign):
                attr = _self_attr(stmt.target)
                if attr is not None:
                    ann = stmt.annotation
                    tname = (_const_str(ann)
                             if isinstance(ann, ast.Constant)
                             else (ann.id if isinstance(ann, ast.Name)
                                   else None))
                    if tname:
                        cm.attr_types.setdefault(attr, tname)  # raw name
    # Condition(self._lock) aliases: collapse onto the underlying lock's
    # key so "holding the condition" and "holding the lock" are one node
    for li in cm.locks.values():
        if li.alias_of and li.alias_of in cm.locks:
            base = cm.locks[li.alias_of]
            li.key = base.key
            li.reentrant = base.reentrant
    return cm


class _Ctx:
    """Resolution context for one function body scan."""

    def __init__(self, model: ConcurrencyModel, mod: ModuleInfo,
                 cls: "ClassModel | None"):
        self.model = model
        self.mod = mod
        self.cls = cls
        #: guarded global name -> owning module-level lock name
        self.mod_guarded: "dict[str, str]" = {
            name: lock
            for lock, names in model.module_guarded.get(mod.path,
                                                        {}).items()
            for name in names}
        #: local name -> lock key ("l = self._lock", "with X as l")
        self.lock_aliases: "dict[str, str]" = {}
        #: local name -> ClassModel ("obj = ClassName(...)", annotations)
        self.local_types: "dict[str, ClassModel]" = {}

    def lock_key_of(self, expr: ast.AST) -> "str | None":
        attr = _self_attr(expr)
        if attr is not None and self.cls is not None:
            return self.cls.lock_key(attr)
        if isinstance(expr, ast.Name):
            if expr.id in self.lock_aliases:
                return self.lock_aliases[expr.id]
            li = self.model.module_locks.get(self.mod.path, {}).get(
                expr.id)
            if li is not None:
                return li.key
        return None

    def class_lock_attr(self, expr: ast.AST) -> "str | None":
        """``self._cond`` -> "_lock" (alias-resolved attr name of THIS
        class's lock), for the entry-held fixpoint."""
        attr = _self_attr(expr)
        if attr is None or self.cls is None:
            return None
        li = self.cls.locks.get(attr)
        if li is None:
            return None
        return li.alias_of if li.alias_of in self.cls.locks else attr


def _future_names(mod: ModuleInfo) -> "set[str]":
    """Names that construct a Future in this module: ``Future`` itself
    plus in-module subclasses (transitively)."""
    names = {"Future"}
    changed = True
    while changed:
        changed = False
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef) and node.name not in names:
                if any(_attr_tail(b) in names for b in node.bases):
                    names.add(node.name)
                    changed = True
    return names


class _BodyScan:
    """Statement-ordered lock-scope walker over one function body."""

    def __init__(self, ctx: _Ctx, out: MethodModel,
                 entry_held_keys: "frozenset[str]",
                 entry_held_attrs: "frozenset[str]",
                 future_ctors: "set[str]"):
        self.ctx = ctx
        self.out = out
        self.future_ctors = future_ctors
        self.entry_keys = set(entry_held_keys)
        self.entry_attrs = set(entry_held_attrs)

    # -- expression-level event extraction ---------------------------------
    def _scan_expr_events(self, stmt: ast.stmt, held: "set[str]",
                          held_attrs: "set[str]", nested: bool) -> None:
        from nmfx.analysis.ast_scan import own_nodes

        ctx, out = self.ctx, self.out
        hk = frozenset(held | self.entry_keys)
        ha = frozenset(held_attrs | self.entry_attrs)
        # a lambda body (done-callbacks, sort keys) runs LATER on an
        # unknown thread — locks held lexically here are not held then
        deferred: "set[int]" = set()
        for node in own_nodes(stmt):
            if isinstance(node, ast.Lambda):
                deferred.update(id(sub) for sub in ast.walk(node.body))
        empty = frozenset()
        for node in own_nodes(stmt):
            later = id(node) in deferred
            nhk = empty if later else hk
            nha = empty if later else ha
            nnested = nested or later
            if isinstance(node, ast.Attribute):
                attr = _self_attr(node)
                if (attr is not None and ctx.cls is not None
                        and attr in ctx.cls.guarded):
                    out.accesses.append(
                        (attr, node.lineno, nhk, nnested))
            if (isinstance(node, ast.Name)
                    and node.id in ctx.mod_guarded):
                out.global_accesses.append(
                    (node.id, node.lineno, nhk, nnested))
            if not isinstance(node, ast.Call):
                continue
            tail = _attr_tail(node.func)
            # explicit acquire()/release() on a recognized lock
            if (tail in ("acquire", "release")
                    and isinstance(node.func, ast.Attribute)):
                key = ctx.lock_key_of(node.func.value)
                if key is not None:
                    if tail == "acquire":
                        out.acquisitions.append((nhk, key, node.lineno))
                    continue
            # typed call edges (for the lock graph)
            callee = self._resolve_call(node)
            if callee is not None:
                out.calls.append((nhk, callee, node.lineno))
            # in-class call / reference bookkeeping (entry-held fixpoint)
            if ctx.cls is not None:
                sa = _self_attr(node.func)
                if sa is not None and sa in ctx.cls.methods:
                    out.self_calls.append((sa, nha))
        # self.m references outside call position -> callback escape
        if ctx.cls is not None:
            called = {id(n.func) for n in own_nodes(stmt)
                      if isinstance(n, ast.Call)}
            for node in own_nodes(stmt):
                if (isinstance(node, ast.Attribute)
                        and id(node) not in called):
                    sa = _self_attr(node)
                    if sa is not None and sa in ctx.cls.methods:
                        out.self_refs.add(sa)

    def _resolve_call(self, node: ast.Call) -> "tuple | None":
        """Typed resolution of a call to a project function id —
        (module path, "Class.meth") / (module path, "func"); None when
        the receiver's type is unknown (deliberate under-approximation,
        see module docstring)."""
        ctx = self.ctx
        func = node.func
        if isinstance(func, ast.Name):
            name = func.id
            # constructor of a known class -> its __init__
            cm = ctx.model.class_of(ctx.mod, name)
            if cm is not None:
                if "__init__" in cm.methods:
                    return (cm.module.path, f"{cm.name}.__init__")
                return None
            # module-level function (local or from-imported)
            if name in ctx.mod.functions:
                return (ctx.mod.path, name)
            if name in ctx.mod.from_imports:
                src, orig = ctx.mod.from_imports[name]
                target = ctx.model.project._module_for(src)
                if target is not None and orig in target.functions:
                    return (target.path, orig)
            return None
        if not isinstance(func, ast.Attribute):
            return None
        meth = func.attr
        recv = func.value
        sa = _self_attr(recv)
        if sa is not None and ctx.cls is not None:
            # self.m() handled by self_calls; here: self.attr.m()
            tcm = ctx.cls.attr_types.get(sa)
            if isinstance(tcm, ClassModel) and meth in tcm.methods:
                return (tcm.module.path, f"{tcm.name}.{meth}")
            return None
        if isinstance(recv, ast.Attribute):
            sa2 = _self_attr(recv.value)
            if sa2 is None:
                return None
        if isinstance(recv, ast.Name):
            base = recv.id
            if base == "self" and ctx.cls is not None:
                if meth in ctx.cls.methods:
                    return (ctx.mod.path, f"{ctx.cls.name}.{meth}")
                return None
            # typed local / module-level instance / module alias
            tcm = ctx.local_types.get(base)
            if tcm is not None and meth in tcm.methods:
                return (tcm.module.path, f"{tcm.name}.{meth}")
            inst = ctx.model._instance_type(ctx.mod, base)
            if inst is not None and meth in inst.methods:
                return (inst.module.path, f"{inst.name}.{meth}")
            if base in ctx.mod.module_aliases:
                target = ctx.model.project._module_for(
                    ctx.mod.module_aliases[base])
                if target is not None and meth in target.functions:
                    return (target.path, meth)
        return None

    # -- statement walk ----------------------------------------------------
    def scan(self, body: "list[ast.stmt]", held: "set[str]",
             held_attrs: "set[str]", nested: bool = False) -> None:
        from nmfx.analysis.ast_scan import own_nodes

        ctx, out = self.ctx, self.out
        for stmt in body:
            # nested defs run LATER on an unknown thread: locks held
            # lexically here are NOT held when the body executes
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.scan(stmt.body, set(), set(), nested=True)
                continue
            # local aliases: l = self._lock / obj = ClassName(...)
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                tgt = stmt.targets[0]
                if isinstance(tgt, ast.Name):
                    key = ctx.lock_key_of(stmt.value)
                    if key is not None:
                        ctx.lock_aliases[tgt.id] = key
                    if isinstance(stmt.value, ast.Call):
                        t2 = stmt.value.func
                        name = (t2.id if isinstance(t2, ast.Name)
                                else None)
                        cm = (ctx.model.class_of(ctx.mod, name)
                              if name else None)
                        if cm is not None:
                            ctx.local_types[tgt.id] = cm
            self._scan_expr_events(stmt, held, held_attrs, nested)
            self._scan_futures_threads(stmt, nested)
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                entered: "list[tuple[str, str | None]]" = []
                for item in stmt.items:
                    key = ctx.lock_key_of(item.context_expr)
                    if key is None:
                        continue
                    hk = frozenset(held | self.entry_keys)
                    out.acquisitions.append((hk, key, stmt.lineno))
                    attr = ctx.class_lock_attr(item.context_expr)
                    entered.append((key, attr))
                    if (item.optional_vars is not None
                            and isinstance(item.optional_vars, ast.Name)):
                        ctx.lock_aliases[item.optional_vars.id] = key
                inner = set(held) | {k for k, _ in entered}
                inner_attrs = set(held_attrs) | {
                    a for _, a in entered if a is not None}
                self.scan(stmt.body, inner, inner_attrs, nested)
                continue
            # explicit acquire()/release() adjust the LINEAR held set
            for node in own_nodes(stmt):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)):
                    key = ctx.lock_key_of(node.func.value)
                    if key is None:
                        continue
                    if node.func.attr == "acquire":
                        held.add(key)
                        attr = ctx.class_lock_attr(node.func.value)
                        if attr is not None:
                            held_attrs.add(attr)
                    elif node.func.attr == "release":
                        held.discard(key)
                        attr = ctx.class_lock_attr(node.func.value)
                        if attr is not None:
                            held_attrs.discard(attr)
            for block in self._sub_blocks(stmt):
                self.scan(block, set(held), set(held_attrs), nested)
            # a release buried in a finally ends the region for the
            # statements that FOLLOW the try
            for sub in getattr(stmt, "finalbody", []) or []:
                for node in ast.walk(sub):
                    if (isinstance(node, ast.Call)
                            and isinstance(node.func, ast.Attribute)
                            and node.func.attr == "release"):
                        key = ctx.lock_key_of(node.func.value)
                        if key is not None:
                            held.discard(key)
                            attr = ctx.class_lock_attr(node.func.value)
                            if attr is not None:
                                held_attrs.discard(attr)

    @staticmethod
    def _sub_blocks(stmt: ast.stmt) -> "Iterable[list[ast.stmt]]":
        for field in ("body", "orelse", "finalbody"):
            block = getattr(stmt, field, None)
            if block:
                yield block
        for handler in getattr(stmt, "handlers", []) or []:
            yield handler.body

    # -- NMFX014 / NMFX015 raw material ------------------------------------
    def _scan_futures_threads(self, stmt: ast.stmt, nested: bool) -> None:
        from nmfx.analysis.ast_scan import own_nodes

        out = self.out
        for node in own_nodes(stmt):
            if not isinstance(node, ast.Call):
                continue
            tail = _attr_tail(node.func)
            if tail in self.future_ctors:
                # the binding owns the future(s) — a direct assign, an
                # annotated assign, or a container/wrapper built around
                # the construction (comprehensions, _Pending(future=..))
                name = None
                if (isinstance(stmt, ast.Assign)
                        and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)):
                    name = stmt.targets[0].id
                elif (isinstance(stmt, ast.AnnAssign)
                      and isinstance(stmt.target, ast.Name)):
                    name = stmt.target.id
                out.futures.append(
                    FutureCreation(line=node.lineno, name=name))
            elif tail in ("Thread", "Timer"):
                daemon = any(
                    kw.arg == "daemon"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is True
                    for kw in node.keywords)
                name = None
                if (isinstance(stmt, ast.Assign)
                        and len(stmt.targets) == 1):
                    tgt = stmt.targets[0]
                    if isinstance(tgt, ast.Name):
                        name = tgt.id
                out.threads.append(ThreadStart(
                    line=node.lineno, kind=tail, daemon=daemon,
                    name=name,
                    stored_attr=_self_attr(
                        stmt.targets[0]) if (
                            isinstance(stmt, ast.Assign)
                            and len(stmt.targets) == 1) else None))


# ---------------------------------------------------------------------------
# assembly

def _collect_module_locks(model: ConcurrencyModel,
                          mod: ModuleInfo) -> None:
    locks: "dict[str, LockInfo]" = {}
    guarded: "dict[str, tuple]" = {}
    for stmt in mod.tree.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)):
            ctor = _lock_ctor(stmt.value)
            if ctor is not None:
                name = stmt.targets[0].id
                locks[name] = LockInfo(
                    key=f"{_mod_stem(mod)}.{name}", attr=name,
                    reentrant=ctor[1], site=(mod.path, stmt.lineno))
        elif (isinstance(stmt, ast.Expr)
              and isinstance(stmt.value, ast.Call)
              and _attr_tail(stmt.value.func) == "module_guarded"):
            args = [_const_str(a) for a in stmt.value.args]
            if args and args[0] and all(args):
                guarded[args[0]] = tuple(args[1:])
    if locks:
        model.module_locks[mod.path] = locks
    if guarded:
        model.module_guarded[mod.path] = guarded


def _module_instance_types(model: ConcurrencyModel,
                           mod: ModuleInfo) -> "dict[str, ClassModel]":
    """Module-level singletons: ``_flight = FlightRecorder(...)``."""
    out: "dict[str, ClassModel]" = {}
    for stmt in mod.tree.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and isinstance(stmt.value, ast.Call)
                and isinstance(stmt.value.func, ast.Name)):
            cm = model.class_of(mod, stmt.value.func.id)
            if cm is not None:
                out[stmt.targets[0].id] = cm
    return out


def _resolve_attr_types(model: ConcurrencyModel) -> None:
    """Second pass: raw annotation names and ``self.x = ClassName(...)``
    constructor assignments become ClassModel references."""
    for cm in model.classes.values():
        resolved: "dict[str, ClassModel]" = {}
        for attr, raw in list(cm.attr_types.items()):
            if isinstance(raw, str):
                target = model.class_of(cm.module, raw)
                if target is not None:
                    resolved[attr] = target
            else:
                resolved[attr] = raw
        for meth in cm.methods.values():
            # parameter annotations type the attrs they are stored into:
            #   def __init__(self, server: "NMFXServer"): self.server = server
            ann: "dict[str, ClassModel]" = {}
            for arg in meth.args.args + meth.args.kwonlyargs:
                if arg.annotation is None:
                    continue
                raw = (_const_str(arg.annotation)
                       if isinstance(arg.annotation, ast.Constant)
                       else (arg.annotation.id
                             if isinstance(arg.annotation, ast.Name)
                             else None))
                if raw:
                    target = model.class_of(cm.module, raw)
                    if target is not None:
                        ann[arg.arg] = target
            for stmt in ast.walk(meth):
                if not (isinstance(stmt, ast.Assign)
                        and len(stmt.targets) == 1):
                    continue
                attr = _self_attr(stmt.targets[0])
                if attr is None or attr in resolved:
                    continue
                val = stmt.value
                if (isinstance(val, ast.Call)
                        and isinstance(val.func, ast.Name)):
                    target = model.class_of(cm.module, val.func.id)
                    if target is not None:
                        resolved[attr] = target
                elif isinstance(val, ast.Name) and val.id in ann:
                    resolved[attr] = ann[val.id]
        cm.attr_types = resolved


def _entry_held_fixpoint(cm: ClassModel,
                         fns: "dict[str, MethodModel]") -> None:
    """Which of the class's locks is provably held at entry of each
    PRIVATE method: the intersection over every in-class call site's
    held set. A method referenced as a value (callback), called from
    outside the class, public, or never called resolves to the empty
    set — the conservative answer."""
    refs: "set[str]" = set()
    sites: "dict[str, list]" = {m: [] for m in cm.methods}
    for caller, mm in fns.items():
        refs.update(mm.self_refs)
        for callee, held in mm.self_calls:
            sites[callee].append((caller, held))
    entry = {m: frozenset() for m in cm.methods}
    eligible = {m for m in cm.methods
                if m.startswith("_") and not m.startswith("__")
                and m not in refs and m not in cm.external_calls
                and sites[m]}
    for _ in range(len(cm.methods) + 1):
        changed = False
        for m in eligible:
            new = None
            for caller, held in sites[m]:
                eff = frozenset(held) | entry.get(caller, frozenset())
                new = eff if new is None else (new & eff)
            new = new or frozenset()
            if new != entry[m]:
                entry[m] = new
                changed = True
        if not changed:
            break
    cm.entry_held = entry


def _collect_joins(cm: ClassModel) -> None:
    for meth in cm.methods.values():
        for node in ast.walk(meth):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("join", "cancel")):
                continue
            recv = node.func.value
            attr = _self_attr(recv)
            if attr is not None:
                cm.joined_attrs.add(attr)
            elif isinstance(recv, ast.Name):
                # "for t in self._threads: t.join()" — credit every
                # container attr the loop variable ranges over
                cm.joined_attrs.add(f"<var>{recv.id}")
        for node in ast.walk(meth):
            if isinstance(node, ast.For) and isinstance(node.target,
                                                        ast.Name):
                var = f"<var>{node.target.id}"
                if var in cm.joined_attrs:
                    for sub in ast.walk(node.iter):
                        attr = _self_attr(sub)
                        if attr is not None:
                            cm.joined_attrs.add(attr)


def build_model(project: Project) -> ConcurrencyModel:
    model = ConcurrencyModel(project=project)
    # pass 1: classes, locks, module locks
    for mod in project.modules:
        _collect_module_locks(model, mod)
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                cm = _collect_class(model, mod, node)
                model.classes[(mod.path, node.name)] = cm
                model.by_class_name.setdefault(node.name, []).append(cm)
    _resolve_attr_types(model)
    for locks in model.module_locks.values():
        for li in locks.values():
            model.lock_index[li.key] = li
    for cm in model.classes.values():
        for li in cm.locks.values():
            model.lock_index.setdefault(li.key, li)
    # pass 2a: cross-class calls into private methods void entry-held
    for cm in model.classes.values():
        cm.external_calls = set()
    for mod in project.modules:
        inst_types = _module_instance_types(model, mod)
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            recv = node.func.value
            if isinstance(recv, ast.Name) and recv.id in inst_types:
                inst_types[recv.id].external_calls.add(node.func.attr)
        for cm in (c for c in model.classes.values()
                   if c.module is mod):
            for attr, target in cm.attr_types.items():
                for meth in cm.methods.values():
                    for node in ast.walk(meth):
                        if (isinstance(node, ast.Call)
                                and isinstance(node.func, ast.Attribute)
                                and node.func.attr in target.methods):
                            sa = _self_attr(node.func.value)
                            if sa == attr:
                                target.external_calls.add(
                                    node.func.attr)
    # pass 2b: per-function scan (first with empty entry-held to feed
    # the fixpoint, then re-scanned with the fixpoint answer)
    def scan_all(use_entry: bool) -> None:
        model.functions.clear()
        for mod in project.modules:
            futures = _future_names(mod)
            for node in mod.tree.body:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    ctx = _Ctx(model, mod, None)
                    mm = MethodModel(qual=node.name, node=node)
                    _BodyScan(ctx, mm, frozenset(), frozenset(),
                              futures).scan(node.body, set(), set())
                    model.functions[(mod.path, node.name)] = mm
                elif isinstance(node, ast.ClassDef):
                    cm = model.classes[(mod.path, node.name)]
                    for name, meth in cm.methods.items():
                        ctx = _Ctx(model, mod, cm)
                        mm = MethodModel(
                            qual=f"{cm.name}.{name}", node=meth)
                        attrs = (cm.entry_held.get(name, frozenset())
                                 if use_entry else frozenset())
                        keys = frozenset(
                            k for k in (cm.lock_key(a) for a in attrs)
                            if k is not None)
                        _BodyScan(ctx, mm, keys, attrs, futures).scan(
                            meth.body, set(), set())
                        model.functions[
                            (mod.path, f"{cm.name}.{name}")] = mm

    scan_all(use_entry=False)
    for mod in project.modules:
        for cm in (c for c in model.classes.values()
                   if c.module is mod):
            fns = {name: model.functions[(mod.path,
                                          f"{cm.name}.{name}")]
                   for name in cm.methods}
            _entry_held_fixpoint(cm, fns)
            _collect_joins(cm)
    scan_all(use_entry=True)
    _compute_acquires(model)
    _compute_order_edges(model)
    return model


def _compute_acquires(model: ConcurrencyModel) -> None:
    """Transitive lock-acquisition sets per function over the typed
    call graph (self-calls resolve within the class)."""
    direct: "dict[tuple, set]" = {}
    edges: "dict[tuple, set]" = {}
    for fid, mm in model.functions.items():
        direct[fid] = {key for _, key, _ in mm.acquisitions}
        out = set()
        for _, callee, _ in mm.calls:
            out.add(callee)
        mod_path, qual = fid
        if "." in qual:
            cls_name = qual.split(".", 1)[0]
            if (mod_path, cls_name) in model.classes:
                for callee, _ in mm.self_calls:
                    out.add((mod_path, f"{cls_name}.{callee}"))
        edges[fid] = out
    # fixpoint BFS
    acquires = {fid: set(d) for fid, d in direct.items()}
    changed = True
    while changed:
        changed = False
        for fid in acquires:
            for callee in edges.get(fid, ()):
                extra = acquires.get(callee)
                if extra and not extra <= acquires[fid]:
                    acquires[fid] |= extra
                    changed = True
    model.acquires = {fid: frozenset(s) for fid, s in acquires.items()}


def _compute_order_edges(model: ConcurrencyModel) -> None:
    """The static lock-order graph: held -> acquired, from direct
    acquisitions and from typed calls whose callees acquire."""
    for fid, mm in model.functions.items():
        mod_path, qual = fid
        for held, key, line in mm.acquisitions:
            for h in held:
                model.order_edges.setdefault(
                    (h, key), (mod_path, line))
        call_edges = list(mm.calls)
        if "." in qual:
            cls_name = qual.split(".", 1)[0]
            cm = model.classes.get((mod_path, cls_name))
            if cm is not None:
                for callee, held_attrs in mm.self_calls:
                    keys = frozenset(
                        k for k in (cm.lock_key(a) for a in held_attrs)
                        if k is not None)
                    call_edges.append(
                        (keys, (mod_path, f"{cls_name}.{callee}"),
                         mm.node.lineno))
        for held, callee, line in call_edges:
            if not held:
                continue
            for key in model.acquires.get(callee, ()):
                for h in held:
                    model.order_edges.setdefault(
                        (h, key), (mod_path, line))


def concurrency_model(project: Project) -> ConcurrencyModel:
    """The per-run shared model (built once, memoized on the project)."""
    cached = getattr(project, "_concurrency_model", None)
    if cached is None:
        cached = build_model(project)
        project._concurrency_model = cached
    return cached
