"""NMFX015 — thread lifecycle: daemonize or provably join.

Incident class: the drained-replica phantom heartbeat — a non-daemon
helper thread that outlives its owner keeps a "drained" replica
looking alive (and keeps the interpreter itself alive at shutdown,
which is how a background warm used to hang process exit until XLA
finished compiles whose results were already discarded).

The contract: every ``threading.Thread`` / ``threading.Timer``
constructed in the tree is either

* daemonized at construction (``daemon=True``) or via an explicit
  ``t.daemon = True`` before ``start()``, or
* provably joined/cancelled on its owner's close path: stored into an
  instance attribute the class somewhere ``join()``s (or
  ``cancel()``s, for Timers), including container attributes drained
  by a ``for t in self._threads: t.join()`` loop, or joined locally in
  the creating function (a run-and-wait helper).

A thread that is neither is an unowned lifetime: nothing bounds it,
nothing observes its death, and process exit blocks on it.
"""

from __future__ import annotations

import ast
from typing import Iterable

from nmfx.analysis.core import Finding, Rule, register
from nmfx.analysis.ast_scan import Project, _attr_tail
from nmfx.analysis.concurrency.model import (concurrency_model,
                                             _self_attr)


def _local_facts(fn: ast.AST, name: str) -> "dict":
    """What happens to local ``name`` in this function: daemonized,
    joined locally, or stored into a self attribute (directly or via
    ``self.<attr>.append(name)``)."""
    facts = {"daemon": False, "joined": False, "stored": None,
             "container": False}
    for node in ast.walk(fn):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Attribute)):
            tgt = node.targets[0]
            if (isinstance(tgt.value, ast.Name)
                    and tgt.value.id == name
                    and tgt.attr == "daemon"
                    and isinstance(node.value, ast.Constant)
                    and node.value.value is True):
                facts["daemon"] = True
            attr = _self_attr(tgt)
            if (attr is not None and isinstance(node.value, ast.Name)
                    and node.value.id == name):
                facts["stored"] = attr
        if isinstance(node, ast.Call):
            tail = _attr_tail(node.func)
            if (tail in ("join", "cancel")
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == name):
                facts["joined"] = True
            if (tail == "append" and isinstance(node.func, ast.Attribute)
                    and any(isinstance(a, ast.Name) and a.id == name
                            for a in node.args)):
                attr = _self_attr(node.func.value)
                if attr is not None:
                    facts["stored"] = attr
                    facts["container"] = True
    return facts


@register
class ThreadLifecycleRule(Rule):
    rule_id = "NMFX015"
    title = "threads are daemonized or joined on the close path"

    def check(self, project: Project) -> "Iterable[Finding]":
        model = concurrency_model(project)
        for (mod_path, qual), mm in sorted(model.functions.items()):
            if not mm.threads:
                continue
            cls = None
            if "." in qual:
                cls = model.classes.get((mod_path, qual.split(".")[0]))
            for ts in mm.threads:
                if ts.daemon:
                    continue
                stored = ts.stored_attr
                facts = {"daemon": False, "joined": False,
                         "stored": stored, "container": False}
                if ts.name is not None:
                    f2 = _local_facts(mm.node, ts.name)
                    facts["daemon"] = f2["daemon"]
                    facts["joined"] = f2["joined"]
                    if f2["stored"] is not None:
                        facts["stored"] = f2["stored"]
                        facts["container"] = f2["container"]
                if facts["daemon"] or facts["joined"]:
                    continue
                if (facts["stored"] is not None and cls is not None
                        and facts["stored"] in cls.joined_attrs):
                    continue
                target = (f"self.{facts['stored']}"
                          if facts["stored"] else
                          (ts.name or "an unbound expression"))
                yield Finding(
                    file=mod_path, line=ts.line, rule_id=self.rule_id,
                    message=(f"{qual} starts a non-daemon "
                             f"{ts.kind} ({target}) that is never "
                             "joined"
                             + ("" if ts.kind == "Thread"
                                else "/cancelled")
                             + " on any close path — pass daemon=True "
                             "or join it where the owner shuts down"))
