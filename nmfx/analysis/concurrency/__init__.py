"""Concurrency-discipline rules (NMFX012-015) for the threaded service
tier, built on one shared statically-derived model (``model.py``) and
cross-validated at runtime by the instrumented-lock witness
(``nmfx/analysis/witness.py``). See docs/analysis.md for the incident
behind each rule."""

from nmfx.analysis.concurrency.model import (ConcurrencyModel,
                                             concurrency_model)

# registering imports — each populates nmfx.analysis.core.RULES
from nmfx.analysis.concurrency import rules_guarded    # noqa: F401
from nmfx.analysis.concurrency import rules_lockorder  # noqa: F401
from nmfx.analysis.concurrency import rules_futures    # noqa: F401
from nmfx.analysis.concurrency import rules_threads    # noqa: F401

__all__ = ["ConcurrencyModel", "concurrency_model"]
