"""NMFX006 — the silent-degradation class: broad exception handlers
that swallow failures without a trace.

ISSUE 7 turned the serve stack fault-tolerant, and every recovery path
it added shares one discipline: a broad ``except`` either **re-raises**
(possibly as a typed error chaining the cause), **resolves a Future**
(``set_exception``/``set_result`` — the failure reaches the waiter,
typed, instead of hanging it), or **routes through the warn-once
degradation helper** (``nmfx.faults.warn_once`` — the first fallback of
a kind is loud, and nothing is silently swallowed). A broad handler
doing none of these is exactly how a server "survives" a failure by
hiding it: the request hangs or quietly returns degraded output, and
the first evidence is a production consensus nobody can explain —
the failure class the scheduler-death motivation in ISSUE.md names
(an exception escaping the scheduler used to strand every queued
Future forever, precisely because nothing enforced this contract).

Scope: every ``except Exception`` / ``except BaseException`` (bare
``except:`` included) in the analyzed tree. Narrow handlers
(``except KeyError``, ``except OSError``) are out of scope — catching
a SPECIFIC exception is a considered decision the author can defend;
catching everything demands an auditable disposal path.

A handler is compliant when its body (nested statements included,
nested ``def``/``lambda`` excluded — those run later, not as part of
the disposal) contains any of:

* a ``raise`` statement (bare re-raise or typed ``raise X from e``);
* a call whose attribute tail is ``set_exception`` or ``set_result``
  (Future resolution — ``concurrent.futures`` or compatible);
* a call to a ``*warn_once`` helper (bare or attribute tail):
  ``nmfx.faults.warn_once`` itself, or a scoped variant of it such as
  ``ExecCache._warn_once`` (warn-once-per-instance — same loudness
  contract, narrower dedup scope).

Suppress a deliberate swallow with a recorded reason::

    except Exception:  # nmfx: ignore[NMFX006] -- best-effort cleanup
"""

from __future__ import annotations

import ast
from typing import Iterable

from nmfx.analysis.core import Finding, Rule, register

#: except types treated as "broad" (a tuple containing one counts)
_BROAD = {"Exception", "BaseException"}

#: call attribute tails that resolve a Future with the failure
_FUTURE_RESOLVERS = {"set_exception", "set_result"}

#: the shared degradation helper (nmfx.faults.warn_once) and scoped
#: variants (ExecCache._warn_once) — matched by name suffix
_WARN_ONCE_SUFFIX = "warn_once"


def _broad_name(handler: ast.ExceptHandler) -> "str | None":
    """The broad class this handler catches, or None for narrow ones.
    Resolves ``except Exception``, ``except (ValueError, Exception)``,
    and the bare ``except:`` (implicitly BaseException)."""
    t = handler.type
    if t is None:
        return "BaseException (bare except)"
    candidates = t.elts if isinstance(t, ast.Tuple) else [t]
    for cand in candidates:
        if isinstance(cand, ast.Name) and cand.id in _BROAD:
            return cand.id
    return None


def _disposes(handler: ast.ExceptHandler) -> bool:
    """Whether the handler body re-raises, resolves a Future, or warns
    once — scanning nested statements but not nested function bodies
    (a callback defined here runs later; it is not this handler's
    disposal of this failure)."""
    skip: "set[int]" = set()
    for node in ast.walk(handler):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            skip.update(id(sub) for sub in ast.walk(node))
    for node in ast.walk(handler):
        if id(node) in skip or node is handler:
            continue
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) and (
                    fn.attr in _FUTURE_RESOLVERS
                    or fn.attr.endswith(_WARN_ONCE_SUFFIX)):
                return True
            if isinstance(fn, ast.Name) \
                    and fn.id.endswith(_WARN_ONCE_SUFFIX):
                return True
    return False


@register
class SilentDegradation(Rule):
    """NMFX006: broad except must re-raise, resolve a Future, or
    route through the warn-once degradation helper."""

    rule_id = "NMFX006"
    title = "silent degradation in broad exception handler"

    def check(self, project) -> "Iterable[Finding]":
        for mod in project.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                broad = _broad_name(node)
                if broad is None or _disposes(node):
                    continue
                yield self.finding(
                    mod.path, node.lineno,
                    f"broad handler (except {broad}) neither "
                    "re-raises, resolves a Future, nor routes through "
                    "nmfx.faults.warn_once — the failure is silently "
                    "swallowed (the degradation class ISSUE 7's "
                    "recovery matrix exists to prevent). Re-raise a "
                    "typed error chaining the cause, resolve the "
                    "waiter's Future, or warn_once(category, msg); a "
                    "deliberate swallow needs a suppression with a "
                    "recorded reason")
