"""NMFX008 — fault-site flight-recorder coverage.

The failure class: a chaos rehearsal whose postmortem is silent about
its own injected failure. ISSUE 10's flight recorder
(``nmfx/obs/flight.py``) turns "the watchdog resolved 14 stranded
Futures" from a warn-once line into an inspectable artifact — but only
for events that actually reach the ring. Fault-site fires reach it
through ONE central emission (``nmfx.faults.fire`` routes every fire
through ``flight.FAULT_EVENTS``), which makes the mapping the
authoritative coverage declaration: a site registered in
``nmfx.faults.SITES`` but missing from ``FAULT_EVENTS`` would fire
with a made-up fallback category no dashboard or postmortem query
knows to look for, and a mapping entry for an unregistered site is a
stale declaration that can mask a rename (the site fires under its
new name, the mapping still "covers" the old one).

The rule cross-references the two AUTHORITATIVE declarations — the
``SITES`` tuple in ``nmfx/faults.py`` and the
``fault_event_categories()`` introspection hook over ``FAULT_EVENTS``
— the same hook-vs-universe shape as NMFX001 (config-fingerprint
coverage) and NMFX007 (checkpoint-manifest coverage). The check itself
is a pure function over the two sets (``check_fault_event_coverage``)
so the per-rule tests can inject a mutated universe (a dropped site, a
stale mapping entry) and watch the rule fire; the Rule wrapper reads
the live modules and anchors findings at the ``SITES`` declaration.
"""

from __future__ import annotations

import ast
from typing import Iterable

from nmfx.analysis.core import Finding, Rule, register


def check_fault_event_coverage(
    sites: "frozenset[str]",
    event_covered: "frozenset[str]",
) -> "list[str]":
    """The pure contract check: every registered fault site must have
    a flight-recorder event category, and every mapped category must
    correspond to a registered site (no stale declarations). Tests
    inject mutated universes; the Rule wrapper reads the live
    modules."""
    problems: "list[str]" = []
    for name in sorted(sites - event_covered):
        problems.append(
            f"fault site {name!r} is registered in nmfx.faults.SITES "
            "but has no flight-recorder event category "
            "(nmfx.obs.flight.FAULT_EVENTS) — an armed fire of it "
            "would reach the postmortem only under an ad-hoc fallback "
            "category no query knows to look for; add the site to "
            "FAULT_EVENTS")
    for name in sorted(event_covered - sites):
        problems.append(
            f"nmfx.obs.flight.FAULT_EVENTS maps {name!r}, which is not "
            "a registered fault site (nmfx.faults.SITES) — stale "
            "declaration; a renamed site would fire uncovered while "
            "the mapping still claims the old name")
    return problems


def _sites_decl_line(tree: ast.Module) -> int:
    """Line of the module-level ``SITES = (...)`` assignment, best
    effort (findings anchor there — the declaration a new site lands
    on)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "SITES":
                    return node.lineno
    return 1


def _live_universe() -> dict:
    from nmfx import faults
    from nmfx.obs import flight

    return dict(sites=frozenset(faults.SITES),
                event_covered=flight.fault_event_categories())


@register
class FaultFlightCoverage(Rule):
    """NMFX008: every fault site registered in nmfx/faults.py must have
    a matching flight-recorder event emission
    (nmfx.obs.flight.FAULT_EVENTS), and no mapping entry may go
    stale."""

    rule_id = "NMFX008"
    title = "fault-site flight-recorder coverage"

    def check(self, project) -> "Iterable[Finding]":
        # semantic whole-package rule (the NMFX001/NMFX007 gating):
        # runs only when the real package is the analyzed set, and only
        # against the checkout the import machinery resolves
        import inspect
        import os

        analyzed = next(
            (m for m in project.modules
             if m.path.replace("\\", "/").endswith("nmfx/faults.py")),
            None)
        if analyzed is None:
            return []
        from nmfx import faults

        live_file = inspect.getsourcefile(faults) or analyzed.path
        if os.path.abspath(live_file) != os.path.abspath(analyzed.path):
            # NMFX001 already reports the wrong-tree condition loudly;
            # don't double-report it per rule
            return []
        line = _sites_decl_line(analyzed.tree)
        return [self.finding(analyzed.path, line, msg)
                for msg in check_fault_event_coverage(**_live_universe())]
