"""NMFX008 — fault-site flight-recorder coverage.
NMFX010 — registry metric naming + docs-table coverage.

The failure class: a chaos rehearsal whose postmortem is silent about
its own injected failure. ISSUE 10's flight recorder
(``nmfx/obs/flight.py``) turns "the watchdog resolved 14 stranded
Futures" from a warn-once line into an inspectable artifact — but only
for events that actually reach the ring. Fault-site fires reach it
through ONE central emission (``nmfx.faults.fire`` routes every fire
through ``flight.FAULT_EVENTS``), which makes the mapping the
authoritative coverage declaration: a site registered in
``nmfx.faults.SITES`` but missing from ``FAULT_EVENTS`` would fire
with a made-up fallback category no dashboard or postmortem query
knows to look for, and a mapping entry for an unregistered site is a
stale declaration that can mask a rename (the site fires under its
new name, the mapping still "covers" the old one).

The rule cross-references the two AUTHORITATIVE declarations — the
``SITES`` tuple in ``nmfx/faults.py`` and the
``fault_event_categories()`` introspection hook over ``FAULT_EVENTS``
— the same hook-vs-universe shape as NMFX001 (config-fingerprint
coverage) and NMFX007 (checkpoint-manifest coverage). The check itself
is a pure function over the two sets (``check_fault_event_coverage``)
so the per-rule tests can inject a mutated universe (a dropped site, a
stale mapping entry) and watch the rule fire; the Rule wrapper reads
the live modules and anchors findings at the ``SITES`` declaration.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable

from nmfx.analysis.core import Finding, Rule, register


def check_fault_event_coverage(
    sites: "frozenset[str]",
    event_covered: "frozenset[str]",
) -> "list[str]":
    """The pure contract check: every registered fault site must have
    a flight-recorder event category, and every mapped category must
    correspond to a registered site (no stale declarations). Tests
    inject mutated universes; the Rule wrapper reads the live
    modules."""
    problems: "list[str]" = []
    for name in sorted(sites - event_covered):
        problems.append(
            f"fault site {name!r} is registered in nmfx.faults.SITES "
            "but has no flight-recorder event category "
            "(nmfx.obs.flight.FAULT_EVENTS) — an armed fire of it "
            "would reach the postmortem only under an ad-hoc fallback "
            "category no query knows to look for; add the site to "
            "FAULT_EVENTS")
    for name in sorted(event_covered - sites):
        problems.append(
            f"nmfx.obs.flight.FAULT_EVENTS maps {name!r}, which is not "
            "a registered fault site (nmfx.faults.SITES) — stale "
            "declaration; a renamed site would fire uncovered while "
            "the mapping still claims the old name")
    return problems


def _sites_decl_line(tree: ast.Module) -> int:
    """Line of the module-level ``SITES = (...)`` assignment, best
    effort (findings anchor there — the declaration a new site lands
    on)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "SITES":
                    return node.lineno
    return 1


def _live_universe() -> dict:
    from nmfx import faults
    from nmfx.obs import flight

    return dict(sites=frozenset(faults.SITES),
                event_covered=flight.fault_event_categories())


@register
class FaultFlightCoverage(Rule):
    """NMFX008: every fault site registered in nmfx/faults.py must have
    a matching flight-recorder event emission
    (nmfx.obs.flight.FAULT_EVENTS), and no mapping entry may go
    stale."""

    rule_id = "NMFX008"
    title = "fault-site flight-recorder coverage"

    def check(self, project) -> "Iterable[Finding]":
        # semantic whole-package rule (the NMFX001/NMFX007 gating):
        # runs only when the real package is the analyzed set, and only
        # against the checkout the import machinery resolves
        import inspect
        import os

        analyzed = next(
            (m for m in project.modules
             if m.path.replace("\\", "/").endswith("nmfx/faults.py")),
            None)
        if analyzed is None:
            return []
        from nmfx import faults

        live_file = inspect.getsourcefile(faults) or analyzed.path
        if os.path.abspath(live_file) != os.path.abspath(analyzed.path):
            # NMFX001 already reports the wrong-tree condition loudly;
            # don't double-report it per rule
            return []
        line = _sites_decl_line(analyzed.tree)
        return [self.finding(analyzed.path, line, msg)
                for msg in check_fault_event_coverage(**_live_universe())]


# --------------------------------------------------------------------------
# NMFX010 — registry metric naming + docs-table coverage (ISSUE 14)
# --------------------------------------------------------------------------
# The failure class: a fleet namespace is only mergeable and queryable
# while its names stay disciplined. The collector (nmfx.obs.aggregate)
# merges N processes' registries BY NAME, dashboards and SLO
# objectives address series BY NAME, and docs/observability.md's
# metric table is the operator's index of what exists. A metric that
# breaks the ``nmfx_<subsystem>_<what>[_<unit>]`` scheme (or a counter
# without the ``_total`` convention) scrapes wrong; a live metric
# missing from the docs table is invisible to operators; a documented
# name with no live metric is a stale row that misdirects queries. The
# rule cross-references the LIVE registry (every declaring module
# imported, names filtered to the ``nmfx_`` namespace — test fixtures
# register foreign names in-process) against the names in
# docs/observability.md's tables, both ways, via a pure check tests
# can feed mutated universes.

#: the naming scheme: nmfx_ + at least <subsystem>_<what>, lowercase
#: alphanumeric segments (Prometheus-clean; docs/observability.md
#: "Metric naming")
_METRIC_NAME_RE = re.compile(r"nmfx(_[a-z][a-z0-9]*){2,}")

#: a docs metric-table row's first cell: | `nmfx_...{labels}` | ...
_DOC_ROW_RE = re.compile(r"^\s*\|\s*`(nmfx_[a-z0-9_]+)(?:\{[^}]*\})?`")


def check_metric_naming(live: "dict[str, str]",
                        documented: "frozenset[str]") -> "list[str]":
    """The pure contract check: every live ``nmfx_*`` registry metric
    must match the naming scheme, carry the type-appropriate suffix
    (counters end ``_total``; nothing else may), and appear in the
    docs metric table; every documented name must exist live (no
    stale rows). ``live`` maps name -> instrument kind."""
    problems: "list[str]" = []
    for name in sorted(live):
        kind = live[name]
        if not _METRIC_NAME_RE.fullmatch(name):
            problems.append(
                f"metric {name!r} breaks the naming scheme "
                "nmfx_<subsystem>_<what>[_<unit>] (lowercase "
                "alphanumeric segments; docs/observability.md "
                "'Metric naming') — the fleet collector and every "
                "dashboard/SLO query address series by name, so the "
                "scheme is the namespace contract")
        if kind == "counter" and not name.endswith("_total"):
            problems.append(
                f"counter {name!r} must end in '_total' (the "
                "Prometheus counter convention the naming scheme "
                "adopts)")
        elif kind != "counter" and name.endswith("_total"):
            problems.append(
                f"{kind} {name!r} ends in '_total', which declares a "
                "counter to every Prometheus consumer — rename it or "
                "make it a counter")
        if name not in documented:
            problems.append(
                f"metric {name!r} is live in the registry but missing "
                "from the docs/observability.md metric table — an "
                "undocumented series is invisible to operators; add a "
                "table row")
    for name in sorted(documented - live.keys()):
        problems.append(
            f"docs/observability.md documents metric {name!r}, which "
            "is not live in the registry — stale row; a renamed "
            "metric would ship while the table still claims the old "
            "name")
    return problems


def _documented_metrics(doc_path: str) -> frozenset:
    """Metric names from docs/observability.md's table rows (first
    cell, backticked, optional ``{labels}`` suffix)."""
    names = set()
    with open(doc_path, encoding="utf-8") as f:
        for line in f:
            m = _DOC_ROW_RE.match(line)
            if m:
                names.add(m.group(1))
    return frozenset(names)


def _live_metrics() -> "dict[str, str]":
    """Name -> kind of every ``nmfx_``-namespaced metric on the live
    registry, with every instrument-declaring module imported first
    (declarations are module-level, so importing is registering).
    Foreign (non-``nmfx_``) names — test fixtures register plenty
    in-process — are out of scope."""
    import importlib

    for mod in ("nmfx.exec_cache", "nmfx.data_cache", "nmfx.serve",
                "nmfx.checkpoint", "nmfx.distributed", "nmfx.router",
                "nmfx.replica", "nmfx.result_cache", "nmfx.tiles",
                "nmfx.sparse", "nmfx.sweep", "nmfx.autotune",
                "nmfx.obs.costmodel", "nmfx.obs.export",
                "nmfx.obs.slo"):
        importlib.import_module(mod)
    from nmfx.obs import metrics as obs_metrics

    snap = obs_metrics.registry().snapshot()
    return {name: rec["type"] for name, rec in snap.items()
            if name.startswith("nmfx_")}


@register
class MetricNamingCoverage(Rule):
    """NMFX010: every live ``nmfx_*`` registry metric must match the
    ``nmfx_<subsystem>_<what>[_<unit>]`` scheme (counters end
    ``_total``) AND appear in docs/observability.md's metric table;
    no documented name may go stale."""

    rule_id = "NMFX010"
    title = "registry metric naming + docs-table coverage"

    def check(self, project) -> "Iterable[Finding]":
        # semantic whole-package rule, gated like NMFX008: runs only
        # when the real registry module is analyzed, and only against
        # the checkout the import machinery resolves
        import inspect
        import os

        analyzed = next(
            (m for m in project.modules
             if m.path.replace("\\", "/")
             .endswith("nmfx/obs/metrics.py")),
            None)
        if analyzed is None:
            return []
        from nmfx.obs import metrics as obs_metrics

        live_file = inspect.getsourcefile(obs_metrics) or analyzed.path
        if os.path.abspath(live_file) != os.path.abspath(analyzed.path):
            # NMFX001 already reports the wrong-tree condition loudly
            return []
        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(analyzed.path))))
        doc_path = os.path.join(repo, "docs", "observability.md")
        if not os.path.isfile(doc_path):
            return [self.finding(
                analyzed.path, 1,
                "docs/observability.md (the metric table NMFX010 "
                "cross-references) does not exist next to this "
                "checkout — the metric namespace has no operator "
                "index")]
        return [self.finding(analyzed.path, 1, msg)
                for msg in check_metric_naming(
                    _live_metrics(), _documented_metrics(doc_path))]
