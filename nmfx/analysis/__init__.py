"""nmfx-lint: contract-checking static analysis for the solver/serving stack.

Usage::

    python -m nmfx.analysis nmfx/            # lint the package
    python -m nmfx.analysis nmfx/ --json     # machine-readable findings
    python -m nmfx.analysis nmfx/ --baseline lint_baseline.json

Rules (each encodes an observed failure class — see docs/analysis.md
for the incident behind each one):

=========  ==============================================================
NMFX001    config-fingerprint coverage (registry + exec-cache bucket key)
NMFX002    trace-time environment reads
NMFX003    donation/aliasing safety (read-after-donate)
NMFX004    PRNG discipline (key reuse, host RNG in traced code)
NMFX005    implicit host syncs in traced/hot-path code
NMFX006    silent degradation: broad except must re-raise, resolve a
           Future, or route through nmfx.faults.warn_once
NMFX007    checkpoint-manifest coverage (the durable sweep ledger's
           resume-safety fingerprint, nmfx/checkpoint.py)
NMFX008    fault-site flight-recorder coverage (every registered fault
           site reaches the crash postmortem, nmfx/obs/flight.py)
NMFX009    engine-family cost-model coverage (nmfx/obs/costmodel.py)
NMFX012    guarded state: attributes declared via nmfx.guards are only
           accessed under their owning lock (concurrency layer)
NMFX013    lock order: the static lock-acquisition graph stays
           cycle-free (deadlock freedom; cross-validated at runtime by
           nmfx/analysis/witness.py in the threaded test suites)
NMFX014    future-resolution completeness: every owned Future resolves,
           transfers, or is unpublished on every exception path
NMFX015    thread lifecycle: every Thread/Timer is daemonized or
           provably joined on its owner's close path
NMFX101    engine jaxpr stays f32 under x64 parity (jaxpr layer)
NMFX102    no device_put inside engine loop bodies (jaxpr layer)
=========  ==============================================================

Suppress a finding inline with a REQUIRED reason::

    read_env()  # nmfx: ignore[NMFX002] -- import-time read, not traced

The jaxpr layer (NMFX101/102) imports jax and traces every registered
engine abstractly; it runs by default when the analyzed paths contain
the nmfx package and can be disabled with ``--no-jaxpr`` for fast
AST-only iteration.
"""

from __future__ import annotations

from typing import Iterable

from nmfx.analysis.core import (RULES, Finding, Rule, active,
                                apply_baseline, load_baseline,
                                parse_suppressions, register)
from nmfx.analysis.ast_scan import Project, load_project

# registering imports — each module populates RULES at import time
from nmfx.analysis import rules_config  # noqa: F401  (NMFX001)
from nmfx.analysis import rules_traced  # noqa: F401  (NMFX002/004/005)
from nmfx.analysis import rules_alias   # noqa: F401  (NMFX003)
from nmfx.analysis import rules_handlers  # noqa: F401  (NMFX006)
from nmfx.analysis import rules_obs     # noqa: F401  (NMFX008)
from nmfx.analysis import rules_perf    # noqa: F401  (NMFX009)
from nmfx.analysis import concurrency   # noqa: F401  (NMFX012-015)
from nmfx.analysis import jaxpr_rules   # noqa: F401  (NMFX101/102)

__all__ = ["run", "RULES", "Finding", "Rule", "register", "active",
           "Project", "load_project"]


def run(paths: "Iterable[str]", baseline: "str | None" = None,
        jaxpr: bool = True,
        rule_ids: "Iterable[str] | None" = None) -> "list[Finding]":
    """Lint ``paths`` and return every finding, suppression- and
    baseline-annotated. ``active(findings)`` is what should gate a
    build. ``jaxpr=False`` skips the engine-tracing layer (NMFX101/102);
    ``rule_ids`` restricts to a subset (fixture tests)."""
    import os as _os

    project = load_project(paths)
    # the engine-tracing layer runs only when the real package is in
    # the analyzed set (its findings anchor at the engine registries —
    # a lint of an unrelated file must not go red for code outside it)
    project.jaxpr_checks_enabled = jaxpr and any(
        m.path.replace("\\", "/").endswith("nmfx/ops/grid_mu.py")
        for m in project.modules)
    findings: "list[Finding]" = []
    suppressions = {}
    for mod in project.modules:
        by_line, bad = parse_suppressions(mod.path, mod.text)
        # keyed by abspath so findings anchored via inspect (NMFX001)
        # or repo-relative constants (jaxpr rules) still match the
        # inline suppressions in the analyzed sources
        suppressions[_os.path.abspath(mod.path)] = by_line
        findings.extend(bad)
    for rule_id, rule in RULES.items():
        if rule_ids is not None and rule_id not in set(rule_ids):
            continue
        findings.extend(rule.check(project))
    import dataclasses

    annotated = []
    for f in findings:
        ids = suppressions.get(_os.path.abspath(f.file),
                               {}).get(f.line, set())
        annotated.append(dataclasses.replace(f, suppressed=True)
                         if f.rule_id in ids else f)
    annotated = apply_baseline(annotated, load_baseline(baseline))
    annotated.sort(key=lambda f: (f.file, f.line, f.rule_id))
    return annotated
