"""AST project model for nmfx-lint: modules, functions, traced reachability.

The trace-context rules (NMFX002 env reads, NMFX004 PRNG discipline,
NMFX005 host syncs) all need the same question answered: *is this code
reachable from something JAX traces?* Inside traced code the usual
dynamic defenses do not exist — an env read happens once at trace time
and is baked into every cached executable, a ``np.random`` draw becomes
a compile-time constant, a host sync stalls the dispatch pipeline — so
the lint boundary is "reachable from a traced root", computed here once
and shared.

Roots are detected syntactically:

* functions decorated with ``jax.jit`` / ``jit`` /
  ``(functools.)partial(jax.jit, ...)``;
* functions passed to ``jax.jit(f)`` / ``jax.vmap(f)`` /
  ``jax.pmap(f)`` / ``shard_map(f, ...)`` as a bare name;
* kernel/body functions handed to ``pl.pallas_call`` or
  ``lax.while_loop`` / ``lax.scan`` / ``lax.cond`` / ``lax.fori_loop``
  / ``lax.switch``.

Reachability then follows an IMPORT-AWARE name-based call graph across
the analyzed file set. A bare call ``foo(...)`` resolves to the same
module's ``foo`` if one exists, else through the module's
``from X import foo`` to module X's ``foo`` (when X is in the analyzed
set; an import from OUTSIDE the set resolves to nothing — jax/numpy
calls never alias project helpers). ``base.foo(...)`` resolves inside
module ``base`` when ``base`` is an imported-module alias, and falls
back to every analyzed function named ``foo`` when the base is an
ordinary variable. The fallback over-approximates — a method call can
alias a same-named helper — which is the right direction for a
contract linter: a false edge surfaces for human review and gets an
inline suppression with a reason; a missed edge would hide a real
trace-time hazard. Nested functions belong to their enclosing function
(a closure inside a jitted body is traced with it) AND are nodes of
their own, reachable from the enclosing scope.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from collections import deque
from typing import Iterable

#: callables whose function-typed arguments are traced
_TRACING_CALLS = {
    "jit", "vmap", "pmap", "pallas_call", "while_loop", "scan", "cond",
    "fori_loop", "switch", "shard_map", "checkpoint", "remat",
    "custom_vjp", "custom_jvp", "grad", "value_and_grad", "make_jaxpr",
}


def _attr_tail(node: ast.AST) -> "str | None":
    """``a.b.c`` -> "c"; bare name -> itself; else None."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _dotted(node: ast.AST) -> "str | None":
    """``a.b.c`` -> "a.b.c" when every link is a Name/Attribute."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def own_nodes(stmt: ast.stmt) -> "list[ast.AST]":
    """The statement's OWN subtree — header expressions included, nested
    statement lists excluded. Statement-ordered rules (NMFX003's
    donation tracking, NMFX004's key threading, the NMFX012-015
    concurrency scans) flatten compound statements into source order;
    walking the full subtree at the compound's position would process
    nested events OUT of order (a donation deep in the body would
    precede a read that textually comes before it).

    Memoized on the node (one project = one parse, trees are
    immutable for the run's lifetime) and pruned at the excluded
    statement lists instead of filtering a full ``ast.walk`` — every
    rule shares the same per-statement index, which is where the bulk
    of a multi-rule run's time went before the cache. Returns in
    ``ast.walk`` (breadth-first) order."""
    cached = getattr(stmt, "_nmfx_own_nodes", None)
    if cached is not None:
        return cached
    skip: "set[int]" = set()
    for field in ("body", "orelse", "finalbody"):
        children = getattr(stmt, field, None)
        if isinstance(children, list):
            skip.update(id(c) for c in children)
    skip.update(id(h) for h in getattr(stmt, "handlers", []) or [])
    out: "list[ast.AST]" = []
    queue: "deque[ast.AST]" = deque([stmt])
    while queue:
        node = queue.popleft()
        out.append(node)
        for child in ast.iter_child_nodes(node):
            if id(child) not in skip:
                queue.append(child)
    stmt._nmfx_own_nodes = out
    return out


def stores(stmt: ast.stmt) -> "set[str]":
    """Names (re)bound at the statement's own level."""
    return {node.id for node in own_nodes(stmt)
            if isinstance(node, ast.Name)
            and isinstance(node.ctx, (ast.Store, ast.Del))}


def is_jit_decorator(dec: ast.AST) -> bool:
    """``@jax.jit`` / ``@jit`` / ``@(functools.)partial(jax.jit, ...)``
    (and the pallas/checkpoint spellings) — a decorator that makes the
    decorated function a traced root."""
    if _attr_tail(dec) in ("jit", "pallas_call", "checkpoint", "remat"):
        return True
    if isinstance(dec, ast.Call):
        tail = _attr_tail(dec.func)
        if tail in ("jit", "pallas_call", "checkpoint", "remat"):
            return True
        if tail == "partial" and dec.args:
            return _attr_tail(dec.args[0]) in ("jit", "pallas_call",
                                               "checkpoint", "remat")
    return False


@dataclasses.dataclass
class FunctionInfo:
    """One (possibly nested) function definition."""

    module: "ModuleInfo"
    qualname: str  # "outer.<locals>.inner" style, dots only
    node: ast.AST  # FunctionDef | AsyncFunctionDef | Lambda
    is_root: bool = False  # syntactically traced (decorator/arg position)
    #: (base, tail) call/reference edges out of this function's body:
    #: base None = bare name, "" = attribute on a non-name expression,
    #: else the leading name of a dotted call ("jax" in jax.jit). Bare
    #: Name arguments passed to any call are recorded too — function
    #: values travel through partial/callback positions
    calls: "set[tuple]" = dataclasses.field(default_factory=set)
    #: names of directly nested function defs
    nested: "set[str]" = dataclasses.field(default_factory=set)

    @property
    def name(self) -> str:
        return self.qualname.rsplit(".", 1)[-1]

    @property
    def line(self) -> int:
        return getattr(self.node, "lineno", 1)


@dataclasses.dataclass
class ModuleInfo:
    path: str  # as given (project-relative when invoked that way)
    text: str
    tree: ast.Module
    functions: "dict[str, FunctionInfo]" = dataclasses.field(
        default_factory=dict)
    #: local name -> (source module dotted path, original name) for
    #: ``from X import name [as alias]``
    from_imports: "dict[str, tuple[str, str]]" = dataclasses.field(
        default_factory=dict)
    #: local alias -> dotted module for ``import X [as Y]`` and
    #: ``from pkg import submodule`` (resolved against the analyzed set)
    module_aliases: "dict[str, str]" = dataclasses.field(
        default_factory=dict)


def _collect_imports(mod: ModuleInfo) -> None:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.asname:
                    mod.module_aliases[alias.asname] = alias.name
                else:
                    # `import a.b` binds the TOP-LEVEL name `a` (to
                    # module a, not a.b) — recording a->a.b would make
                    # `import jax.scipy.linalg` shadow `jax` itself and
                    # break jax.random key-consumption resolution
                    top = alias.name.split(".")[0]
                    mod.module_aliases[top] = top
        elif isinstance(node, ast.ImportFrom) and node.level == 0:
            src = node.module or ""
            for alias in node.names:
                local = alias.asname or alias.name
                mod.from_imports[local] = (src, alias.name)
                # `from pkg import submodule` doubles as a module alias
                mod.module_aliases.setdefault(local,
                                              f"{src}.{alias.name}")


class _FunctionCollector(ast.NodeVisitor):
    """Collect every function def with qualname, root-ness, and the
    names it calls. Calls made by a nested def are credited to every
    enclosing function as well — tracing a jitted outer function traces
    the closures it builds."""

    def __init__(self, module: ModuleInfo):
        self.module = module
        self.stack: "list[FunctionInfo]" = []

    def _handle_def(self, node, name: str):
        qual = (self.stack[-1].qualname + "." + name if self.stack
                else name)
        info = FunctionInfo(module=self.module, qualname=qual, node=node)
        decos = getattr(node, "decorator_list", [])
        info.is_root = any(is_jit_decorator(d) for d in decos)
        if self.stack:
            self.stack[-1].nested.add(name)
        self.module.functions[qual] = info
        self.stack.append(info)
        for child in ast.iter_child_nodes(node):
            self.visit(child)
        self.stack.pop()

    def visit_FunctionDef(self, node):
        self._handle_def(node, node.name)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        self._handle_def(node, f"<lambda@{node.lineno}>")

    def visit_Call(self, node):
        callee = _attr_tail(node.func)
        if callee:
            base = None
            if isinstance(node.func, ast.Attribute):
                dotted = _dotted(node.func)
                base = dotted.split(".")[0] if dotted else ""
            for fn in self.stack:
                fn.calls.add((base, callee))
        # a bare function name passed as an argument is an edge too —
        # function values travel through partial()/callback positions.
        # Marked "<ref>": resolved STRICTLY (local defs and explicit
        # imports, never the global name fallback), because most Name
        # arguments are data whose names can collide with functions
        # elsewhere in the project
        args = list(node.args) + [kw.value for kw in node.keywords]
        for arg in args:
            if isinstance(arg, ast.Name):
                for fn in self.stack:
                    fn.calls.add(("<ref>", arg.id))
        # function-typed arguments of tracing combinators are roots:
        # jax.jit(f), lax.while_loop(cond, body, ...), pallas_call(k, ...)
        if callee in _TRACING_CALLS:
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    self._mark_root(arg.id)
        self.generic_visit(node)

    def _mark_root(self, name: str):
        """Mark ``name`` as traced: prefer a function visible from the
        current scope, else any module-level def seen later (second
        pass resolves by name)."""
        self.module._pending_roots.add(name)


def parse_module(path: str, text: "str | None" = None) -> ModuleInfo:
    if text is None:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    tree = ast.parse(text, filename=path)
    mod = ModuleInfo(path=path, text=text, tree=tree)
    mod._pending_roots = set()  # type: ignore[attr-defined]
    _collect_imports(mod)
    _FunctionCollector(mod).visit(tree)
    for info in mod.functions.values():
        if info.name in mod._pending_roots:  # type: ignore[attr-defined]
            info.is_root = True
    return mod


def _dotted_module(path: str) -> "tuple[str, ...]":
    """Path -> dotted-name segments for import matching:
    ``a/b/nmfx/ops/grid_mu.py`` -> ("a", "b", "nmfx", "ops", "grid_mu");
    ``__init__.py`` collapses onto its package."""
    norm = path.replace("\\", "/").rstrip("/")
    if norm.endswith(".py"):
        norm = norm[:-3]
    parts = tuple(p for p in norm.split("/") if p and p != ".")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return parts


class Project:
    """The analyzed file set plus the shared reachability answer."""

    def __init__(self, modules: "list[ModuleInfo]"):
        self.modules = modules
        #: simple name -> functions bearing it, across the project
        self.by_name: "dict[str, list[FunctionInfo]]" = {}
        for mod in modules:
            for fn in mod.functions.values():
                self.by_name.setdefault(fn.name, []).append(fn)
        #: dotted-segment tuple -> module, for import resolution
        self._by_dotted = {_dotted_module(m.path): m for m in modules}
        self._traced = self._compute_traced()

    def _module_for(self, dotted: str) -> "ModuleInfo | None":
        """The analyzed module an absolute import refers to — matched by
        dotted-path suffix, so 'nmfx.ops.grid_mu' finds
        '/any/prefix/nmfx/ops/grid_mu.py'. None = external (jax, numpy,
        stdlib): its functions are nobody's in this project."""
        want = tuple(dotted.split("."))
        for segs, mod in self._by_dotted.items():
            if segs[-len(want):] == want:
                return mod
        return None

    def _resolve(self, caller: FunctionInfo, base: "str | None",
                 tail: str) -> "list[FunctionInfo]":
        mod = caller.module
        if base is None or base == "<ref>":
            local = [f for f in mod.functions.values() if f.name == tail]
            if local:
                return local
            if tail in mod.from_imports:
                src, orig = mod.from_imports[tail]
                target = self._module_for(src)
                if target is None:
                    return []  # imported from outside the analyzed set
                return [f for f in target.functions.values()
                        if f.name == orig]
            # direct calls of an unresolved bare name fall back to every
            # bearer; a mere reference does not (data names collide with
            # function names far too often)
            return [] if base == "<ref>" else self.by_name.get(tail, [])
        if base and base in mod.module_aliases:
            target = self._module_for(mod.module_aliases[base])
            if target is None:
                return []  # jax.jit, np.sum, os.environ... not ours
            return [f for f in target.functions.values()
                    if f.name == tail]
        # attribute on an ordinary variable (method call): fall back to
        # every bearer of the name — over-approximate, reviewable
        return self.by_name.get(tail, [])

    def _compute_traced(self) -> "set[int]":
        """BFS over the import-aware call graph from the syntactic
        roots; returns id()s of reachable FunctionInfos (identity —
        qualnames collide across modules)."""
        work = [fn for mod in self.modules
                for fn in mod.functions.values() if fn.is_root]
        seen = {id(fn) for fn in work}
        while work:
            fn = work.pop()
            # nested defs trace with their parent (a closure built
            # inside a jitted body); called names resolve via imports
            edges = [(None, n) for n in fn.nested] + list(fn.calls)
            for base, tail in edges:
                for cand in self._resolve(fn, base, tail):
                    if id(cand) not in seen:
                        seen.add(id(cand))
                        work.append(cand)
        return seen

    def is_traced(self, fn: FunctionInfo) -> bool:
        """Whether ``fn`` is a traced root or (name-graph) reachable
        from one."""
        return id(fn) in self._traced

    def traced_functions(self) -> "Iterable[FunctionInfo]":
        for mod in self.modules:
            for fn in mod.functions.values():
                if self.is_traced(fn):
                    yield fn


def collect_paths(paths: "Iterable[str]") -> "list[str]":
    """Expand files/directories into a sorted .py file list (skips
    __pycache__ and hidden directories). A path that exists as neither
    raises — a typo'd CI lint target must fail the job, not lint
    nothing and report clean forever."""
    out = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d != "__pycache__"
                                 and not d.startswith("."))
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if f.endswith(".py"))
        elif p.endswith(".py") and os.path.isfile(p):
            out.append(p)
        else:
            raise FileNotFoundError(
                f"lint target {p!r} is neither a directory nor an "
                "existing .py file")
    return out


def load_project(paths: "Iterable[str]") -> Project:
    return Project([parse_module(p) for p in collect_paths(paths)])
