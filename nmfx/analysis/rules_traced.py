"""NMFX002/NMFX004/NMFX005 — hazards inside traced code.

All three rules share the traced-reachability answer from
``ast_scan.Project`` (functions jitted, handed to pallas/lax
combinators, or name-graph reachable from one): inside traced code the
hazards below leave no runtime trace.

* **NMFX002 — trace-time environment reads.** ``os.environ`` /
  ``os.getenv`` inside traced code executes ONCE at trace time and is
  baked into every cached executable: toggling the variable mid-process
  silently serves the stale program, and a process that merely
  *inherits* the variable (a test harness spawning a service) changes
  production numerics with no record. This repo shipped exactly this
  class: ``NMFX_FAULT_INJECT_STALE_RELOAD`` was read at trace time in
  the production reload path (ADVICE.md round 5) until the explicit
  ``enable_stale_reload_fault()`` opt-in replaced it.

* **NMFX004 — PRNG discipline.** ``np.random``/stdlib ``random`` inside
  traced code freezes one host draw into the executable (every call of
  the compiled program replays the same "random" numbers — the
  reference's irreproducibility bug, inverted). And a JAX key consumed
  by two sampling calls without an intervening ``split``/``fold_in``
  correlates draws that the consensus math assumes independent —
  restarts collapse toward each other with no numerical signature
  (PAPER.md's whole premise is independent restarts).

* **NMFX005 — implicit host syncs.** ``.item()`` / ``float()`` /
  ``bool()`` / ``int()`` / ``np.asarray`` on a traced array either
  aborts tracing (good case) or — in host-side dispatch loops — blocks
  the dispatch pipeline on a device round trip per call (the transfer
  discipline docs/design.md §5b exists to protect). The rule is
  dataflow-gated to stay quiet on the pervasive legitimate host math on
  STATIC config values: only conversions of names bound from
  ``jnp.``/``jax.``/``lax.`` results or of the traced function's own
  array parameters are flagged.
"""

from __future__ import annotations

import ast
from typing import Iterable

from nmfx.analysis.ast_scan import (FunctionInfo, _attr_tail,
                                    _dotted, own_nodes, stores)
from nmfx.analysis.core import Finding, Rule, register

#: jax.random functions that DERIVE new keys rather than consuming one
#: for sampling (calling these repeatedly on one key is the intended
#: idiom); constructors take seeds, not keys
_KEY_DERIVERS = {"split", "fold_in", "clone", "key_data", "wrap_key_data"}
_KEY_CONSTRUCTORS = {"key", "PRNGKey"}


def _function_body_calls(fn: FunctionInfo) -> "Iterable[ast.Call]":
    """Call nodes lexically inside ``fn`` but NOT inside a nested def
    (nested defs are their own FunctionInfo and get visited there)."""
    skip: "set[int]" = set()
    for node in ast.walk(fn.node):
        if node is not fn.node and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            skip.update(id(sub) for sub in ast.walk(node))
    for node in ast.walk(fn.node):
        if isinstance(node, ast.Call) and id(node) not in skip:
            yield node


@register
class TraceTimeEnvRead(Rule):
    """NMFX002: os.environ/os.getenv reachable from jitted/pallas code."""

    rule_id = "NMFX002"
    title = "trace-time environment read"

    @staticmethod
    def _is_env_read(fn: FunctionInfo, dotted: str) -> bool:
        """Whether a dotted name chain reaches os.environ/os.getenv,
        resolving the leading name through the module's imports — so
        ``import os as _os``, ``from os import getenv`` and
        ``from os import environ`` spellings are all caught, while a
        user-defined ``environ`` object from elsewhere is not."""
        parts = dotted.split(".")
        aliases = fn.module.module_aliases
        from_imports = fn.module.from_imports
        head = parts[0]
        # module alias chain: <os-alias>.environ... / <os-alias>.getenv
        if (len(parts) >= 2 and aliases.get(head) == "os"
                and parts[1] in ("environ", "getenv")):
            return True
        # from os import getenv/environ (any local alias)
        origin = from_imports.get(head)
        if origin is not None:
            src, orig = origin
            return src == "os" and orig in ("getenv", "environ")
        return False

    def check(self, project) -> "Iterable[Finding]":
        for fn in project.traced_functions():
            for node in ast.walk(fn.node):
                dotted = None
                if isinstance(node, ast.Call):
                    dotted = _dotted(node.func)
                elif isinstance(node, (ast.Attribute, ast.Name)):
                    dotted = _dotted(node)
                if dotted is None or not self._is_env_read(fn, dotted):
                    continue
                yield self.finding(
                    fn.module.path, node.lineno,
                    f"environment read ({dotted}) inside "
                    f"'{fn.qualname}', which is traced or reachable "
                    "from traced code: the value is read ONCE at "
                    "trace time and baked into every cached "
                    "executable — changing the variable later "
                    "silently serves the stale program. Read env "
                    "vars at import/call-site setup and pass the "
                    "value in explicitly")
                break  # one finding per function per rule keeps
                # output actionable; re-lint after the fix


@register
class PRNGDiscipline(Rule):
    """NMFX004: host RNG in traced code; JAX key reuse without split."""

    rule_id = "NMFX004"
    title = "PRNG discipline"

    def check(self, project) -> "Iterable[Finding]":
        for fn in project.traced_functions():
            yield from self._host_rng(fn)
        # key reuse is a per-function property of ANY function (a host
        # driver reusing a key across two traced calls is just as
        # correlated), so scan them all
        for mod in project.modules:
            for fn in mod.functions.values():
                yield from self._key_reuse(fn)

    def _host_rng(self, fn: FunctionInfo) -> "Iterable[Finding]":
        aliases = fn.module.module_aliases
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func) or ""
            parts = dotted.split(".")
            head_res = aliases.get(parts[0], parts[0])
            # numpy resolved through the module's imports (import numpy
            # as onp; from numpy import random as nprand) — and
            # "random." only for the STDLIB module: a module that did
            # `from jax import random` is consuming keys, not host RNG
            np_random = ((len(parts) >= 3 and parts[1] == "random"
                          and head_res in ("numpy", "np"))
                         or (len(parts) >= 2
                             and head_res == "numpy.random"))
            stdlib_random = (len(parts) >= 2 and head_res == "random")
            if np_random or stdlib_random:
                yield self.finding(
                    fn.module.path, node.lineno,
                    f"host RNG call ({dotted}) inside traced "
                    f"'{fn.qualname}': the draw happens once at trace "
                    "time and becomes a compile-time constant — every "
                    "execution replays the same numbers. Use jax.random "
                    "with an explicit key")

    def _consumption(self, fn: FunctionInfo,
                     node: ast.Call) -> "str | None":
        """The key Name this call consumes for sampling, or None.

        Only jax.random consumption counts as KEY use: the call's base
        resolves through the module's imports, so stdlib
        ``random.shuffle(data)`` (base resolves to "random", not
        "jax.random") never flags a data argument as a reused key."""
        aliases = fn.module.module_aliases
        dotted = _dotted(node.func) or ""
        parts = dotted.split(".")
        if (len(parts) >= 3 and parts[1] == "random"
                and aliases.get(parts[0], parts[0]) == "jax"):
            pass
        elif len(parts) == 2 and aliases.get(parts[0]) == "jax.random":
            pass  # `from jax import random` / `import jax.random as X`
        else:
            return None
        leaf = parts[-1]
        if leaf in _KEY_DERIVERS or leaf in _KEY_CONSTRUCTORS:
            return None
        if not node.args or not isinstance(node.args[0], ast.Name):
            return None
        return node.args[0].id

    def _reuse_finding(self, fn: FunctionInfo, node: ast.Call,
                       key_name: str, first_line: int) -> Finding:
        leaf = (_dotted(node.func) or "?").split(".")[-1]
        return self.finding(
            fn.module.path, node.lineno,
            f"PRNG key '{key_name}' is consumed by jax.random.{leaf} "
            f"at line {node.lineno} after already being consumed at "
            f"line {first_line} in '{fn.qualname}' — reused keys "
            "correlate draws that downstream consensus math assumes "
            "independent; split the key (jax.random.split) so each "
            "sampling call owns a fresh one")

    def _key_reuse(self, fn: FunctionInfo) -> "Iterable[Finding]":
        """Same Name consumed by 2+ jax.random sampling calls without
        an intervening rebind — the canonical threading idiom
        ``key = jax.random.fold_in(key, i)`` RESURRECTS the name (the
        statement-ordered scan clears it on store), and branch bodies
        scan with copies so sibling branches never see each other's
        consumptions."""
        if not isinstance(fn.node, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
            # lambda: one expression, no rebinds possible — flat scan
            consumed: "dict[str, int]" = {}
            for node in _function_body_calls(fn):
                key = self._consumption(fn, node)
                if key is None:
                    continue
                if key in consumed:
                    yield self._reuse_finding(fn, node, key,
                                              consumed[key])
                else:
                    consumed[key] = node.lineno
            return
        yield from self._scan_keys(fn, fn.node.body, {})

    def _scan_keys(self, fn: FunctionInfo, body,
                   consumed: "dict[str, int]") -> "Iterable[Finding]":
        for stmt in body:
            for node in own_nodes(stmt):
                if not isinstance(node, ast.Call):
                    continue
                key = self._consumption(fn, node)
                if key is None:
                    continue
                if key in consumed:
                    yield self._reuse_finding(fn, node, key,
                                              consumed[key])
                else:
                    consumed[key] = node.lineno
            for name in stores(stmt):
                consumed.pop(name, None)
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                # loop-carried reuse: ONE textual consumption inside
                # the body runs once per iteration — identical draws
                # every trip unless the body rebinds the key (the
                # `k = fold_in(key, i)` idiom stores a fresh name and
                # stays quiet)
                yield from self._loop_carried(fn, stmt)
            for field in ("body", "orelse", "finalbody"):
                child = getattr(stmt, field, None)
                if child and not isinstance(
                        stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield from self._scan_keys(fn, child,
                                               dict(consumed))
            for handler in getattr(stmt, "handlers", []) or []:
                yield from self._scan_keys(fn, handler.body,
                                           dict(consumed))

    def _loop_carried(self, fn: FunctionInfo,
                      loop) -> "Iterable[Finding]":
        # inner loops run their own _loop_carried pass (from
        # _scan_keys's recursion) — excluding their subtrees here keeps
        # one finding per defect instead of one per enclosing loop
        inner: "set[int]" = set()
        for node in ast.walk(loop):
            if node is not loop and isinstance(
                    node, (ast.For, ast.AsyncFor, ast.While,
                           ast.FunctionDef, ast.AsyncFunctionDef)):
                inner.update(id(sub) for sub in ast.walk(node))
        body_stores: "set[str]" = set()
        for stmt in ast.walk(loop):
            if isinstance(stmt, ast.stmt) and id(stmt) not in inner:
                body_stores.update(stores(stmt))
        # the loop target itself rebinds each iteration
        target = getattr(loop, "target", None)
        if target is not None:
            body_stores.update(n.id for n in ast.walk(target)
                               if isinstance(n, ast.Name))
        seen: "set[str]" = set()
        for node in ast.walk(loop):
            if not isinstance(node, ast.Call) or id(node) in inner:
                continue
            key = self._consumption(fn, node)
            if key is None or key in body_stores or key in seen:
                continue
            seen.add(key)
            yield self.finding(
                fn.module.path, node.lineno,
                f"PRNG key '{key}' is consumed inside a loop body in "
                f"'{fn.qualname}' without being rebound per iteration "
                "— every iteration replays the identical draw "
                "(restarts collapse together); derive a fresh key per "
                "iteration (jax.random.fold_in(key, i) or a "
                "pre-split key array)")


#: conversion calls that force a device->host sync on a traced array
#: (int() stays off the list: the codebase's int() sites coerce static
#: config/shape values, and ISSUE-class incidents were float/bool/item)
_SYNC_CALLS = {"float", "bool"}
_SYNC_NP = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}


def _array_tainted(fn: FunctionInfo) -> "set[str]":
    """Names plausibly bound to device arrays in ``fn``: its parameters
    plus anything assigned from a ``jnp.``/``jax.``/``lax.`` call.
    Config objects and static shape math arrive as attributes/ints and
    never enter this set — that is what keeps NMFX005 quiet on the
    pervasive legitimate host math inside jitted builders."""
    tainted: "set[str]" = set()
    args = getattr(fn.node, "args", None)
    if args is not None:
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            tainted.add(a.arg)
        if args.vararg:
            tainted.add(args.vararg.arg)
    for node in ast.walk(fn.node):
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call):
            dotted = _dotted(node.value.func) or ""
            if dotted.split(".")[0] in ("jnp", "jax", "lax"):
                for tgt in node.targets:
                    for leaf in ast.walk(tgt):
                        if isinstance(leaf, ast.Name):
                            tainted.add(leaf.id)
    return tainted


@register
class ImplicitHostSync(Rule):
    """NMFX005: .item()/float()/bool()/np.asarray on traced arrays."""

    rule_id = "NMFX005"
    title = "implicit host sync"

    def check(self, project) -> "Iterable[Finding]":
        for fn in project.traced_functions():
            tainted = _array_tainted(fn)
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                hit = self._classify(node, tainted)
                if hit:
                    yield self.finding(
                        fn.module.path, node.lineno,
                        f"{hit} inside traced '{fn.qualname}': on a "
                        "traced array this either aborts tracing or — "
                        "in the dispatch hot path — blocks the pipeline "
                        "on a device round trip per call (see "
                        "docs/design.md §5b). Keep reductions on device "
                        "(jnp) and convert once, after the batch")

    @staticmethod
    def _classify(node: ast.Call, tainted: "set[str]") -> "str | None":
        # x.item() where x is array-tainted (or a jnp/lax call result)
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr == "item"):
            recv = node.func.value
            if ((isinstance(recv, ast.Name) and recv.id in tainted)
                    or (isinstance(recv, ast.Call)
                        and (_dotted(recv.func) or "").split(".")[0]
                        in ("jnp", "jax", "lax"))):
                return ".item() call"
            return None
        dotted = _dotted(node.func) or ""
        name = _attr_tail(node.func)
        is_sync = (dotted in _SYNC_NP
                   or (name in _SYNC_CALLS
                       and isinstance(node.func, ast.Name)))
        if not is_sync or not node.args:
            return None
        arg = node.args[0]
        if isinstance(arg, ast.Name) and arg.id in tainted:
            return f"{dotted or name}() on traced array '{arg.id}'"
        return None
