"""NMFX003 — donation/aliasing safety (read-after-donate).

The round-3 incident (VERDICT.md round 3): the pallas scheduler aliased
its input factor buffers onto the output VMEM windows and made the
alias the DATA path — bit-exact standalone, silently stale inside
``lax.while_loop`` on hardware. The round-5 successor (``alias_io``)
re-admits donation ONLY as an optimization on top of an explicit copy,
and the boundary between the two is a buffer-lifetime contract no test
can see: a buffer named in ``donate_argnums`` / ``input_output_aliases``
is DEAD after the call that consumes it, and a later read returns
whatever the executable scribbled there — on backends that honor
donation, which CPU tests do not (jax warns at most).

The rule tracks, per function body, in statement order:

* ``g = jax.jit(f, donate_argnums=(...))`` (and
  ``functools.partial``-spelled jit) — ``g`` carries the donated
  positions;
* ``pl.pallas_call(..., input_output_aliases={...})`` — the returned
  callable carries the aliased input positions;
* direct forms ``jax.jit(f, donate_argnums=...)(x, y)`` and
  ``pl.pallas_call(..., input_output_aliases=...)(x, y)``;

then at each call through a donating callable records which argument
NAMES died, and flags any later load of a dead name. A rebind
(assignment) resurrects the name — ``w = donating(w)`` is the intended
idiom. Only literal int/dict donation specs are tracked: a computed
spec (e.g. pallas_mu's conditional ``alias`` dict) marks the call
donating-with-unknown-positions, which kills nothing — the rule prefers
missed edges over false kills here because a false read-after-donate
error on the main kernel path would teach people to suppress the rule.
"""

from __future__ import annotations

import ast
from typing import Iterable

from nmfx.analysis.ast_scan import _attr_tail, own_nodes, stores
from nmfx.analysis.core import Finding, Rule, register


def _donated_positions(call: ast.Call) -> "tuple[str | None, set[int]]":
    """(kind, positions) for a jit/pallas_call constructor node.

    kind "callable": the call RESULT takes the buffers directly
    (``jax.jit(f, donate_argnums=...)``, ``pl.pallas_call(...,
    input_output_aliases=...)``) — calling it kills the positional args.
    kind "factory": one more application stands between this node and
    the buffers (``partial(jax.jit, donate_argnums=...)``) — calling IT
    produces a donating callable and kills nothing itself (its
    arguments are functions, not buffers). None: not donating.
    Positions are argument indices of the eventual buffer call; empty
    set means donating-with-unknown-positions (computed spec)."""
    tail = _attr_tail(call.func)
    kind = None
    if tail in ("jit", "pallas_call"):
        kind = "callable"
    elif tail == "partial" and call.args and \
            _attr_tail(call.args[0]) in ("jit", "pallas_call"):
        kind = "factory"
    if kind is None:
        return None, set()
    for kw in call.keywords:
        if kw.arg in ("donate_argnums", "donate_argnames"):
            if isinstance(kw.value, ast.Tuple):
                vals = kw.value.elts
            else:
                vals = [kw.value]
            # ints = call positions; strs (donate_argnames) = parameter
            # names, matched at the call site against keyword args and
            # same-named positional Name args (the common idiom)
            pos = {v.value for v in vals
                   if isinstance(v, ast.Constant)
                   and isinstance(v.value, (int, str))}
            known = all(isinstance(v, ast.Constant) for v in vals)
            return kind, (pos if known else set())
        if kw.arg == "input_output_aliases":
            if isinstance(kw.value, ast.Dict):
                pos = {k.value for k in kw.value.keys
                       if isinstance(k, ast.Constant)
                       and isinstance(k.value, int)}
                known = all(isinstance(k, ast.Constant)
                            for k in kw.value.keys)
                return kind, (pos if known else set())
            return kind, set()  # computed spec: donating, unknown args
    return None, set()


def _loads(stmt: ast.stmt) -> "Iterable[ast.Name]":
    for node in own_nodes(stmt):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            yield node


@register
class ReadAfterDonate(Rule):
    """NMFX003: a buffer read after being donated/aliased away."""

    rule_id = "NMFX003"
    title = "donation/aliasing safety"

    def check(self, project) -> "Iterable[Finding]":
        for mod in project.modules:
            for fn in mod.functions.values():
                yield from self._check_function(mod, fn)

    def _check_function(self, mod, fn) -> "Iterable[Finding]":
        if not isinstance(fn.node, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
            return  # lambdas: single expression, nothing to order
        yield from self._scan_block(mod, fn.node.body, {}, {})

    def _scan_block(self, mod, body, donating_vars, dead
                    ) -> "Iterable[Finding]":
        """One statement block in source order. Child blocks (if/for/
        try bodies) scan with COPIES of the donation state: kills made
        on one branch do not escape to statements after the compound —
        whether the donating call actually ran there is path-sensitive,
        and a false read-after-donate error would teach people to
        suppress the rule (missed cross-branch kills are the accepted
        cost; same philosophy as unknown-position donation specs).

        ``donating_vars``: name -> ("callable"|"factory", positions).
        ``dead``: buffer name -> (donation line, callee description).
        """
        for stmt in body:
            # reads of dead names first (the statement's loads happen
            # before its stores rebind anything)
            for load in _loads(stmt):
                if load.id in dead:
                    line, desc = dead[load.id]
                    yield self.finding(
                        mod.path, load.lineno,
                        f"'{load.id}' is read after being donated to "
                        f"{desc} at line {line}: donated buffers are "
                        "dead — on backends that honor donation the "
                        "read returns whatever the executable wrote "
                        "there (the round-3 alias_io hazard class; "
                        "CPU tests will NOT catch this). Re-bind the "
                        "result or copy before donating")
                    del dead[load.id]  # one report per death
            for call in self._calls(stmt):
                self._track(call, donating_vars, dead)
            for name in stores(stmt):
                dead.pop(name, None)
                donating_vars.pop(name, None)
            self._record_bindings(stmt, donating_vars)
            for field in ("body", "orelse", "finalbody"):
                child = getattr(stmt, field, None)
                if child and not isinstance(
                        stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield from self._scan_block(
                        mod, child, dict(donating_vars), dict(dead))
            for handler in getattr(stmt, "handlers", []) or []:
                yield from self._scan_block(
                    mod, handler.body, dict(donating_vars), dict(dead))

    @staticmethod
    def _calls(stmt: ast.stmt) -> "Iterable[ast.Call]":
        for node in own_nodes(stmt):
            if isinstance(node, ast.Call):
                yield node

    @staticmethod
    def _callable_kind(call: ast.Call, donating_vars):
        """What calling this node's FUNC yields: ("callable"|"factory",
        positions) for donation-relevant calls, else (None, set()).
        Covers constructors (``jit(f, donate_argnums=...)``,
        ``partial(jax.jit, ...)``), applied factories
        (``partial(jax.jit, ...)(f)`` / ``mk(f)``), and bound names."""
        if isinstance(call.func, ast.Call):
            inner_kind, pos = _donated_positions(call.func)
            if inner_kind == "callable":
                return "callable", pos
            if inner_kind == "factory":
                # `partial(jax.jit, ...)(f)` applies the factory: the
                # RESULT is the donating callable
                return "applied-factory", pos
            # `mk(f)` where mk is a bound factory: handled by the Name
            # branch below when the factory result is itself called —
            # an inner Call func that is a Name call through a factory
            inner = call.func
            if (isinstance(inner.func, ast.Name)
                    and donating_vars.get(inner.func.id,
                                          (None,))[0] == "factory"):
                return "callable", donating_vars[inner.func.id][1]
            return None, set()
        if isinstance(call.func, ast.Name) \
                and call.func.id in donating_vars:
            kind, pos = donating_vars[call.func.id]
            return kind, pos
        return None, set()

    def _record_bindings(self, stmt, donating_vars):
        """Bind names produced by donation constructors/factories:
        ``g = jax.jit(f, donate_argnums=...)`` (callable),
        ``mk = partial(jax.jit, donate_argnums=...)`` (factory),
        ``g = mk(f)`` / ``g = partial(jax.jit, ...)(f)`` (callable)."""
        if not isinstance(stmt, ast.Assign):
            return
        if not isinstance(stmt.value, ast.Call):
            return
        call = stmt.value
        kind, pos = _donated_positions(call)
        if kind is None:
            # applying a factory (inline `partial(jax.jit, ...)(f)` or a
            # bound `mk(f)`) yields the donating CALLABLE
            applied, apos = self._callable_kind(call, donating_vars)
            if applied in ("applied-factory", "factory"):
                kind, pos = "callable", apos
        if kind is not None:
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    donating_vars[tgt.id] = (kind, pos)

    def _track(self, call: ast.Call, donating_vars, dead):
        """Mark arguments killed by this call: only CALLABLE-kind calls
        take buffers (a factory's arguments are functions)."""
        kind, positions = self._callable_kind(call, donating_vars)
        if kind != "callable":
            return
        if isinstance(call.func, ast.Name):
            desc = f"'{call.func.id}'"
        elif isinstance(call.func, ast.Call):
            desc = _attr_tail(call.func.func) or "a donating callable"
        else:
            desc = "a donating callable"
        for i, arg in enumerate(call.args):
            # int entries match by position; str entries
            # (donate_argnames) match a positional Name whose variable
            # name equals the donated parameter name
            if isinstance(arg, ast.Name) and (i in positions
                                              or arg.id in positions):
                dead[arg.id] = (call.lineno, desc)
        for kw in call.keywords:
            if (kw.arg in positions
                    and isinstance(kw.value, ast.Name)):
                dead[kw.value.id] = (call.lineno, desc)