"""CLI entrypoint: ``python -m nmfx.analysis [paths] [options]``.

Exit code 0 when no unsuppressed, unbaselined ERROR findings remain;
1 otherwise; 2 on usage errors. ``--json`` emits one machine-readable
document (findings + summary) on stdout for CI consumption.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m nmfx.analysis",
        description="nmfx-lint: contract-checking static analysis "
                    "(see docs/analysis.md)")
    ap.add_argument("paths", nargs="*", default=["nmfx"],
                    help="files/directories to lint (default: nmfx)")
    ap.add_argument("--baseline", metavar="FILE", default=None,
                    help="JSON baseline of tolerated findings "
                         "(shipped policy: empty)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--no-jaxpr", action="store_true",
                    help="skip the engine-tracing layer (NMFX101/102) "
                         "for fast AST-only runs")
    ap.add_argument("--rules", metavar="IDS", default=None,
                    help="comma-separated rule ids to run (default all)")
    ap.add_argument("--write-baseline", metavar="FILE", default=None,
                    help="write the current unsuppressed findings as a "
                         "baseline file and exit 0")
    args = ap.parse_args(argv)

    from nmfx.analysis import active, run

    rule_ids = (None if args.rules is None
                else tuple(s.strip() for s in args.rules.split(",")
                           if s.strip()))
    try:
        findings = run(args.paths, baseline=args.baseline,
                       jaxpr=not args.no_jaxpr, rule_ids=rule_ids)
    except FileNotFoundError as e:
        print(f"nmfx-lint: {e}", file=sys.stderr)
        return 2

    errors = active(findings, "error")
    warnings = active(findings, "warning")

    if args.write_baseline:
        # include findings the CURRENT --baseline already tolerates —
        # refreshing a baseline in place must re-record them, not
        # truncate the file to [] because they were annotated away
        records = [{"file": f.file, "rule": f.rule_id, "line": f.line}
                   for f in findings if not f.suppressed]
        with open(args.write_baseline, "w") as fh:
            json.dump(records, fh, indent=2)
            fh.write("\n")
        print(f"nmfx-lint: wrote {len(records)} baseline records to "
              f"{args.write_baseline}")
        return 0

    if args.as_json:
        doc = {
            "findings": [f.to_json() for f in findings],
            "summary": {
                "errors": len(errors),
                "warnings": len(warnings),
                "suppressed": sum(f.suppressed for f in findings),
                "baselined": sum(f.baselined for f in findings),
            },
            "ok": not errors,
        }
        print(json.dumps(doc, indent=2))
    else:
        for f in findings:
            print(f.render())
        print(f"nmfx-lint: {len(errors)} error(s), {len(warnings)} "
              f"warning(s), {sum(f.suppressed for f in findings)} "
              f"suppressed, {sum(f.baselined for f in findings)} "
              "baselined")
    return 0 if not errors else 1


if __name__ == "__main__":
    raise SystemExit(main())
