"""CLI entrypoint: ``python -m nmfx.analysis [paths] [options]``.

Exit code 0 when no unsuppressed, unbaselined ERROR findings remain;
1 otherwise; 2 on usage errors. ``--json`` emits one machine-readable
document (findings + summary) on stdout for CI consumption.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m nmfx.analysis",
        description="nmfx-lint: contract-checking static analysis "
                    "(see docs/analysis.md)")
    ap.add_argument("paths", nargs="*", default=["nmfx"],
                    help="files/directories to lint (default: nmfx)")
    ap.add_argument("--baseline", metavar="FILE", default=None,
                    help="JSON baseline of tolerated findings "
                         "(shipped policy: empty)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--no-jaxpr", action="store_true",
                    help="skip the engine-tracing layer (NMFX101/102) "
                         "for fast AST-only runs")
    ap.add_argument("--rules", metavar="IDS", default=None,
                    help="comma-separated rule ids to run (default all)")
    ap.add_argument("--write-baseline", metavar="FILE", default=None,
                    help="write the current unsuppressed findings as a "
                         "baseline file and exit 0")
    ap.add_argument("--update-baseline", metavar="FILE", nargs="?",
                    const="lint_baseline.json", default=None,
                    help="regenerate a baseline file IN PLACE from the "
                         "current findings, preserving each surviving "
                         "record's required 'reason' field (default "
                         "target: lint_baseline.json); exits 0")
    args = ap.parse_args(argv)

    import os

    from nmfx.analysis import active, run

    rule_ids = (None if args.rules is None
                else tuple(s.strip() for s in args.rules.split(",")
                           if s.strip()))
    baseline_path = args.baseline
    if (baseline_path is None and args.update_baseline is not None
            and os.path.exists(args.update_baseline)):
        # refreshing in place: the current file's records must be
        # treated as tolerated (and re-recorded), not re-reported
        baseline_path = args.update_baseline
    try:
        findings = run(args.paths, baseline=baseline_path,
                       jaxpr=not args.no_jaxpr, rule_ids=rule_ids)
    except FileNotFoundError as e:
        print(f"nmfx-lint: {e}", file=sys.stderr)
        return 2

    errors = active(findings, "error")
    warnings = active(findings, "warning")

    if args.update_baseline:
        target = args.update_baseline
        old: "list[dict]" = []
        if os.path.exists(target):
            with open(target) as fh:
                old = json.load(fh)
        # reasons survive regeneration: exact (file, rule, line) match
        # first, then (file, rule) so a finding that merely moved keeps
        # its recorded justification instead of silently losing it
        exact: "dict[tuple, str]" = {}
        loose: "dict[tuple, str]" = {}
        for r in old:
            reason = str(r.get("reason") or "")
            if not reason:
                continue
            fkey = (os.path.abspath(str(r.get("file"))), r.get("rule"))
            exact[fkey + (r.get("line"),)] = reason
            loose.setdefault(fkey, reason)
        records = []
        for f in findings:
            if f.suppressed:
                continue
            fkey = (os.path.abspath(f.file), f.rule_id)
            records.append({"file": f.file, "rule": f.rule_id,
                            "line": f.line,
                            "reason": exact.get(fkey + (f.line,),
                                                loose.get(fkey, ""))})
        records.sort(key=lambda r: (r["file"], r["line"], r["rule"]))
        with open(target, "w") as fh:
            json.dump(records, fh, indent=2)
            fh.write("\n")
        missing = sum(1 for r in records if not r["reason"])
        msg = (f"nmfx-lint: rewrote {target} with {len(records)} "
               "baseline record(s)")
        if missing:
            msg += (f"; {missing} lack a 'reason' — every tolerated "
                    "finding needs one before review")
        print(msg)
        return 0

    if args.write_baseline:
        # include findings the CURRENT --baseline already tolerates —
        # refreshing a baseline in place must re-record them, not
        # truncate the file to [] because they were annotated away
        records = [{"file": f.file, "rule": f.rule_id, "line": f.line}
                   for f in findings if not f.suppressed]
        with open(args.write_baseline, "w") as fh:
            json.dump(records, fh, indent=2)
            fh.write("\n")
        print(f"nmfx-lint: wrote {len(records)} baseline records to "
              f"{args.write_baseline}")
        return 0

    if args.as_json:
        doc = {
            "findings": [f.to_json() for f in findings],
            "summary": {
                "errors": len(errors),
                "warnings": len(warnings),
                "suppressed": sum(f.suppressed for f in findings),
                "baselined": sum(f.baselined for f in findings),
            },
            "ok": not errors,
        }
        print(json.dumps(doc, indent=2))
    else:
        for f in findings:
            print(f.render())
        print(f"nmfx-lint: {len(errors)} error(s), {len(warnings)} "
              f"warning(s), {sum(f.suppressed for f in findings)} "
              f"suppressed, {sum(f.baselined for f in findings)} "
              "baselined")
    return 0 if not errors else 1


if __name__ == "__main__":
    raise SystemExit(main())
