"""NMFX009 — engine-family cost-model coverage.

The failure class: an engine whose dispatches the performance
observatory silently cannot see. ISSUE 13 promoted the bench's three
coarse per-algorithm FLOP formulas (mu/kl/hals — als/neals/snmf
reported ``mfu: None`` for five rounds and nothing flagged it) into the
registry-keyed table ``nmfx.obs.costmodel._FLOPS``/``_BYTES``, and
every dispatch-attribution surface (bench MFU, the ``nmfx_perf_*``
histograms, ``Profiler.report()``'s roofline verdicts) reads it. A new
algorithm, or a new engine-family routing for an existing one, that
lands without a model entry would ship with exactly the old blind spot
— dispatches run, ``mfu: None``, no roofline verdict, and no error
anywhere; a model entry for a REMOVED engine is a stale declaration
that can mask a rename (the successor engine ships unmodeled while the
table still "covers" the old name).

The rule cross-references the two AUTHORITATIVE declarations — the
reachable engine universe derived from the live routing tables
(``costmodel.engine_universe()``: the solver registry ×
``PACKED_ALGORITHMS``/``SKETCHED_ALGORITHMS``/the slot-scheduler
backend table) and the literal model-table coverage
(``costmodel.covered_engines()``) — plus the ``COSTMODEL_EXEMPT``
honesty conditions (an exempt algorithm must not also be modeled; an
exemption must name a registered algorithm). Same hook-vs-universe
shape as NMFX001 (config-fingerprint coverage), NMFX007
(checkpoint-manifest coverage), and NMFX008 (fault-event coverage); the
check itself is the pure function ``costmodel.
check_costmodel_coverage`` so the per-rule tests inject mutated
universes, and this wrapper reads the live modules and anchors findings
at the ``_FLOPS`` declaration.
"""

from __future__ import annotations

import ast
from typing import Iterable

from nmfx.analysis.core import Finding, Rule, register


def _flops_decl_line(tree: ast.Module) -> int:
    """Line of the module-level ``_FLOPS = {...}`` assignment, best
    effort (findings anchor there — the table a new engine's entry
    belongs in)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "_FLOPS":
                    return node.lineno
    return 1


def _live_universe() -> dict:
    from nmfx.obs import costmodel
    from nmfx.solvers import SOLVERS

    return dict(universe=costmodel.engine_universe(),
                covered=costmodel.covered_engines(),
                exempt=tuple(costmodel.COSTMODEL_EXEMPT),
                algorithms=frozenset(SOLVERS))


@register
class CostModelCoverage(Rule):
    """NMFX009: every reachable (algorithm, engine-family) pair must
    have a FLOPs+bytes cost model in nmfx.obs.costmodel (or an honest
    COSTMODEL_EXEMPT rationale), and no model/exemption entry may go
    stale."""

    rule_id = "NMFX009"
    title = "engine-family cost-model coverage"

    def check(self, project) -> "Iterable[Finding]":
        # semantic whole-package rule (the NMFX001/NMFX007/NMFX008
        # gating): runs only when the real package is the analyzed
        # set, and only against the checkout the import machinery
        # resolves
        import inspect
        import os

        analyzed = next(
            (m for m in project.modules
             if m.path.replace("\\", "/").endswith(
                 "nmfx/obs/costmodel.py")),
            None)
        if analyzed is None:
            return []
        from nmfx.obs import costmodel
        from nmfx.obs.costmodel import check_costmodel_coverage

        live_file = inspect.getsourcefile(costmodel) or analyzed.path
        if os.path.abspath(live_file) != os.path.abspath(analyzed.path):
            # NMFX001 already reports the wrong-tree condition loudly;
            # don't double-report it per rule
            return []
        line = _flops_decl_line(analyzed.tree)
        return [self.finding(analyzed.path, line, msg)
                for msg in check_costmodel_coverage(**_live_universe())]
