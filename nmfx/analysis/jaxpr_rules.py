"""NMFX101/NMFX102 — jaxpr-level contract checks on the registered engines.

The AST layer reads what the code SAYS; this layer reads what JAX
actually traces. Each registered batched engine (one ``mu_grid`` per
algorithm in ``grid_mu.BLOCKS``, plus the slot scheduler ``mu_sched``)
is traced to a jaxpr with small abstract inputs and walked recursively
(while/scan/cond sub-jaxprs included):

* **NMFX101 — f64 leak.** With ``jax_enable_x64`` enabled (the parity
  configuration ``tests/test_x64_parity.py`` runs under) an engine
  configured ``dtype="float32"`` must stay f32: any float64-producing
  equation — a ``convert_element_type`` to f64 or an op with an f64
  output aval — means a Python/NumPy double leaked into the traced
  math (a weak-typed scalar, an np.float64 config value). Under the
  normal f32 session such a leak is INVISIBLE (x64-off silently
  downcasts it); under the documented parity workflow it silently
  doubles compute and diverges from the f32 fleet. The suite probes
  this only dynamically, per release; the lint proves it per engine,
  statically.

* **NMFX102 — transfer in the loop body.** The transfer-overlap
  contract (docs/design.md §5b, the exec-cache pipeline) assumes the
  solve loop is transfer-free: every host↔device movement happens
  before dispatch or after harvest. A ``device_put`` equation inside a
  ``while``/``scan`` body re-stages a buffer every iteration — the
  round-trip-per-trip class the round-5 trace decomposition hunted at
  microsecond scale. Integer iota/broadcast constants are fine; actual
  ``device_put`` in a loop body is not.

Engines are traced, never compiled or executed — CPU-cheap (the whole
layer runs in a few seconds) and shape-independent by design: the tiny
trace shapes see the same program structure the north-star shapes do,
because the engines are shape-polymorphic up to padding.
"""

from __future__ import annotations

from typing import Iterable

from nmfx.analysis.core import Finding, Rule, register


def _engine_specs():
    """(name, thunk) per registered engine; each thunk returns a traced
    ClosedJaxpr. Imported lazily — the AST rules must not pay the jax
    import."""
    import jax

    from nmfx.config import SolverConfig
    from nmfx.ops.grid_mu import BLOCKS, mu_grid
    from nmfx.ops.sched_mu import mu_sched

    m, n, k, b = 16, 12, 2, 4
    specs = []

    def _abstract(shape, dtype="float32"):
        import jax.numpy as jnp

        return jax.ShapeDtypeStruct(shape, jnp.dtype(dtype))

    def _grid_thunk(algorithm):
        def thunk():
            cfg = SolverConfig(algorithm=algorithm, max_iter=4,
                               backend="packed")
            # job_ks: the exact per-lane ranks — the direct-driver
            # idiom (grid_mu.pad_live_mask) the round-5 advisor asked
            # every caller that knows its lane composition to use
            return jax.make_jaxpr(
                lambda a, w0, h0: mu_grid(a, w0, h0, cfg,
                                          job_ks=(k,) * b))(
                    _abstract((m, n)), _abstract((b, m, k)),
                    _abstract((b, k, n)))
        return thunk

    for algorithm in sorted(BLOCKS):
        specs.append((f"mu_grid[{algorithm}]", _grid_thunk(algorithm)))

    def _sched_thunk():
        cfg = SolverConfig(algorithm="mu", max_iter=4, backend="packed")
        return jax.make_jaxpr(
            lambda a, w0, h0: mu_sched(a, w0, h0, cfg, slots=2,
                                       tail_slots=None,
                                       job_ks=(k,) * b))(
                _abstract((m, n)), _abstract((b, m, k)),
                _abstract((b, k, n)))

    specs.append(("mu_sched[mu]", _sched_thunk))
    return specs


def _walk_eqns(jaxpr, in_loop: bool = False):
    """Yield (eqn, in_loop) over a jaxpr and every sub-jaxpr; in_loop
    marks equations inside a while/scan body."""
    for eqn in jaxpr.eqns:
        yield eqn, in_loop
        looping = in_loop or eqn.primitive.name in ("while", "scan")
        for sub in _sub_jaxprs(eqn):
            yield from _walk_eqns(sub, looping)


def _sub_jaxprs(eqn):
    for val in eqn.params.values():
        for item in (val if isinstance(val, (list, tuple)) else [val]):
            jx = getattr(item, "jaxpr", None)
            if jx is not None:
                yield jx
            elif hasattr(item, "eqns"):
                yield item


def check_engine_jaxpr(name: str, closed_jaxpr) -> "list[str]":
    """The pure per-jaxpr checks; returns problem strings. Split out so
    the rule tests can feed deliberately-bad jaxprs."""
    problems = []
    f64_lines = set()
    for eqn, in_loop in _walk_eqns(closed_jaxpr.jaxpr):
        prim = eqn.primitive.name
        if prim == "convert_element_type":
            new = str(eqn.params.get("new_dtype"))
            if new == "float64":
                f64_lines.add(
                    f"{name}: convert_element_type to float64 "
                    "(a Python/NumPy double leaked into the traced "
                    "math — under x64 parity runs the f32 engine "
                    "silently computes in f64)")
        for var in eqn.outvars:
            aval = getattr(var, "aval", None)
            if aval is not None and str(getattr(aval, "dtype", ""
                                                )) == "float64":
                f64_lines.add(
                    f"{name}: op '{prim}' produces a float64 value in "
                    "an engine configured dtype='float32' — x64-parity "
                    "contract violation")
        if prim == "device_put" and in_loop:
            problems.append(
                f"{name}: device_put inside a while/scan body — the "
                "solve loop must be transfer-free (docs/design.md §5b); "
                "a per-iteration restage defeats the transfer-overlap "
                "pipeline")
    problems.extend(sorted(f64_lines))
    return problems


def run_jaxpr_checks() -> "list[tuple[str, str, str]]":
    """Trace every registered engine under x64 (the parity
    configuration) and run the checks. Returns (engine, rule_id,
    message) triples; tracing failures surface as NMFX101 problems
    rather than crashing the linter."""
    import jax

    out = []
    try:
        ctx_factory = jax.experimental.enable_x64
    except AttributeError:
        # without x64 the f64-leak check would be a silent false-clean
        # (x64-off downcasts the very leaks NMFX101 exists to see) —
        # report the capability gap as a finding instead of passing
        out.append((
            "jaxpr-layer", "NMFX101",
            "this jax build has no jax.experimental.enable_x64 — the "
            "engines cannot be traced under the x64 parity "
            "configuration, so the NMFX101 f64-leak contract is "
            "UNVERIFIED (not clean). Run the linter on a jax with the "
            "context manager, or suppress via baseline with that "
            "reason on record"))
        return out
    for name, thunk in _engine_specs():
        try:
            with ctx_factory(True):
                closed = thunk()
                problems = check_engine_jaxpr(name, closed)
        except Exception as e:  # nmfx: ignore[NMFX006] -- becomes a finding below
            out.append((name, "NMFX101",
                        f"{name}: engine failed to trace abstractly "
                        f"({type(e).__name__}: {e}) — every registered "
                        "engine must trace with abstract inputs"))
            continue
        for msg in problems:
            rule = "NMFX102" if "device_put" in msg else "NMFX101"
            out.append((name, rule, msg))
    return out


def _project_jaxpr_results(project) -> "list[tuple[str, str, str]]":
    """Engine tracing is shared (and memoized on the project) between
    the two jaxpr rules, so running both costs one trace of each
    engine — and ``--rules NMFX102`` alone still traces."""
    cached = getattr(project, "_jaxpr_results", None)
    if cached is None:
        cached = run_jaxpr_checks()
        project._jaxpr_results = cached
    return cached


class _JaxprRule(Rule):
    """Base for the jaxpr-layer rules: emits only the findings bearing
    its own rule id from the shared engine-trace results."""

    def check(self, project) -> "Iterable[Finding]":
        if not getattr(project, "jaxpr_checks_enabled", False):
            return
        for _name, rule_id, msg in _project_jaxpr_results(project):
            if rule_id != self.rule_id:
                continue
            # findings anchor at the engine registries rather than a
            # synthetic location — at the ANALYZED module's path when
            # present, so inline suppressions/baselines (both matched
            # by abspath against the analyzed sources) can reach them
            # from any invocation cwd
            rel = ("nmfx/ops/sched_mu.py" if "mu_sched" in msg
                   else "nmfx/ops/grid_mu.py")
            path = next(
                (m.path for m in project.modules
                 if m.path.replace("\\", "/").endswith(rel)), rel)
            yield Finding(file=path, line=1, rule_id=rule_id,
                          message=msg, severity="error")


@register
class EngineX64Parity(_JaxprRule):
    """NMFX101: traced engines stay f32 under x64; no f64 leaks."""

    rule_id = "NMFX101"
    title = "engine jaxpr x64-parity contract"


@register
class EngineLoopTransferFree(_JaxprRule):
    """NMFX102: no device_put inside engine while/scan bodies."""

    rule_id = "NMFX102"
    title = "engine loop bodies transfer-free"
