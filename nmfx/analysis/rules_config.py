"""NMFX001 — config-fingerprint coverage.

The silent-corruption class this rule kills: a numerics-affecting
``SolverConfig``/``ExperimentalConfig`` field that never reaches the
registry fingerprint (``nmfx/registry.py``) lets a checkpoint written
under one configuration resume under another — plausible factors, wrong
numbers, no crash (the exact hazard the fingerprint's v3→v6 history in
``registry.py`` documents release by release). The same field missing
from the exec-cache bucket key (``nmfx/exec_cache.py``) serves one
compiled executable to two configurations that should compile
differently.

The rule cross-references three AUTHORITATIVE declarations (the
introspection hooks added for it — no hash-body parsing):

* ``dataclasses.fields(SolverConfig/ExperimentalConfig)`` — what exists;
* ``registry.FINGERPRINT_SOLVER_EXCLUDED`` + ``fingerprint_solver_fields``
  — what the fingerprint covers;
* ``SolverConfig.NON_NUMERICS_FIELDS`` — which fields are DECLARED
  execution-strategy-only (the only legitimate exclusions);
* ``exec_cache.solver_key_fields()`` — what the in-memory bucket key
  covers (dataclass hash/eq → ``field.compare``);
* ``exec_cache.persist_key_fields()`` — what the PERSISTENT disk key
  covers (dataclass repr → ``field.repr``): a field added with
  ``repr=False`` stays in the in-memory key but vanishes from the disk
  key, so two configs differing only in it would share one on-disk
  entry and a fresh process would deserialize the wrong executable.
* ``data_cache.data_key_fields()`` — what the device-resident input
  cache's content-fingerprint key (``data_cache.DataKey``) compares: a
  key field added with ``compare=False`` would serve ONE resident
  device buffer to two (matrix, placement) pairs that must differ —
  the data-plane twin of the executable-key hazard above.
* ``serve.serve_key_fields()`` — what the serving front-end's
  :class:`ServeConfig` comparison covers (``field.compare``): the
  bench traffic stage and tests compare serving policies by dataclass
  eq/hash, so a field added with ``compare=False`` would alias two
  different admission/packing policies onto one — the control-plane
  twin of the key hazards above.
* ``autotune.autotune_key_fields()`` — what the block-shape autotune
  store's key covers, against the declared tunable exemptions
  (``autotune.AUTOTUNE_EXEMPT_SOLVER`` /
  ``AUTOTUNE_EXEMPT_EXPERIMENTAL``): a config field outside both would
  let a shape tuned under one configuration be SERVED to another that
  compiles (and times) differently — a silent performance downgrade,
  and for fields like ``use_tol_checks`` a tuned ``check_block`` the
  scheduler then rejects outright.

Every field must be fingerprint-covered or declared non-numerics; every
exclusion must be declared; the declaration must not go stale; both
config dataclasses must stay frozen-with-hash (the bucket key and jit
static-argument machinery depend on it); nothing may be missing from
either exec-cache key. The check itself is a pure function over field
sets (``check_config_coverage``) so the per-rule tests can inject a
mutated universe and watch the rule fire.
"""

from __future__ import annotations

import dataclasses
import inspect
from typing import Iterable

from nmfx.analysis.core import Finding, Rule, register


def _decl_site(obj, fallback_file: str) -> "tuple[str, int]":
    """file:line of a class/module-level declaration, best effort."""
    try:
        f = inspect.getsourcefile(obj) or fallback_file
        _, line = inspect.getsourcelines(obj)
        return f, line
    except (OSError, TypeError):
        return fallback_file, 1


def check_config_coverage(
    solver_fields: "frozenset[str]",
    experimental_fields: "frozenset[str]",
    fingerprint_covered: "frozenset[str]",
    fingerprint_excluded: "tuple[str, ...]",
    declared_non_numerics: "tuple[str, ...]",
    exec_key_covered: "frozenset[str]",
    hashable_configs: "dict[str, bool]",
    fingerprint_resolved: "tuple[str, ...]" = (),
    noncompare_fields: "dict[str, tuple[str, ...]]" = {},
    persist_key_covered: "frozenset[str] | None" = None,
    nonrepr_fields: "dict[str, tuple[str, ...]]" = {},
    data_fields: "frozenset[str] | None" = None,
    data_key_covered: "frozenset[str] | None" = None,
    serve_fields: "frozenset[str] | None" = None,
    serve_key_covered: "frozenset[str] | None" = None,
    autotune_solver_covered: "frozenset[str] | None" = None,
    autotune_experimental_covered: "frozenset[str] | None" = None,
    autotune_exempt_solver: "tuple[str, ...]" = (),
    autotune_exempt_experimental: "tuple[str, ...]" = (),
) -> "list[str]":
    """The pure contract check; returns human-readable problems.

    Parameters default to nothing — the Rule wrapper reads the live
    modules; tests inject mutated universes (a field dropped from
    ``fingerprint_covered``, an exclusion not declared) and assert the
    corresponding message appears.
    """
    problems: "list[str]" = []
    # 1. declarations must not go stale
    for name in declared_non_numerics:
        if name not in solver_fields:
            problems.append(
                f"SolverConfig.NON_NUMERICS_FIELDS names {name!r}, which "
                "is not a SolverConfig field — stale declaration")
    for name in fingerprint_resolved:
        if name not in solver_fields:
            problems.append(
                f"registry.FINGERPRINT_SOLVER_RESOLVED names {name!r}, "
                "which is not a SolverConfig field — stale declaration")
    # 2. every fingerprint exclusion must be a declared non-numerics
    #    field (numerics-affecting fields may NEVER be excluded)
    for name in fingerprint_excluded:
        if name not in declared_non_numerics:
            problems.append(
                f"SolverConfig.{name} is excluded from the registry "
                "fingerprint (registry.FINGERPRINT_SOLVER_EXCLUDED) but "
                "not declared execution-strategy-only in "
                "SolverConfig.NON_NUMERICS_FIELDS — a numerics-affecting "
                "field excluded from the fingerprint resumes stale "
                "checkpoints silently")
    # 3. every field must reach the fingerprint unless declared
    for name in sorted(solver_fields - fingerprint_covered):
        if name not in declared_non_numerics:
            problems.append(
                f"SolverConfig.{name} does not reach the registry "
                "fingerprint and is not declared in NON_NUMERICS_FIELDS "
                "— checkpoints written under different values of it "
                "would be served interchangeably")
    # 4. the exec-cache bucket key must cover every field that can
    #    change the compiled program (everything; even declared
    #    non-numerics fields like restart_chunk change program
    #    STRUCTURE, so nothing may be missing here)
    for name in sorted(solver_fields - exec_key_covered):
        problems.append(
            f"SolverConfig.{name} is not covered by the exec-cache "
            "bucket key (exec_cache.solver_key_fields) — two configs "
            "differing in it would share one compiled executable")
    # 4b. the PERSISTENT disk key must cover the same universe: it is
    #     derived from the key's repr (field.repr), so a repr=False
    #     field survives the in-memory key but drops out of the disk
    #     key — a fresh process would deserialize the wrong executable
    if persist_key_covered is not None:
        for name in sorted(solver_fields - persist_key_covered):
            problems.append(
                f"SolverConfig.{name} is not covered by the persistent "
                "exec-cache disk key (exec_cache.persist_key_fields) — "
                "disk entries written under different values of it would "
                "be served interchangeably across processes")
    # 5. the nested experimental knobs ride along via the
    #    'experimental' field; it must itself be covered on both sides
    if experimental_fields and "experimental" not in fingerprint_covered:
        problems.append(
            "SolverConfig.experimental (the ExperimentalConfig knobs) "
            "does not reach the registry fingerprint — every "
            f"experimental field ({', '.join(sorted(experimental_fields))}) "
            "is numerics-affecting by definition")
    # 6. both config dataclasses must stay frozen-with-hash: the bucket
    #    key and jit static-argnames hash the VALUES
    for cls_name, ok in hashable_configs.items():
        if not ok:
            problems.append(
                f"{cls_name} is not a frozen/hashable dataclass — the "
                "exec-cache bucket key and jit static-argument caching "
                "hash config values; an unhashable config breaks both")
    # 7. no field anywhere in the config tree may opt out of comparison:
    #    dataclass __eq__/__hash__ skip compare=False fields, so two
    #    configs differing there would hash equal and share one cached
    #    executable — including fields of the NESTED ExperimentalConfig,
    #    which ride into the bucket key through SolverConfig's hash
    for cls_name, names in noncompare_fields.items():
        for name in names:
            problems.append(
                f"{cls_name}.{name} is declared compare=False — it is "
                "invisible to dataclass __eq__/__hash__ and therefore "
                "to the exec-cache bucket key and jit static-argument "
                "caching; two configs differing in it would share one "
                "compiled executable")
    # 8. ...and none may opt out of REPR either: the persistent disk key
    #    is the key's repr, and dataclass __repr__ elides repr=False
    #    fields — including fields of the NESTED ExperimentalConfig,
    #    which the SolverConfig-level persist_key_fields hook cannot
    #    see. Such a field would stay in the in-memory key (hash/eq)
    #    but vanish from the disk key, so a fresh process would
    #    deserialize the wrong executable.
    for cls_name, names in nonrepr_fields.items():
        for name in names:
            problems.append(
                f"{cls_name}.{name} is declared repr=False — it is "
                "invisible to the repr-derived persistent exec-cache "
                "disk key (exec_cache.persist_key_fields); disk entries "
                "written under different values of it would be served "
                "interchangeably across processes")
    # 9. the device-resident input cache's DataKey must compare on
    #    every field it declares: the cache looks entries up by the
    #    key's dataclass hash/eq, so a compare=False field would alias
    #    two (matrix, placement) pairs onto one cached device buffer —
    #    the data-plane twin of the executable-key hazards above
    if data_fields is not None and data_key_covered is not None:
        for name in sorted(data_fields - data_key_covered):
            problems.append(
                f"DataKey.{name} is not covered by the device-resident "
                "input-cache key (data_cache.data_key_fields) — two "
                "placements differing in it would share one cached "
                "device buffer")
    # 10. the serving front-end's ServeConfig must compare on every
    #     field: serving policies are compared/keyed by dataclass
    #     eq/hash (bench traffic stage, comparable-server tests), so a
    #     compare=False field would alias two different admission/
    #     packing/deadline policies onto one
    if serve_fields is not None and serve_key_covered is not None:
        for name in sorted(serve_fields - serve_key_covered):
            problems.append(
                f"ServeConfig.{name} is not covered by the serving-"
                "policy fingerprint (serve.serve_key_fields) — two "
                "serving policies differing in it would compare equal")
    # 11. the block-shape autotune store's key must cover every config
    #     field that is not a DECLARED tunable: a tunable is what the
    #     stored entry decides (so it must be normalized out of the
    #     key), while any other field outside the key would serve one
    #     tuned shape to two configs whose kernels compile — and time —
    #     differently (a silent performance downgrade, or a tuned
    #     check_block the scheduler rejects under the other config)
    if autotune_solver_covered is not None:
        for name in autotune_exempt_solver:
            if name not in solver_fields:
                problems.append(
                    "autotune.AUTOTUNE_EXEMPT_SOLVER names "
                    f"{name!r}, which is not a SolverConfig field — "
                    "stale declaration")
        for name in sorted(solver_fields - autotune_solver_covered):
            if name not in autotune_exempt_solver:
                problems.append(
                    f"SolverConfig.{name} neither reaches the autotune "
                    "store key (autotune.autotune_key_fields) nor is "
                    "declared tunable in AUTOTUNE_EXEMPT_SOLVER — a "
                    "shape tuned under one value would be served to "
                    "the other")
        for name in autotune_exempt_solver:
            if name in autotune_solver_covered:
                problems.append(
                    f"SolverConfig.{name} is declared tunable in "
                    "AUTOTUNE_EXEMPT_SOLVER but still reaches the "
                    "autotune key — the entry could never be applied "
                    "to the field it claims to decide; drop one "
                    "declaration")
    if autotune_experimental_covered is not None:
        for name in autotune_exempt_experimental:
            if name not in experimental_fields:
                problems.append(
                    "autotune.AUTOTUNE_EXEMPT_EXPERIMENTAL names "
                    f"{name!r}, which is not an ExperimentalConfig "
                    "field — stale declaration")
        for name in sorted(
                experimental_fields - autotune_experimental_covered):
            if name not in autotune_exempt_experimental:
                problems.append(
                    f"ExperimentalConfig.{name} neither reaches the "
                    "autotune store key (autotune.autotune_key_fields) "
                    "nor is declared tunable in "
                    "AUTOTUNE_EXEMPT_EXPERIMENTAL — a shape tuned "
                    "under one value would be served to the other")
        for name in autotune_exempt_experimental:
            if name in autotune_experimental_covered:
                problems.append(
                    f"ExperimentalConfig.{name} is declared tunable in "
                    "AUTOTUNE_EXEMPT_EXPERIMENTAL but still reaches "
                    "the autotune key — the entry could never be "
                    "applied to the field it claims to decide; drop "
                    "one declaration")
    return problems


def _live_universe():
    from nmfx import autotune, data_cache, exec_cache, registry, serve
    from nmfx.config import ExperimentalConfig, SolverConfig

    def _hashable(cls) -> bool:
        return (dataclasses.is_dataclass(cls)
                and cls.__hash__ is not None
                and cls.__dataclass_params__.frozen)

    at_solver, at_experimental = autotune.autotune_key_fields()
    return dict(
        solver_fields=frozenset(
            f.name for f in dataclasses.fields(SolverConfig)),
        experimental_fields=frozenset(
            f.name for f in dataclasses.fields(ExperimentalConfig)),
        fingerprint_covered=registry.fingerprint_solver_fields(),
        fingerprint_excluded=tuple(registry.FINGERPRINT_SOLVER_EXCLUDED),
        fingerprint_resolved=tuple(registry.FINGERPRINT_SOLVER_RESOLVED),
        declared_non_numerics=tuple(SolverConfig.NON_NUMERICS_FIELDS),
        exec_key_covered=exec_cache.solver_key_fields(),
        persist_key_covered=exec_cache.persist_key_fields(),
        data_fields=frozenset(
            f.name for f in dataclasses.fields(data_cache.DataKey)),
        data_key_covered=data_cache.data_key_fields(),
        serve_fields=frozenset(
            f.name for f in dataclasses.fields(serve.ServeConfig)),
        serve_key_covered=serve.serve_key_fields(),
        hashable_configs={"SolverConfig": _hashable(SolverConfig),
                          "ExperimentalConfig": _hashable(
                              ExperimentalConfig),
                          "DataKey": _hashable(data_cache.DataKey),
                          "ServeConfig": _hashable(serve.ServeConfig)},
        noncompare_fields={
            cls.__name__: tuple(f.name
                                for f in dataclasses.fields(cls)
                                if not f.compare)
            for cls in (SolverConfig, ExperimentalConfig)},
        nonrepr_fields={
            cls.__name__: tuple(f.name
                                for f in dataclasses.fields(cls)
                                if not f.repr)
            for cls in (SolverConfig, ExperimentalConfig)},
        autotune_solver_covered=at_solver,
        autotune_experimental_covered=at_experimental,
        autotune_exempt_solver=tuple(
            sorted(autotune.AUTOTUNE_EXEMPT_SOLVER)),
        autotune_exempt_experimental=tuple(
            sorted(autotune.AUTOTUNE_EXEMPT_EXPERIMENTAL)),
    )


def check_manifest_coverage(
    solver_fields: "frozenset[str]",
    consensus_fields: "frozenset[str]",
    manifest_solver: "frozenset[str]",
    manifest_consensus: "frozenset[str]",
    declared_non_numerics: "tuple[str, ...]",
    manifest_consensus_excluded: "tuple[str, ...]",
    declared_checkpoint_exempt: "tuple[str, ...]",
) -> "list[str]":
    """NMFX007's pure contract check (the ``check_config_coverage``
    pattern): every result-affecting ``SolverConfig``/``ConsensusConfig``
    field must appear in ``checkpoint.manifest_key_fields()`` or be
    explicitly declared exempt — a field invisible to the manifest lets
    a durable-sweep ledger written under one configuration resume under
    another (plausible records, wrong numbers, no crash: the
    stale-resume class). Tests inject mutated universes; the Rule
    wrapper reads the live modules."""
    problems: "list[str]" = []
    # 1. declarations must not go stale
    for name in declared_checkpoint_exempt:
        if name not in consensus_fields:
            problems.append(
                f"ConsensusConfig.CHECKPOINT_EXEMPT_FIELDS names {name!r}, "
                "which is not a ConsensusConfig field — stale declaration")
    # 2. every manifest exclusion must be a declared exempt field
    for name in manifest_consensus_excluded:
        if name not in declared_checkpoint_exempt:
            problems.append(
                f"ConsensusConfig.{name} is excluded from the checkpoint "
                "manifest (checkpoint.MANIFEST_CONSENSUS_EXCLUDED) but "
                "not declared in "
                "ConsensusConfig.CHECKPOINT_EXEMPT_FIELDS — a result-"
                "affecting field excluded from the manifest resumes "
                "stale ledgers silently")
    # 3. every SolverConfig field must reach the manifest unless it is
    #    declared execution-strategy-only (the registry-fingerprint
    #    discipline, shared declaration)
    for name in sorted(solver_fields - manifest_solver):
        if name not in declared_non_numerics:
            problems.append(
                f"SolverConfig.{name} does not reach the checkpoint "
                "manifest (checkpoint.manifest_key_fields()['solver']) "
                "and is not declared in NON_NUMERICS_FIELDS — ledgers "
                "written under different values of it would resume "
                "interchangeably")
    # 4. every ConsensusConfig field must reach the manifest unless
    #    declared checkpoint-exempt (with its rationale on record)
    for name in sorted(consensus_fields - manifest_consensus):
        if name not in declared_checkpoint_exempt:
            problems.append(
                f"ConsensusConfig.{name} does not reach the checkpoint "
                "manifest (checkpoint.manifest_key_fields()"
                "['consensus']) and is not declared in "
                "CHECKPOINT_EXEMPT_FIELDS — ledgers written under "
                "different values of it would resume interchangeably")
    return problems


def _live_manifest_universe():
    from nmfx import checkpoint
    from nmfx.config import ConsensusConfig, SolverConfig

    covered = checkpoint.manifest_key_fields()
    return dict(
        solver_fields=frozenset(
            f.name for f in dataclasses.fields(SolverConfig)),
        consensus_fields=frozenset(
            f.name for f in dataclasses.fields(ConsensusConfig)),
        manifest_solver=covered["solver"],
        manifest_consensus=covered["consensus"],
        declared_non_numerics=tuple(SolverConfig.NON_NUMERICS_FIELDS),
        manifest_consensus_excluded=tuple(
            checkpoint.MANIFEST_CONSENSUS_EXCLUDED),
        declared_checkpoint_exempt=tuple(
            ConsensusConfig.CHECKPOINT_EXEMPT_FIELDS),
    )


@register
class CheckpointManifestCoverage(Rule):
    """NMFX007: every result-affecting SolverConfig/ConsensusConfig
    field must reach the durable-sweep checkpoint manifest
    (``nmfx.checkpoint.manifest_key_fields``) or be explicitly declared
    exempt with its rationale."""

    rule_id = "NMFX007"
    title = "checkpoint-manifest coverage"

    def check(self, project) -> "Iterable[Finding]":
        # semantic whole-package rule, same gating as NMFX001: run only
        # when the real package is the analyzed set, and only against
        # the checkout the import machinery actually resolves
        import os

        analyzed_cfg = next(
            (m.path for m in project.modules
             if m.path.replace("\\", "/").endswith("nmfx/config.py")),
            None)
        if analyzed_cfg is None:
            return []
        from nmfx.config import ConsensusConfig

        cfg_file, cfg_line = _decl_site(ConsensusConfig, "nmfx/config.py")
        if os.path.abspath(cfg_file) != os.path.abspath(analyzed_cfg):
            # NMFX001 already reports the wrong-tree condition loudly;
            # don't double-report it per rule
            return []
        return [self.finding(cfg_file, cfg_line, msg)
                for msg in check_manifest_coverage(
                    **_live_manifest_universe())]


def check_result_cache_coverage(
    solver_fields: "frozenset[str]",
    consensus_fields: "frozenset[str]",
    cache_solver: "frozenset[str]",
    cache_consensus: "frozenset[str]",
    declared_non_numerics: "tuple[str, ...]",
    declared_result_cache_exempt: "tuple[str, ...]",
) -> "list[str]":
    """NMFX011's pure contract check (the ``check_config_coverage``
    pattern): every result-affecting ``SolverConfig``/``ConsensusConfig``
    field must appear in ``result_cache.cache_key_fields()`` or be
    explicitly declared exempt. A field invisible to the result-cache
    key lets a finished consensus computed under one configuration be
    SERVED verbatim to a request for another — plausible result, wrong
    numbers, no crash, and unlike a stale checkpoint resume the cache
    replays it in O(1) forever. Note the asymmetry with NMFX007: the
    checkpoint ledger legitimately exempts ``restarts``/``ks`` (its
    per-(k, chunk) records make them resumable deltas), but the result
    cache stores the FINISHED result, so those fields MUST be in this
    key — which is why the exemption list is a separate declaration
    (``ConsensusConfig.RESULT_CACHE_EXEMPT_FIELDS``), not a reuse of
    ``CHECKPOINT_EXEMPT_FIELDS``. Tests inject mutated universes; the
    Rule wrapper reads the live modules."""
    problems: "list[str]" = []
    # 1. declarations must not go stale
    for name in declared_result_cache_exempt:
        if name not in consensus_fields:
            problems.append(
                "ConsensusConfig.RESULT_CACHE_EXEMPT_FIELDS names "
                f"{name!r}, which is not a ConsensusConfig field — "
                "stale declaration")
    # 2. every SolverConfig field must reach the result-cache key
    #    unless declared execution-strategy-only (the shared
    #    NON_NUMERICS_FIELDS declaration: those fields change
    #    scheduling, never the finished numbers, so excluding them is
    #    what makes a restart_chunk-retuned rerun a HIT)
    for name in sorted(solver_fields - cache_solver):
        if name not in declared_non_numerics:
            problems.append(
                f"SolverConfig.{name} does not reach the result-cache "
                "key (result_cache.cache_key_fields()['solver']) and "
                "is not declared in NON_NUMERICS_FIELDS — finished "
                "results computed under different values of it would "
                "be served interchangeably")
    # 3. every ConsensusConfig field must reach the key unless
    #    declared result-cache-exempt (with its rationale on record)
    for name in sorted(consensus_fields - cache_consensus):
        if name not in declared_result_cache_exempt:
            problems.append(
                f"ConsensusConfig.{name} does not reach the result-"
                "cache key (result_cache.cache_key_fields()"
                "['consensus']) and is not declared in "
                "RESULT_CACHE_EXEMPT_FIELDS — finished results "
                "computed under different values of it would be "
                "served interchangeably")
    # 4. a field both declared exempt AND covered is a contradictory
    #    declaration — one of the two is stale
    for name in declared_result_cache_exempt:
        if name in cache_consensus:
            problems.append(
                f"ConsensusConfig.{name} is declared in "
                "RESULT_CACHE_EXEMPT_FIELDS but still reaches the "
                "result-cache key — contradictory declarations; "
                "drop one")
    return problems


def _live_result_cache_universe():
    from nmfx import result_cache
    from nmfx.config import ConsensusConfig, SolverConfig

    covered = result_cache.cache_key_fields()
    return dict(
        solver_fields=frozenset(
            f.name for f in dataclasses.fields(SolverConfig)),
        consensus_fields=frozenset(
            f.name for f in dataclasses.fields(ConsensusConfig)),
        cache_solver=covered["solver"],
        cache_consensus=covered["consensus"],
        declared_non_numerics=tuple(SolverConfig.NON_NUMERICS_FIELDS),
        declared_result_cache_exempt=tuple(
            ConsensusConfig.RESULT_CACHE_EXEMPT_FIELDS),
    )


@register
class ResultCacheKeyCoverage(Rule):
    """NMFX011: every result-affecting SolverConfig/ConsensusConfig
    field must reach the content-addressed result-cache key
    (``nmfx.result_cache.cache_key_fields``) or be explicitly declared
    exempt with its rationale."""

    rule_id = "NMFX011"
    title = "result-cache key coverage"

    def check(self, project) -> "Iterable[Finding]":
        # semantic whole-package rule, same gating as NMFX001/007: run
        # only when the real package is the analyzed set, and only
        # against the checkout the import machinery actually resolves
        import os

        analyzed_cfg = next(
            (m.path for m in project.modules
             if m.path.replace("\\", "/").endswith("nmfx/config.py")),
            None)
        if analyzed_cfg is None:
            return []
        from nmfx.config import ConsensusConfig

        cfg_file, cfg_line = _decl_site(ConsensusConfig, "nmfx/config.py")
        if os.path.abspath(cfg_file) != os.path.abspath(analyzed_cfg):
            # NMFX001 already reports the wrong-tree condition loudly;
            # don't double-report it per rule
            return []
        return [self.finding(cfg_file, cfg_line, msg)
                for msg in check_result_cache_coverage(
                    **_live_result_cache_universe())]


@register
class ConfigFingerprintCoverage(Rule):
    """NMFX001: every numerics-affecting config field must reach the
    registry fingerprint and the exec-cache bucket key."""

    rule_id = "NMFX001"
    title = "config-fingerprint coverage"

    def check(self, project) -> "Iterable[Finding]":
        # this is a semantic whole-package rule: it runs only when the
        # real package is in the analyzed set (fixture runs over test
        # snippets call check_config_coverage directly)
        import os

        analyzed_cfg = next(
            (m.path for m in project.modules
             if m.path.replace("\\", "/").endswith("nmfx/config.py")),
            None)
        if analyzed_cfg is None:
            return []
        from nmfx.config import SolverConfig

        cfg_file, cfg_line = _decl_site(SolverConfig, "nmfx/config.py")
        # this rule (and the jaxpr layer) checks the IMPORTED package;
        # if the import resolves outside the analyzed checkout (a stale
        # site-packages install shadowing a worktree), the results
        # would describe the wrong tree — fail loudly instead
        if os.path.abspath(cfg_file) != os.path.abspath(analyzed_cfg):
            return [self.finding(
                analyzed_cfg, 1,
                f"the importable nmfx package resolves to {cfg_file!r}, "
                f"not the analyzed {analyzed_cfg!r} — NMFX001 and the "
                "jaxpr layer would check the WRONG tree. Run the "
                "linter with the analyzed checkout first on sys.path "
                "(e.g. `PYTHONPATH=<checkout> python -m nmfx.analysis "
                "<checkout>/nmfx`)")]
        return [self.finding(cfg_file, cfg_line, msg)
                for msg in check_config_coverage(**_live_universe())]
