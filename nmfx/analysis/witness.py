"""Runtime lock-order witness: instrumented locks for the threaded
test suites.

The static rules (NMFX012/013, ``nmfx/analysis/concurrency/``) derive
a lock-acquisition order graph from the source; this module is the
other half of the contract — it observes the orders threads ACTUALLY
acquire locks in while the serve/router/replica/harvest suites run,
and

* fails a test when two lock creation sites are acquired in both
  orders (a dynamic inversion — the precondition of every real
  deadlock the static graph exists to prevent), or when an observed
  order inverts an edge the static graph already pinned;
* exposes :func:`observed_edges` so a test can assert the static
  graph's completeness against real executions (every observed edge
  between statically-known locks must be a static edge — see
  tests/test_witness.py).

Arming (``arm()``/``disarm()``, or the :func:`armed` context manager;
tests/conftest.py arms it per-test for the threaded suites) patches
``threading.Lock``/``threading.RLock`` with factories that wrap locks
CREATED BY NMFX OR TEST CODE in recording proxies — creation sites
are classified by caller filename, so third-party locks (jax,
concurrent.futures internals) pass through untouched and pay one
frame inspection at creation, nothing per acquisition.

Known blind spots, by design:

* locks created BEFORE arming are never wrapped — module-level
  singletons (``nmfx.faults._lock``, the flight-recorder and metrics
  registry locks) are born at import time and stay invisible; the
  static rules cover them.
* ``threading.Condition()`` with no argument allocates its RLock from
  inside ``threading.py`` — a non-nmfx creation site, unwrapped.
  ``Condition(self._lock)`` on a wrapped lock IS tracked: the
  condition's release/reacquire protocol routes through the proxy's
  plain ``acquire``/``release`` (the CPython fallback paths, since
  neither the proxy nor the raw C lock exposes ``_release_save``/
  ``_acquire_restore``/``_is_owned``).

Edges are keyed by lock CREATION site ``(abspath, lineno)`` — the
same identity the static model's ``LockInfo.site`` records — so many
instances of one class collapse onto one node, exactly like the
static graph's ``mod.Class._attr`` keys.
"""

from __future__ import annotations

import os
import sys
import threading

__all__ = ["arm", "disarm", "armed", "reset", "is_armed",
           "observed_edges", "violations", "check_static_inversions",
           "static_order_edges"]

#: originals, captured at import of THIS module (before any patching)
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

_armed_depth = 0

#: (site_a, site_b) -> (thread name, example acquire site pair count)
_edges: "dict[tuple, int]" = {}
#: recorded inversions: dicts with kind/site_a/site_b/thread
_violations: "list[dict]" = []
_state_lock = _REAL_LOCK()
_tls = threading.local()


def _held() -> list:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


class _LockWitness:
    """Proxy around one lock object, recording acquisition order by
    creation site. Context-manager and acquire/release compatible;
    everything else delegates to the wrapped lock."""

    __slots__ = ("_inner", "site", "reentrant")

    def __init__(self, inner, site: "tuple[str, int]", reentrant: bool):
        self._inner = inner
        self.site = site
        self.reentrant = reentrant

    # -- the recorded protocol ------------------------------------------
    def acquire(self, *args, **kwargs):
        blocking = bool(args[0]) if args else kwargs.get("blocking", True)
        if blocking:
            self._pre_acquire()
        got = self._inner.acquire(*args, **kwargs)
        if got:
            _held().append(self)
            self._record_edges()
        return got

    def release(self):
        stack = _held()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                break
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    def __getattr__(self, name):
        return getattr(self._inner, name)

    # -- recording ------------------------------------------------------
    def _pre_acquire(self) -> None:
        if self.reentrant:
            return
        for h in _held():
            if h is self:
                # a plain Lock re-acquired by its owner: guaranteed
                # self-deadlock. Record BEFORE blocking so the hang's
                # postmortem names the site, then block as the real
                # lock would — the witness never changes semantics.
                with _state_lock:
                    _violations.append({
                        "kind": "self-deadlock",
                        "site_a": self.site, "site_b": self.site,
                        "thread": threading.current_thread().name})
                return

    def _record_edges(self) -> None:
        me = self.site
        seen = set()
        for h in _held():
            if h is self or h.site == me or h.site in seen:
                continue
            seen.add(h.site)
            edge = (h.site, me)
            with _state_lock:
                _edges[edge] = _edges.get(edge, 0) + 1
                if (me, h.site) in _edges:
                    _violations.append({
                        "kind": "inversion",
                        "site_a": h.site, "site_b": me,
                        "thread": threading.current_thread().name})


def _wrap_site(depth: int) -> "tuple[str, int] | None":
    """The creation call site when it belongs to nmfx or its test
    suite, else None (leave the lock unwrapped)."""
    try:
        frame = sys._getframe(depth)
    except ValueError:  # pragma: no cover - no caller frame
        return None
    fn = frame.f_globals.get("__file__") or frame.f_code.co_filename
    fn = os.path.abspath(fn)
    parts = fn.replace("\\", "/")
    if "/nmfx/analysis/" in parts:
        return None  # never instrument the instrumentation
    if "/nmfx/" in parts or "/tests/" in parts:
        return (fn, frame.f_lineno)
    return None


def _patched_lock():
    inner = _REAL_LOCK()
    site = _wrap_site(2)
    if site is None:
        return inner
    return _LockWitness(inner, site, reentrant=False)


def _patched_rlock():
    inner = _REAL_RLOCK()
    site = _wrap_site(2)
    if site is None:
        return inner
    return _LockWitness(inner, site, reentrant=True)


# -- arming ------------------------------------------------------------
def arm() -> None:
    """Start wrapping newly created nmfx locks (idempotent/nested)."""
    global _armed_depth
    with _state_lock:
        _armed_depth += 1
        if _armed_depth == 1:
            threading.Lock = _patched_lock
            threading.RLock = _patched_rlock


def disarm() -> None:
    """Undo one :func:`arm`. Locks wrapped while armed keep recording
    until garbage-collected — disarming only stops wrapping NEW ones,
    so a server outliving its test keeps a consistent proxy."""
    global _armed_depth
    with _state_lock:
        if _armed_depth == 0:
            return
        _armed_depth -= 1
        if _armed_depth == 0:
            threading.Lock = _REAL_LOCK
            threading.RLock = _REAL_RLOCK


def is_armed() -> bool:
    return _armed_depth > 0


class armed:
    """``with witness.armed():`` — arm for the block, disarm after."""

    def __enter__(self):
        arm()
        return sys.modules[__name__]

    def __exit__(self, *exc):
        disarm()
        return False


def reset() -> None:
    """Clear observed edges and violations (per-test isolation)."""
    with _state_lock:
        _edges.clear()
        _violations.clear()


def observed_edges() -> "dict[tuple, int]":
    """``{(site_a, site_b): count}`` — site is the lock's creation
    ``(abspath, lineno)``; the edge means a thread acquired b while
    holding a."""
    with _state_lock:
        return dict(_edges)


def violations() -> "list[dict]":
    with _state_lock:
        return list(_violations)


# -- static cross-check ------------------------------------------------
_static_cache: "dict | None" = None


def static_order_edges() -> "dict[tuple, tuple]":
    """The static model's order graph translated to creation-site
    keys: ``{(site_a, site_b): (key_a, key_b)}``. Built once per
    process (one AST pass over the package)."""
    global _static_cache
    if _static_cache is not None:
        return _static_cache
    from nmfx.analysis.ast_scan import load_project
    from nmfx.analysis.concurrency.model import concurrency_model

    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    model = concurrency_model(load_project([pkg_dir]))
    site_of = {key: (os.path.abspath(li.site[0]), li.site[1])
               for key, li in model.lock_index.items()}
    out = {}
    for (a, b) in model.order_edges:
        sa, sb = site_of.get(a), site_of.get(b)
        if sa is not None and sb is not None:
            out[(sa, sb)] = (a, b)
    _static_cache = out
    return out


def check_static_inversions() -> "list[dict]":
    """Observed edges whose REVERSE is a static-graph edge — a runtime
    order contradicting the order the source pins. Returned, not
    raised; the conftest fixture asserts on it at teardown."""
    observed = observed_edges()
    if not observed:
        return []  # nothing to cross-check; skip the model build
    static = static_order_edges()
    out = []
    for (sa, sb) in observed:
        if (sb, sa) in static:
            ka, kb = static[(sb, sa)]
            out.append({"kind": "static-inversion",
                        "site_a": sa, "site_b": sb,
                        "static_edge": f"{kb} -> {ka}"})
    return out


def render(problems: "list[dict]") -> str:
    def site(s):
        return f"{os.path.relpath(s[0])}:{s[1]}"

    lines = []
    for v in problems:
        head = (f"lock-order {v['kind']}: "
                f"{site(v['site_a'])} -> {site(v['site_b'])}")
        if v.get("thread"):
            head += f"  [thread {v['thread']}]"
        if v.get("static_edge"):
            head += f"  (static graph pins {v['static_edge']})"
        lines.append(head)
    return "\n".join(lines)
