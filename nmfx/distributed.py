"""Multi-host execution: one SPMD sweep over every device in the job.

The reference's distributed story is BatchJobs job farming — independent R
worker processes scattered over a cluster scheduler, results gathered back
through a shared filesystem registry (reference ``nmf.r:63,112-113``,
SURVEY.md §2c). The TPU-native replacement is single-program multiple-data:
every host runs this same sweep; the restart axis is sharded over a *global*
``Mesh`` spanning all hosts' devices, so each device solves its slice of the
restarts and the consensus reduction and output replication become XLA
collectives riding ICI within a slice and DCN across slices — no job queue,
no filesystem gather, no idle coordinator.

Launch on each host (or let the TPU runtime infer everything)::

    import nmfx.distributed as dist
    dist.initialize()                    # jax.distributed — env-driven
    result = dist.consensus(data, ks=range(2, 11), restarts=400)

Every host returns the identical ``ConsensusResult`` (outputs are
constrained replicated inside jit — see ``sweep._build_sweep_fn``); host-side
steps (cophenetic rank selection, file writes) are therefore pure replays,
and only ``is_coordinator()`` should write files.

Single-process runs degenerate cleanly: ``global_mesh()`` is then just the
local-device mesh and no DCN traffic exists — which is how the multi-device
CPU tests exercise this exact code path (SURVEY.md §4).
"""

from __future__ import annotations

import collections
import itertools
import os
import threading

import jax
import numpy as np
from jax.sharding import Mesh

from nmfx.obs import metrics as _metrics
from nmfx.obs import trace as _trace
from nmfx.sweep import RESTART_AXIS

#: elastic-runner fleet instruments (ISSUE 14): per-shard progress as
#: labeled counters (the fleet view sums them; the shard label keeps
#: the per-shard drill-down) and the live-shard level gauge
_units_solved_total = _metrics.counter(
    "nmfx_elastic_units_solved_total",
    "work units solved and committed by elastic shards",
    labelnames=("shard",))
_shards_alive_gauge = _metrics.gauge(
    "nmfx_elastic_shards_alive",
    "elastic shards currently alive in this process's runner")

#: per-process elastic run sequence — with the pid it forms the
#: cross-process trace id shard heartbeats and spans carry
_run_seq = itertools.count()


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> None:
    """Bring up the jax.distributed runtime. Call before any other JAX use.

    On Cloud TPU pods / SLURM all three arguments are inferred from the
    environment; elsewhere pass them explicitly (the analogue of a BatchJobs
    site config, minus the filesystem registry). Idempotent: a second call is
    a no-op. With no arguments in a plain single-process environment (no
    cluster metadata to auto-detect), this degenerates to a no-op so the same
    script runs unmodified on a laptop.

    NOTE: must run before the XLA backend initializes — do not call
    ``jax.devices()``/``jax.process_count()`` (or run any computation) first.
    """
    from nmfx._compat import distributed_is_initialized

    if distributed_is_initialized():
        return
    explicit = {k: v for k, v in (
        ("coordinator_address", coordinator_address),
        ("num_processes", num_processes),
        ("process_id", process_id)) if v is not None}
    if explicit:
        jax.distributed.initialize(**explicit)
        return
    try:
        jax.distributed.initialize()  # env/cluster auto-detection
    except ValueError:
        # no coordinator address detectable ⇒ genuinely not a cluster job;
        # degenerate to single-process. Connection failures (RuntimeError)
        # must propagate — swallowing one would leave every host believing
        # it is process 0, redundantly computing the sweep and racing on
        # coordinator-only file writes.
        return
    except RuntimeError:
        if not _cluster_env_detected():
            # single-process program that touched JAX before calling us
            # (the "must be called before any JAX calls" case) — with no
            # cluster environment, distribution was never possible; no-op
            return
        # inside a real multi-process job every failure mode here (late
        # call, unreachable coordinator, ...) would otherwise make every
        # host act as coordinator — always fatal
        raise


def _cluster_env_detected() -> bool:
    """Best-effort: does the environment look like a multi-process job?

    Mirrors the markers jax.distributed auto-detection keys off (explicit
    coordinator, SLURM/Open MPI/PMI world sizes, multi-worker Cloud TPU).
    """
    import os

    if os.environ.get("JAX_COORDINATOR_ADDRESS"):
        return True
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if len([h for h in hosts.split(",") if h]) > 1:
        return True
    for var in ("SLURM_NTASKS", "SLURM_NPROCS", "OMPI_COMM_WORLD_SIZE",
                "PMI_SIZE"):
        try:
            if int(os.environ.get(var, "")) > 1:
                return True
        except ValueError:
            continue
    return False


def is_coordinator() -> bool:
    return jax.process_index() == 0


class MeshSpecError(ValueError):
    """A mesh specification does not fit the available devices.

    Typed so callers (CLI validation, `ReplicaPool` spawn, the serve
    config) can catch the *spec* problem distinctly from arbitrary
    ``ValueError``s — before it would otherwise surface as an opaque
    XLA reshape failure deep inside device assignment."""


def parse_mesh_spec(spec: str) -> "tuple[int, int, int]":
    """Parse a replica mesh spec into ``(restart_shards,
    feature_shards, sample_shards)``.

    Grammar: ``"R"`` (restart-only, e.g. ``"4"``), ``"RxF"`` or
    ``"RxFxS"`` (e.g. ``"2x2"``, ``"2x2x2"``) — the axis order of
    :func:`nmfx.grid_mesh`. Every count must be a positive integer;
    anything else raises :class:`MeshSpecError`."""
    parts = str(spec).lower().split("x")
    if not 1 <= len(parts) <= 3:
        raise MeshSpecError(
            f"mesh spec {spec!r} must be R, RxF, or RxFxS "
            "(restarts × features × samples)")
    try:
        counts = tuple(int(p) for p in parts)
    except ValueError:
        raise MeshSpecError(
            f"mesh spec {spec!r} has a non-integer axis count") from None
    if any(c < 1 for c in counts):
        raise MeshSpecError(
            f"mesh spec {spec!r} has a non-positive axis count")
    return counts + (1,) * (3 - len(counts))


def build_replica_mesh(spec: str, devices=None) -> Mesh:
    """Build the mesh a replica's device set executes on, from its
    ``ServeConfig.mesh_spec`` string.

    An explicit ``devices`` set (a pool-carved block) must be consumed
    exactly — a replica owning 8 chips but meshing 4 would silently
    idle half its capacity while the router prices it as an 8-chip
    replica, so the mismatch is a :class:`MeshSpecError`, not a
    truncation. With ``devices=None`` (a standalone server) the mesh
    takes the first ``r*f*s`` of ``jax.devices()``."""
    r, f, s = parse_mesh_spec(spec)
    need = r * f * s
    if devices is None:
        devices = list(jax.devices())
        if len(devices) < need:
            raise MeshSpecError(
                f"mesh spec {spec!r} needs {need} device(s) "
                f"({r}x{f}x{s}); this process has {len(devices)}")
        devices = devices[:need]
    else:
        devices = list(devices)
        if len(devices) != need:
            raise MeshSpecError(
                f"mesh spec {spec!r} needs exactly {need} device(s) "
                f"({r}x{f}x{s}); this replica owns {len(devices)}")
    if f == 1 and s == 1:
        return Mesh(np.array(devices), (RESTART_AXIS,))
    from nmfx.sweep import grid_mesh

    return grid_mesh(r, f, s, devices=devices)


def global_mesh(feature_shards: int = 1, sample_shards: int = 1) -> Mesh:
    """Mesh over every device in the job (all hosts): restart axis by
    default, optionally a 3-D restarts×features×samples grid.

    ``jax.devices()`` is the *global* device list under multi-process JAX,
    so jitting with this mesh is the cross-host SPMD program; with one
    process it equals the local mesh. The grid axes are laid out innermost
    (the global device list is process-major), so the per-iteration psums
    of the feature/sample axes ride ICI within a host/slice while the
    collective-light restart axis spans DCN — the layout
    jax-ml.github.io/scaling-book prescribes for bandwidth-hungry axes.
    """
    if feature_shards < 1 or sample_shards < 1:
        raise MeshSpecError(
            "feature_shards/sample_shards must be >= 1, got "
            f"{feature_shards}×{sample_shards}")
    devices = jax.devices()
    if feature_shards == 1 and sample_shards == 1:
        return Mesh(np.array(devices), (RESTART_AXIS,))
    grid = feature_shards * sample_shards
    if grid > len(devices):
        raise MeshSpecError(
            f"features×samples={feature_shards}×{sample_shards} needs "
            f"{grid} devices; this job has {len(devices)}")
    if len(devices) % grid:
        raise MeshSpecError(
            f"{len(devices)} devices don't divide into "
            f"features×samples={feature_shards}×{sample_shards} "
            f"(= {grid}); the restart axis would be ragged")
    from nmfx.sweep import grid_mesh

    return grid_mesh(len(devices) // grid, feature_shards, sample_shards,
                     devices=devices)


def consensus(data, ks=(2, 3, 4, 5), restarts: int = 10,
              feature_shards: int = 1, sample_shards: int = 1, **kwargs):
    """``nmfx.api.nmfconsensus`` over the global mesh.

    ``feature_shards``/``sample_shards`` tile each factorization across
    devices (tensor/sequence parallelism — for A too large for one device's
    HBM); the remaining devices parallelize restarts. File/plot outputs
    (``output=``, ``checkpoint_dir=``) are only honored on the coordinator
    so hosts sharing a filesystem don't race on the same paths; the
    returned in-memory result is identical on every host.
    """
    from nmfx.api import nmfconsensus

    if not is_coordinator():
        kwargs = dict(kwargs, output=None, checkpoint_dir=None)
    return nmfconsensus(data, ks=ks, restarts=restarts,
                        mesh=global_mesh(feature_shards, sample_shards),
                        **kwargs)


# --------------------------------------------------------------------------
# Elastic shard recovery (ISSUE 9): the durable-ledger counterpart of the
# SPMD mesh above. The mesh path is fail-stop — one device/host dying
# kills the collective and the whole job restarts. Here the restart grid
# shards as independent (k, restart-chunk) WORK UNITS over the devices,
# every unit's results come from the same canonical per-(seed, k,
# restart) key chain regardless of which shard runs it, and completion
# is recorded in the shared SweepCheckpoint ledger — so when a shard
# dies mid-sweep, the survivors simply re-dispatch its incomplete units
# (same keys => same results) and the sweep finishes with ZERO stranded
# work. This is the MPI-FAUN restart-grid sharding (arxiv 1609.09154)
# turned elastic, testable on forced host devices in a CPU container.
# --------------------------------------------------------------------------
class ElasticShardRunner:
    """Restart-grid sharding with shard-loss recovery over a durable
    ledger.

    Each device is one shard, driven by a worker thread that pulls
    (k, r0, r1) units from a shared queue (deterministically ordered:
    ks-major, chunk-minor — the checkpoint plan order), solves the unit
    on ITS device through the checkpoint chunk executor, and commits
    the completion record to the shared :class:`~nmfx.checkpoint
    .SweepCheckpoint`. Per-unit heartbeats land in the ledger
    (``shard_<i>.json``), so a cross-process deployment can detect a
    shard whose heartbeat went stale; in-process, a shard death (a
    raised ``checkpoint.Preempted`` — the armed ``proc.preempt`` chaos
    site — or any crash) returns its in-flight unit to the queue, where
    a survivor picks it up.

    Exactness: a unit's chunk executor draws the canonical
    ``split(fold_in(key(seed), k), restarts)[r0:r1]`` keys and the
    finalize step accumulates integer connectivity counts in canonical
    restart order — so the result is bit-identical to a single-device
    checkpointed run of the same plan, no matter how units were
    distributed, re-dispatched, or interleaved
    (tests/test_distributed.py pins it on forced CPU devices).
    """

    def __init__(self, ck, ccfg, scfg, icfg, arr, devices=None,
                 telemetry_dir=None, trace_id=None,
                 shard_devices: int = 1):
        self.ck = ck
        self.ccfg = ccfg
        self.scfg = scfg
        self.icfg = icfg
        self.arr = np.asarray(arr)
        self.devices = list(jax.local_devices()
                            if devices is None else devices)
        if not self.devices:
            raise ValueError("need at least one device")
        # meshed mode (ISSUE 19): a shard owns a device SET — its units
        # solve over a restart-only sub-mesh (communication-avoiding;
        # records stay bit-identical to the unmeshed executor's)
        if shard_devices < 1:
            raise MeshSpecError("shard_devices must be >= 1, got "
                                f"{shard_devices}")
        if shard_devices > len(self.devices):
            raise MeshSpecError(
                f"shard_devices={shard_devices} exceeds the "
                f"{len(self.devices)} available device(s)")
        if len(self.devices) % shard_devices:
            raise MeshSpecError(
                f"{len(self.devices)} device(s) don't divide into "
                f"sub-meshes of {shard_devices}; a ragged remainder "
                "would idle silently")
        self.shard_devices = shard_devices
        self._groups = [self.devices[i:i + shard_devices]
                        for i in range(0, len(self.devices),
                                       shard_devices)]
        #: cross-process sweep identity (ISSUE 14): every shard
        #: heartbeat in the ledger and every elastic.unit trace span
        #: carries it, so N processes sharding one ledger join into one
        #: merged timeline (trace.merge_traces) and one fleet view
        self.trace_id = trace_id if trace_id is not None else \
            f"elastic-{os.getpid()}-{next(_run_seq)}"
        #: telemetry ledger (nmfx.obs.export): run() publishes this
        #: process's registry snapshots here for the fleet collector
        self.telemetry_dir = telemetry_dir
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending = collections.deque(
            (k, r0, r1) for k in ccfg.ks for r0, r1 in ck.plan
            if not ck.has(k, r0, r1))
        self._inflight = 0  # units taken but not yet committed/returned
        self._records: dict = {}
        self.dead_shards: "list[int]" = []
        self._errors: "list[BaseException]" = []

    def _worker(self, idx: int, dev) -> None:
        from nmfx import checkpoint as ckpt
        from nmfx.sweep import place_input

        done = 0
        group = list(dev) if isinstance(dev, (list, tuple)) else [dev]
        submesh = None
        if len(group) > 1:
            from jax.sharding import NamedSharding, PartitionSpec

            submesh = Mesh(np.array(group), (RESTART_AXIS,))
            a_dev = jax.device_put(
                place_input(self.arr, self.scfg, None),
                NamedSharding(submesh, PartitionSpec()))
        else:
            a_dev = jax.device_put(
                place_input(self.arr, self.scfg, None), group[0])
        key_cache: dict = {}
        tracer = _trace.default_tracer()
        while True:
            with self._cond:
                # an empty queue is NOT the end while units are still in
                # flight: a dying shard hands its unit back via
                # appendleft, and a survivor that already exited would
                # strand it — wait instead (the late-sweep preemption
                # case the elastic contract exists for)
                while not self._pending and self._inflight > 0:
                    self._cond.wait()
                if not self._pending:
                    self.ck.heartbeat(idx, alive=True, done=done,
                                      unit=None, trace_id=self.trace_id)
                    _shards_alive_gauge.inc(-1)
                    return
                unit = self._pending.popleft()
                self._inflight += 1
            k, r0, r1 = unit
            try:
                if k not in key_cache:
                    keys_k = jax.random.split(
                        jax.random.fold_in(jax.random.key(self.ccfg.seed),
                                           k),
                        self.ccfg.restarts)
                    # meshed shards leave keys host-side: the meshed
                    # chunk executor shards them over the sub-mesh
                    key_cache[k] = (keys_k if submesh is not None
                                    else jax.device_put(keys_k, group[0]))
                with tracer.span("elastic.unit", cat="elastic",
                                 args={"shard": idx, "k": k, "r0": r0,
                                       "r1": r1,
                                       "trace_id": self.trace_id}):
                    rec = ckpt.solve_chunk_host(a_dev, k, r0, r1,
                                                self.ccfg, self.scfg,
                                                self.icfg,
                                                keys=key_cache[k],
                                                mesh=submesh)
            except ckpt.Preempted:
                # shard death: hand the in-flight unit back so a
                # survivor re-runs it (same keys => same results), and
                # leave a final not-alive heartbeat in the ledger
                with self._cond:
                    self._pending.appendleft(unit)
                    self._inflight -= 1
                    self.dead_shards.append(idx)
                    self._cond.notify_all()
                self.ck.heartbeat(idx, alive=False, done=done, unit=unit,
                                  trace_id=self.trace_id)
                _shards_alive_gauge.inc(-1)
                return
            except BaseException as e:  # real crash: recorded (raised
                from nmfx.faults import warn_once  # by run() only if
                                                   # work STRANDS),
                with self._cond:                   # unit returned,
                    self._pending.appendleft(unit)  # shard retired
                    self._inflight -= 1
                    self.dead_shards.append(idx)
                    self._errors.append(e)
                    self._cond.notify_all()
                self.ck.heartbeat(idx, alive=False, done=done, unit=unit,
                                  trace_id=self.trace_id)
                _shards_alive_gauge.inc(-1)
                warn_once(
                    "elastic-shard-crash",
                    f"elastic shard {idx} ({dev}) crashed on unit "
                    f"{unit} ({e!r}); its incomplete units were "
                    "returned to the queue for the surviving shards")
                return
            self.ck.save(k, r0, r1, rec)
            done += 1
            _units_solved_total.inc(shard=str(idx))
            self.ck.heartbeat(idx, alive=True, done=done, unit=unit,
                              trace_id=self.trace_id)
            with self._cond:
                self._records[unit] = rec
                self._inflight -= 1
                self._cond.notify_all()

    def shard_status(self, stale_after_s: "float | None" = None) -> dict:
        """``{shard: heartbeat_payload}`` from the shared heartbeat
        ledger this runner's shards beat into (``SweepCheckpoint
        .heartbeat_ledger`` — the same :class:`nmfx.obs.export
        .HeartbeatLedger` idiom the replica pool behind ``NMFXRouter``
        uses for replica liveness, ISSUE 15). With ``stale_after_s``
        each payload carries ``stale``/``age_s``, so a cross-process
        supervisor can spot a shard whose process died without a final
        ``alive=False`` heartbeat and re-dispatch its incomplete units
        (completion records stay the ground truth)."""
        return self.ck.shard_status(stale_after_s)

    def run(self) -> dict:
        """Dispatch until every unit is committed (or every shard died);
        returns ``{(k, r0, r1): ChunkSweepOutput}`` for the units this
        process solved. Units already committed in the ledger are
        loaded at finalize, not re-run (zero stranded AND zero wasted
        committed work)."""
        publisher = None
        if self.telemetry_dir is not None:
            # per-shard publishing (ISSUE 14): this process's registry
            # snapshots — the per-shard nmfx_elastic_* series included
            # — land in the shared telemetry ledger while the sweep
            # runs, so a fleet view over N sharding processes sees
            # every shard's progress and liveness
            from nmfx.obs.export import TelemetryPublisher

            publisher = TelemetryPublisher(
                self.telemetry_dir, role="elastic",
                instance=f"elastic-{os.getpid()}",
                interval_s=1.0).start()
        _shards_alive_gauge.set(len(self._groups))
        threads = [threading.Thread(target=self._worker, args=(i, g),
                                    daemon=True,
                                    name=f"nmfx-elastic-{i}")
                   for i, g in enumerate(self._groups)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if publisher is not None:
            publisher.close()
        # every_s-buffered records land NOW — before the all-dead error
        # below claims "the committed records remain", and before the
        # process can exit with a 'durable' run that never touched disk
        self.ck.flush()
        if self._pending:
            err = RuntimeError(
                f"every shard died with {len(self._pending)} unit(s) "
                "still pending; the committed records remain in "
                f"{self.ck.directory!r} — re-run to resume from them")
            if self._errors:
                raise err from self._errors[0]
            raise err
        # shard crashes whose units the survivors absorbed are NOT
        # re-raised: the result is complete and exact (the crash was
        # already announced warn-once) — raising only when work strands
        # is the documented elastic contract
        return dict(self._records)


def elastic_consensus(data, ks=(2, 3, 4, 5), restarts: int = 10, *,
                      checkpoint, seed: int = 123, solver_cfg=None,
                      init_cfg=None, label_rule: str = "argmax",
                      linkage: str = "average", min_restarts: int = 1,
                      devices=None, telemetry_dir=None,
                      shard_devices: int = 1):
    """Durable, elastic restart-grid consensus sweep: the (k x chunk)
    units of ``checkpoint``'s plan are dispatched across ``devices``
    (default: all local devices) by :class:`ElasticShardRunner`; a
    shard lost mid-sweep is recovered by the survivors, and the result
    is bit-identical to a single-device checkpointed run of the same
    plan. ``checkpoint`` is an ``nmfx.CheckpointConfig`` or a directory
    path; a partially-complete ledger resumes (only missing units
    dispatch). ``telemetry_dir`` publishes this process's registry
    snapshots (per-shard progress included) into a shared fleet-
    telemetry ledger while the sweep runs (``nmfx.obs.export``;
    docs/observability.md "Fleet telemetry"). ``shard_devices`` makes
    each shard a SUB-MESH of that many devices (meshed mode: units
    solve restart-sharded over the sub-mesh, same records). Returns
    the same ``ConsensusResult`` as ``nmfconsensus``."""
    from nmfx import checkpoint as ckpt
    from nmfx.api import ConsensusResult, _as_matrix, _build_k_result
    from nmfx.config import (CheckpointConfig, ConsensusConfig,
                             InitConfig, SolverConfig)

    import os

    if isinstance(checkpoint, (str, os.PathLike)):
        checkpoint = CheckpointConfig(directory=os.fspath(checkpoint))
    arr, col_names = _as_matrix(data)
    if not np.isfinite(arr).all():
        raise ValueError("input matrix contains non-finite values")
    if (arr < 0).any():
        raise ValueError("input matrix must be non-negative")
    ccfg = ConsensusConfig(ks=tuple(ks), restarts=restarts, seed=seed,
                           label_rule=label_rule, linkage=linkage,
                           min_restarts=min_restarts)
    scfg = solver_cfg if solver_cfg is not None else SolverConfig()
    icfg = init_cfg if init_cfg is not None else InitConfig()
    ck = ckpt.SweepCheckpoint.open(arr, ccfg, scfg, icfg, checkpoint)
    runner = ElasticShardRunner(ck, ccfg, scfg, icfg, arr,
                                devices=devices,
                                telemetry_dir=telemetry_dir,
                                shard_devices=shard_devices)
    solved = runner.run()
    per_k = {}
    for k in ccfg.ks:
        recs = {}
        for r0, r1 in ck.plan:
            rec = solved.get((k, r0, r1))
            if rec is None:
                rec = ck.try_load(k, r0, r1)
            if rec is None:  # committed by a peer process mid-scan and
                # then torn? — solve inline rather than fail the sweep
                rec = ckpt.solve_chunk_host(
                    jax.numpy.asarray(arr, scfg.dtype), k, r0, r1,
                    ccfg, scfg, icfg)
                ck.save(k, r0, r1, rec)
            recs[(r0, r1)] = rec
        out = ckpt._finalize_rank(k, recs, ccfg, arr.shape)
        per_k[k] = _build_k_result(k, out, ccfg.linkage,
                                   min_restarts=ccfg.min_restarts)
    ck.flush()  # inline re-solves above may have buffered (every_s)
    return ConsensusResult(ks=ccfg.ks, per_k=per_k,
                           col_names=tuple(col_names))
