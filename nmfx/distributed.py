"""Multi-host execution: one SPMD sweep over every device in the job.

The reference's distributed story is BatchJobs job farming — independent R
worker processes scattered over a cluster scheduler, results gathered back
through a shared filesystem registry (reference ``nmf.r:63,112-113``,
SURVEY.md §2c). The TPU-native replacement is single-program multiple-data:
every host runs this same sweep; the restart axis is sharded over a *global*
``Mesh`` spanning all hosts' devices, so each device solves its slice of the
restarts and the consensus reduction and output replication become XLA
collectives riding ICI within a slice and DCN across slices — no job queue,
no filesystem gather, no idle coordinator.

Launch on each host (or let the TPU runtime infer everything)::

    import nmfx.distributed as dist
    dist.initialize()                    # jax.distributed — env-driven
    result = dist.consensus(data, ks=range(2, 11), restarts=400)

Every host returns the identical ``ConsensusResult`` (outputs are
constrained replicated inside jit — see ``sweep._build_sweep_fn``); host-side
steps (cophenetic rank selection, file writes) are therefore pure replays,
and only ``is_coordinator()`` should write files.

Single-process runs degenerate cleanly: ``global_mesh()`` is then just the
local-device mesh and no DCN traffic exists — which is how the multi-device
CPU tests exercise this exact code path (SURVEY.md §4).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

from nmfx.sweep import RESTART_AXIS


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> None:
    """Bring up the jax.distributed runtime. Call before any other JAX use.

    On Cloud TPU pods / SLURM all three arguments are inferred from the
    environment; elsewhere pass them explicitly (the analogue of a BatchJobs
    site config, minus the filesystem registry). Idempotent: a second call is
    a no-op. With no arguments in a plain single-process environment (no
    cluster metadata to auto-detect), this degenerates to a no-op so the same
    script runs unmodified on a laptop.

    NOTE: must run before the XLA backend initializes — do not call
    ``jax.devices()``/``jax.process_count()`` (or run any computation) first.
    """
    from nmfx._compat import distributed_is_initialized

    if distributed_is_initialized():
        return
    explicit = {k: v for k, v in (
        ("coordinator_address", coordinator_address),
        ("num_processes", num_processes),
        ("process_id", process_id)) if v is not None}
    if explicit:
        jax.distributed.initialize(**explicit)
        return
    try:
        jax.distributed.initialize()  # env/cluster auto-detection
    except ValueError:
        # no coordinator address detectable ⇒ genuinely not a cluster job;
        # degenerate to single-process. Connection failures (RuntimeError)
        # must propagate — swallowing one would leave every host believing
        # it is process 0, redundantly computing the sweep and racing on
        # coordinator-only file writes.
        return
    except RuntimeError:
        if not _cluster_env_detected():
            # single-process program that touched JAX before calling us
            # (the "must be called before any JAX calls" case) — with no
            # cluster environment, distribution was never possible; no-op
            return
        # inside a real multi-process job every failure mode here (late
        # call, unreachable coordinator, ...) would otherwise make every
        # host act as coordinator — always fatal
        raise


def _cluster_env_detected() -> bool:
    """Best-effort: does the environment look like a multi-process job?

    Mirrors the markers jax.distributed auto-detection keys off (explicit
    coordinator, SLURM/Open MPI/PMI world sizes, multi-worker Cloud TPU).
    """
    import os

    if os.environ.get("JAX_COORDINATOR_ADDRESS"):
        return True
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if len([h for h in hosts.split(",") if h]) > 1:
        return True
    for var in ("SLURM_NTASKS", "SLURM_NPROCS", "OMPI_COMM_WORLD_SIZE",
                "PMI_SIZE"):
        try:
            if int(os.environ.get(var, "")) > 1:
                return True
        except ValueError:
            continue
    return False


def is_coordinator() -> bool:
    return jax.process_index() == 0


def global_mesh(feature_shards: int = 1, sample_shards: int = 1) -> Mesh:
    """Mesh over every device in the job (all hosts): restart axis by
    default, optionally a 3-D restarts×features×samples grid.

    ``jax.devices()`` is the *global* device list under multi-process JAX,
    so jitting with this mesh is the cross-host SPMD program; with one
    process it equals the local mesh. The grid axes are laid out innermost
    (the global device list is process-major), so the per-iteration psums
    of the feature/sample axes ride ICI within a host/slice while the
    collective-light restart axis spans DCN — the layout
    jax-ml.github.io/scaling-book prescribes for bandwidth-hungry axes.
    """
    devices = jax.devices()
    if feature_shards == 1 and sample_shards == 1:
        return Mesh(np.array(devices), (RESTART_AXIS,))
    grid = feature_shards * sample_shards
    if len(devices) % grid:
        raise ValueError(
            f"{len(devices)} devices don't divide into "
            f"features×samples={feature_shards}×{sample_shards}")
    from nmfx.sweep import grid_mesh

    return grid_mesh(len(devices) // grid, feature_shards, sample_shards,
                     devices=devices)


def consensus(data, ks=(2, 3, 4, 5), restarts: int = 10,
              feature_shards: int = 1, sample_shards: int = 1, **kwargs):
    """``nmfx.api.nmfconsensus`` over the global mesh.

    ``feature_shards``/``sample_shards`` tile each factorization across
    devices (tensor/sequence parallelism — for A too large for one device's
    HBM); the remaining devices parallelize restarts. File/plot outputs
    (``output=``, ``checkpoint_dir=``) are only honored on the coordinator
    so hosts sharing a filesystem don't race on the same paths; the
    returned in-memory result is identical on every host.
    """
    from nmfx.api import nmfconsensus

    if not is_coordinator():
        kwargs = dict(kwargs, output=None, checkpoint_dir=None)
    return nmfconsensus(data, ks=ks, restarts=restarts,
                        mesh=global_mesh(feature_shards, sample_shards),
                        **kwargs)
