"""Streaming per-rank harvest: device→host transfer and host rank
selection pipelined behind the device solve.

The reference pipeline is strictly phase-sequential — load → solve grid
→ gather → hclust/cophenetic (``nmf.r:106-119, 146-253``) — and the
warm path here used to be too: every rank's results crossed to host in
one end-of-sweep barrier, and the hclust/cophenetic/cutree rank
selection ran after that, entirely outside the phase accounting
(BENCH_r05: 0.278 s of device→host plus an untracked host tail against
a 1.21 s solve). The batch-streaming NMF line (arxiv 2202.09518) gets
its throughput from exactly this overlap; this module brings it to the
DEFAULT warm path:

The sweep layer (``sweep()``, ``ExecCache.run_sweep``) starts each
rank's non-blocking ``copy_to_host_async`` (``start_host_fetch``) and
invokes an ``on_rank(k, KSweepOutput)`` callback the moment rank k's
device output EXISTS — dispatched, not completed: JAX arrays are
futures. :meth:`HarvestPipeline.submit` is that callback. It hands the
rank to a worker thread, which blocks on exactly that rank's arrays
(ranks k+1… keep solving on device underneath), then runs the host rank
selection (linkage/cophenetic/cutree from ``nmfx/cophenetic.py``) and
assembles the rank's ``KResult``. :meth:`HarvestPipeline.results` joins
the workers and returns ``{k: KResult}``.

Bit-identity: the workers consume the same device outputs through the
same ``device_get`` and the same ``api._build_k_result`` host math as
the sequential path — per-rank results are bit-identical by
construction, and tests/test_harvest.py pins streamed-vs-sequential
equality across runs on every engine family reachable on CPU.

Accounting: worker walls are credited to the OVERLAP phases
``xfer.d2h_overlap`` (the blocking host fetch, which overlaps device
compute of later ranks) and ``post.rank_selection`` (the host
clustering) via the thread-safe ``Profiler.add_seconds`` — see
``Profiler.audit`` for how they reconcile against the wall.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from concurrent.futures import Future

import jax

__all__ = ["HarvestPipeline", "harvest_rank"]


def harvest_rank(k: int, out, linkage: str, profiler,
                 min_restarts: int = 1) -> "tuple[object, float, float]":
    """The per-rank harvest body: blocking device→host fetch of rank
    ``k``'s output, then the host rank selection, through the SAME
    ``api._build_k_result`` as the sequential path — the single
    implementation shared by the :class:`HarvestPipeline` workers and
    the serving engine's completion workers (``nmfx/serve.py``), so
    every consumer is bit-identical by construction.

    ``min_restarts`` is the numeric-quarantine survivor floor
    (``ConsensusConfig.min_restarts``; raises a typed
    ``nmfx.faults.InsufficientRestarts`` through ``_build_k_result``).
    Returns ``(KResult, fetch_seconds, select_seconds)``; the walls are
    also credited to the overlap phases ``xfer.d2h_overlap`` /
    ``post.rank_selection`` on ``profiler`` (thread-safe
    ``add_seconds``)."""
    from nmfx.api import _build_k_result

    t0 = time.perf_counter()
    # block on THIS rank only; labels feed the on-device consensus
    # reduction and are never read host-side, so they stay out of the
    # transfer (design.md §5b)
    host = jax.device_get(out._replace(labels=None))
    t1 = time.perf_counter()
    fetch_s = t1 - t0
    profiler.add_seconds("xfer.d2h_overlap", fetch_s)
    res = _build_k_result(k, host, linkage, min_restarts=min_restarts)
    select_s = time.perf_counter() - t1
    profiler.add_seconds("post.rank_selection", select_s)
    return res, fetch_s, select_s


class HarvestPipeline:
    """Producer/consumer pipeline from per-rank device outputs to
    per-rank ``KResult``\\ s.

    ``workers`` bounds the harvest threads (default: half the CPUs,
    capped at 4 — rank selection is host-CPU-bound and must not starve
    the main thread's dispatch). Threads are daemons and spawn lazily on
    the first submit; :meth:`results` (or :meth:`close`) shuts them
    down, re-raising the first worker failure.
    """

    def __init__(self, linkage: str = "average", profiler=None,
                 workers: "int | None" = None, min_restarts: int = 1):
        from nmfx.profiling import NullProfiler

        self._linkage = linkage
        self._prof = profiler if profiler is not None else NullProfiler()
        self._min_restarts = min_restarts
        self._max_workers = (workers if workers is not None
                             else max(1, min(4, (os.cpu_count() or 2) // 2)))
        if self._max_workers < 1:
            raise ValueError("workers must be >= 1")
        self._queue: "queue.SimpleQueue" = queue.SimpleQueue()
        self._futures: "dict[int, Future]" = {}
        #: each rank's device output, retained so a dead worker's rank
        #: can be re-harvested sequentially in results(); dropped the
        #: moment the rank resolves (progressive deallocation)
        self._outs: "dict[int, object]" = {}
        self._threads: "list[threading.Thread]" = []
        self._closed = False

    # -- producer side ----------------------------------------------------
    def submit(self, k: int, out) -> None:
        """Accept rank ``k``'s (possibly still-computing) device output.

        Called by the sweep layer the moment the rank's arrays exist.
        The sweep layer owns starting the non-blocking device→host
        copies (``start_host_fetch`` runs at every ``on_rank`` call
        site before the callback fires), so this only enqueues the
        host-side harvest; it never blocks on device work.
        """
        if self._closed:
            raise RuntimeError("harvest pipeline already closed")
        if k in self._futures:
            raise ValueError(f"rank {k} submitted twice")
        fut: Future = Future()
        # grow the worker pool BEFORE publishing the future: a failed
        # thread spawn must surface here, while nothing references the
        # future yet — spawning after self._futures[k] = fut stranded
        # the waiter forever when start() raised (the worker just
        # blocks on queue.get(), so starting it early is free)
        if len(self._threads) < min(self._max_workers,
                                    len(self._futures) + 1):
            t = threading.Thread(target=self._work, daemon=True,
                                 name="nmfx-harvest")
            t.start()
            self._threads.append(t)
        self._futures[k] = fut
        self._outs[k] = out
        self._queue.put((k, out, fut))

    # -- consumer side ----------------------------------------------------
    def _work(self) -> None:
        from nmfx import faults

        while True:
            item = self._queue.get()
            if item is None:
                return
            k, out, fut = item
            try:
                # chaos site: a harvest WORKER dying (thread-level
                # failure, distinct from the harvest math itself — the
                # sequential fallback in results() re-runs the rank
                # without passing this site)
                faults.inject("harvest.worker")
                res, _, _ = harvest_rank(k, out, self._linkage,
                                         self._prof, self._min_restarts)
                fut.set_result(res)
                # a resolved rank no longer needs its re-harvest copy:
                # drop the device-output reference NOW so buffers (and
                # keep_factors stacks) free progressively, not at
                # pipeline teardown
                self._outs.pop(k, None)
            except BaseException as e:  # re-raised (or recovered
                fut.set_exception(e)   # sequentially) by results()

    def results(self) -> dict:
        """Join every submitted rank and return ``{k: KResult}`` in
        submission order. A rank whose WORKER died is re-harvested
        sequentially on this thread (warn-once) — the same device
        output through the same host math, so the recovery is exact;
        deterministic per-rank failures (``InsufficientRestarts``, a
        corrupt device output) re-raise as before."""
        from nmfx.faults import InsufficientRestarts, warn_once

        try:
            out: dict = {}
            for k, fut in self._futures.items():
                try:
                    out[k] = fut.result()
                except InsufficientRestarts:
                    raise  # deterministic: a re-run cannot succeed
                except BaseException as e:
                    warn_once(
                        "harvest-worker-fallback",
                        f"harvest worker for rank {k} died ({e!r}); "
                        "re-running that rank's harvest sequentially — "
                        "results are unaffected, the overlap win is "
                        "lost for this rank")
                    out[k], _, _ = harvest_rank(
                        k, self._outs[k], self._linkage, self._prof,
                        self._min_restarts)
                    self._outs.pop(k, None)
            return out
        finally:
            self._outs.clear()
            self.close()

    def close(self) -> None:
        """Shut the worker threads down (idempotent). Ranks already
        submitted still finish; their futures stay retrievable."""
        if self._closed:
            return
        self._closed = True
        for _ in self._threads:
            self._queue.put(None)
