"""Compare all eight solver families on one factorization problem.

Runs each algorithm on the same matrix/seed and reports the final RMS
residual, iterations, and stop reason — the single-factorization API
(``nmfx.nmf``, the analogue of the reference's ``doNMF``).

    python examples/solver_comparison.py
"""

import time

import numpy as np

import nmfx
from nmfx.config import ALGORITHMS
from nmfx.datasets import grouped_matrix
from nmfx.solvers import StopReason

a = grouped_matrix(n_genes=800, group_sizes=(20, 20, 20), effect=2.0, seed=1)

print(f"{'algorithm':10s} {'rms residual':>13s} {'iters':>6s} "
      f"{'stop':>13s} {'wall s':>7s}")
for algo in ALGORITHMS:
    t0 = time.perf_counter()
    res = nmfx.nmf(a, k=3, algorithm=algo, seed=0, max_iter=2000)
    dnorm = float(np.asarray(res.dnorm))  # materialization = sync
    wall = time.perf_counter() - t0
    print(f"{algo:10s} {dnorm:13.5f} {int(res.iterations):6d} "
          f"{StopReason(int(res.stop_reason)).name:>13s} {wall:7.2f}")
