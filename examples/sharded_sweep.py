"""Mesh-sharded sweeps: restart data-parallelism and grid sharding.

Demonstrates the three parallel axes on whatever devices are visible
(run with 1 TPU, 8 TPUs, or a virtual CPU mesh):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/sharded_sweep.py

Restart sharding needs no configuration (``nmfx.nmfconsensus`` builds the
default mesh). This script shows the explicit forms, including the grid
axes for factorizations too large for one device's HBM.
"""

import jax

import nmfx
from nmfx.datasets import two_group_matrix
from nmfx.sweep import grid_mesh

n_dev = len(jax.devices())
print(f"{n_dev} device(s): {jax.devices()}")
a = two_group_matrix(n_genes=400, n_per_group=12, seed=0)

# 1) restart axis over all devices (what use_mesh=True does automatically).
#    Multi-rank mu/hals sweeps also default to whole-grid execution: every
#    (k, restart) cell solves in ONE compiled slot-scheduled batch, each
#    device running its own job queue over its restart shard
#    (grid_exec="auto"; pass grid_exec="per_k" for sequential ranks, or
#    solver_cfg backend="pallas" for the fused-kernel pool on TPU)
result = nmfx.nmfconsensus(a, ks=(2, 3), restarts=2 * max(n_dev, 1),
                           seed=7)
print("\nrestart-sharded sweep (whole-grid scheduler):")
print(result.summary())

# 2) grid sharding: tile each factorization's rows/columns across devices.
#    Results are identical on every mesh shape (same seeds -> same draws).
if n_dev >= 4:
    mesh = grid_mesh(restart_shards=n_dev // 4, feature_shards=2,
                     sample_shards=2)
    result2 = nmfx.nmfconsensus(a, ks=(2, 3), restarts=2 * max(n_dev, 1),
                                seed=7, mesh=mesh)
    print("\n2x2 grid-sharded sweep (identical by construction):")
    print(result2.summary())

    # 3) grid sharding is not mu-only: kl (whose per-restart m x n quotient
    #    makes the tiling a memory necessity at scale) and the Gram-based
    #    neals/snmf shard through the same psum placement
    result3 = nmfx.nmfconsensus(a, ks=(2,), restarts=2 * n_dev, seed=7,
                                algorithm="kl", max_iter=2000, mesh=mesh)
    print("\nkl on the same grid mesh:")
    print(result3.summary())
else:
    print("\n(grid-sharding demo needs >= 4 devices; skipped)")
