"""Long-sweep workflow: checkpoint/resume and whole-result persistence.

Runs a sweep with a checkpoint registry, simulates an interruption by
re-running (finished ranks load from disk instead of recomputing), and
saves/reloads the final result for later analysis.

    python examples/long_sweeps.py
"""

import time

import nmfx
from nmfx.datasets import grouped_matrix

a = grouped_matrix(n_genes=800, group_sizes=(15, 15, 15), effect=2.0,
                   seed=7)

t0 = time.perf_counter()
result = nmfx.nmfconsensus(a, ks=(2, 3, 4), restarts=10, seed=42,
                           checkpoint_dir="ckpt_demo", output=None)
print(f"cold sweep: {time.perf_counter() - t0:.2f}s")

# a re-run with the same data+config resumes from the registry: every
# rank loads from ckpt_demo/ instead of recomputing
t0 = time.perf_counter()
resumed = nmfx.nmfconsensus(a, ks=(2, 3, 4), restarts=10, seed=42,
                            checkpoint_dir="ckpt_demo", output=None)
print(f"resumed sweep: {time.perf_counter() - t0:.2f}s "
      "(ranks loaded from checkpoint)")
assert resumed.summary() == result.summary()

# the DURABLE ledger (docs/serving.md "Durability model") goes finer:
# per-(rank, restart-chunk) completion records, so even a kill -9
# mid-RANK loses at most one chunk, and the resumed result is
# bit-identical to an uninterrupted checkpointed run
cfg = nmfx.CheckpointConfig("ckpt_demo_chunks", every_n_restarts=5)
t0 = time.perf_counter()
durable = nmfx.nmfconsensus(a, ks=(2, 3, 4), restarts=10, seed=42,
                            checkpoint=cfg, output=None)
print(f"\ndurable chunked sweep: {time.perf_counter() - t0:.2f}s")
t0 = time.perf_counter()
durable2 = nmfx.nmfconsensus(a, ks=(2, 3, 4), restarts=10, seed=42,
                             checkpoint=cfg, output=None)
print(f"durable resume: {time.perf_counter() - t0:.2f}s "
      "(every chunk loaded from its completion record)")
assert durable2.summary() == durable.summary()

# persist everything for later analysis without rerunning
result.save("result_demo.npz")
later = nmfx.ConsensusResult.load("result_demo.npz")
print(f"\nreloaded from result_demo.npz: best k = {later.best_k}")
print(later.summary())
print("\nordered consensus at best k:")
print(later.per_k[later.best_k].ordered_consensus.round(2))
