"""Per-restart factors and generic grid reductions.

The reference's BatchJobs registry keeps every job's full ``list(W, H,
iter)`` and ``reduceGridBy`` applies arbitrary reductions to the (k ×
restart) job grid (reference ``nmf.r:50, 72-98``). This walkthrough shows
the three equivalents:

1. ``keep_factors=True`` — retain all restarts' (W, H) in the result;
2. ``nmfx.restart_factors`` — recompute any single restart exactly from
   its seed-derived key, no retention needed;
3. ``nmfx.reduce_grid`` — group the grid by k or by restart index and
   apply any function to each group's cells.

    python examples/restart_analysis.py
"""

import numpy as np

import nmfx
from nmfx.datasets import two_group_matrix

KS = (2, 3)
RESTARTS = 8
SEED = 123


def main():
    a = two_group_matrix(n_genes=400, n_per_group=12, seed=1)

    # 1. retention through the high-level API
    result = nmfx.nmfconsensus(a, ks=KS, restarts=RESTARTS, seed=SEED,
                               max_iter=2000, keep_factors=True)
    r2 = result.per_k[2]
    print(f"k=2: all_w {r2.all_w.shape}, all_h {r2.all_h.shape}")
    best = int(np.argmin(r2.dnorms))
    assert np.array_equal(r2.best_w, r2.all_w[best])

    # 2. recompute-by-key: restart 3's factors without having kept any
    solo = nmfx.restart_factors(a, k=2, restart=3, restarts=RESTARTS,
                                seed=SEED, max_iter=2000)
    print("recomputed restart 3 matches retained:",
          np.allclose(solo.w, r2.all_w[3], rtol=1e-5, atol=1e-6))

    # 3. generic grid reductions — directly on the result from step 1
    # (reduce_grid also accepts raw nmfx.sweep.sweep output)
    cons = nmfx.reduce_grid(result)  # default fun = reference's reduction
    print("reduce_grid consensus matches on-device:",
          {k: bool(np.allclose(cons[k], result.per_k[k].consensus,
                               atol=1e-6)) for k in KS})
    # a reduction the fixed pipeline can't express: per-k residual spread
    spread = nmfx.reduce_grid(
        result, lambda cells: (min(c.dnorm for c in cells),
                               max(c.dnorm for c in cells)))
    for k, (lo, hi) in spread.items():
        print(f"k={k}: residual range over restarts [{lo:.5f}, {hi:.5f}]")
    # transpose grouping: every rank's result for restart 0
    per_restart = nmfx.reduce_grid(
        result, lambda cells: [(c.k, c.iterations) for c in cells],
        by="restart")
    print("restart 0 across ranks (k, iters):", per_restart[0])


if __name__ == "__main__":
    main()
