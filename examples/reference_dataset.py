"""Run the pipeline on a GCT file — e.g. the reference's bundled dataset.

The reference ships ``20+20x1000.gct`` (1000 genes × 40 samples, two
20-sample groups; reference ``nmf.r:11``). Point this script at any GCT:

    python examples/reference_dataset.py path/to/data.gct
"""

import sys

import nmfx

path = sys.argv[1] if len(sys.argv) > 1 else "20+20x1000.gct"
ds = nmfx.read_gct(path)
print(f"{path}: {ds.values.shape[0]} genes x {ds.values.shape[1]} samples")

result = nmfx.nmfconsensus(
    ds,
    ks=range(2, 6),
    restarts=10,
    seed=123,  # the reference example's seed (nmf.r:13)
    output=nmfx.OutputConfig(directory="out_gct"),
)
print(result.summary())
