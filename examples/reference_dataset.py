"""Run the pipeline on a GCT file — e.g. the reference's bundled dataset.

The reference ships ``20+20x1000.gct`` (1000 genes × 40 samples, two
20-sample groups; reference ``nmf.r:11``). Point this script at any GCT:

    python examples/reference_dataset.py path/to/data.gct
"""

import os
import sys

import nmfx

_DEFAULTS = ("20+20x1000.gct", "/root/reference/20+20x1000.gct")
if len(sys.argv) > 1:
    path = sys.argv[1]
else:
    path = next((p for p in _DEFAULTS if os.path.exists(p)), None)
    if path is None:
        sys.exit("no GCT given and none of the default locations exist "
                 f"({', '.join(_DEFAULTS)}); pass a path: "
                 "python examples/reference_dataset.py data.gct")
ds = nmfx.read_gct(path)
print(f"{path}: {ds.values.shape[0]} genes x {ds.values.shape[1]} samples")

result = nmfx.nmfconsensus(
    ds,
    ks=range(2, 6),
    restarts=10,
    seed=123,  # the reference example's seed (nmf.r:13)
    output=nmfx.OutputConfig(directory="out_gct"),
)
print(result.summary())
