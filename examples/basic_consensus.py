"""Minimal end-to-end consensus-NMF run on synthetic two-group data.

Generates a 500-gene × 30-sample matrix with two planted groups, sweeps
k = 2..5 × 20 restarts, and prints the rank-selection table — the
cophenetic rho should peak at k = 2 with a crisp (dispersion ≈ 1.0)
consensus matrix.

    python examples/basic_consensus.py
"""

import nmfx
from nmfx.datasets import two_group_matrix

a = two_group_matrix(n_genes=500, n_per_group=15, seed=42)

result = nmfx.nmfconsensus(
    a,
    ks=range(2, 6),
    restarts=20,
    seed=123,
    solver_cfg=nmfx.SolverConfig(algorithm="mu",
                                 matmul_precision="bfloat16"),
    output=nmfx.OutputConfig(directory="out_basic"),
)

print(result.summary())
print(f"\nbest k = {result.best_k}; outputs in out_basic/")
print("consensus matrix for k=2, dendrogram-ordered:")
print(result.per_k[2].ordered_consensus.round(2))
