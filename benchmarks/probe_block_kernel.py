"""Hardware probe: fused_block_iterations vs the per-iteration kernels.

Round-4 bisect tool for the round-3 corruption (VERDICT.md Weak #1): runs
the resident-W block kernel and the verified-correct per-iteration pallas
kernels side by side on the REAL device (no interpret mode) with identical
inputs at scheduler shapes, entirely outside the slot scheduler — so a
divergence here indicts the kernel itself, agreement indicts the
scheduler's evict/reload gating.

Usage: python benchmarks/probe_block_kernel.py [--precision bfloat16]
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np

from nmfx.ops.packed_mu import block_diag_mask
from nmfx.ops.pallas_mu import (fused_block_iterations, fused_h_update,
                                fused_w_update)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--precision", default="default",
                    choices=["default", "bfloat16"])
    ap.add_argument("--m", type=int, default=5120)
    ap.add_argument("--n", type=int, default=512)
    ap.add_argument("--k", type=int, default=8)
    ap.add_argument("--slots", type=int, default=64)
    ap.add_argument("--blocks", type=int, default=30,
                    help="number of 2-iteration blocks to run")
    args = ap.parse_args()

    m, n, k, s = args.m, args.n, args.k, args.slots
    rk = s * k
    print(f"platform={jax.default_backend()} m={m} n={n} rk={rk} "
          f"precision={args.precision}")

    key = jax.random.PRNGKey(0)
    ka, kw, kh = jax.random.split(key, 3)
    a = jax.random.uniform(ka, (m, n), jnp.float32)
    wp0 = jax.random.uniform(kw, (m, rk), jnp.float32)
    hp0 = jax.random.uniform(kh, (rk, n), jnp.float32)
    bd = block_diag_mask(s, k, jnp.float32)
    kern_kw = dict(block_m=512, eps=1e-9, zero_threshold=0.0,
                   matmul_precision=args.precision, interpret=False)
    frozen0 = jnp.zeros((1, rk), jnp.float32)

    def one_step(wp, hp):
        hn = fused_h_update(a, wp, hp, k=k, **kern_kw)
        gh = (hn @ hn.T) * bd
        wn = fused_w_update(a, wp, hn, gh, **kern_kw)
        return wn, hn

    def report(tag, w_ref, h_ref, w_blk, h_blk):
        w_ref, h_ref, w_blk, h_blk = map(np.asarray,
                                         (w_ref, h_ref, w_blk, h_blk))
        dw = np.max(np.abs(w_blk - w_ref)) / (np.max(np.abs(w_ref)) + 1e-30)
        dh = np.max(np.abs(h_blk - h_ref)) / (np.max(np.abs(h_ref)) + 1e-30)
        wn_ref = np.linalg.norm(w_ref, axis=0)
        wn_blk = np.linalg.norm(w_blk, axis=0)
        print(f"[{tag}] rel|dW|={dw:.3e} rel|dH|={dh:.3e}  "
              f"Wcol-norm ref[min/max]={wn_ref.min():.3f}/{wn_ref.max():.3f}"
              f" blk[min/max]={wn_blk.min():.3f}/{wn_blk.max():.3f}")
        return dw, dh

    # --- probe 1: ONE block of 2 iterations vs 2 per-iteration steps ----
    w_r, h_r = one_step(*one_step(wp0, hp0))
    w_b, h_b, wd, wm, hd, hm = fused_block_iterations(
        a, wp0 + 0, hp0 + 0, frozen0, k=k, iters=2, **kern_kw)
    report("1 block (2 iters)", w_r, h_r, w_b, h_b)

    # stats cross-check: wd/wm from the kernel vs recomputed from the
    # per-iteration path's last step
    w_r1, h_r1 = one_step(wp0, hp0)
    wd_ref = jnp.max(jnp.abs(w_r - w_r1), axis=0)
    wm_ref = jnp.max(jnp.abs(w_r1), axis=0)
    hd_ref = jnp.max(jnp.abs(h_r - h_r1), axis=1)
    hm_ref = jnp.max(jnp.abs(h_r1), axis=1)
    for nm, got, ref in (("wd", wd.ravel(), wd_ref), ("wm", wm.ravel(), wm_ref),
                         ("hd", hd.ravel(), hd_ref), ("hm", hm.ravel(), hm_ref)):
        err = np.max(np.abs(np.asarray(got) - np.asarray(ref))) / (
            float(np.max(np.abs(np.asarray(ref)))) + 1e-30)
        print(f"  stat {nm}: rel err {err:.3e}")

    # --- probe 2: trajectory over many blocks ---------------------------
    w_r, h_r = wp0, hp0
    w_b, h_b = wp0 + 0, hp0 + 0
    for i in range(args.blocks):
        w_r, h_r = one_step(*one_step(w_r, h_r))
        w_b, h_b, *_ = fused_block_iterations(
            a, w_b, h_b, frozen0, k=k, iters=2, **kern_kw)
        if i in (0, 4, args.blocks - 1):
            report(f"block {i + 1}", w_r, h_r, w_b, h_b)

    # --- probe 3: frozen-lane invariance --------------------------------
    frozen = (jnp.arange(rk) % (2 * k) < k).astype(jnp.float32)[None, :]
    w_b, h_b, *_ = fused_block_iterations(
        a, wp0 + 0, hp0 + 0, frozen, k=k, iters=4, **kern_kw)
    fmask = np.asarray(frozen.ravel() > 0)
    dw_frozen = np.max(np.abs(np.asarray(w_b)[:, fmask]
                              - np.asarray(wp0)[:, fmask]))
    dh_frozen = np.max(np.abs(np.asarray(h_b)[fmask, :]
                              - np.asarray(hp0)[fmask, :]))
    moved = np.max(np.abs(np.asarray(w_b)[:, ~fmask]
                          - np.asarray(wp0)[:, ~fmask]))
    print(f"[frozen] max|d frozen W|={dw_frozen:.3e} "
          f"max|d frozen H|={dh_frozen:.3e} (should be 0); "
          f"active lanes moved {moved:.3e} (should be >0)")


if __name__ == "__main__":
    main()
