"""Marginal per-iteration cost of the slot scheduler: dense vs pallas.

The repo's marginal-cost protocol (round 3, kept for round 4 re-runs on
the FIXED block kernel): class/TolX stops OFF so every job runs exactly
max_iter iterations with the pool permanently full (48 jobs in 48
slots, no reloads), then the per-whole-pool-iteration cost is the
min-of-N delta between a long and a short run divided by the iteration
difference — short-delta timing on the tunneled chip fabricates fixed
costs, so the delta must span hundreds of iterations.

Usage: python benchmarks/probe_sched_marginal.py [--reps 5]
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

from nmfx.config import SolverConfig
from nmfx.datasets import grouped_matrix
from nmfx.ops.sched_mu import mu_sched


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--genes", type=int, default=5000)
    ap.add_argument("--samples", type=int, default=500)
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--jobs", type=int, default=48)
    ap.add_argument("--iters", type=int, nargs=2, default=[200, 800])
    args = ap.parse_args()

    m, n, k, j = args.genes, args.samples, args.k, args.jobs
    lo, hi = args.iters
    a = grouped_matrix(m, (n // 4,) * 4, effect=2.0, seed=0)
    key = jax.random.PRNGKey(3)
    kw, kh = jax.random.split(key)
    w0 = jax.random.uniform(kw, (j, m, k), jnp.float32)
    h0 = jax.random.uniform(kh, (j, k, n), jnp.float32)

    def run(backend, max_iter):
        cfg = SolverConfig(algorithm="mu", max_iter=max_iter,
                           use_class_stop=False, use_tol_checks=False,
                           matmul_precision="bfloat16", backend=backend)
        t0 = time.perf_counter()
        r = mu_sched(a, w0, h0, cfg, slots=j)
        np.asarray(r.iterations)  # host materialization
        np.asarray(r.w[0])
        return time.perf_counter() - t0

    cells = [(b, it) for b in ("auto", "pallas") for it in (lo, hi)]
    for b, it in cells:  # compile
        t0 = time.perf_counter()
        run(b, it)
        print(f"warm {b}@{it}: {time.perf_counter() - t0:.1f}s", flush=True)

    walls = {c: [] for c in cells}
    for rep in range(args.reps):
        for c in cells:
            w = run(*c)
            walls[c].append(w)
            print(f"rep {rep} {c}: {w:.3f}s", flush=True)

    for b in ("auto", "pallas"):
        wlo = min(walls[(b, lo)])
        whi = min(walls[(b, hi)])
        per_iter = (whi - wlo) / (hi - lo)
        print(f"{b}: min {lo}-iter={wlo:.3f}s min {hi}-iter={whi:.3f}s "
              f"marginal={per_iter * 1e3:.4f} ms/pool-iteration")


if __name__ == "__main__":
    main()
