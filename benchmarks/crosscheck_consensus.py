"""North-star cross-engine consensus equivalence — the recorded artifact.

Runs the FULL north-star sweep (k=2..10 × 50 restarts, 5000×500) through
the three mu execution engines on the real device — per-k packed,
grid-dense (slot scheduler on XLA blocks), grid-pallas (slot scheduler
on the fused kernels) — and records the user-visible deltas: per-k
max |ΔC| between consensus matrices, Δrho, the rank table each engine
selects, and mean iterations. `bench.py --verify` is the fast scaled
gate; this is the full-scale evidence artifact (VERDICT r3 #6), written
to benchmarks/CROSSCHECK_r04.json + a markdown summary on stdout.

Usage: python benchmarks/crosscheck_consensus.py
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import _run_sweep_engine  # noqa: E402
from nmfx.config import ConsensusConfig, InitConfig, SolverConfig  # noqa: E402
from nmfx.datasets import grouped_matrix  # noqa: E402
from nmfx.sweep import default_mesh  # noqa: E402


def main():
    m, n, restarts = 5000, 500, 50
    ks = tuple(range(2, 11))
    a = grouped_matrix(m, (n // 4,) * 4, effect=2.0, seed=0)
    scfg = SolverConfig(algorithm="mu", max_iter=10000,
                        matmul_precision="bfloat16")
    icfg = InitConfig()
    mesh = default_mesh()
    engines = {
        "per-k": (dataclasses.replace(scfg, backend="packed"), "per_k"),
        "grid-dense": (dataclasses.replace(scfg, backend="auto"), "grid"),
        "grid-pallas": (dataclasses.replace(scfg, backend="pallas"),
                        "grid"),
    }
    results = {}
    for name, (cfg_e, grid_exec) in engines.items():
        ccfg = ConsensusConfig(ks=ks, restarts=restarts, seed=123,
                               grid_exec=grid_exec)
        t0 = time.perf_counter()
        results[name] = _run_sweep_engine(a, ks, cfg_e, ccfg, icfg, mesh)
        print(f"# {name}: {time.perf_counter() - t0:.1f}s "
              "(incl. compile on first run)", file=sys.stderr)

    record = {"shape": f"{m}x{n}", "ks": list(ks), "restarts": restarts,
              "config": "maxiter=10000, bf16, seed=123", "engines": {}}
    for name, (its, _, cons, rho) in results.items():
        record["engines"][name] = {
            "rho": {str(k): round(float(rho[k]), 4) for k in ks},
            "best_k": int(max(ks, key=lambda k: rho[k])),
            "mean_iters": {str(k): round(float(its[k].mean()), 1)
                           for k in ks},
        }
    ref_name = "grid-dense"
    _, _, ref_cons, ref_rho = results[ref_name]
    record["deltas_vs_grid_dense"] = {}
    for name in engines:
        if name == ref_name:
            continue
        _, _, cons, rho = results[name]
        record["deltas_vs_grid_dense"][name] = {
            str(k): {"max_dC": round(float(np.max(np.abs(
                cons[k] - ref_cons[k]))), 4),
                "mean_dC": round(float(np.mean(np.abs(
                    cons[k] - ref_cons[k]))), 5),
                "d_rho": round(abs(float(rho[k]) - float(ref_rho[k])), 4)}
            for k in ks}

    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "CROSSCHECK_r04.json")
    with open(out, "w") as f:
        json.dump(record, f, indent=1)
    print(f"# wrote {out}", file=sys.stderr)

    # markdown summary
    print("| engine | best k | rho(k=2..10) |")
    print("|---|---|---|")
    for name, e in record["engines"].items():
        rhos = " ".join(e["rho"][str(k)] if isinstance(e["rho"][str(k)], str)
                        else f"{e['rho'][str(k)]:.3f}" for k in ks)
        print(f"| {name} | {e['best_k']} | {rhos} |")
    print()
    print("| engine vs grid-dense | worst max|dC| | worst d_rho |")
    print("|---|---|---|")
    for name, d in record["deltas_vs_grid_dense"].items():
        worst_dc = max(v["max_dC"] for v in d.values())
        worst_dr = max(v["d_rho"] for v in d.values())
        print(f"| {name} | {worst_dc} | {worst_dr} |")


if __name__ == "__main__":
    main()
