"""rank_selection='device' vs 'host' end-to-end (api.nmfconsensus).

Round-5 datapoint for the "consensus never leaves HBM" north star
(SURVEY §2c): the on-device average-linkage hclust + cophenetic path
(ops/hclust_jax.py) vs the host path (one n^2 consensus pull per rank +
the native C++ NN-cached UPGMA). Interleaved min-of-N through the
tunneled chip. See RESULTS.md "Device-side rank selection in the
pipeline" for the measured verdict and its environment caveat.

Usage: PYTHONPATH=. python benchmarks/probe_rank_selection.py
"""
import argparse, time
from nmfx.api import nmfconsensus
from nmfx.config import SolverConfig
from nmfx.datasets import grouped_matrix

ap = argparse.ArgumentParser()
ap.add_argument("--reps", type=int, default=3)
args = ap.parse_args()

cases = {
    "n=500": dict(a=grouped_matrix(5000, (125,) * 4, effect=2.0, seed=0),
                  ks=(2, 3, 4, 5), restarts=20),
    "n=2000": dict(a=grouped_matrix(2000, (500,) * 4, effect=2.0, seed=0),
                   ks=(2, 3, 4), restarts=12),
}
scfg = SolverConfig(algorithm="mu", max_iter=2000,
                    matmul_precision="bfloat16")
for label, case in cases.items():
    def run(mode):
        t0 = time.perf_counter()
        res = nmfconsensus(case["a"], ks=case["ks"],
                           restarts=case["restarts"], solver_cfg=scfg,
                           rank_selection=mode)
        assert res.best_k is not None
        return time.perf_counter() - t0
    walls = {}
    for mode in ("host", "device"):
        print(f"warm {label} {mode}: {run(mode):.1f}s", flush=True)
        walls[mode] = []
    for rep in range(args.reps):
        for mode in ("host", "device"):
            walls[mode].append(run(mode))
            print(f"rep {rep} {label} {mode}: {walls[mode][-1]:.3f}s",
                  flush=True)
    for mode, ws in walls.items():
        ws = sorted(ws)
        print(f"{label} {mode}: min={ws[0]:.3f}s "
              f"all={[round(x, 3) for x in ws]}")
