"""Tail-pool width sweep: tune mu_sched's straggler tail compaction.

Interleaved same-session reps of the full north-star sweep across tail
widths for both scheduler engines; the winner sets
``sched_mu._AUTO_TAIL_SLOTS``. Protocol as in probe_ab_northstar.py
(same-session minima only).

Usage: python benchmarks/probe_tail_slots.py [--reps 4]
"""

from __future__ import annotations

import argparse
import time

import jax
import numpy as np

from nmfx.config import ConsensusConfig, InitConfig, SolverConfig
from nmfx.datasets import grouped_matrix
from nmfx.sweep import default_mesh, sweep


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=4)
    ap.add_argument("--tails", nargs="+", default=["0", "4", "8", "16"])
    ap.add_argument("--backends", nargs="+", default=["auto", "pallas"])
    args = ap.parse_args()
    tails = [tuple(int(x) for x in t.split(",")) if "," in t else int(t)
             for t in args.tails]

    ks = tuple(range(2, 11))
    a = grouped_matrix(5000, (125,) * 4, effect=2.0, seed=0)
    icfg = InitConfig()
    mesh = default_mesh()

    def run(backend, tail):
        scfg = SolverConfig(algorithm="mu", max_iter=10000,
                            matmul_precision="bfloat16", backend=backend)
        ccfg = ConsensusConfig(ks=ks, restarts=50, seed=123,
                               grid_exec="grid", grid_tail_slots=tail)
        t0 = time.perf_counter()
        raw = sweep(a, ccfg, scfg, icfg, mesh)
        jax.device_get({k: raw[k].consensus for k in ks})
        return time.perf_counter() - t0

    cells = [(b, t) for b in args.backends for t in tails]
    for c in cells:
        t0 = time.perf_counter()
        run(*c)
        print(f"warm {c}: {time.perf_counter() - t0:.1f}s", flush=True)
    walls = {c: [] for c in cells}
    for rep in range(args.reps):
        for c in cells:
            w = run(*c)
            walls[c].append(w)
            print(f"rep {rep} {c}: {w:.3f}s", flush=True)
    for c in cells:
        v = np.array(walls[c])
        print(f"{c}: min={v.min():.3f} median={np.median(v):.3f} "
              f"all={[round(x, 3) for x in v.tolist()]}")


if __name__ == "__main__":
    main()
