"""pg/alspg at the reference's own stopping rule — no budget truncation.

VERDICT r4 Missing #3: every published nmfx pg/alspg number was
budget-truncated (100-iter / 20×100 caps), so "matching-or-beating" was
never demonstrated under the reference stop
``projnorm < tol·initgrad`` (reference nmf_pg.c:228-243,
nmf_alspg.c:193-209). This probe runs the rule honestly at two scales:

1. **Reference-fixture scale** (1000×40, the bundled 20+20x1000.gct's
   shape class): k=2..5 × 10 restarts, tol_pg=1e-4 (Lin's customary
   tolerance — the reference's own driver default is tol=2e-16,
   setdefaultopts.c:51, which NEVER fires; 1e-4 is the strictest
   published practice), maxiter=10000 (the reference R-flow's cap,
   nmf.r:13). Reports the stop-reason split, iteration distribution,
   and wall.
2. **Bench shape** (5000×500, k=4 × 50 restarts): single timed run each
   at the same rule — pg to maxiter=10000, alspg to maxiter=2000 outer
   (its outer iterations each run two ≤1000-step NNLS chains; 2000
   outer already exceeds any observed stop by 4× and a 10000-outer run
   is ~17 min of pure chain latency — recorded as such, not hidden).

Usage: PYTHONPATH=. python benchmarks/probe_pg_convergence.py
"""

from __future__ import annotations

import argparse
import collections
import time

import jax
import numpy as np

from nmfx.config import ConsensusConfig, InitConfig, SolverConfig
from nmfx.datasets import grouped_matrix
from nmfx.solvers.base import StopReason
from nmfx.sweep import default_mesh, sweep


def run_case(a, algorithm, ks, restarts, max_iter, label):
    scfg = SolverConfig(algorithm=algorithm, max_iter=max_iter,
                        matmul_precision="bfloat16")
    ccfg = ConsensusConfig(ks=tuple(ks), restarts=restarts, seed=123,
                           grid_exec="per_k")
    mesh = default_mesh()
    t0 = time.perf_counter()
    raw = sweep(a, ccfg, scfg, InitConfig(), mesh)
    host = jax.device_get({k: (raw[k].iterations, raw[k].stop_reasons)
                           for k in ks})
    wall = time.perf_counter() - t0
    print(f"\n{label}: wall={wall:.1f}s (includes compile on first call)")
    for k in ks:
        its, stops = host[k]
        reasons = collections.Counter(
            StopReason(int(r)).name for r in stops)
        print(f"  k={k}: iters min/median/max = {int(its.min())}/"
              f"{int(np.median(its))}/{int(its.max())}; stops: "
              f"{dict(reasons)}")
    return wall


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip-large", action="store_true",
                    help="only the reference-fixture-scale runs")
    args = ap.parse_args()

    # 1. reference fixture scale
    a_small = grouped_matrix(1000, (20, 20), effect=2.0, seed=0)
    for algo in ("pg", "alspg"):
        run_case(a_small, algo, range(2, 6), 10, 10000,
                 f"{algo} @ 1000x40, k=2..5 x 10, tol_pg rule, "
                 "maxiter=10000")

    if args.skip_large:
        return
    # 2. bench shape, single timed runs
    a_big = grouped_matrix(5000, (125,) * 4, effect=2.0, seed=0)
    run_case(a_big, "pg", [4], 50, 10000,
             "pg @ 5000x500, k=4 x 50, tol_pg rule, maxiter=10000")
    run_case(a_big, "alspg", [4], 50, 2000,
             "alspg @ 5000x500, k=4 x 50, tol_pg rule, maxiter=2000")


if __name__ == "__main__":
    main()
