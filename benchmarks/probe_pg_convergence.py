"""pg/alspg at the reference's own stopping rule — no budget truncation.

VERDICT r4 Missing #3: every published nmfx pg/alspg number was
budget-truncated (100-iter / 20×100 caps), so "matching-or-beating" was
never demonstrated under the reference stop
``projnorm < tol·initgrad`` (reference nmf_pg.c:228-243,
nmf_alspg.c:193-209). This probe runs the rule honestly at two scales:

1. **Reference-fixture scale** (1000×40, the bundled 20+20x1000.gct's
   shape class): k=2..5 × 10 restarts, tol_pg=1e-4 (Lin's customary
   tolerance — the reference's own driver default is tol=2e-16,
   setdefaultopts.c:51, which NEVER fires; 1e-4 is the strictest
   published practice). Reports the stop-reason split, iteration
   distribution, and wall.
2. **Bench shape** (5000×500, k=4 × 50 restarts): single timed runs at
   the same rule.

Environment limit, measured round 5: the tunneled TPU worker CRASHES
("TPU worker process crashed or restarted") on single dispatches
longer than ~250–300 s — pg's one-jit whole-solve at maxiter=10000
(the reference R-flow's cap) reproducibly kills it; maxiter=4000
(a ~208 s dispatch at the fixture scale) survives and 6000 does not.
The caps below are therefore 4000 (pg) / 2000–1000 (alspg, whose outer
iterations each run two ≤1000-step NNLS chains). The stop-rule
conclusion is unaffected: whether the projected-gradient stop fires is
established well before 4000 iterations at both scales.

Usage: PYTHONPATH=. python benchmarks/probe_pg_convergence.py
"""

from __future__ import annotations

import argparse
import collections
import time

import jax
import numpy as np

from nmfx.config import ConsensusConfig, InitConfig, SolverConfig
from nmfx.datasets import grouped_matrix
from nmfx.solvers.base import StopReason
from nmfx.sweep import default_mesh, sweep


def run_case(a, algorithm, ks, restarts, max_iter, label):
    scfg = SolverConfig(algorithm=algorithm, max_iter=max_iter,
                        matmul_precision="bfloat16")
    ccfg = ConsensusConfig(ks=tuple(ks), restarts=restarts, seed=123,
                           grid_exec="per_k")
    mesh = default_mesh()
    t0 = time.perf_counter()
    raw = sweep(a, ccfg, scfg, InitConfig(), mesh)
    host = jax.device_get({k: (raw[k].iterations, raw[k].stop_reasons)
                           for k in ks})
    wall = time.perf_counter() - t0
    print(f"\n{label}: wall={wall:.1f}s (includes compile on first call)")
    for k in ks:
        its, stops = host[k]
        reasons = collections.Counter(
            StopReason(int(r)).name for r in stops)
        print(f"  k={k}: iters min/median/max = {int(its.min())}/"
              f"{int(np.median(its))}/{int(its.max())}; stops: "
              f"{dict(reasons)}")
    return wall


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip-large", action="store_true",
                    help="only the reference-fixture-scale runs")
    args = ap.parse_args()

    # 1. reference fixture scale (caps: see the watchdog note above)
    a_small = grouped_matrix(1000, (20, 20), effect=2.0, seed=0)
    run_case(a_small, "pg", range(2, 6), 10, 4000,
             "pg @ 1000x40, k=2..5 x 10, tol_pg rule, maxiter=4000")
    run_case(a_small, "alspg", range(2, 6), 10, 2000,
             "alspg @ 1000x40, k=2..5 x 10, tol_pg rule, maxiter=2000")

    if args.skip_large:
        return
    # 2. bench shape, single timed runs (pg@4000 crashed the worker at
    # THIS shape too — 2000/500 are the proven caps here)
    a_big = grouped_matrix(5000, (125,) * 4, effect=2.0, seed=0)
    run_case(a_big, "pg", [4], 50, 2000,
             "pg @ 5000x500, k=4 x 50, tol_pg rule, maxiter=2000")
    run_case(a_big, "alspg", [4], 50, 500,
             "alspg @ 5000x500, k=4 x 50, tol_pg rule, maxiter=500")


if __name__ == "__main__":
    main()
