"""On-chip bisect + A/B for alias_io (round 5): donate the block
kernel's w_in/h_in buffers as its outputs.

Round 3 shipped input/output-aliased VMEM windows WITHOUT an explicit
DMA and the windows went stale inside while_loop bodies (the corruption
the fault-injection gate now proves catchable). alias_io is a different
design — the data path is the explicit step-0 DMA; the alias only lets
XLA update the while-carry in place, targeting the ~30 µs/trip factor
copies the round-5 profiler trace attributed to the carry. Because this
is the same HAZARD CLASS, this probe replays the round-4 bisect at
three levels before any timing:

1. standalone kernel: aliased vs not, bit-exact outputs;
2. the round-3 failure shape: the kernel inside a lax.while_loop whose
   body REWRITES slot columns between calls (simulated reloads) — the
   exact pattern that exposed the stale windows;
3. the full scheduler: experimental.alias_io=True vs False — per-job stop
   iterations bit-equal ON HARDWARE is not expected (position/timing
   drift), so level 3 asserts the verify-gate invariants instead
   (iteration ratios, restart-normalized consensus drift), then times
   interleaved min-of-N.

After this probe, the decision gate is `bench.py --verify` (incl. the
reload-exercising boundary stage) + `probe_fault_gate.py` on the
aliased build.

Usage: PYTHONPATH=. python benchmarks/probe_alias_io.py [--reps 5]
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from nmfx.config import InitConfig, SolverConfig
from nmfx.consensus import consensus_matrix, labels_from_h
from nmfx.datasets import grouped_matrix
from nmfx.init import initialize
from nmfx.ops.pallas_mu import fused_block_iterations
from nmfx.ops.sched_mu import mu_sched


def level1(a, wp, hp, fcol, k):
    outs = {}
    for alias in (False, True):
        outs[alias] = fused_block_iterations(
            a, wp, hp, fcol, k=k, iters=2,
            matmul_precision="bfloat16", alias_io=alias)
    for i, name in enumerate(("wp", "hp", "wd", "wm", "hd", "hm")):
        x, y = np.asarray(outs[False][i]), np.asarray(outs[True][i])
        assert np.array_equal(x, y), f"level1: {name} differs"
    print("level1 standalone: bit-exact", flush=True)


def level2(a, wp, hp, k):
    """Kernel inside a while_loop whose body rewrites a slot's columns
    between calls — the round-3 staleness pattern."""
    rk = wp.shape[1]
    fcol = jnp.zeros((1, rk), jnp.float32)
    fresh_w = jnp.ones((wp.shape[0], k), wp.dtype) * 0.5
    fresh_h = jnp.ones((k, hp.shape[1]), hp.dtype) * 0.5

    def make(alias):
        def body(c):
            i, w, h = c
            w, h, *_ = fused_block_iterations(
                a, w, h, fcol, k=k, iters=2,
                matmul_precision="bfloat16", alias_io=alias)
            # rewrite slot 1's columns every other trip (a "reload"):
            # the next call MUST see these values
            do = (i % 2) == 0
            w = jnp.where(do, w.at[:, k:2 * k].set(fresh_w), w)
            h = jnp.where(do, h.at[k:2 * k, :].set(fresh_h), h)
            return i + 1, w, h

        _, w, h = lax.while_loop(lambda c: c[0] < 20, body,
                                 (jnp.asarray(0), wp, hp))
        return np.asarray(w), np.asarray(h)

    w0, h0 = make(False)
    w1, h1 = make(True)
    assert np.array_equal(w0, w1), "level2: W diverged under aliasing"
    assert np.array_equal(h0, h1), "level2: H diverged under aliasing"
    print("level2 while_loop + slot rewrites: bit-exact", flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=5)
    args = ap.parse_args()

    # levels 1-2 at a small padded shape
    m, n, k, slots = 1024, 128, 4, 6
    key = jax.random.PRNGKey(0)
    ka, kw, kh = jax.random.split(key, 3)
    a = jax.random.uniform(ka, (m, n), jnp.float32).astype(jnp.bfloat16)
    wp = jax.random.uniform(kw, (m, slots * k), jnp.float32)
    hp = jax.random.uniform(kh, (slots * k, n), jnp.float32)
    fcol = jnp.zeros((1, slots * k), jnp.float32)
    level1(a, wp, hp, fcol, k)
    level2(a, wp, hp, k)

    # level 3: full scheduler, gate invariants + interleaved timing
    ks = tuple(range(10, 1, -1))
    k_max = 10
    restarts = 50
    big = grouped_matrix(5000, (125,) * 4, effect=2.0, seed=0)
    root = jax.random.PRNGKey(123)
    w0l, h0l = [], []
    for kk_ in ks:
        keys = jax.random.split(jax.random.fold_in(root, kk_), restarts)
        w0s, h0s = jax.vmap(
            lambda q, kk_=kk_: initialize(q, big, kk_, InitConfig(),
                                          jnp.float32))(keys)
        w0l.append(jnp.pad(w0s, ((0, 0), (0, 0), (0, k_max - kk_))))
        h0l.append(jnp.pad(h0s, ((0, 0), (0, k_max - kk_), (0, 0))))
    w0g = jnp.concatenate(w0l)
    h0g = jnp.concatenate(h0l)
    cfg = SolverConfig(algorithm="mu", max_iter=10000,
                       matmul_precision="bfloat16", backend="pallas")

    def run(alias):
        import dataclasses

        from nmfx.config import ExperimentalConfig

        cfg_a = dataclasses.replace(
            cfg, experimental=ExperimentalConfig(alias_io=alias))
        t0 = time.perf_counter()
        r = mu_sched(big, w0g, h0g, cfg_a, slots=48)
        its = np.asarray(r.iterations)
        h = np.asarray(r.h)
        return time.perf_counter() - t0, its, h

    res = {}
    for alias in (False, True):
        t0 = time.perf_counter()
        wall, its, h = run(alias)
        res[alias] = (wall, its, h)
        print(f"warm alias={alias}: {time.perf_counter() - t0:.1f}s "
              f"iters_total={int(its.sum())}", flush=True)
    _, its0, h0_ = res[False]
    _, its1, h1_ = res[True]
    for gi, kk_ in enumerate(ks):
        sl = slice(gi * restarts, (gi + 1) * restarts)
        ratio = its1[sl].mean() / its0[sl].mean()
        lab0 = jax.vmap(labels_from_h)(jnp.asarray(h0_[sl, :kk_, :]))
        lab1 = jax.vmap(labels_from_h)(jnp.asarray(h1_[sl, :kk_, :]))
        dc = np.abs(np.asarray(consensus_matrix(lab1, kk_))
                    - np.asarray(consensus_matrix(lab0, kk_)))
        line = (f"level3 k={kk_}: iters_ratio={ratio:.3f} "
                f"mean|dC|*R={dc.mean() * restarts:.3f} "
                f"max|dC|={dc.max():.3f}")
        print(line, flush=True)
        assert 1 / 1.6 < ratio < 1.6, line
        assert dc.mean() * restarts <= 0.6, line

    walls = {False: [], True: []}
    for rep in range(args.reps):
        for alias in (False, True):
            w_, _, _ = run(alias)
            walls[alias].append(w_)
            print(f"rep {rep} alias={alias}: {w_:.3f}s", flush=True)
    for alias, ws in walls.items():
        ws = sorted(ws)
        print(f"alias={alias}: min={ws[0]:.3f}s "
              f"median={ws[len(ws) // 2]:.3f}s "
              f"all={[round(x, 3) for x in ws]}")


if __name__ == "__main__":
    main()
