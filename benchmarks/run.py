"""Benchmark harness beyond the single north-star number (SURVEY.md §7
build step 8): per-solver restart throughput and sweep wall-clock across
problem sizes. Prints a table and emits one JSON document; bench.py at the
repo root remains the driver-facing single-line harness.

    python benchmarks/run.py            # full table (TPU, ~2-4 min)
    python benchmarks/run.py --quick    # smaller sizes
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _time_sweep(a, ks, restarts, scfg, warm_seed=999, seed=123):
    import jax

    from nmfx.config import ConsensusConfig, InitConfig
    from nmfx.sweep import default_mesh, sweep

    mesh = default_mesh()
    icfg = InitConfig()

    def run(seed):
        out = sweep(a, ConsensusConfig(ks=ks, restarts=restarts, seed=seed),
                    scfg, icfg, mesh)
        # one batched host materialization = the sync point (per-array
        # pulls pay a tunnel round trip each; see bench.py / api.py)
        return out, jax.device_get(
            {k: (out[k].consensus, out[k].iterations) for k in ks})

    t0 = time.perf_counter()
    run(warm_seed)  # compile — timed: the first-run cost a user pays
    cold = time.perf_counter() - t0
    t0 = time.perf_counter()
    _, host = run(seed)
    wall = time.perf_counter() - t0
    iters = float(np.mean([host[k][1].mean() for k in ks]))
    return wall, iters, cold


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--quick", action="store_true")
    p.add_argument("--maxiter", type=int, default=2000)
    args = p.parse_args()

    import jax

    from nmfx.config import ALGORITHMS, SolverConfig
    from nmfx.datasets import grouped_matrix

    m, n = (1000, 120) if args.quick else (5000, 500)
    restarts = 8 if args.quick else 20
    ks = (2, 4) if args.quick else (2, 4, 6)
    a = grouped_matrix(m, tuple([n // 4] * 4), effect=2.0, seed=0)

    results = {"device": str(jax.devices()[0]), "shape": [m, n],
               "restarts_per_k": restarts, "ks": list(ks),
               "maxiter": args.maxiter, "solvers": {}, "scaling": []}

    # the projected-gradient family pays nested line searches per outer
    # iteration (~50 ms/iter at this size) — cap it so the table stays
    # minutes, and record the caps in the output
    per_solver = {
        "pg": dict(max_iter=100),
        "alspg": dict(max_iter=20, sub_max_iter=100),
    }
    print(f"# per-solver: {m}x{n}, k={list(ks)}, {restarts} restarts/k, "
          f"maxiter={args.maxiter} (pg: 100; alspg: 20x100 sub)")
    print(f"{'solver':14s} {'wall s':>8s} {'cold s':>8s} "
          f"{'restarts/s':>11s} {'mean iters':>11s}")
    # the round-4/5 whole-grid opt-ins measured alongside their
    # defaults: one compile for the whole sweep vs one per rank
    # (derived from the routing table; mu/hals excluded because the
    # grid scheduler already IS their "auto" engine)
    from nmfx.config import PACKED_ALGORITHMS

    packed_optins = tuple(a for a in PACKED_ALGORITHMS
                          if a not in ("mu", "hals"))
    for algo in ALGORITHMS:
        kw = dict(max_iter=args.maxiter)
        kw.update(per_solver.get(algo, {}))
        # the +packed variant runs FIRST so its cold number does NOT
        # benefit from compiles the auto variant already warmed
        # (vmapped init, consensus reduction, ...) — the order bias runs
        # AGAINST the one-compile claim, so the published cold speedups
        # are conservative; the auto row's cold is the one that
        # inherits shared warm-ups within a solver
        variants = [("", "auto")]
        if algo in packed_optins:
            variants.insert(0, ("+packed", "packed"))
        for suffix, backend in variants:
            scfg = SolverConfig(algorithm=algo,
                                matmul_precision="bfloat16",
                                backend=backend, **kw)
            wall, iters, cold = _time_sweep(a, ks, restarts, scfg)
            rps = len(ks) * restarts / wall
            results["solvers"][algo + suffix] = {
                "wall_s": round(wall, 3), "cold_s": round(cold, 3),
                "restarts_per_s": round(rps, 2),
                "mean_iters": round(iters, 1),
                "max_iter": kw["max_iter"]}
            print(f"{algo + suffix:14s} {wall:8.2f} {cold:8.2f} "
                  f"{rps:11.1f} {iters:11.0f}")

    sizes = ([(500, 60), (1000, 120)] if args.quick
             else [(1000, 100), (5000, 500), (20000, 1000)])
    print(f"\n# mu sweep scaling (k={list(ks)}, {restarts} restarts/k)")
    print(f"{'genes x samples':>16s} {'wall s':>8s} {'restarts/s':>11s}")
    for sm, sn in sizes:
        sa = grouped_matrix(sm, tuple([sn // 4] * 4), effect=2.0, seed=0)
        scfg = SolverConfig(algorithm="mu", max_iter=args.maxiter,
                            matmul_precision="bfloat16")
        wall, _, _cold = _time_sweep(sa, ks, restarts, scfg)
        results["scaling"].append({"shape": [sm, sn],
                                   "wall_s": round(wall, 3)})
        print(f"{f'{sm}x{sn}':>16s} {wall:8.2f} "
              f"{len(ks) * restarts / wall:11.1f}")

    print("\n" + json.dumps(results))


if __name__ == "__main__":
    main()
