"""Prove the hardware verify gate catches the round-3 bug class.

Round 3 shipped a corrupted pallas scheduler: slot reloads written to an
input/output-aliased HBM buffer never reached the VMEM-resident factor
windows, so reloaded jobs iterated on the PREVIOUS job's converged
factors and "converged" within a check or two (VERDICT.md round 3).
Round 4 built ``bench.py --verify`` to make that class of bug unable to
ship — but the gate itself was trusted, never tested (VERDICT.md round 4,
Missing #2). This probe closes that loop:

1. runs ``bench.py --verify`` clean → must PASS (exit 0);
2. runs it again in a subprocess with ``NMFX_FAULT_INJECT_STALE_RELOAD``
   set — ``bench.py --verify`` translates the var into the explicit
   ``nmfx.ops.sched_mu.enable_stale_reload_fault()`` opt-in at startup
   (since round 7 the env var is INERT in library code: trace-time env
   reads are the lint class NMFX002), which drops the factor writes for
   a deterministic fraction of pallas-path slot reloads while the
   scheduler's bookkeeping proceeds, reproducing the round-3 failure
   signature exactly — and the gate must FAIL (exit 1).

Reload traffic only exists where jobs outnumber slots: the gate's
boundary stage (108 jobs through a 48-slot pool at the VMEM-envelope
shape — 60 evict/reload events) is what forces evictions, which is why
that stage exists. The
probe writes ``benchmarks/FAULTGATE_r05.json`` with both exit codes and
the tripped assertions; overall PASS means gate-pass-on-trunk AND
gate-fail-on-injection.

Usage: python benchmarks/probe_fault_gate.py [--fraction 0.75]
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent


def run_verify(extra_env: dict[str, str]) -> tuple[int, dict | None, str]:
    """One subprocess run of bench.py --verify; returns (exit code,
    parsed JSON record or None, stderr tail)."""
    env = dict(os.environ)
    # share the persistent compile cache across the two runs (the
    # injected trace differs only in the pallas scheduler's reload
    # subgraph; every other engine's compile is reused)
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   str(pathlib.Path.home() / ".cache/nmfx/xla"))
    env.update(extra_env)
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--verify"],
        capture_output=True, text=True, env=env, cwd=REPO)
    record = None
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            record = json.loads(line)
    tail = "\n".join(proc.stderr.splitlines()[-25:])
    return proc.returncode, record, tail


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--fraction", type=float, default=0.75,
                    help="fraction of slot reloads whose factor writes "
                         "are dropped in the injected run")
    args = ap.parse_args()

    print("probe_fault_gate: clean run (expect gate PASS) ...",
          flush=True)
    clean_code, clean_rec, clean_err = run_verify({})
    print(clean_err, file=sys.stderr)
    print(f"clean exit code: {clean_code}", flush=True)

    print("probe_fault_gate: injected run (expect gate FAIL) ...",
          flush=True)
    inj_code, inj_rec, inj_err = run_verify(
        {"NMFX_FAULT_INJECT_STALE_RELOAD": str(args.fraction)})
    print(inj_err, file=sys.stderr)
    print(f"injected exit code: {inj_code}", flush=True)

    ok = clean_code == 0 and inj_code != 0
    out = {
        "metric": "fault_gate_proof",
        "value": 1 if ok else 0,
        "unit": "pass",
        "detail": {
            "clean_exit": clean_code,
            "injected_exit": inj_code,
            "injected_fraction": args.fraction,
            "clean_gaps": (clean_rec or {}).get("detail", {}).get("gaps"),
            "injected_problems": (inj_rec or {}).get("detail", {}).get(
                "problems"),
        },
    }
    path = REPO / "benchmarks" / "FAULTGATE_r05.json"
    path.write_text(json.dumps(out, indent=2) + "\n")
    print(json.dumps({k: out[k] for k in ("metric", "value", "unit")}))
    print(f"wrote {path}")
    if not ok:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
