"""Compile-feasibility sweep for fused_block_iterations VMEM envelope."""
import jax, jax.numpy as jnp
from nmfx.ops.pallas_mu import fused_block_iterations

def try_cfg(m, n, rk, k, block_m, a_dtype, precision):
    a = jnp.ones((m, n), a_dtype)
    wp = jnp.ones((m, rk), jnp.float32)
    hp = jnp.ones((rk, n), jnp.float32)
    fc = jnp.zeros((1, rk), jnp.float32)
    try:
        r = fused_block_iterations(a, wp, hp, fc, k=k, iters=2,
                                   block_m=block_m,
                                   matmul_precision=precision)
        jax.block_until_ready(r)
        return "OK"
    except Exception as e:
        msg = str(e)
        if "vmem" in msg.lower() or "memory" in msg.lower():
            import re
            mm = re.search(r"size ([0-9.]+)M", msg)
            return f"VMEM OOM ({mm.group(1)}M)" if mm else "VMEM OOM"
        return "ERR: " + msg.splitlines()[0][:100]

if __name__ == "__main__":
    for a_dtype, prec in ((jnp.float32, "default"),
                          (jnp.bfloat16, "bfloat16")):
        for rk in (512, 448, 384):
            for bm in (512, 256, 128):
                res = try_cfg(5120, 512, rk, 8, bm, a_dtype, prec)
                print(f"a={a_dtype.__name__} rk={rk} block_m={bm}: {res}",
                      flush=True)
