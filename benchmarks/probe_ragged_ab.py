"""Same-session interleaved A/B: ragged (class-blocked) vs uniform
pallas pool vs dense scheduler, full north-star job grid.

Round-5 context: the occupancy probe showed the uniform pallas pool at
98.5% slot occupancy with bookkeeping ~free — the wall is the kernel
marginal times trips. But 40% of the uniform pool's packed columns are
zero padding at the k=2..10 mix (Σk/(|ks|·k_max)), and padded columns
burn GEMM cycles like real ones. The ragged pool (sched_mu._ragged_*)
eliminates padding with class-blocked variable-width slots; column-work
arithmetic predicts ~1.33× on the main stage
(Σ k·iters(k) / (k_max·Σ iters(k)) ≈ 0.75 at iters ∝ k^1.5).

Protocol per BASELINE.md: one process, all configs compiled first, then
interleaved timed reps; compare same-session minima only.

Usage: PYTHONPATH=. python benchmarks/probe_ragged_ab.py [--reps 5]
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

from nmfx.config import InitConfig, SolverConfig
from nmfx.datasets import grouped_matrix
from nmfx.init import initialize
from nmfx.ops.sched_mu import mu_sched


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--genes", type=int, default=5000)
    ap.add_argument("--samples", type=int, default=500)
    ap.add_argument("--kmax", type=int, default=10)
    ap.add_argument("--restarts", type=int, default=50)
    args = ap.parse_args()

    ks = tuple(range(args.kmax, 1, -1))  # LPT dispatch order
    k_max = max(ks)
    a = grouped_matrix(args.genes, (args.samples // 4,) * 4, effect=2.0,
                       seed=0)
    root = jax.random.PRNGKey(123)
    w0l, h0l, job_ks = [], [], []
    for k in ks:
        keys = jax.random.split(jax.random.fold_in(root, k), args.restarts)
        w0s, h0s = jax.vmap(
            lambda kk, k=k: initialize(kk, a, k, InitConfig(),
                                       jnp.float32))(keys)
        w0l.append(jnp.pad(w0s, ((0, 0), (0, 0), (0, k_max - k))))
        h0l.append(jnp.pad(h0s, ((0, 0), (0, k_max - k), (0, 0))))
        job_ks += [k] * args.restarts
    w0 = jnp.concatenate(w0l)
    h0 = jnp.concatenate(h0l)
    job_ks = tuple(job_ks)

    cells = {
        "dense": dict(backend="auto", ragged=False),
        "pallas-uniform": dict(backend="pallas", ragged=False),
        "pallas-ragged": dict(backend="pallas", ragged=True),
    }

    def run(backend, ragged):
        from nmfx.config import ExperimentalConfig

        cfg = SolverConfig(algorithm="mu", max_iter=10000,
                           matmul_precision="bfloat16", backend=backend,
                           check_block=1,
                           experimental=ExperimentalConfig(ragged=ragged))
        t0 = time.perf_counter()
        r = mu_sched(a, w0, h0, cfg, slots=48, job_ks=job_ks)
        its = np.asarray(r.iterations)
        np.asarray(r.w[0])
        return time.perf_counter() - t0, its, \
            (np.asarray(r.pool_widths), np.asarray(r.pool_trips),
             np.asarray(r.pool_lanes))

    its_ref = None
    for name, kw in cells.items():
        t0 = time.perf_counter()
        _, its, stages = run(**kw)
        print(f"warm {name}: {time.perf_counter() - t0:.1f}s "
              f"iters_total={int(its.sum())} stages={stages}", flush=True)
        if its_ref is None:
            its_ref = its
        else:
            ratio = float(its.mean() / its_ref.mean())
            print(f"  mean-iteration ratio vs dense: {ratio:.3f}")

    walls = {name: [] for name in cells}
    for rep in range(args.reps):
        for name, kw in cells.items():
            w, _, _ = run(**kw)
            walls[name].append(w)
            print(f"rep {rep} {name}: {w:.3f}s", flush=True)

    for name, ws in walls.items():
        ws = sorted(ws)
        print(f"{name}: min={ws[0]:.3f}s median={ws[len(ws) // 2]:.3f}s "
              f"all={[round(x, 3) for x in ws]}")


if __name__ == "__main__":
    main()
