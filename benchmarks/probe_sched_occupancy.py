"""Decompose the scheduler's solve wall: kernel vs bookkeeping vs
occupancy.

Round 4 measured the pallas scheduler's marginal cost at ~0.053
ms/pool-iteration (full 48-slot pool, stops off) yet realized solve-only
MFU sits ~3× below that steady-state rate (VERDICT.md round 4, Weak #2).
This probe attributes the gap with two independent measurements at the
north-star shape:

1. **Bookkeeping marginal** — the marginal-cost protocol of
   ``probe_sched_marginal`` run twice: stops OFF (pure kernel + loop) vs
   class-stop bookkeeping ON but unsatisfiable (``stable_checks`` huge →
   labels argmax, mismatch counters, and the convergence scatter run
   every check block, but no job ever stops, no evictions fire). The
   delta is the per-check bookkeeping cost the in-kernel fusion avenue
   would recover.
2. **Occupancy** — a REAL north-star sweep reading the round-5
   ``SchedMUResult.pool_trips/pool_lanes/pool_widths`` diagnostics: per
   cascade stage, how many check-block trips ran and how many live lanes
   they carried. ``wall_model = Σ trips(stage) · c(width)`` with c from
   the marginal measurements; ``occupancy = lanes / (trips · width)``.
   Idle lanes (1 − occupancy) are drain/straggler waste the cascade
   tuning avenue would recover.

Usage: python benchmarks/probe_sched_occupancy.py [--reps 5]
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from nmfx.config import InitConfig, SolverConfig
from nmfx.datasets import grouped_matrix
from nmfx.ops.sched_mu import mu_sched


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--genes", type=int, default=5000)
    ap.add_argument("--samples", type=int, default=500)
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--jobs", type=int, default=48)
    ap.add_argument("--iters", type=int, nargs=2, default=[200, 800])
    ap.add_argument("--backend", default="pallas",
                    choices=("auto", "pallas"))
    ap.add_argument("--tail", default="auto",
                    help="tail cascade for the occupancy sweep: 'auto', "
                         "'0', or comma widths like '24,12,6'")
    args = ap.parse_args()

    m, n, k, j = args.genes, args.samples, args.k, args.jobs
    lo, hi = args.iters
    a = grouped_matrix(m, (n // 4,) * 4, effect=2.0, seed=0)
    key = jax.random.PRNGKey(3)
    kw, kh = jax.random.split(key)
    w0 = jax.random.uniform(kw, (j, m, k), jnp.float32)
    h0 = jax.random.uniform(kh, (j, k, n), jnp.float32)

    def run_fixed(max_iter, bookkeeping):
        """Full-pool fixed-iteration run (no stops, no evictions)."""
        cfg = SolverConfig(
            algorithm="mu", max_iter=max_iter,
            use_class_stop=bookkeeping, use_tol_checks=False,
            # unsatisfiable: stability can never reach the threshold, so
            # the bookkeeping runs every check block but nothing stops
            stable_checks=10**7 if bookkeeping else 200,
            matmul_precision="bfloat16", backend=args.backend)
        t0 = time.perf_counter()
        r = mu_sched(a, w0, h0, cfg, slots=j, tail_slots=0)
        np.asarray(r.iterations)
        np.asarray(r.w[0])
        return time.perf_counter() - t0

    cells = [(bk, it) for bk in (False, True) for it in (lo, hi)]
    for c in cells:
        t0 = time.perf_counter()
        run_fixed(c[1], c[0])
        print(f"warm book={c[0]}@{c[1]}: {time.perf_counter() - t0:.1f}s",
              flush=True)
    walls = {c: [] for c in cells}
    for rep in range(args.reps):
        for c in cells:
            w = run_fixed(c[1], c[0])
            walls[c].append(w)
            print(f"rep {rep} book={c[0]} iters={c[1]}: {w:.3f}s",
                  flush=True)

    out = {}
    for bk in (False, True):
        wlo, whi = min(walls[(bk, lo)]), min(walls[(bk, hi)])
        per_iter = (whi - wlo) / (hi - lo)
        out["marginal_book" if bk else "marginal_kernel"] = per_iter
        print(f"book={bk}: marginal {per_iter * 1e3:.4f} ms/pool-iter "
              f"({wlo:.3f}s → {whi:.3f}s)")
    print(f"bookkeeping overhead: "
          f"{(out['marginal_book'] / out['marginal_kernel'] - 1) * 100:.1f}"
          "% of kernel marginal")

    # --- occupancy of a real north-star sweep -------------------------
    tail = args.tail
    if tail not in ("auto",):
        tail = tuple(int(x) for x in tail.split(",") if x) or 0
        if tail == (0,):
            tail = 0
    scfg = SolverConfig(algorithm="mu", max_iter=10000,
                        matmul_precision="bfloat16", backend=args.backend)

    # the sweep API reduces to consensus and discards the scheduler
    # diagnostics — run mu_sched directly on the sweep's job grid
    # (rank-descending LPT, same layout as _build_grid_exec_sweep_fn)
    from nmfx.init import initialize
    ks = tuple(range(2, 11))
    k_max = max(ks)
    w0l, h0l = [], []
    root = jax.random.PRNGKey(123)
    for kk in sorted(ks, reverse=True):
        keys = jax.random.split(jax.random.fold_in(root, kk), 50)
        w0s, h0s = jax.vmap(
            lambda key, kk=kk: initialize(key, a, kk, InitConfig(),
                                          jnp.float32))(keys)
        w0l.append(jnp.pad(w0s, ((0, 0), (0, 0), (0, k_max - kk))))
        h0l.append(jnp.pad(h0s, ((0, 0), (0, k_max - kk), (0, 0))))
    w0g = jnp.concatenate(w0l)
    h0g = jnp.concatenate(h0l)

    def run_sweep():
        t0 = time.perf_counter()
        r = mu_sched(a, w0g, h0g, scfg, slots=48,
                     tail_slots=tail if tail != 0 else None)
        np.asarray(r.iterations)
        widths = np.asarray(r.pool_widths)
        trips = np.asarray(r.pool_trips)
        lanes = np.asarray(r.pool_lanes)
        return time.perf_counter() - t0, widths, trips, lanes, \
            np.asarray(r.iterations)

    t0 = time.perf_counter()
    run_sweep()
    print(f"warm sweep: {time.perf_counter() - t0:.1f}s", flush=True)
    best = None
    for rep in range(args.reps):
        wall, widths, trips, lanes, iters = run_sweep()
        print(f"rep {rep} sweep: {wall:.3f}s", flush=True)
        if best is None or wall < best[0]:
            best = (wall, widths, trips, lanes, iters)

    wall, widths, trips, lanes, iters = best
    total_lane_blocks = int(lanes.sum())
    ck = 2  # check_every
    print(f"\nsweep wall (min of {args.reps}): {wall:.3f}s; "
          f"total job iterations {int(iters.sum())} "
          f"(= {int(iters.sum()) // ck} lane-blocks; scheduler ran "
          f"{total_lane_blocks} live lane-blocks)")
    for w_, t_, l_ in zip(widths, trips, lanes):
        occ = l_ / (t_ * w_) if t_ else float("nan")
        print(f"  stage width={w_:2d}: trips={t_:6d} "
              f"live-lanes={l_:8d} occupancy={occ:.3f}")
    # model the wall from the measured marginals (c scales ~ width/48
    # only for the GEMM part; report both bounds)
    mk, mb = out["marginal_kernel"], out["marginal_book"]
    model = sum(int(t_) * ck * mb * (w_ / j)
                for w_, t_ in zip(widths, trips))
    print(f"wall model (book marginal, c∝width): {model:.3f}s — "
          f"unmodeled residue {wall - model:.3f}s")
    rec = {"metric": "sched_occupancy", "wall_s": round(wall, 3),
           "marginal_kernel_ms": round(mk * 1e3, 4),
           "marginal_book_ms": round(mb * 1e3, 4),
           "stages": [{"width": int(w_), "trips": int(t_),
                       "lanes": int(l_)}
                      for w_, t_, l_ in zip(widths, trips, lanes)]}
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
