"""Same-session interleaved A/B: dense vs pallas scheduler, north star.

The repo's measurement protocol for backend comparisons (BASELINE.md):
the tunneled chip swings ±50% between sessions and single runs flip 2×,
so both configurations compile once in ONE process and then alternate
timed reps; only same-session minima (and medians) are compared.

Usage: python benchmarks/probe_ab_northstar.py [--reps 5]
"""

from __future__ import annotations

import argparse
import time

import jax
import numpy as np

from nmfx.config import ConsensusConfig, InitConfig, SolverConfig
from nmfx.datasets import grouped_matrix
from nmfx.sweep import default_mesh, sweep


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--genes", type=int, default=5000)
    ap.add_argument("--samples", type=int, default=500)
    ap.add_argument("--kmax", type=int, default=10)
    ap.add_argument("--restarts", type=int, default=50)
    ap.add_argument("--backends", nargs="+", default=["auto", "pallas"])
    args = ap.parse_args()

    ks = tuple(range(2, args.kmax + 1))
    sizes = [args.samples // 4] * 4
    sizes[0] += args.samples % 4
    a = grouped_matrix(args.genes, tuple(sizes), effect=2.0, seed=0)
    icfg = InitConfig()
    mesh = default_mesh()
    ccfg = ConsensusConfig(ks=ks, restarts=args.restarts, seed=123,
                           grid_exec="grid")

    def run(backend):
        scfg = SolverConfig(algorithm="mu", max_iter=10000,
                            matmul_precision="bfloat16", backend=backend)
        t0 = time.perf_counter()
        raw = sweep(a, ccfg, scfg, icfg, mesh)
        host = jax.device_get({k: (raw[k].consensus, raw[k].iterations)
                               for k in ks})
        wall = time.perf_counter() - t0
        mean_iters = {k: float(host[k][1].mean()) for k in ks}
        return wall, mean_iters

    # warm both (compile) before any timing
    for b in args.backends:
        t0 = time.perf_counter()
        _, its = run(b)
        print(f"warm {b}: {time.perf_counter() - t0:.1f}s "
              f"mean_iters={ {k: round(v, 1) for k, v in its.items()} }",
              flush=True)

    walls = {b: [] for b in args.backends}
    for rep in range(args.reps):
        for b in args.backends:
            w, _ = run(b)
            walls[b].append(w)
            print(f"rep {rep} {b}: {w:.3f}s", flush=True)

    for b in args.backends:
        v = np.array(walls[b])
        print(f"{b}: min={v.min():.3f}s median={np.median(v):.3f}s "
              f"all={[round(x, 3) for x in v.tolist()]}")


if __name__ == "__main__":
    main()
