"""Same-session interleaved kl A/B: vmap vs packed-grid on an IDENTICAL
k-range, plus the bf16-quotient decision.

VERDICT r4 Weak #5: the round-4 "38% faster warm" kl claim compared
k={2,4,6} (vmap) against k=2..4 (packed) — overlapping but not
identical sweeps. This probe closes it: both engines run the SAME
k-range in one session, interleaved, min-of-N. It also measures the
round-5 ``ExperimentalConfig.kl_bf16_quotient`` opt-in (stream A as bf16
through the packed-grid loop, halving A's HBM reread): wall delta plus
the consensus/rank-selection drift it introduces — the accept/reject
evidence for that knob's default.

Usage: PYTHONPATH=. python benchmarks/probe_kl_ab.py [--reps 5]
"""

from __future__ import annotations

import argparse
import time

import jax
import numpy as np

from nmfx.config import ConsensusConfig, InitConfig, SolverConfig
from nmfx.cophenetic import rank_selection
from nmfx.datasets import grouped_matrix
from nmfx.sweep import default_mesh, sweep


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--genes", type=int, default=5000)
    ap.add_argument("--samples", type=int, default=500)
    ap.add_argument("--ks", type=int, nargs="+", default=[2, 3, 4, 5, 6])
    ap.add_argument("--restarts", type=int, default=20)
    args = ap.parse_args()

    ks = tuple(args.ks)
    a = grouped_matrix(args.genes, (args.samples // 4,) * 4, effect=2.0,
                       seed=0)
    icfg = InitConfig()
    mesh = default_mesh()

    cells = {
        "kl-vmap": dict(backend="vmap", grid_exec="per_k",
                        kl_bf16_quotient=False),
        "kl-packed": dict(backend="packed", grid_exec="grid",
                          kl_bf16_quotient=False),
        "kl-packed-bf16q": dict(backend="packed", grid_exec="grid",
                                kl_bf16_quotient=True),
    }

    def run(backend, grid_exec, kl_bf16_quotient):
        from nmfx.config import ExperimentalConfig

        scfg = SolverConfig(algorithm="kl", max_iter=10000,
                            matmul_precision="bfloat16", backend=backend,
                            experimental=ExperimentalConfig(
                                kl_bf16_quotient=kl_bf16_quotient))
        ccfg = ConsensusConfig(ks=ks, restarts=args.restarts, seed=123,
                               grid_exec=grid_exec)
        t0 = time.perf_counter()
        raw = sweep(a, ccfg, scfg, icfg, mesh)
        host = jax.device_get({k: (raw[k].consensus, raw[k].iterations)
                               for k in ks})
        wall = time.perf_counter() - t0
        return wall, host

    results = {}
    for name, kw in cells.items():
        t0 = time.perf_counter()
        _, host = run(**kw)
        results[name] = host
        print(f"warm {name}: {time.perf_counter() - t0:.1f}s "
              f"mean_iters="
              f"{ {k: round(float(host[k][1].mean()), 1) for k in ks} }",
              flush=True)

    # parity of the bf16-quotient opt-in vs the f32 packed engine, and
    # packed vs vmap (the same-range check VERDICT asked for)
    for name, ref in (("kl-packed", "kl-vmap"),
                      ("kl-packed-bf16q", "kl-packed")):
        for k in ks:
            dc = float(np.max(np.abs(results[name][k][0]
                                     - results[ref][k][0])))
            rho_a = rank_selection(np.asarray(results[name][k][0]), k)[0]
            rho_b = rank_selection(np.asarray(results[ref][k][0]), k)[0]
            dit = float(results[name][k][1].mean()
                        / max(results[ref][k][1].mean(), 1.0))
            print(f"{name} vs {ref} k={k}: max|dC|={dc:.4f} "
                  f"|d rho|={abs(rho_a - rho_b):.4f} "
                  f"iters_ratio={dit:.3f}", flush=True)

    walls = {name: [] for name in cells}
    for rep in range(args.reps):
        for name, kw in cells.items():
            w, _ = run(**kw)
            walls[name].append(w)
            print(f"rep {rep} {name}: {w:.3f}s", flush=True)
    for name, ws in walls.items():
        ws = sorted(ws)
        print(f"{name}: min={ws[0]:.3f}s median={ws[len(ws) // 2]:.3f}s "
              f"all={[round(x, 3) for x in ws]}")


if __name__ == "__main__":
    main()
