"""Hardware probe: mu_sched dense vs pallas — per-job stop parity.

Second bisect stage for the round-3 corruption: probe_block_kernel.py
shows the block kernel is bit-exact standalone, so this drives the FULL
scheduler (while_loop + lax.cond evict/reload) on the real chip at a
scaled shape and compares per-job iteration counts and stop reasons
between backend='pallas' (block-kernel path) and the XLA-dense scheduler.

Usage: python benchmarks/probe_sched_pallas.py [--max-iter 10000]
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np

from nmfx.config import SolverConfig
from nmfx.ops.sched_mu import mu_sched


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--m", type=int, default=1000)
    ap.add_argument("--n", type=int, default=200)
    ap.add_argument("--jobs", type=int, default=16)
    ap.add_argument("--k", type=int, default=5)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--max-iter", type=int, default=10000)
    ap.add_argument("--stable-checks", type=int, default=50)
    ap.add_argument("--precision", default="bfloat16",
                    choices=["default", "bfloat16"])
    args = ap.parse_args()
    j, m, n, k = args.jobs, args.m, args.n, args.k
    print(f"platform={jax.default_backend()} J={j} m={m} n={n} k={k} "
          f"slots={args.slots} stable_checks={args.stable_checks}")

    key = jax.random.PRNGKey(7)
    ka, k0 = jax.random.split(key)
    # planted 3-group structure so class labels genuinely stabilize
    groups = jnp.repeat(jnp.arange(3), n // 3 + 1)[:n]
    base_sig = jax.random.uniform(ka, (m, 3)) * 2.0
    a = base_sig[:, groups] + 0.1 * jax.random.uniform(k0, (m, n))
    keys = jax.random.split(jax.random.PRNGKey(11), 2 * j)
    w0 = jnp.stack([jax.random.uniform(keys[i], (m, k)) for i in range(j)])
    h0 = jnp.stack([jax.random.uniform(keys[j + i], (k, n))
                    for i in range(j)])

    results = {}
    for backend in ("auto", "pallas"):
        cfg = SolverConfig(algorithm="mu", backend=backend,
                           max_iter=args.max_iter,
                           stable_checks=args.stable_checks,
                           matmul_precision=args.precision)
        r = mu_sched(a, w0, h0, cfg, slots=args.slots)
        iters = np.asarray(r.iterations)
        stops = np.asarray(r.stop_reason)
        results[backend] = (iters, stops)
        print(f"backend={backend:7s} iters={iters.tolist()}")
        print(f"                 stops={stops.tolist()}")

    di, ds = results["auto"]
    pi, ps = results["pallas"]
    # min credible class-stable stop: first counted check at iteration
    # 2·check_every, then stable_checks consecutive stable checks
    # (same formula as bench._integrity_problems)
    floor = 2 * (args.stable_checks + 1)
    bad = pi < floor
    print(f"\nmin-credible-stop floor = {floor}")
    print(f"pallas jobs below floor: {int(bad.sum())}/{j}")
    print(f"iter agreement (exact): {int((di == pi).sum())}/{j}; "
          f"max |diff| = {int(np.max(np.abs(di - pi)))}")
    print(f"stop-reason agreement: {int((ds == ps).sum())}/{j}")


if __name__ == "__main__":
    main()
