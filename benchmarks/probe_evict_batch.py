"""Evict-batch hysteresis A/B: mu_sched(evict_batch=E) at the north star.

Round-5 measurement behind the `evict_batch` knob's default (1) — see
RESULTS.md "Evict-batch hysteresis". Interleaved min-of-N, both engines,
E in {1, 4, 8}. Per-job recorded results are invariant on CPU
(bit-identical); on hardware, reload timing shifts jobs' column
positions and Mosaic tiling drift moves stop iterations a few percent
(the same benign class as slot-count changes) — reported, not asserted.

Usage: PYTHONPATH=. python benchmarks/probe_evict_batch.py [--reps 5]
"""
import argparse, time
import jax, jax.numpy as jnp, numpy as np
from nmfx.config import InitConfig, SolverConfig
from nmfx.datasets import grouped_matrix
from nmfx.init import initialize
from nmfx.ops.sched_mu import mu_sched

ap = argparse.ArgumentParser()
ap.add_argument("--reps", type=int, default=5)
args = ap.parse_args()
ks = tuple(range(10, 1, -1)); k_max = 10; restarts = 50
a = grouped_matrix(5000, (125,)*4, effect=2.0, seed=0)
root = jax.random.PRNGKey(123)
w0l, h0l, job_ks = [], [], []
for k in ks:
    keys = jax.random.split(jax.random.fold_in(root, k), restarts)
    w0s, h0s = jax.vmap(lambda kk, k=k: initialize(kk, a, k, InitConfig(), jnp.float32))(keys)
    w0l.append(jnp.pad(w0s, ((0,0),(0,0),(0,k_max-k))))
    h0l.append(jnp.pad(h0s, ((0,0),(0,k_max-k),(0,0))))
    job_ks += [k]*restarts
w0 = jnp.concatenate(w0l); h0 = jnp.concatenate(h0l); job_ks = tuple(job_ks)

cells = [(b, e) for b in ("auto", "pallas") for e in (1, 4, 8)]
def run(backend, eb):
    from nmfx.config import ExperimentalConfig

    cfg = SolverConfig(algorithm="mu", max_iter=10000,
                       matmul_precision="bfloat16", backend=backend,
                       check_block=1,
                       experimental=ExperimentalConfig(evict_batch=eb))
    t0 = time.perf_counter()
    r = mu_sched(a, w0, h0, cfg, slots=48, job_ks=job_ks)
    its = np.asarray(r.iterations); np.asarray(r.w[0])
    return time.perf_counter() - t0, int(its.sum()), np.asarray(r.pool_trips)

ref_iters = {}
for c in cells:
    t0 = time.perf_counter(); _, itot, trips = run(*c)
    print(f"warm {c}: {time.perf_counter()-t0:.1f}s iters={itot} trips={trips}", flush=True)
    ref_iters.setdefault(c[0], itot)
    print(f"  iters vs {c[0]} E=1: {itot/ref_iters[c[0]]:.4f}x", flush=True)
walls = {c: [] for c in cells}
for rep in range(args.reps):
    for c in cells:
        w, _, _ = run(*c)
        walls[c].append(w)
        print(f"rep {rep} {c}: {w:.3f}s", flush=True)
for c in cells:
    ws = sorted(walls[c])
    print(f"{c}: min={ws[0]:.3f}s median={ws[len(ws)//2]:.3f}s all={[round(x,3) for x in ws]}")
