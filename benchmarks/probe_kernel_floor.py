"""Kernel-floor probes: fused_block_iterations marginal vs tile size.

Round-5 decomposition (RESULTS.md "Where the pallas wall actually is"):
one pallas_call per measurement with a 448-iteration delta isolates the
kernel from the scheduler. Found: 62.9 us/pool-iter at rk=480 /
block_m=512 (131 ns per column-iteration, 1.23x the no-overlap
compute+memory roofline); block_m=1024 neutral, 2560 3x worse — the
512-row tiling already sits at the kernel's operating point.

Usage: PYTHONPATH=. python benchmarks/probe_kernel_floor.py
"""
import time
import jax, jax.numpy as jnp, numpy as np
from nmfx.ops.pallas_mu import fused_block_iterations

m, n = 5120, 512
key = jax.random.PRNGKey(0)
a = jax.random.uniform(key, (m, n), jnp.float32).astype(jnp.bfloat16)
cells = [(512, 480), (1024, 448), (1024, 384), (2560, 384), (2560, 320), (512, 384)]
for block_m, rk in cells:
    kw, kh = jax.random.split(jax.random.PRNGKey(1))
    wp = jax.random.uniform(kw, (m, rk), jnp.float32)
    hp = jax.random.uniform(kh, (rk, n), jnp.float32)
    fcol = jnp.zeros((1, rk), jnp.float32)
    def run(iters):
        t0 = time.perf_counter()
        out = fused_block_iterations(a, wp, hp, fcol, k=8, iters=iters,
                                     block_m=block_m,
                                     matmul_precision="bfloat16")
        np.asarray(out[0][0])
        return time.perf_counter() - t0
    try:
        for it in (64, 512):
            run(it)  # compile
        lo = min(run(64) for _ in range(5))
        hi = min(run(512) for _ in range(5))
        per = (hi - lo) / (512 - 64)
        cols_rate = rk / per * 1e-6
        # model-flops rate for k-true columns == rk here (no padding)
        flops = (4 * m * n + 0) * rk / 8 * 8  # 4mn per column pair? report raw
        print(f"block_m={block_m} rk={rk}: {per*1e6:.1f} us/iter "
              f"({per/rk*1e9:.1f} ns/col-iter) lo={lo:.3f} hi={hi:.3f}", flush=True)
    except Exception as e:
        print(f"block_m={block_m} rk={rk}: FAILED {type(e).__name__}: {str(e)[:200]}", flush=True)
