"""bf16-factor-storage pool A/B: the round-5 'wide pool' experiment.

The round-5 decomposition left one kernel-level lever: storing the slot
pool's W/H as bf16 halves the W round-trip per check block AND fits
~1.5× more packed columns in the VMEM envelope (wider GEMMs, fewer
trips). Unlike bf16 A-streaming this is a REAL numerics change — each
block store quantizes the factor state (~0.4% relative), the class
counters see noisier labels, and iterations can grow (+18% measured on
the tiny CPU fixture). This probe measures whether width wins at the
north star, separating the two effects:

* f32-48: the shipping pool (rk=480)
* bf16-48: storage effect only (same width)
* bf16-wide: storage + width (the envelope's bf16 maximum)

plus per-k iteration ratios and consensus drift vs f32-48 (labels →
consensus per rank from the returned factors, restart-normalized
mean|ΔC| as in the verify gate).

Usage: PYTHONPATH=. python benchmarks/probe_bf16_pool.py [--reps 5]
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

from nmfx.config import InitConfig, SolverConfig
from nmfx.consensus import consensus_matrix, labels_from_h
from nmfx.datasets import grouped_matrix
from nmfx.init import initialize
from nmfx.ops.sched_mu import _pallas_max_rk, mu_sched


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--genes", type=int, default=5000)
    ap.add_argument("--samples", type=int, default=500)
    ap.add_argument("--kmax", type=int, default=10)
    ap.add_argument("--restarts", type=int, default=50)
    args = ap.parse_args()

    ks = tuple(range(args.kmax, 1, -1))
    k_max = max(ks)
    a = grouped_matrix(args.genes, (args.samples // 4,) * 4, effect=2.0,
                      seed=0)
    root = jax.random.PRNGKey(123)
    w0l, h0l, job_ks = [], [], []
    for k in ks:
        keys = jax.random.split(jax.random.fold_in(root, k), args.restarts)
        w0s, h0s = jax.vmap(
            lambda kk, k=k: initialize(kk, a, k, InitConfig(),
                                       jnp.float32))(keys)
        w0l.append(jnp.pad(w0s, ((0, 0), (0, 0), (0, k_max - k))))
        h0l.append(jnp.pad(h0s, ((0, 0), (0, k_max - k), (0, 0))))
        job_ks += [k] * args.restarts
    w0 = jnp.concatenate(w0l)
    h0 = jnp.concatenate(h0l)
    job_ks = tuple(job_ks)

    cfg = SolverConfig(algorithm="mu", max_iter=10000,
                       matmul_precision="bfloat16", backend="pallas")
    wide = _pallas_max_rk(args.genes, args.samples, cfg,
                          factor_bytes=2) // k_max
    print(f"bf16 envelope admits {wide} slots "
          f"(f32: {_pallas_max_rk(args.genes, args.samples, cfg) // k_max})",
          flush=True)
    # the probe separates storage from width; at shapes where the bf16
    # envelope admits <= 48 slots the two cells would collide (or the
    # '48' cell would be silently clamped) and the A/B would mislabel
    # what ran — fail loudly instead
    assert wide > 48, (
        f"bf16 envelope admits only {wide} slots at this shape; the "
        "storage-vs-width separation needs wide > 48 — pick a smaller "
        "n or lower --kmax")
    cells = {
        "f32-48": dict(slots=48, factor_dtype=None),
        "bf16-48": dict(slots=48, factor_dtype="bfloat16"),
        f"bf16-{wide}": dict(slots=wide, factor_dtype="bfloat16"),
    }

    def run(slots, factor_dtype):
        import dataclasses

        from nmfx.config import ExperimentalConfig

        cfg_f = dataclasses.replace(
            cfg, experimental=ExperimentalConfig(factor_dtype=factor_dtype))
        t0 = time.perf_counter()
        r = mu_sched(a, w0, h0, cfg_f, slots=slots, job_ks=job_ks)
        its = np.asarray(r.iterations)
        h = np.asarray(r.h)
        wall = time.perf_counter() - t0
        return wall, its, h

    results = {}
    for name, kw in cells.items():
        t0 = time.perf_counter()
        _, its, h = run(**kw)
        results[name] = (its, h)
        print(f"warm {name}: {time.perf_counter() - t0:.1f}s "
              f"iters_total={int(its.sum())}", flush=True)

    # parity vs f32-48: per-k iteration ratio + restart-normalized
    # consensus drift (the verify gate's invariants)
    ref_its, ref_h = results["f32-48"]
    r_per_k = args.restarts
    for name in list(cells)[1:]:
        its, h = results[name]
        for gi, k in enumerate(ks):
            sl = slice(gi * r_per_k, (gi + 1) * r_per_k)
            ratio = its[sl].mean() / ref_its[sl].mean()
            lab = jax.vmap(labels_from_h)(jnp.asarray(h[sl, :k, :]))
            lab_r = jax.vmap(labels_from_h)(jnp.asarray(ref_h[sl, :k, :]))
            dc = np.abs(np.asarray(consensus_matrix(lab, k))
                        - np.asarray(consensus_matrix(lab_r, k)))
            print(f"{name} vs f32-48 k={k}: iters_ratio={ratio:.3f} "
                  f"mean|dC|*R={dc.mean() * r_per_k:.3f} "
                  f"max|dC|={dc.max():.3f}", flush=True)

    walls = {name: [] for name in cells}
    for rep in range(args.reps):
        for name, kw in cells.items():
            wall, _, _ = run(**kw)
            walls[name].append(wall)
            print(f"rep {rep} {name}: {wall:.3f}s", flush=True)
    for name, ws in walls.items():
        ws = sorted(ws)
        print(f"{name}: min={ws[0]:.3f}s median={ws[len(ws) // 2]:.3f}s "
              f"all={[round(x, 3) for x in ws]}")


if __name__ == "__main__":
    main()
