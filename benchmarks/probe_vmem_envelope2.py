"""Second VMEM-envelope sweep: vary m and n to calibrate the slot-clamp
byte model (`sched_mu._pallas_slot_clamp`); see probe_vmem_envelope.py
for the rk/block_m sweep at the north-star shape (and for try_cfg)."""
import jax.numpy as jnp

from probe_vmem_envelope import try_cfg

cases = [
    # vary m at n=512
    (10240, 512, 256, 8, 512), (10240, 512, 224, 8, 512),
    (20480, 512, 128, 8, 512), (20480, 512, 112, 8, 512),
    # vary n at m=5120
    (5120, 1024, 384, 8, 512), (5120, 1024, 320, 8, 512),
    (5120, 1024, 256, 8, 512),
    (5120, 2048, 192, 8, 256), (5120, 2048, 160, 8, 256),
    # small-k north-star-ish: k=10 -> rk=440 (44 slots)
    (5120, 512, 440, 10, 512), (5120, 512, 480, 10, 512),
]
for m, n, rk, k, bm in cases:
    res = try_cfg(m, n, rk, k, bm, jnp.bfloat16, "bfloat16")
    print(f"m={m} n={n} rk={rk} block_m={bm}: {res}", flush=True)
