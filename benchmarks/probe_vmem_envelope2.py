"""Second VMEM-envelope sweep: vary m and n to calibrate the slot-clamp
byte model (`sched_mu._pallas_slot_clamp`); see probe_vmem_envelope.py
for the rk/block_m sweep at the north-star shape."""
import jax, jax.numpy as jnp
from nmfx.ops.pallas_mu import fused_block_iterations

def try_cfg(m, n, rk, k, block_m, a_dtype, precision):
    a = jnp.ones((m, n), a_dtype)
    wp = jnp.ones((m, rk), jnp.float32)
    hp = jnp.ones((rk, n), jnp.float32)
    fc = jnp.zeros((1, rk), jnp.float32)
    try:
        r = fused_block_iterations(a, wp, hp, fc, k=k, iters=2,
                                   block_m=block_m, matmul_precision=precision)
        jax.block_until_ready(r)
        return "OK"
    except Exception as e:
        msg = str(e)
        if "vmem" in msg.lower():
            import re
            mm = re.search(r"size ([0-9.]+)M", msg)
            return f"OOM({mm.group(1)}M)" if mm else "OOM"
        return "ERR: " + msg.splitlines()[0][:80]

cases = [
    # vary m at n=512
    (10240, 512, 256, 8, 512), (10240, 512, 224, 8, 512),
    (20480, 512, 128, 8, 512), (20480, 512, 112, 8, 512),
    # vary n at m=5120
    (5120, 1024, 384, 8, 512), (5120, 1024, 320, 8, 512),
    (5120, 1024, 256, 8, 512),
    (5120, 2048, 192, 8, 256), (5120, 2048, 160, 8, 256),
    # small-k north-star-ish: k=10 -> rk=440 (44 slots)
    (5120, 512, 440, 10, 512), (5120, 512, 480, 10, 512),
]
for m, n, rk, k, bm in cases:
    res = try_cfg(m, n, rk, k, bm, jnp.bfloat16, "bfloat16")
    print(f"m={m} n={n} rk={rk} block_m={bm}: {res}", flush=True)
