"""Benchmark: full consensus sweep vs the north-star target.

Target (BASELINE.md / BASELINE.json): k=2..10 × 50 restarts on a 5000×500
matrix in < 10 s wall-clock on TPU v5e-8. The reference publishes no numbers
(its only harness is `system.time` around the R pipeline, reference
test_nmf.r:25-27), so `vs_baseline` is reported against the 10 s driver
target: vs_baseline = target_s / measured_s (>1 = beating the target).

Prints ONE JSON line:
    {"metric": "consensus_sweep_wall_s", "value": ..., "unit": "s",
     "vs_baseline": ...}
plus detail fields (restarts/sec, per-k iterations, hardware).
"""

import argparse
import json
import time

#: per-chip dense bf16 matmul peak (FLOP/s) by jax device_kind — the MFU
#: denominator. bf16 is both the bench default and what "default" matmul
#: precision runs on TPU, so MFU is reported against the bf16 peak even for
#: --precision highest (which burns multiple MXU passes per matmul: its
#: lower MFU is real, not an accounting artifact).
_BF16_PEAK_FLOPS = {
    "TPU v5 lite": 197e12,  # v5e
    "TPU v4": 275e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,  # v6e / Trillium
}


def _mu_model_flops(m: int, n: int, k: int) -> float:
    """Model FLOPs of ONE mu iteration for ONE restart: the six-GEMM update
    (reference nmf_mu.c:174-216) — H: WᵀA (2mnk) + WᵀW (2mk²) + (WᵀW)H
    (2nk²); W: AHᵀ (2mnk) + HHᵀ (2nk²) + W(HHᵀ) (2mk²). Total
    4mnk + 4k²(m+n); elementwise terms (O(mk + kn)) are omitted —
    sub-percent at bench shapes."""
    return 4.0 * m * n * k + 4.0 * k * k * (m + n)


def _kl_model_flops(m: int, n: int, k: int) -> float:
    """One kl (Brunet) iteration per restart (solvers/kl.py): two quotient
    reconstructions W@H (2·2mnk), the two quotient contractions WᵀQ and QHᵀ
    (2·2mnk), and the two elementwise quotient passes (one add + one divide
    over m×n each: 4mn); the remaining elementwise work is O(kn + mk) —
    8mnk + 4mn to leading order."""
    return 8.0 * m * n * k + 4.0 * m * n


#: hals' per-iteration FLOPs match mu's to leading order: the same two big
#: GEMMs + two Grams, with the coordinate passes summing to the same
#: 2k²(m+n) as mu's Gram-product terms (solvers/hals.py)
_MODEL_FLOPS = {"mu": _mu_model_flops, "kl": _kl_model_flops,
                "hals": _mu_model_flops}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--genes", type=int, default=5000)
    p.add_argument("--samples", type=int, default=500)
    p.add_argument("--kmax", type=int, default=10)
    p.add_argument("--restarts", type=int, default=50)
    p.add_argument("--maxiter", type=int, default=10000)
    p.add_argument("--algorithm", default="mu")
    p.add_argument("--precision", default="bfloat16",
                   choices=("default", "bfloat16", "highest"),
                   help="solver matmul precision (bfloat16 validated to give "
                        "identical consensus on this workload)")
    p.add_argument("--backend", default=None,
                   choices=("auto", "vmap", "packed", "pallas"),
                   help="restart-batch execution strategy (SolverConfig."
                        "backend). Default: 'pallas' for mu (the fused-"
                        "kernel whole-grid scheduler — measured fastest, "
                        "1.37 vs 1.70 s north star; falls back to 'auto' "
                        "if the warmup fails), else 'auto'")
    p.add_argument("--grid-exec", default="auto",
                   choices=("auto", "grid", "per_k"),
                   help="whole-grid single-compile execution vs sequential "
                        "per-rank (ConsensusConfig.grid_exec)")
    p.add_argument("--target-s", type=float, default=10.0)
    args = p.parse_args()

    import jax
    import numpy as np

    from nmfx.config import ConsensusConfig, InitConfig, SolverConfig
    from nmfx.datasets import grouped_matrix
    from nmfx.sweep import default_mesh, sweep, sweep_one_k

    ks = tuple(range(2, args.kmax + 1))
    if not ks:
        p.error("--kmax must be >= 2")
    if args.backend == "pallas" and args.algorithm != "mu":
        p.error("--backend pallas is only implemented for --algorithm mu "
                "(use auto to fall back per algorithm)")
    if args.backend == "packed" and args.algorithm not in ("mu", "hals"):
        p.error("--backend packed is only implemented for --algorithm "
                "mu/hals (use auto to fall back per algorithm)")
    if args.backend is None:
        # mu's fused-kernel whole-grid scheduler is the measured fastest
        # path on real TPUs (benchmarks/RESULTS.md round 3); off-TPU the
        # kernels would run in interpret-mode emulation, so gate on the
        # platform. Any warmup failure falls back to the library default.
        on_tpu = jax.default_backend() == "tpu"
        args.backend = ("pallas" if args.algorithm == "mu" and on_tpu
                        else "auto")
        backend_fallback = "auto" if args.backend == "pallas" else None
    else:
        backend_fallback = None
    scfg = SolverConfig(algorithm=args.algorithm, max_iter=args.maxiter,
                        matmul_precision=args.precision,
                        backend=args.backend)
    ccfg = ConsensusConfig(ks=ks, restarts=args.restarts, seed=123,
                           grid_exec=args.grid_exec)
    icfg = InitConfig()
    mesh = default_mesh()

    # 4 planted groups summing to exactly --samples columns
    sizes = [args.samples // 4] * 4
    sizes[0] += args.samples % 4
    a = grouped_matrix(args.genes, tuple(sizes), effect=2.0, seed=0)
    assert a.shape == (args.genes, args.samples)

    # warmup: one full sweep triggers every compile at the exact static
    # config (a different max_iter would be a different jit cache entry);
    # different seed than the timed run so no layer can serve cached
    # results. TIMED: this is the cold-start number a first-time user pays
    # (the reference has no compile step at all — its R workers start
    # solving immediately, nmf.r:112) — recorded as cold_wall_s, with
    # compile_wall_s ≈ cold − warm the compile share. The persistent
    # compilation cache (CLI default-on; JAX_COMPILATION_CACHE_DIR here)
    # collapses it on re-runs.
    warm_cfg = ConsensusConfig(ks=ks, restarts=args.restarts,
                               seed=ccfg.seed + 1, grid_exec=args.grid_exec)
    t_cold = time.perf_counter()
    fell_back = False
    try:
        warm = sweep(a, warm_cfg, scfg, icfg, mesh)
        jax.device_get({k: warm[k].consensus for k in ks})
    except Exception as e:
        if backend_fallback is None:
            raise
        # e.g. a Mosaic rejection outside the pallas pool's VMEM envelope
        # on unusual shapes: re-warm on the library default — loudly, and
        # flagged in the record (the failed attempt's wall is NOT counted
        # in cold_wall_s; a silent swap would make a pallas regression
        # read as a plausible slower run)
        import dataclasses
        import sys as _sys

        print(f"bench: backend=pallas warmup failed ({e!r}); "
              f"falling back to backend={backend_fallback}",
              file=_sys.stderr)
        fell_back = True
        args.backend = backend_fallback
        scfg = dataclasses.replace(scfg, backend=backend_fallback)
        t_cold = time.perf_counter()
        warm = sweep(a, warm_cfg, scfg, icfg, mesh)
        jax.device_get({k: warm[k].consensus for k in ks})
    cold_wall = time.perf_counter() - t_cold

    # time with host materialization of every output inside the region:
    # block_until_ready has been observed returning early on experimental
    # platforms, and the pipeline is only done when consensus+stats land on
    # host (that IS the workload's contract). ONE batched device_get — a
    # per-array pull pays a tunnel round trip each (~50–150 ms depending on
    # session; batching the 18 north-star pulls measured 0.4–1.4 s faster;
    # the API pipeline batches identically)
    t0 = time.perf_counter()
    raw = sweep(a, ccfg, scfg, icfg, mesh)
    host = jax.device_get(
        {k: (raw[k].consensus, raw[k].iterations) for k in ks})
    wall = time.perf_counter() - t0

    total_restarts = len(ks) * args.restarts
    its = {k: host[k][1] for k in ks}
    iters = {k: float(v.mean()) for k, v in its.items()}

    # MFU accounting for the algorithms in _MODEL_FLOPS (the pg/alspg
    # families' per-iteration FLOPs differ per line-search trial /
    # subproblem and are not modeled):
    # model FLOPs = Σ_k Σ_restart iters · flops_per_iter(k), achieved rate
    # over the measured wall, utilization vs the devices' bf16 peak
    model_flops = mfu = achieved = None
    flops_fn = _MODEL_FLOPS.get(args.algorithm)
    if flops_fn is not None:
        model_flops = sum(
            flops_fn(args.genes, args.samples, k)
            * float(its[k].sum()) for k in ks)
        achieved = model_flops / wall
        peak = _BF16_PEAK_FLOPS.get(jax.devices()[0].device_kind)
        if peak is not None:
            mfu = achieved / (peak * len(jax.devices()))
    record = {
        "metric": "consensus_sweep_wall_s",
        "value": round(wall, 3),
        "unit": "s",
        "vs_baseline": round(args.target_s / wall, 3),
        "detail": {
            "config": f"k=2..{args.kmax} x {args.restarts} restarts, "
                      f"{args.genes}x{args.samples}, {args.algorithm}, "
                      f"maxiter={args.maxiter}, precision={args.precision}, "
                      f"backend={args.backend}, grid_exec={args.grid_exec}",
            "restarts_per_s": round(total_restarts / wall, 2),
            "cold_wall_s": round(cold_wall, 3),
            "compile_wall_s": round(max(cold_wall - wall, 0.0), 3),
            **({"backend_fallback": True} if fell_back else {}),
            "mean_iters_per_k": {str(k): round(v, 1) for k, v in
                                 iters.items()},
            "model_tflop": (None if model_flops is None
                            else round(model_flops / 1e12, 3)),
            "achieved_tflop_per_s": (None if achieved is None
                                     else round(achieved / 1e12, 3)),
            "mfu": None if mfu is None else round(mfu, 4),
            "devices": [str(d) for d in jax.devices()],
        },
    }
    print(json.dumps(record))


if __name__ == "__main__":
    main()
