"""Benchmark: full consensus sweep vs the north-star target.

Target (BASELINE.md / BASELINE.json): k=2..10 × 50 restarts on a 5000×500
matrix in < 10 s wall-clock on TPU v5e-8. The reference publishes no numbers
(its only harness is `system.time` around the R pipeline, reference
test_nmf.r:25-27), so `vs_baseline` is reported against the 10 s driver
target: vs_baseline = target_s / measured_s (>1 = beating the target).

Prints ONE JSON line:
    {"metric": "consensus_sweep_wall_s", "value": ..., "unit": "s",
     "vs_baseline": ...}
plus detail fields (restarts/sec, per-k iterations, hardware).

Hardware-truth guards (round 4 — after BENCH_r03 shipped a corrupted
pallas run whose own record said mean_iters_per_k=2.0 and nothing
noticed, VERDICT.md round 3):

* every bench run passes its iteration counts and stop reasons through
  ``_integrity_problems`` — a physically-impossible record (class-stable
  stops below the ``check_every·(stable_checks+1)`` floor, mass early
  TolX stops from random init) aborts with a loud error instead of
  printing a JSON line that looks like a result;
* ``--verify`` runs the cross-engine parity gate ON THE REAL DEVICE at a
  scaled shape — mu's grid-dense vs grid-pallas vs per-k packed (the
  pallas engine under its default check_block cadence), hals grid vs
  vmap, and the kl/als/neals/snmf packed-grid opt-ins vs their vmapped
  defaults — and asserts
  iteration/stop/consensus/rho agreement. This is the on-hardware
  correctness tier the CPU-forced pytest suite cannot provide (Mosaic
  compilation is exactly what interpret-mode tests bypass).

Measurement protocol (round 5 — the recorded artifact now follows the
same discipline as the ``benchmarks/probe_*`` scripts): the tunneled
dev-chip environment swings ±50% between sessions (BASELINE.md), so one
warm run is a sample, not a measurement. ``--reps N`` (default 3) runs N
same-session warm reps per backend — interleaved across backends so no
backend monopolizes a fast or slow window — and the JSON records
min/median/all reps per backend. The headline ``value`` is the
requested backend's min (same-session minima are the only
cross-session-comparable statistic here); every rep passes the
integrity gate before anything is printed. On TPU with the default
``--backend auto`` and ``--algorithm mu``, the pallas engine is
measured alongside as a second backend in the same session.
"""

import argparse
import json
import os
import sys
import time

# Model FLOPs and device peaks now live in nmfx.obs.costmodel (ISSUE 13):
# one registry-keyed table covering EVERY engine family and algorithm
# (mfu below is no longer None for als/neals/snmf), cross-validated
# against compiled.cost_analysis() by tests/test_costmodel.py, with the
# per-device-kind bf16 peak + HBM bandwidth table (the MFU denominator;
# bf16 is both the bench default and what "default" matmul precision
# runs on TPU, so MFU is reported against the bf16 peak even for
# --precision highest, whose lower MFU is real, not an accounting
# artifact). pg/alspg stay unmodeled by declaration
# (costmodel.COSTMODEL_EXEMPT: data-dependent line-search/subproblem
# inner work).


def _integrity_problems(scfg, its, stops) -> list[str]:
    """Physical-plausibility checks on a sweep's per-restart iteration
    counts and stop reasons (dicts k -> (restarts,) arrays).

    The class-stability rule cannot stop before
    ``check_every·(stable_checks+1)`` iterations (first counted check at
    iteration 2·check_every, then stable_checks consecutive stable checks
    — reference nmf_mu.c:253-282 semantics), so a CLASS_STABLE stop below
    that floor is impossible, not merely unlikely. TolX stops below the
    same floor are individually possible but cannot dominate from random
    init — BENCH_r03's corrupted record had ~89% of jobs at 2 iterations.
    The impossible-CLASS_STABLE check applies to every algorithm (the
    reason code itself certifies the floor was reached); the dominance
    checks apply only where the class stop is the expected terminator
    from random init — mu and kl, which run hundreds of iterations.
    hals/snmf legitimately TolX-stop in ~20 iterations and als/neals/
    pg/alspg stop on TolX/TolFun/projgrad in ~14–100, so sub-floor stops
    are healthy there. MAX_ITER stops below the floor are legitimate for
    low --maxiter smoke runs and never counted. Returns a list of
    human-readable problems; empty = plausible.
    """
    from nmfx.solvers.base import StopReason

    problems = []
    floor = scfg.check_every * (scfg.stable_checks + 1)
    for k in sorted(its):
        it_k, st_k = its[k], stops[k]
        impossible = (st_k == int(StopReason.CLASS_STABLE)) & (it_k < floor)
        if impossible.any():
            problems.append(
                f"k={k}: {int(impossible.sum())} job(s) recorded "
                f"CLASS_STABLE below the {floor}-iteration floor "
                f"(min recorded: {int(it_k[impossible].min())})")
    if scfg.algorithm not in ("mu", "kl") or not scfg.use_class_stop \
            or scfg.backend == "sketched":
        # the sketched engine's conservative Lipschitz-bounded gradient
        # steps legitimately TolX-stop below (or crawl past) the exact
        # mu class floor — dominance has no signal there; the
        # impossible-CLASS_STABLE check above still applies (the class
        # cadence machinery is shared)
        return problems
    for k in sorted(its):
        it_k, st_k = its[k], stops[k]
        early = (it_k < floor) & (st_k != int(StopReason.MAX_ITER))
        if early.mean() > 0.2:
            problems.append(
                f"k={k}: {int(early.sum())}/{it_k.size} jobs stopped below "
                f"the {floor}-iteration class-stability floor — "
                "implausible from random init")
        if scfg.max_iter >= floor and float(it_k.mean()) < floor:
            problems.append(
                f"k={k}: mean iterations {float(it_k.mean()):.1f} is below "
                f"the {floor}-iteration floor")
    return problems


def _pipeline_parity_problems(per_k, host, ks, restarts,
                              linkage="average") -> list[str]:
    """The streamed harvest must be EXACTLY the sequential path: same
    consensus bytes, same rho after the reference's signif-4 rounding,
    same memberships/order, same per-restart stats. ``per_k`` is the
    streamed pipeline's {k: KResult}; ``host`` the independently-pulled
    {k: (consensus, iterations, stop_reasons)} of the same sweep. The
    sequential reference is recomputed here from the pulled consensus
    with the exact host math of ``api._build_k_result``'s sequential
    path — any drift (a transposed rank, a dropped column, a
    float-order change from threading) fails the rep."""
    import numpy as np

    from nmfx.cophenetic import rank_selection

    problems = []
    for k in ks:
        r = per_k.get(k)
        if r is None:
            problems.append(f"k={k}: missing from the streamed harvest")
            continue
        cons = np.asarray(host[k][0], dtype=np.float64)
        if not np.array_equal(r.consensus, cons):
            problems.append(f"k={k}: streamed consensus differs from the "
                            "sequential pull (bitwise)")
            continue  # rank selection on different bytes proves nothing
        rho, membership, order = rank_selection(cons, k, linkage)
        rho = float(np.format_float_positional(rho, precision=4,
                                               fractional=False))
        if r.rho != rho:
            problems.append(f"k={k}: streamed rho {r.rho} != sequential "
                            f"{rho}")
        if not np.array_equal(r.membership, membership):
            problems.append(f"k={k}: streamed membership differs from "
                            "sequential rank selection")
        if not np.array_equal(r.order, order):
            problems.append(f"k={k}: streamed leaf order differs from "
                            "sequential rank selection")
        if not (np.array_equal(r.iterations, host[k][1])
                and np.array_equal(r.stop_reasons, host[k][2])):
            problems.append(f"k={k}: streamed per-restart stats differ "
                            "from the sequential pull")
        if r.iterations.shape != (restarts,):
            problems.append(f"k={k}: streamed iterations shape "
                            f"{r.iterations.shape} != ({restarts},)")
    return problems


def _serve_parity_problems(got, ref, label: str) -> list[str]:
    """A served request's ConsensusResult must be BIT-IDENTICAL to a
    solo ``nmfconsensus`` run of the same request through the same
    serving layer — the serving front-end's exactness contract
    (docs/serving.md "Serving front-end"). Gated per served request the
    same way streamed-vs-sequential harvest parity is gated per rep:
    any mismatch fails the bench with exit 2."""
    import numpy as np

    if set(got.per_k) != set(ref.per_k):
        return [f"{label}: served rank set {sorted(got.per_k)} != solo "
                f"{sorted(ref.per_k)}"]
    problems = []
    for k in ref.per_k:
        s, q = got.per_k[k], ref.per_k[k]
        for field in ("consensus", "membership", "order", "iterations",
                      "dnorms", "stop_reasons", "best_w", "best_h"):
            # BYTE comparison, not array_equal: literally bit-identical,
            # and a quarantined lane's NaN dnorm (chaos rung) equals the
            # reference's identical NaN instead of failing NaN != NaN
            sv = np.ascontiguousarray(np.asarray(getattr(s, field)))
            qv = np.ascontiguousarray(np.asarray(getattr(q, field)))
            if (sv.shape != qv.shape or sv.dtype != qv.dtype
                    or sv.tobytes() != qv.tobytes()):
                problems.append(f"{label} k={k}: served {field} differs "
                                "from the solo run (bitwise)")
        if s.rho != q.rho:
            problems.append(f"{label} k={k}: served rho {s.rho} != solo "
                            f"{q.rho}")
    return problems


def _best_prior_record(metric: str) -> "dict | None":
    """Best (lowest-wall) prior BENCH_r*.json record of this metric —
    regression tracking: the warm metric drifted 1.384 s (r03) →
    2.041/1.848 s (r04/r05) with only `vs_baseline` (a fixed 10 s
    target) in the record, so nothing flagged it. `vs_best` compares
    against the best result EVER recorded and names which round/config
    produced it, making a regression visible in the record itself.
    Accepts both the driver's wrapper form ({.., "parsed": record}) and
    a bare record; unreadable files are skipped."""
    import glob

    here = os.path.dirname(os.path.abspath(__file__))
    best = None
    for path in sorted(glob.glob(os.path.join(here, "BENCH_r*.json"))):
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(rec, dict):
            continue
        parsed = rec.get("parsed", rec)
        if not isinstance(parsed, dict) or parsed.get("metric") != metric:
            continue
        value = parsed.get("value")
        if not isinstance(value, (int, float)):
            continue
        if best is None or value < best["value"]:
            detail = parsed.get("detail") or {}
            best = {"file": os.path.basename(path), "value": value,
                    "config": detail.get("config"),
                    "commit": detail.get("commit")}
    return best


def _git_commit() -> "str | None":
    """Best-effort current commit, recorded so future rounds' `vs_best`
    can name the commit that produced the best-so-far."""
    import subprocess

    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], capture_output=True,
            text=True, timeout=10,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        return out.stdout.strip() or None if out.returncode == 0 else None
    except (OSError, subprocess.SubprocessError):
        # TimeoutExpired is NOT an OSError; a hung git must degrade to
        # commit=None, never crash a finished multi-minute run
        return None


#: the cold_persist stage's fresh-process child: serve the bench sweep
#: through a WARM exec-cache disk directory and report the wall, the
#: exec-layer compile count (must be zero — the parent gates on it), the
#: deserialize seconds, and the per-rank iteration/stop records for the
#: parent's integrity check. Data build and imports run BEFORE the timer:
#: cold_persist_wall_s is deserialize+dispatch+solve+d2h, the serving
#: path a fresh process actually pays per request.
_COLD_PERSIST_CHILD = r"""
import json, sys, time

cfg = json.loads(sys.argv[1])
import jax
from nmfx.config import (ConsensusConfig, ExecCacheConfig, InitConfig,
                         SolverConfig)
from nmfx.datasets import grouped_matrix
from nmfx import exec_cache as ec
from nmfx.sweep import default_mesh

sizes = [cfg["samples"] // 4] * 4
sizes[0] += cfg["samples"] % 4
a = grouped_matrix(cfg["genes"], tuple(sizes), effect=2.0, seed=0)
ccfg = ConsensusConfig(ks=tuple(cfg["ks"]), restarts=cfg["restarts"],
                       seed=cfg["seed"], grid_exec=cfg["grid_exec"])
scfg = SolverConfig(algorithm=cfg["algorithm"], max_iter=cfg["maxiter"],
                    matmul_precision=cfg["precision"],
                    backend=cfg["backend"])
mesh = default_mesh()
cache = ec.ExecCache(ExecCacheConfig(cache_dir=cfg["cache_dir"]))
t0 = time.perf_counter()
out = cache.run_sweep(a, ccfg, scfg, InitConfig(), mesh)
host = jax.device_get({k: (out[k].iterations, out[k].stop_reasons)
                       for k in ccfg.ks})
wall = time.perf_counter() - t0
print(json.dumps({
    "wall_s": wall, "compiles": ec.compile_count(),
    "persist_hits": cache.stats["persist_hits"],
    "deserialize_s": sum(e.deserialize_s
                         for e in cache._entries.values()),
    "its": {str(k): host[k][0].tolist() for k in ccfg.ks},
    "stops": {str(k): host[k][1].tolist() for k in ccfg.ks}}))
"""


def _run_sweep_engine(a, ks, scfg, ccfg, icfg, mesh):
    """One full sweep; returns per-k dicts (iters, stops, consensus, rho)."""
    import jax

    from nmfx.cophenetic import rank_selection
    from nmfx.sweep import sweep

    raw = sweep(a, ccfg, scfg, icfg, mesh)
    host = jax.device_get({k: (raw[k].iterations, raw[k].stop_reasons,
                               raw[k].consensus) for k in ks})
    its = {k: host[k][0] for k in ks}
    stops = {k: host[k][1] for k in ks}
    cons = {k: host[k][2] for k in ks}
    rho = {k: rank_selection(cons[k], k)[0] for k in ks}
    return its, stops, cons, rho


def run_verify(args) -> int:
    """Cross-engine parity gate on the real device at a scaled shape.

    Engines: the whole-grid slot scheduler on XLA-dense blocks
    (grid-dense), the same scheduler on the fused pallas kernels
    (grid-pallas — under the default check_block cadence, so the
    round-6 launch-resident multi-check path is what gets gated), and
    the sequential per-rank packed path (per-k) — the three mu
    execution engines users can select — plus a second stage gating
    EVERY non-mu scheduler engine against its vmapped default (hals
    grid vs vmap; the kl/als/neals/snmf backend='packed' opt-ins —
    round 6 closed the als/neals/snmf coverage gap). Asserts, per
    rank:

    * integrity (``_integrity_problems``) for every engine;
    * no MAX_ITER burns (everything converges at this shape);
    * mean AND median iterations within a 1.6× band of grid-dense —
      Mosaic accumulation order legitimately drifts trajectories (stop
      iterations with them), but the round-3 corruption was 50–130×,
      and the median catches a partial corruption (a subset of
      short-circuiting jobs) before it saturates the mean;
    * cophenetic rho within 0.05 and consensus matrices within
      max|ΔC| ≤ 0.3 AND mean|ΔC| ≤ 0.6 restart-equivalents of
      grid-dense — the user-visible quantities (see ``compare`` for the
      band calibration against measured legitimate drift);
    * a third stage at the VMEM-envelope boundary shape (m=5120, n=512,
      k≤10 → the full rk=480 resident pool, 108 jobs through 48 slots
      so evict/reload traffic exists) comparing grid-pallas to
      grid-dense where the slot clamp and block geometry bind.

    The gate is fault-injection-proven: ``benchmarks/probe_fault_gate.py``
    re-introduces the round-3 stale-reload corruption behind
    ``NMFX_FAULT_INJECT_STALE_RELOAD`` and asserts this gate FAILS on
    it while passing on trunk (artifact:
    ``benchmarks/FAULTGATE_r05.json``).

    Exit code 0 = gate passed (one JSON line with the measured gaps),
    1 = failed (problems listed on stderr).
    """
    import dataclasses

    import numpy as np

    from nmfx.config import ConsensusConfig, InitConfig, SolverConfig
    from nmfx.datasets import grouped_matrix
    from nmfx.solvers.base import StopReason
    from nmfx.sweep import default_mesh

    m, n, restarts = 1000, 200, 12
    ks = tuple(range(2, 6))
    a = grouped_matrix(m, (n // 4,) * 4, effect=2.0, seed=0)
    scfg = SolverConfig(algorithm="mu", max_iter=args.maxiter,
                        matmul_precision=args.precision)
    icfg = InitConfig()
    mesh = default_mesh()
    engines = {
        "grid-dense": (dataclasses.replace(scfg, backend="auto"), "grid"),
        "grid-pallas": (dataclasses.replace(scfg, backend="pallas"),
                        "grid"),
        "per-k": (dataclasses.replace(scfg, backend="packed"), "per_k"),
    }
    results = {}
    for name, (cfg_e, grid_exec) in engines.items():
        ccfg = ConsensusConfig(ks=ks, restarts=restarts, seed=123,
                               grid_exec=grid_exec)
        t0 = time.perf_counter()
        results[name] = _run_sweep_engine(a, ks, cfg_e, ccfg, icfg, mesh)
        print(f"verify: {name} ran in {time.perf_counter() - t0:.1f}s",
              file=sys.stderr)

    problems = []
    gaps = {}

    def check_engine(name, cfg_e, result, ks=ks):
        """Integrity + no-MAX_ITER-burn assertions, shared by every
        engine of all three stages."""
        its, stops, _, _ = result
        problems.extend(f"{name}: {p}"
                        for p in _integrity_problems(cfg_e, its, stops))
        for k in ks:
            burned = stops[k] == int(StopReason.MAX_ITER)
            if burned.any():
                problems.append(
                    f"{name}: k={k}: {int(burned.sum())} job(s) burned to "
                    f"MAX_ITER at a shape where every engine converges")

    def compare(name, result, ref_name, ref_result, ks=ks,
                n_restarts=restarts, max_dc_band=0.3):
        """Engine-vs-reference gaps, uniform orientation everywhere:
        iters_ratio = this engine's mean iterations / the reference's.

        Round-5 tightening (VERDICT r4: correct drift consumed ~50% of
        the old bands and a partial corruption could hide inside them):

        * the per-k MEDIAN iteration ratio is asserted alongside the
          mean — a subset of corrupted short-circuiting jobs drags the
          median before it saturates the mean;
        * mean|ΔC| is asserted in RESTART-EQUIVALENTS:
          mean|ΔC|·R ≤ 0.6, i.e. at most ~0.6 restarts' worth of
          average co-assignment drift. A consensus entry moves in
          steps of 1/R, so the raw mean scales with R — normalizing
          makes one band correct at every stage (at R=50 it equals the
          0.012 band CROSSCHECK_r04's measured ≤0.004 suggested; at the
          gate's R=12 it allows 0.05, measured legitimate drift 0.030);
        * ``max_dc_band`` is per-stage: 0.3 at the structured stages,
          None at the boundary stage, where k≥6 on 4-group data makes
          the surplus clusters split near-ties arbitrarily — measured
          legitimate max|ΔC| reaches 3/6 restarts there with ρ agreeing
          to 0.0014 and iteration ratios clean, so a max-based band has
          no signal; corruption at that stage is caught by integrity,
          iteration quantiles, and the normalized mean|ΔC|."""
        its, _, cons, rho = result
        ref_its, _, ref_cons, ref_rho = ref_result
        for k in ks:
            ratio = float(its[k].mean()) / float(ref_its[k].mean())
            med_ratio = (float(np.median(its[k]))
                         / max(float(np.median(ref_its[k])), 1.0))
            drho = abs(rho[k] - ref_rho[k])
            dc = float(np.max(np.abs(cons[k] - ref_cons[k])))
            mean_dc = float(np.mean(np.abs(cons[k] - ref_cons[k])))
            gaps[f"{name}.k{k}"] = {"ref": ref_name,
                                    "iters_ratio": round(ratio, 3),
                                    "iters_median_ratio": round(
                                        med_ratio, 3),
                                    "d_rho": round(drho, 4),
                                    "max_dC": round(dc, 3),
                                    "mean_dC": round(mean_dc, 4),
                                    "mean_dC_restarts": round(
                                        mean_dc * n_restarts, 3)}
            if not (1 / 1.6 <= ratio <= 1.6):
                problems.append(f"{name}: k={k}: mean-iteration ratio "
                                f"{ratio:.2f} vs {ref_name} outside 1.6x")
            if not (1 / 1.6 <= med_ratio <= 1.6):
                problems.append(f"{name}: k={k}: median-iteration ratio "
                                f"{med_ratio:.2f} vs {ref_name} outside "
                                "1.6x")
            if drho > 0.05:
                problems.append(f"{name}: k={k}: |d rho| = {drho:.4f} "
                                f"vs {ref_name} exceeds 0.05")
            if max_dc_band is not None and dc > max_dc_band:
                problems.append(f"{name}: k={k}: max |dC| = {dc:.3f} "
                                f"vs {ref_name} exceeds {max_dc_band}")
            if mean_dc * n_restarts > 0.6:
                problems.append(
                    f"{name}: k={k}: mean |dC| = {mean_dc:.4f} "
                    f"(x{n_restarts} restarts = "
                    f"{mean_dc * n_restarts:.2f}) vs {ref_name} exceeds "
                    "0.6 restart-equivalents")

    for name, (cfg_e, _) in engines.items():
        check_engine(name, cfg_e, results[name])
    for name in ("grid-pallas", "per-k"):
        compare(name, results[name], "grid-dense", results["grid-dense"])

    # --- second stage: the non-mu scheduler engines (round 4/6) --------
    # hals' default IS the grid engine (gate it against the vmapped
    # driver); the kl/als/neals/snmf whole-grid engines are the
    # backend='packed' opt-ins (gated against their vmapped defaults —
    # round 6 closed the coverage gap: the user-selectable
    # als/neals/snmf packed engines shipped UNGATED through round 5,
    # exactly the round-3 failure class, and they converge in ~14–21
    # iterations so the stage costs seconds). Same assertions as
    # stage 1; integrity applies per engine (kl is class-stop gated;
    # hals/snmf's ~20-iteration TolX stops and als/neals' ~14-iteration
    # TolX/TolFun stops are exempt by design).
    for algo, alt_pair, ref_pair in (
            ("hals",
             ("hals-grid", dataclasses.replace(
                 scfg, algorithm="hals", backend="auto"), "grid"),
             ("hals-vmap", dataclasses.replace(
                 scfg, algorithm="hals", backend="vmap"), "per_k")),
            ("kl",
             ("kl-packed-grid", dataclasses.replace(
                 scfg, algorithm="kl", backend="packed"), "grid"),
             ("kl-vmap", dataclasses.replace(
                 scfg, algorithm="kl", backend="auto"), "per_k")),
            ("als",
             ("als-packed-grid", dataclasses.replace(
                 scfg, algorithm="als", backend="packed"), "grid"),
             ("als-vmap", dataclasses.replace(
                 scfg, algorithm="als", backend="auto"), "per_k")),
            ("neals",
             ("neals-packed-grid", dataclasses.replace(
                 scfg, algorithm="neals", backend="packed"), "grid"),
             ("neals-vmap", dataclasses.replace(
                 scfg, algorithm="neals", backend="auto"), "per_k")),
            ("snmf",
             ("snmf-packed-grid", dataclasses.replace(
                 scfg, algorithm="snmf", backend="packed"), "grid"),
             ("snmf-vmap", dataclasses.replace(
                 scfg, algorithm="snmf", backend="auto"), "per_k"))):
        res = {}
        for name, cfg_e, grid_exec in (alt_pair, ref_pair):
            ccfg = ConsensusConfig(ks=ks, restarts=restarts, seed=123,
                                   grid_exec=grid_exec)
            t0 = time.perf_counter()
            res[name] = _run_sweep_engine(a, ks, cfg_e, ccfg, icfg, mesh)
            print(f"verify: {name} ran in "
                  f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)
            check_engine(name, cfg_e, res[name])
        compare(alt_pair[0], res[alt_pair[0]],
                ref_pair[0], res[ref_pair[0]])

    # --- third stage: the VMEM-envelope boundary (round 5) -------------
    # 48 slots × k_max=10 = 480 packed columns at m=5120, n=512 — exactly
    # the measured resident-W envelope boundary (sched_mu._pallas_slot_
    # clamp accepts rk=480 at this shape, model 14.07 of 14.3 MiB), so
    # the clamp arithmetic, the 16-row-aligned block geometry, and
    # boundary-condition Mosaic tiling are all exercised where they
    # actually bind. 108 jobs > 48 slots forces 60 evict/reload events —
    # the round-3 corruption path (stage 1's 48 jobs fill its 48 slots
    # exactly, so only THIS stage exercises reloads). grid-pallas vs
    # grid-dense only (the kernel tier is what the envelope constrains).
    mb, nb, rb = 5120, 512, 12
    ks_b = tuple(range(2, 11))
    a_b = grouped_matrix(mb, (nb // 4,) * 4, effect=2.0, seed=0)
    res_b = {}
    for name, backend in (("bound-dense", "auto"),
                          ("bound-pallas", "pallas")):
        cfg_e = dataclasses.replace(scfg, backend=backend)
        ccfg = ConsensusConfig(ks=ks_b, restarts=rb, seed=123,
                               grid_exec="grid")
        t0 = time.perf_counter()
        # mesh=None (single-device) REGARDLESS of the host's device
        # count: the stage's premise is all 108 jobs through ONE 48-slot
        # queue — on a restart mesh each device would schedule only
        # 108/N jobs and the reload traffic this stage exists to
        # exercise would vanish below N's slot pool
        res_b[name] = _run_sweep_engine(a_b, ks_b, cfg_e, ccfg, icfg,
                                        None)
        print(f"verify: {name} ran in {time.perf_counter() - t0:.1f}s",
              file=sys.stderr)
        check_engine(name, cfg_e, res_b[name], ks=ks_b)
    compare("bound-pallas", res_b["bound-pallas"],
            "bound-dense", res_b["bound-dense"], ks=ks_b, n_restarts=rb,
            max_dc_band=None)

    ok = not problems
    for p in problems:
        print(f"verify FAIL: {p}", file=sys.stderr)
    print(json.dumps({
        "metric": "verify_parity", "value": 1 if ok else 0, "unit": "pass",
        "detail": {"engines": list(engines) + [
                       "hals-grid", "hals-vmap",
                       "kl-packed-grid", "kl-vmap",
                       "als-packed-grid", "als-vmap",
                       "neals-packed-grid", "neals-vmap",
                       "snmf-packed-grid", "snmf-vmap",
                       "bound-dense", "bound-pallas"],
                   "shape": f"{m}x{n}, k=2..5, {restarts} restarts",
                   "gaps": gaps,
                   "problems": problems}}))
    return 0 if ok else 1


def run_durability_child(args) -> int:
    """The durability rung's kill-at-~50% subprocess body: build the
    SAME matrix/config as the parent (deterministic from the args), arm
    ``proc.preempt`` to fire once after ``--preempt-after`` chunk
    solves, and run the checkpointed sweep. The injected preemption
    lands AFTER a chunk's device solve and BEFORE its commit — the
    worst realistic kill point: that chunk's work is lost
    (``wasted_work_frac``), every committed record survives — and the
    child exits 137 (the SIGKILL code) for the parent to assert."""
    import numpy as np  # noqa: F401  (grouped_matrix returns ndarray)

    from nmfx import checkpoint as ckpt
    from nmfx import faults
    from nmfx.api import nmfconsensus
    from nmfx.config import CheckpointConfig, SolverConfig
    from nmfx.datasets import grouped_matrix

    if args.preempt_after is None or args.durability_chunk is None:
        print("bench: --durability-child needs --preempt-after and "
              "--durability-chunk", file=sys.stderr)
        return 2
    sizes = [args.samples // 4] * 4
    sizes[0] += args.samples % 4
    a = grouped_matrix(args.genes, tuple(sizes), effect=2.0, seed=0)
    scfg = SolverConfig(algorithm=args.algorithm, max_iter=args.maxiter,
                        matmul_precision=args.precision,
                        backend=args.backend,
                        tile_rows=args.atlas_tile_rows)
    faults.arm("proc.preempt", every=args.preempt_after, max_fires=1)
    cfg = CheckpointConfig(args.durability_child,
                           every_n_restarts=args.durability_chunk)
    try:
        nmfconsensus(a, ks=tuple(range(2, args.kmax + 1)),
                     restarts=args.restarts, seed=123, solver_cfg=scfg,
                     checkpoint=cfg)
    except ckpt.Preempted:
        print(json.dumps({"durability_child": {
            "solved_chunks": ckpt.chunks_solved_count()}}), flush=True)
        os._exit(137)  # the preemption: no teardown, like SIGKILL
    # preempt never fired: the parent's chunk arithmetic is wrong —
    # report loudly so the stage gates on it
    print(json.dumps({"durability_child": {
        "solved_chunks": ckpt.chunks_solved_count(),
        "completed_without_preempt": True}}), flush=True)
    return 3


def run_mesh_child(args) -> int:
    """The mesh rung's subprocess body (ISSUE 19, detail.mesh): runs
    under forced CPU devices (the parent sets
    ``XLA_FLAGS=--xla_force_host_platform_device_count=N`` before the
    child's jax initializes — device count is fixed at import, which
    is why this is a subprocess). Four sub-rungs, all hard-gated:

    strong / weak
        restart-axis scaling over 1/2/4/8 restart shards: strong holds
        the total restart count fixed, weak holds the per-shard count.
        restarts/s counts REAL restarts only — surplus pad lanes
        (``nmfx_mesh_pad_lanes_total``, booked per rung from the
        counter delta) are computed-and-discarded work and are
        subtracted, so the curves measure honest throughput.
    exactness
        the meshed-vs-unmeshed contract: restart-only mesh
        BIT-IDENTICAL per engine (consensus + labels + dnorms), grid
        (feature×sample) mesh agreement-gated.
    comm
        ``costmodel.comm_model`` vs the compiled HLO's collective ops
        (``xla_comm_cost``): per-iteration allreduce counts must match
        EXACTLY and payload bytes to 1%% — the FLOPs-vs-cost_analysis
        discipline applied to communication.
    fleet
        heterogeneous 1-chip + 4-chip thread-replica pool behind the
        priced router: atlas-shaped submissions MUST place on the mesh
        class and small ones on the 1-chip class (the placement-
        correctness gate), results bit-identical to the direct sweep.
    """
    import numpy as np

    import jax

    from nmfx.config import ConsensusConfig, SolverConfig
    from nmfx.datasets import grouped_matrix
    from nmfx.obs import metrics as obs_metrics
    from nmfx.sweep import GRID_SOLVERS, grid_mesh, sweep

    n_dev = len(jax.devices())
    problems = []
    m_b, n_b = min(args.genes, 96), min(args.samples, 48)
    a = grouped_matrix(m_b, (n_b // 2, n_b - n_b // 2), effect=2.0,
                       seed=0)
    maxiter = min(args.maxiter, 120)

    def pad_lanes_total() -> float:
        snap = obs_metrics.registry().snapshot()
        rec = snap.get("nmfx_mesh_pad_lanes_total")
        if not rec:
            return 0.0
        return float(sum(rec["series"].values()))

    def timed_sweep(scfg, restarts, mesh):
        ccfg = ConsensusConfig(ks=(3,), restarts=restarts, seed=123)
        pads0 = pad_lanes_total()
        t0 = time.perf_counter()
        out = sweep(a, ccfg, scfg, mesh=mesh)
        np.asarray(out[3].consensus)  # sync
        wall = time.perf_counter() - t0
        return out, wall, pad_lanes_total() - pads0

    scfg = SolverConfig(algorithm="kl", max_iter=maxiter)
    shard_counts = [s for s in (1, 2, 4, 8) if s <= n_dev]
    strong_r, weak_per_shard = 12, 2
    strong, weak = [], []
    for s in shard_counts:
        mesh = grid_mesh(s, 1, 1) if s > 1 else None
        _, wall, pads = timed_sweep(scfg, strong_r, mesh)
        strong.append({"shards": s, "restarts": strong_r,
                       "pad_lanes": pads, "wall_s": round(wall, 3),
                       "restarts_per_s": round(strong_r / wall, 2)})
        total = weak_per_shard * s
        _, wall, pads = timed_sweep(scfg, total, mesh)
        weak.append({"shards": s, "restarts": total,
                     "pad_lanes": pads, "wall_s": round(wall, 3),
                     "restarts_per_s": round(total / wall, 2)})

    # exactness: restart-only mesh bit-identical per engine; 12 lanes
    # on 8 shards also pins the pad path (lanes 12..15 discarded)
    exact = {}
    r_mesh = grid_mesh(min(4, n_dev), 1, 1)
    for alg in sorted(set(GRID_SOLVERS) | {"mu"}):
        e_scfg = SolverConfig(algorithm=alg, max_iter=maxiter)
        ccfg = ConsensusConfig(ks=(3,), restarts=6, seed=123)
        ref = sweep(a, ccfg, e_scfg)[3]
        got = sweep(a, ccfg, e_scfg, mesh=r_mesh)[3]
        bit = all(
            np.array_equal(np.asarray(getattr(ref, f)),
                           np.asarray(getattr(got, f)))
            for f in ("consensus", "labels", "dnorms"))
        exact[alg] = "bit-identical" if bit else "MISMATCH"
        if not bit:
            problems.append(f"restart-mesh exactness: {alg} diverged "
                            "from the unmeshed sweep")
        if n_dev >= 4:
            g_mesh = grid_mesh(1, 2, 2)
            grid_got = sweep(a, ccfg, e_scfg, mesh=g_mesh)[3]
            agree = np.allclose(np.asarray(ref.consensus),
                                np.asarray(grid_got.consensus),
                                atol=0.35)
            if not agree:
                problems.append(f"grid-mesh agreement: {alg} consensus "
                                "diverged beyond tolerance")

    # comm model vs compiled HLO (exact count match, ~payload match)
    from nmfx.obs import costmodel

    comm = {}
    if n_dev >= 4:
        g_mesh = grid_mesh(1, 2, 2)
        for alg in sorted(costmodel.comm_covered_algorithms()):
            model = costmodel.comm_model(alg, m_b, n_b, 3,
                                         feature_shards=2,
                                         sample_shards=2, restarts=2)
            meas = costmodel.xla_comm_cost(alg, m_b, n_b, 3, g_mesh,
                                           r_loc=2)
            if meas is None:
                comm[alg] = "unmeasurable"
                continue
            ok_ops = (model["collectives_per_iter"]
                      == meas["collectives_per_iter"])
            pb_m = model["payload_bytes_per_iter"]
            pb_x = meas["payload_bytes_per_iter"]
            ok_bytes = abs(pb_m - pb_x) <= 0.01 * max(pb_m, 1.0)
            comm[alg] = {
                "collectives_per_iter": model["collectives_per_iter"],
                "hlo_collectives_per_iter":
                    meas["collectives_per_iter"],
                "payload_bytes_per_iter": pb_m,
                "hlo_payload_bytes_per_iter": pb_x,
                "match": bool(ok_ops and ok_bytes)}
            if not (ok_ops and ok_bytes):
                problems.append(
                    f"comm model: {alg} predicts "
                    f"{model['collectives_per_iter']} collectives/"
                    f"{pb_m:.0f}B per iter, compiled HLO has "
                    f"{meas['collectives_per_iter']}/{pb_x:.0f}B")

    # heterogeneous fleet: priced placement correctness + parity
    fleet = {}
    if n_dev >= 4:
        import shutil
        import tempfile

        from nmfx.replica import ReplicaPool
        from nmfx.router import NMFXRouter, RouterConfig

        root = tempfile.mkdtemp(prefix="nmfx-bench-mesh-fleet-")
        router = None
        try:
            pool = ReplicaPool(2, root=root, mode="thread",
                               mesh_specs=(None, "4"))
            router = NMFXRouter(
                pool, RouterConfig(atlas_floor_bytes=a.nbytes))
            ccfg = ConsensusConfig(ks=(3,), restarts=6, seed=123)
            ref = sweep(a, ccfg, scfg)[3]
            small = np.ascontiguousarray(a[:12, :8])
            t0 = time.perf_counter()
            futs = [("atlas", router.submit(
                        a, ks=(3,), restarts=6, seed=123,
                        solver_cfg=scfg)) for _ in range(2)]
            futs += [("small", router.submit(
                         small, ks=(2,), restarts=2, seed=123,
                         solver_cfg=scfg)) for _ in range(2)]
            placements = {"atlas": [], "small": []}
            for shape, fut in futs:
                res = fut.result(timeout=300)
                placements[shape].append(fut.stats.placement_class)
                if shape == "atlas" and not np.array_equal(
                        np.asarray(res.per_k[3].consensus),
                        np.asarray(ref.consensus)):
                    problems.append("fleet: routed atlas result "
                                    "diverged from the direct sweep")
            wall = time.perf_counter() - t0
            if any(c != 4 for c in placements["atlas"]):
                problems.append(
                    "fleet placement: atlas-shaped request landed on "
                    f"class {placements['atlas']} with a 4-chip "
                    "replica routable")
            if any(c != 1 for c in placements["small"]):
                problems.append(
                    "fleet placement: small request landed on class "
                    f"{placements['small']} instead of the 1-chip "
                    "replica")
            fleet = {"classes": [1, 4],
                     "atlas_placements": placements["atlas"],
                     "small_placements": placements["small"],
                     "wall_s": round(wall, 3),
                     "placement": ("ok" if not any(
                         "placement" in p for p in problems)
                         else "WRONG")}
        finally:
            if router is not None:
                router.close()
            shutil.rmtree(root, ignore_errors=True)

    out = {"n_devices": n_dev, "strong": strong, "weak": weak,
           "exactness": exact, "comm": comm, "fleet": fleet,
           "problems": problems, "ok": not problems}
    print(json.dumps({"mesh_child": out}), flush=True)
    return 0 if not problems else 2


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--genes", type=int, default=5000)
    p.add_argument("--samples", type=int, default=500)
    p.add_argument("--kmax", type=int, default=10)
    p.add_argument("--restarts", type=int, default=50)
    p.add_argument("--maxiter", type=int, default=10000)
    p.add_argument("--algorithm", default="mu")
    p.add_argument("--precision", default="bfloat16",
                   choices=("default", "bfloat16", "highest"),
                   help="solver matmul precision (bfloat16 validated to give "
                        "identical consensus on this workload)")
    p.add_argument("--backend", default="auto",
                   choices=("auto", "vmap", "packed", "pallas"),
                   help="restart-batch execution strategy (SolverConfig."
                        "backend). Default 'auto' — the LIBRARY default, "
                        "so bench records measure what `nmfx` users get; "
                        "pass --backend pallas explicitly to measure the "
                        "fused-kernel experiment (round-3 defaulted TPU "
                        "benches to pallas and shipped a corrupted record "
                        "— VERDICT.md round 3)")
    p.add_argument("--verify", action="store_true",
                   help="run the cross-engine hardware parity gate "
                        "(mu: grid-dense vs grid-pallas vs per-k; hals: "
                        "grid vs vmap; kl/als/neals/snmf: packed-grid vs "
                        "vmap) instead of the benchmark; exits nonzero "
                        "on any integrity or parity failure")
    p.add_argument("--reps", type=int, default=3,
                   help="warm timed reps per backend (same session, "
                        "interleaved across backends); the JSON records "
                        "min/median/all reps and the headline is the "
                        "requested backend's min — one warm run in this "
                        "±50%%-variance environment is a sample, not a "
                        "measurement")
    p.add_argument("--grid-exec", default="auto",
                   choices=("auto", "grid", "per_k"),
                   help="whole-grid single-compile execution vs sequential "
                        "per-rank (ConsensusConfig.grid_exec)")
    p.add_argument("--target-s", type=float, default=10.0)
    # internal: the durability rung's kill-at-50% subprocess re-enters
    # THIS entrypoint with these flags (the probe_fault_gate discipline:
    # the child translates its CLI args into explicit in-process fault
    # arming — env vars stay inert)
    p.add_argument("--durability-child", default=None,
                   help=argparse.SUPPRESS)
    p.add_argument("--preempt-after", type=int, default=None,
                   help=argparse.SUPPRESS)
    p.add_argument("--durability-chunk", type=int, default=None,
                   help=argparse.SUPPRESS)
    # internal: the atlas rung's kill-at-50% child is the SAME protocol
    # with a tiled solver config — the preemption then lands mid-MATRIX
    # (between Gram passes, after a .part.npz partial landed) instead of
    # between chunks
    p.add_argument("--atlas-tile-rows", type=int, default=None,
                   help=argparse.SUPPRESS)
    # internal: the mesh rung's forced-CPU-devices subprocess re-enters
    # THIS entrypoint (the parent sets XLA_FLAGS before the child's jax
    # initializes — device count is import-time state)
    p.add_argument("--mesh-child", action="store_true",
                   help=argparse.SUPPRESS)
    p.add_argument("--dryrun-multichip", type=int, default=None,
                   metavar="N",
                   help="jit one restart-sharded consensus step across "
                        "N devices (__graft_entry__.dryrun_multichip) "
                        "and exit — the CI multichip smoke entrypoint; "
                        "run under XLA_FLAGS=--xla_force_host_platform"
                        "_device_count=N for forced CPU devices")
    p.add_argument("--regress", action="store_true",
                   help="after recording, judge this run's metrics "
                        "against the best prior BENCH_r*.json round "
                        "with the noise-aware trajectory rules "
                        "(nmfx.obs.regress — min-of-reps values, "
                        "per-metric relative thresholds) and exit 2 "
                        "on any regression: the self-judging gate for "
                        "hardware rounds (docs/observability.md "
                        "'Regression observatory')")
    p.add_argument("--compile-cache", default=None, metavar="DIR",
                   help="persistent XLA compilation cache directory: a "
                        "SECOND bench session re-loads this session's "
                        "compiled programs from disk instead of paying "
                        "cold_wall_s again (the jax_compilation_cache_dir "
                        "the CLI enables by default; recorded in the JSON "
                        "so cold numbers are interpretable)")
    args = p.parse_args()

    import jax

    if args.compile_cache:
        # best-effort like the CLI's default-on cache: an unwritable
        # path degrades to benchmarking uncached, never a traceback
        try:
            os.makedirs(args.compile_cache, exist_ok=True)
        except OSError as e:
            print(f"bench: compilation cache disabled ({e})",
                  file=sys.stderr)
            args.compile_cache = None
        else:
            jax.config.update("jax_compilation_cache_dir",
                              args.compile_cache)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.1)
    if args.durability_child:
        raise SystemExit(run_durability_child(args))
    if args.mesh_child:
        raise SystemExit(run_mesh_child(args))
    if args.dryrun_multichip is not None:
        import __graft_entry__ as graft

        graft.dryrun_multichip(args.dryrun_multichip)
        print(json.dumps({"dryrun_multichip": {
            "n_devices": args.dryrun_multichip, "ok": True}}))
        raise SystemExit(0)
    import numpy as np

    from nmfx.config import ConsensusConfig, InitConfig, SolverConfig
    from nmfx.datasets import grouped_matrix
    from nmfx.sweep import default_mesh, sweep

    ks = tuple(range(2, args.kmax + 1))
    if not ks:
        p.error("--kmax must be >= 2")
    if args.reps < 1:
        p.error("--reps must be >= 1")
    if args.backend == "pallas" and args.algorithm not in ("mu", "hals"):
        p.error("--backend pallas is only implemented for --algorithm "
                "mu/hals (use auto to fall back per algorithm)")
    from nmfx.config import PACKED_ALGORITHMS
    if (args.backend == "packed"
            and args.algorithm not in PACKED_ALGORITHMS):
        p.error("--backend packed is only implemented for --algorithm "
                f"{'/'.join(PACKED_ALGORITHMS)} (use auto to fall back "
                "per algorithm)")
    if args.verify:
        # the gate runs the three MU engines at its own fixed scaled
        # shape — reject, rather than silently ignore, arguments that
        # would suggest something else was verified
        for name in ("algorithm", "genes", "samples", "kmax", "restarts",
                     "backend", "grid_exec"):
            if getattr(args, name) != p.get_default(name):
                p.error(f"--verify gates the mu/hals/kl execution "
                        f"engines at a fixed scaled shape; "
                        f"--{name.replace('_', '-')} does not apply "
                        "(only --maxiter/--precision are honored)")
        # the gate asserts no MAX_ITER burns, which presumes the budget
        # lets every job converge (class-stability floor 402 + headroom)
        if args.maxiter < 2000:
            p.error("--verify needs --maxiter >= 2000 so every job can "
                    "converge; a lower cap would fail the gate's "
                    "no-MAX_ITER assertion on a healthy solver")
        # the gate is the ONE sanctioned fault-injection harness: it
        # translates the probe's env var into the explicit in-process
        # opt-in HERE, at startup, before the first trace. Library code
        # ignores the env var entirely (the nmfx.faults registry; lint
        # rule NMFX002), so an inherited variable alone can no longer
        # alter compiled production reload paths —
        # probe_fault_gate.py's subprocess protocol still works because
        # its subprocess IS this entrypoint. Since ISSUE 7 the canonical
        # arming is the faults registry (sched_mu's
        # enable_stale_reload_fault remains as a deprecation shim for
        # external probe harnesses).
        frac = float(os.environ.get("NMFX_FAULT_INJECT_STALE_RELOAD",
                                    "0") or 0)
        if frac > 0:
            from nmfx import faults

            faults.arm("sched.stale_reload", rate=frac)
            print("bench: stale-reload fault injection ARMED "
                  f"(fraction={frac}) — results from this process are "
                  "INVALID by design (fault-gate probe)",
                  file=sys.stderr)
        raise SystemExit(run_verify(args))
    seed = 123
    icfg = InitConfig()
    mesh = default_mesh()

    # 4 planted groups summing to exactly --samples columns
    sizes = [args.samples // 4] * 4
    sizes[0] += args.samples % 4
    a = grouped_matrix(args.genes, tuple(sizes), effect=2.0, seed=0)
    assert a.shape == (args.genes, args.samples)

    # which backends get measured this session: the requested one always;
    # on TPU the default mu invocation also measures the pallas engine in
    # the SAME session (the only way the two numbers are comparable here)
    backends = [args.backend]
    if (args.backend == "auto" and args.algorithm == "mu"
            and jax.default_backend() == "tpu"):
        backends.append("pallas")
    cfgs = {b: SolverConfig(algorithm=args.algorithm,
                            max_iter=args.maxiter,
                            matmul_precision=args.precision, backend=b)
            for b in backends}

    from nmfx.profiling import Profiler

    def timed_sweep(scfg, seed):
        """One timed end-to-end sweep with host materialization of every
        output inside the region: block_until_ready has been observed
        returning early on experimental platforms, and the pipeline is
        only done when consensus+stats land on host (that IS the
        workload's contract). ONE batched device_get — a per-array pull
        pays a tunnel round trip each (~50–150 ms depending on session;
        batching the 18 north-star pulls measured 0.4–1.4 s faster; the
        API pipeline batches identically).

        Round 7: the streamed harvest pipeline rides along — each
        rank's device→host copy AND its host rank selection
        (hclust/cophenetic/cutree) run in worker threads from the
        moment the rank is dispatched. Two walls come back: `wall`
        (consensus+stats on host — consensus_sweep_wall_s; NOTE the
        protocol changed in r07: harvest workers now run INSIDE the
        timed window, because that IS the default path being served —
        on a device-bound host they cost nothing, but on a CPU-starved
        container they contend with the solve, so vs_best against
        pre-r07 rounds carries that caveat, recorded in the protocol
        string) and `e2e_wall` (… AND rank selection complete —
        consensus_e2e_wall_s, the metric the old phase accounting
        never saw). Per rep, the streamed results are asserted EXACTLY
        equal to the sequential path's
        (`_pipeline_parity_problems`)."""
        from nmfx.harvest import HarvestPipeline

        run_cfg = ConsensusConfig(ks=ks, restarts=args.restarts,
                                  seed=seed, grid_exec=args.grid_exec)
        prof = Profiler()
        pipeline = HarvestPipeline(profiler=prof)
        t0 = time.perf_counter()
        with prof:
            raw = sweep(a, run_cfg, scfg, icfg, mesh, profiler=prof,
                        on_rank=pipeline.submit)
            with prof.phase("device_to_host"):
                host = jax.device_get(
                    {k: (raw[k].consensus, raw[k].iterations,
                         raw[k].stop_reasons) for k in ks})
            wall = time.perf_counter() - t0
            per_k = pipeline.results()
        e2e_wall = time.perf_counter() - t0
        return wall, e2e_wall, prof, host, per_k

    # cold runs first, one per backend: the cold sweep triggers every
    # compile at the exact static config (a different max_iter would be a
    # different jit cache entry); different seed than the timed reps so no
    # layer can serve cached results. TIMED: this is the cold-start number
    # a first-time user pays (the reference has no compile step at all —
    # its R workers start solving immediately, nmf.r:112) — recorded as
    # cold_wall_s, with compile_wall_s ≈ cold − warm-min the compile
    # share. The persistent compilation cache (CLI default-on;
    # JAX_COMPILATION_CACHE_DIR here) collapses it on re-runs.
    # Isolation from the exec-cache DISK store (same reasoning as the
    # --compile-cache note above): the serving/cold-persist stages below
    # persist serialized executables into a FRESH per-run temp directory,
    # created only after these cold runs and removed afterwards, so
    # cold_wall_s keeps measuring the true from-nothing compile wall.
    cold_wall = {}
    warm_cfg = ConsensusConfig(ks=ks, restarts=args.restarts,
                               seed=seed + 1, grid_exec=args.grid_exec)
    for b in backends:
        t_cold = time.perf_counter()
        warm = sweep(a, warm_cfg, cfgs[b], icfg, mesh)
        jax.device_get({k: warm[k].consensus for k in ks})
        cold_wall[b] = time.perf_counter() - t_cold
        print(f"bench: cold {b}: {cold_wall[b]:.2f}s", file=sys.stderr)

    # warm reps, interleaved across backends (rep 1 of every backend,
    # then rep 2, ...) so a drifting session penalizes/favors no backend.
    # The cold runs above already placed A through the device-resident
    # input cache, so every warm rep must transfer ZERO input bytes —
    # gated below on the module transfer counter (the honesty-counter
    # discipline of exec_cache.compile_count())
    from nmfx import data_cache

    h2d_transfers_before = data_cache.transfer_count()
    h2d_bytes_before = data_cache.h2d_bytes()
    reps = {b: [] for b in backends}  # wall seconds per rep
    e2e_reps = {b: [] for b in backends}  # ... + rank selection complete
    best = {}  # backend -> (wall, e2e_wall, prof, host) of fastest rep
    for r in range(args.reps):
        for b in backends:
            wall, e2e_wall, prof, host, per_k = timed_sweep(cfgs[b], seed)
            # hardware-truth gate on EVERY rep: refuse to print a record
            # any of whose runs had physically-impossible iteration
            # counts (see module docstring)
            its = {k: host[k][1] for k in ks}
            problems = _integrity_problems(cfgs[b], its,
                                           {k: host[k][2] for k in ks})
            # streamed-harvest parity gate on EVERY rep: the pipelined
            # path must be EXACTLY the sequential path (bitwise
            # consensus, signif-4 rho, memberships) — overlap must never
            # buy speed with drift
            problems += _pipeline_parity_problems(per_k, host, ks,
                                                  args.restarts)
            if problems:
                for prob in problems:
                    print(f"bench INTEGRITY FAILURE [{b} rep {r + 1}]: "
                          f"{prob}", file=sys.stderr)
                print("bench: refusing to record a physically-"
                      "implausible run — the solver path is broken on "
                      "this hardware (see VERDICT.md round 3 for the "
                      "incident this gate exists to catch)",
                      file=sys.stderr)
                raise SystemExit(2)
            reps[b].append(wall)
            e2e_reps[b].append(e2e_wall)
            if b not in best or wall < best[b][0]:
                best[b] = (wall, e2e_wall, prof, host)
            print(f"bench: warm {b} rep {r + 1}/{args.reps}: {wall:.2f}s "
                  f"(e2e {e2e_wall:.2f}s)", file=sys.stderr)

    warm_h2d_transfers = data_cache.transfer_count() - h2d_transfers_before
    warm_h2d_bytes = data_cache.h2d_bytes() - h2d_bytes_before
    if warm_h2d_transfers != 0:
        print(f"bench INTEGRITY FAILURE: warm reps paid "
              f"{warm_h2d_transfers} input transfer(s) "
              f"({warm_h2d_bytes} bytes) for a matrix the cold runs "
              "already placed — the device-resident input cache's "
              "zero-transfer warm-path contract is broken",
              file=sys.stderr)
        raise SystemExit(2)

    def stats(walls):
        s = sorted(walls)
        mid = len(s) // 2
        median = (s[mid] if len(s) % 2
                  else 0.5 * (s[mid - 1] + s[mid]))
        return {"min_s": round(s[0], 3), "median_s": round(median, 3),
                "reps_s": [round(w, 3) for w in walls]}

    # --- executable-reuse serving stage (nmfx.exec_cache) --------------
    # Two-request pipeline through the shape-bucketed AOT cache: request
    # 1 pays the bucket's one-time compile (measured); request 2 is a
    # DIFFERENT true shape in the same bucket — its dispatch must be
    # compile-free (the cache-hit path) and its host→device transfer was
    # prefetched during request 1's solve, so the only non-overlapped
    # transfer left is its own device→host pull. Integrity-gated like
    # every other number printed here.
    def run_serving_stage(exec_dir):
        from nmfx.config import ExecCacheConfig
        from nmfx.exec_cache import ExecCache

        scfg_s = cfgs[args.backend]
        ccfg_s = ConsensusConfig(ks=ks, restarts=args.restarts, seed=seed,
                                 grid_exec=args.grid_exec)
        # persist into the per-run temp dir: the miss request's compile
        # lands on disk, which is what the cold_persist stage's fresh
        # process re-serves from
        cache = ExecCache(ExecCacheConfig(cache_dir=exec_dir))
        if not cache.cacheable(ccfg_s, scfg_s, mesh):
            return {"skipped": "configuration not exec-cacheable "
                               "(see ExecCache.cacheable)"}
        # second dataset: ~4% smaller per dim, clamped per-dimension to
        # stay inside the first request's bucket (a shrink can cross a
        # lattice point at shapes near a bucket's floor)
        bucket = cache.bucket_shape(args.genes, args.samples)
        m2 = max(1, args.genes - max(1, args.genes // 25))
        if cache.bucket_shape(m2, 1)[0] != bucket[0]:
            m2 = args.genes
        n2 = max(4, args.samples - max(1, args.samples // 25))
        if cache.bucket_shape(1, n2)[1] != bucket[1]:
            n2 = args.samples
        sizes2 = [n2 // 4] * 4
        sizes2[0] += n2 % 4
        a2 = grouped_matrix(m2, tuple(sizes2), effect=2.0, seed=1)

        # request 1: miss — AOT compile (via the public entry record) +
        # solve. With cache_dir set, the miss path now ALSO serializes
        # and atomically writes the executable to disk; that persistence
        # cost rides inside miss_dispatch_s, so it is decomposed out as
        # miss_persist_store_s (≈ executable-call wall − compile wall)
        # to keep miss numbers comparable with pre-persistence rounds.
        t0 = time.perf_counter()
        entry1, _ = cache.executable(a.shape, ccfg_s, scfg_s, icfg, mesh)
        exec1_s = time.perf_counter() - t0
        placed1 = cache.prefetch(a, scfg_s, mesh)
        out1 = cache.run_sweep(placed1, ccfg_s, scfg_s, icfg, mesh)
        dispatch1_s = time.perf_counter() - t0  # includes the compile
        # double-buffer: request 2's transfer starts while 1 solves
        placed2 = cache.prefetch(a2, scfg_s, mesh)
        # measured upper bound on request 2's non-overlapped h2d: the
        # host wait for the in-flight prefetched transfer at dispatch
        # time (conservative — the wait itself still overlaps request
        # 1's device compute, and the device only consumes a_pad after
        # request 1 drains; measured rather than assumed 0 so a slow
        # link shows up here instead of hiding in dispatch/compute)
        t = time.perf_counter()
        jax.block_until_ready(placed2.a_pad)
        req2_h2d_block_s = time.perf_counter() - t
        # request 2 dispatch: cache hit — lookup + true-shape init only
        t2 = time.perf_counter()
        out2 = cache.run_sweep(placed2, ccfg_s, scfg_s, icfg, mesh)
        dispatch2_s = time.perf_counter() - t2  # the hit-path compile wall
        # request 1's results stream back while request 2 computes
        t = time.perf_counter()
        host1 = jax.device_get({k: (out1[k].iterations,
                                    out1[k].stop_reasons) for k in ks})
        req1_block_s = time.perf_counter() - t
        # request 2: separate remaining compute from the d2h pull its
        # async fetches could not hide
        t = time.perf_counter()
        jax.block_until_ready([out2[k].consensus for k in ks])
        req2_compute_s = time.perf_counter() - t
        t = time.perf_counter()
        host2 = jax.device_get({k: (out2[k].consensus, out2[k].iterations,
                                    out2[k].stop_reasons) for k in ks})
        req2_d2h_block_s = time.perf_counter() - t
        total_s = time.perf_counter() - t0

        for name, host in (("req1", {k: (None, v[0], v[1])
                                     for k, v in host1.items()}),
                           ("req2", host2)):
            problems = _integrity_problems(
                scfg_s, {k: host[k][1] for k in ks},
                {k: host[k][2] for k in ks})
            if problems:
                for prob in problems:
                    print(f"bench INTEGRITY FAILURE [serving {name}]: "
                          f"{prob}", file=sys.stderr)
                raise SystemExit(2)

        # non-overlapped transfer on the cache-hit request: h2d was
        # prefetched behind request 1's solve (0 blocked), leaving only
        # the final d2h pull; compare against the main bench's per-rep
        # BLOCKING transfer from THIS session. Since r07 the warm path's
        # h2d goes through the device-resident input cache (zero bytes
        # on warm reps — "host_to_device" no longer exists as a blocking
        # phase), so main_xfer_s is effectively its device_to_host
        main_xfer_s = (phase_s.get("host_to_device", 0.0)
                       + phase_s.get("device_to_host", 0.0))
        nonoverlap_s = req2_h2d_block_s + req2_d2h_block_s
        return {
            "bucket": list(cache.bucket_shape(args.genes, args.samples)),
            "shapes": [[args.genes, args.samples], [m2, n2]],
            "miss_dispatch_s": round(dispatch1_s, 3),
            "miss_compile_s": round(entry1.compile_s, 3),
            "miss_persist_store_s": round(
                max(exec1_s - entry1.compile_s, 0.0), 3),
            "hit_dispatch_s": round(dispatch2_s, 3),
            "hit_compile_free": dispatch2_s < 1.0,
            "req1_result_block_s": round(req1_block_s, 3),
            "req2_compute_block_s": round(req2_compute_s, 3),
            "req2_h2d_block_s": round(req2_h2d_block_s, 3),
            "req2_d2h_block_s": round(req2_d2h_block_s, 3),
            "req2_nonoverlapped_xfer_s": round(nonoverlap_s, 3),
            "main_path_xfer_s": round(main_xfer_s, 3),
            "xfer_reduction_vs_main": (
                None if main_xfer_s <= 0
                else round(1.0 - nonoverlap_s / main_xfer_s, 3)),
            "pipeline_total_s": round(total_s, 3),
            "cache_stats": cache.stats,
            "integrity": "ok",
        }

    # --- cold-persist stage (nmfx.exec_cache disk persistence) ---------
    # The serving stage above persisted the whole-grid executable into
    # exec_dir; this stage measures what a FRESH PROCESS pays to serve
    # the same sweep from that warm disk cache — the cold path as
    # deserialize-and-dispatch. The child is gated on the exec-cache
    # compile counter: with the disk entry present it must perform ZERO
    # .lower().compile() calls, or the stage (and the bench) fails. Also
    # measures parallel compilation: the per-rank executables
    # (pipeline_ranks) compile concurrently in a thread pool, and
    # compile_parallel_speedup = sum of per-entry compile walls over the
    # parallel wall — >1 whenever >=2 executables genuinely overlapped.
    def run_cold_persist_stage(exec_dir, serving):
        import subprocess

        if "skipped" in serving:
            return {"cold_persist_skipped": serving["skipped"]}
        if not any(name.endswith(".nmfxexec")
                   for name in os.listdir(exec_dir)):
            # e.g. a PJRT without executable serialization: the serving
            # stage warned and kept its entry in memory only
            return {"cold_persist_skipped":
                    "executable serialization unavailable on this "
                    "backend (no disk entry was written)"}
        from nmfx.config import ExecCacheConfig
        from nmfx.exec_cache import ExecCache

        scfg_s = cfgs[args.backend]
        ccfg_s = ConsensusConfig(ks=ks, restarts=args.restarts, seed=seed,
                                 grid_exec=args.grid_exec)
        out = {}
        # parallel per-rank compile (>=2 executables whenever the sweep
        # has >=2 ranks); in-memory only so the child's disk cache keeps
        # exactly the serving stage's whole-grid entry
        if len(ks) > 1:
            # measure PARALLEL COMPILATION, not XLA-cache deserialization:
            # with --compile-cache a second session would serve every
            # per-rank compile from jax's persistent cache in
            # milliseconds and the speedup would be meaningless — disable
            # it (config + memoized cache object, as tests/conftest.py
            # does) for the duration of the measurement
            cc_dir = jax.config.jax_compilation_cache_dir
            if cc_dir is not None:
                from jax._src import compilation_cache as _cc

                jax.config.update("jax_compilation_cache_dir", None)
                _cc.reset_cache()
            try:
                pcache = ExecCache(ExecCacheConfig(pipeline_ranks=True))
                # contention-honest protocol: the lowest rank compiles
                # ALONE first — the uncontended per-executable baseline.
                # (Summing per-entry walls measured DURING the parallel
                # phase would inflate the serial estimate by exactly the
                # core contention, fabricating ~N-fold "speedups" on
                # starved machines.) serial_estimate = solo × N rests on
                # per-rank compile costs being near-homogeneous: the
                # programs are structurally identical, only k differs.
                mk_ccfg = lambda kset: ConsensusConfig(  # noqa: E731
                    ks=kset, restarts=args.restarts, seed=seed,
                    grid_exec=args.grid_exec)
                t0 = time.perf_counter()
                rep = pcache.warm([a.shape], mk_ccfg((ks[0],)), scfg_s,
                                  icfg, mesh)
                solo_s = time.perf_counter() - t0
                t1 = time.perf_counter()
                rep += pcache.warm([a.shape], mk_ccfg(ks[1:]), scfg_s,
                                   icfg, mesh)
                par_wall = time.perf_counter() - t1
            finally:
                if cc_dir is not None:
                    jax.config.update("jax_compilation_cache_dir",
                                      cc_dir)
                    _cc.reset_cache()
            n_exec = len([r for r in rep if not r["cache_hit"]])
            serial_est = solo_s * n_exec
            pipeline_wall = solo_s + par_wall
            out["parallel_compile"] = {
                "executables": n_exec,
                "solo_compile_s": round(solo_s, 3),
                "parallel_wall_s": round(par_wall, 3),
                "serial_estimate_s": round(serial_est, 3)}
            out["compile_parallel_speedup"] = (
                round(serial_est / pipeline_wall, 3)
                if n_exec >= 2 and pipeline_wall > 0 else None)
            print(f"bench: parallel compile: {n_exec} per-rank "
                  f"executables, solo baseline {solo_s:.2f}s, "
                  f"remaining {len(ks) - 1} in {par_wall:.2f}s "
                  f"(serial estimate {serial_est:.2f}s)",
                  file=sys.stderr)
        cfg_json = json.dumps({
            "genes": args.genes, "samples": args.samples,
            "ks": list(ks), "restarts": args.restarts, "seed": seed,
            "grid_exec": args.grid_exec, "algorithm": args.algorithm,
            "maxiter": args.maxiter, "precision": args.precision,
            "backend": args.backend, "cache_dir": exec_dir})
        # no persistent XLA compile cache in the child: this stage
        # measures OUR disk layer alone
        child_env = {k: v for k, v in os.environ.items()
                     if not k.startswith("JAX_COMPILATION_CACHE")}
        t0 = time.perf_counter()
        proc = subprocess.run([sys.executable, "-c", _COLD_PERSIST_CHILD,
                               cfg_json], capture_output=True, text=True,
                              env=child_env)
        child_total = time.perf_counter() - t0
        if proc.returncode != 0:
            print("bench COLD-PERSIST FAILURE: fresh-process child "
                  f"failed:\n{proc.stderr[-3000:]}", file=sys.stderr)
            raise SystemExit(2)
        child = json.loads(proc.stdout.strip().splitlines()[-1])
        its_c = {k: np.asarray(child["its"][str(k)]) for k in ks}
        stops_c = {k: np.asarray(child["stops"][str(k)]) for k in ks}
        problems = _integrity_problems(scfg_s, its_c, stops_c)
        if child["compiles"] != 0:
            problems.append(
                f"fresh process performed {child['compiles']} compile(s) "
                "against a warm disk cache — the zero-compile cold-start "
                "contract is broken")
        if problems:
            for prob in problems:
                print(f"bench COLD-PERSIST FAILURE: {prob}",
                      file=sys.stderr)
            raise SystemExit(2)
        out.update({
            "cold_persist_wall_s": round(child["wall_s"], 3),
            "cold_persist_child_total_s": round(child_total, 3),
            "cold_persist_deserialize_s": round(child["deserialize_s"], 3),
            "cold_persist_vs_cold": round(
                child["wall_s"] / cold_wall[args.backend], 3),
            "cold_persist_compiles": child["compiles"],
            "cold_persist_integrity": "ok"})
        print(f"bench: cold_persist (fresh process, warm disk cache): "
              f"{child['wall_s']:.2f}s vs cold "
              f"{cold_wall[args.backend]:.2f}s", file=sys.stderr)
        return out

    # --- durability rung (ISSUE 9, detail.durability) ------------------
    # Kill a checkpointed
    # sweep subprocess at ~50% chunk completion (the injected preemption
    # lands between a chunk's solve and its commit — the in-flight
    # chunk is LOST), resume it in-process, and gate the resumed result
    # BIT-IDENTICAL against an uninterrupted checkpointed reference of
    # the same plan (exit 2 on mismatch). Books resume_overhead_s (the
    # resume wall beyond the missing chunks' pro-rata share of the full
    # wall: ledger scan + manifest validation + re-warm) and
    # wasted_work_frac (chunks solved more than once across kill+resume
    # — exactly the in-flight chunk the preemption discarded).
    def run_durability_stage():
        import shutil
        import subprocess
        import tempfile

        from nmfx import checkpoint as ckpt
        from nmfx.api import nmfconsensus
        from nmfx.config import CheckpointConfig

        scfg_d = cfgs[args.backend]
        ks_d = ks[:2]
        restarts_d = min(args.restarts, 8)
        chunk_d = max(1, restarts_d // 4)
        plan = ckpt.plan_chunks(restarts_d, chunk_d)
        total_chunks = len(plan) * len(ks_d)
        ref_dir = tempfile.mkdtemp(prefix="nmfx-bench-dur-ref-")
        kill_dir = tempfile.mkdtemp(prefix="nmfx-bench-dur-kill-")

        def gate(probs):
            if probs:
                for prob in probs:
                    print(f"bench DURABILITY PARITY FAILURE: {prob}",
                          file=sys.stderr)
                raise SystemExit(2)

        try:
            t0 = time.perf_counter()
            ref = nmfconsensus(
                a, ks=ks_d, restarts=restarts_d, seed=seed,
                solver_cfg=scfg_d,
                checkpoint=CheckpointConfig(ref_dir,
                                            every_n_restarts=chunk_d))
            full_wall = time.perf_counter() - t0
            preempt_after = max(1, total_chunks // 2)
            cmd = [sys.executable, os.path.abspath(__file__),
                   "--durability-child", kill_dir,
                   "--preempt-after", str(preempt_after),
                   "--durability-chunk", str(chunk_d),
                   "--genes", str(args.genes),
                   "--samples", str(args.samples),
                   "--kmax", str(ks_d[-1]),
                   "--restarts", str(restarts_d),
                   "--maxiter", str(args.maxiter),
                   "--precision", args.precision,
                   "--algorithm", args.algorithm,
                   "--backend", args.backend]
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 137:
                print("bench DURABILITY FAILURE: kill-at-50% child "
                      f"exited {proc.returncode}, expected 137 "
                      "(injected preemption)\n"
                      + proc.stderr[-2000:], file=sys.stderr)
                raise SystemExit(2)
            child_solved = None
            for line in proc.stdout.splitlines():
                try:
                    child_solved = json.loads(
                        line)["durability_child"]["solved_chunks"]
                except (ValueError, KeyError, TypeError):
                    continue
            persisted = sum(
                1 for name in os.listdir(kill_dir)
                if name.startswith("k") and name.endswith(".npz"))
            before = ckpt.chunks_solved_count()
            t0 = time.perf_counter()
            res = nmfconsensus(
                a, ks=ks_d, restarts=restarts_d, seed=seed,
                solver_cfg=scfg_d,
                checkpoint=CheckpointConfig(kill_dir,
                                            every_n_restarts=chunk_d))
            resume_wall = time.perf_counter() - t0
            resumed = ckpt.chunks_solved_count() - before
            gate(_serve_parity_problems(res, ref,
                                        "durability kill-resume"))
            solved_total = (child_solved if child_solved is not None
                            else persisted) + resumed
            wasted = (solved_total - total_chunks) / total_chunks
            overhead = resume_wall - full_wall * (
                (total_chunks - persisted) / total_chunks)
            return {
                "total_chunks": total_chunks,
                "chunk_restarts": chunk_d,
                "persisted_at_kill": persisted,
                "child_solved_chunks": child_solved,
                "resumed_chunks": resumed,
                "full_wall_s": round(full_wall, 3),
                "resume_wall_s": round(resume_wall, 3),
                "resume_overhead_s": round(max(overhead, 0.0), 3),
                "wasted_work_frac": round(max(wasted, 0.0), 4),
                "parity": "ok",
            }
        finally:
            shutil.rmtree(ref_dir, ignore_errors=True)
            shutil.rmtree(kill_dir, ignore_errors=True)

    def run_mesh_stage():
        """Mesh rung (ISSUE 19, detail.mesh): run :func:`run_mesh_child`
        under 8 forced CPU devices (a subprocess — XLA fixes the device
        count at import) and hard-gate its verdict: scaling curves are
        data, but a meshed-vs-unmeshed mismatch, a comm-model-vs-HLO
        divergence, or a wrong placement is exit 2. The stage result
        carries a MULTICHIP-record-shaped ``record`` block so mesh
        rounds read like the driver's multichip probes."""
        import subprocess

        n_forced = 8
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "") +
            f" --xla_force_host_platform_device_count={n_forced}"
        ).strip()
        cmd = [sys.executable, os.path.abspath(__file__),
               "--mesh-child",
               "--genes", str(args.genes),
               "--samples", str(args.samples),
               "--restarts", str(args.restarts),
               "--maxiter", str(args.maxiter),
               "--kmax", str(args.kmax),
               "--algorithm", args.algorithm]
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              env=env)
        detail = None
        for line in proc.stdout.splitlines():
            try:
                detail = json.loads(line)["mesh_child"]
            except (ValueError, KeyError, TypeError):
                continue
        ok = proc.returncode == 0 and detail is not None \
            and detail.get("ok", False)
        record = {"n_devices": n_forced, "rc": proc.returncode,
                  "ok": ok, "skipped": False,
                  "tail": "" if ok else proc.stderr[-800:]}
        if not ok:
            probs = (detail or {}).get("problems") or \
                [f"mesh child exited {proc.returncode} without a "
                 "verdict"]
            for prob in probs:
                print(f"bench MESH STAGE FAILURE: {prob}",
                      file=sys.stderr)
            print(proc.stderr[-2000:], file=sys.stderr)
            raise SystemExit(2)
        detail["record"] = record
        return detail

    def run_atlas_stage():
        """Atlas rung (ISSUE 17, detail.atlas): the out-of-core tile
        pipeline + sparse ingestion, exercised for real on CPU by
        forcing the tile budget small enough that the bench matrix no
        longer fits in a single resident tile. Four sub-rungs:

        ladder
            tiled sweeps at 1/4, 1/2 and full feature count under the
            forced budget; the full rung MUST plan >1 tile (that IS the
            larger-than-budget condition) — restarts/s, streamed-pass
            and h2d-byte counters, and the h2d-overlap split from the
            profiler's overlap accounting (``xfer.h2d_tile`` = dispatch
            hidden behind compute, ``xfer.h2d_tile_wait`` = the
            unhidden stall; their ratio is what prefetch buys).
        parity (exit-2 gates)
            single-tile delegation must be BIT-identical to the dense
            sweep (the in-core contract); a multi-tile sweep with
            prefetch ON must be bit-identical to prefetch OFF (overlap
            must never change math); multi-tile vs dense is agreement-
            gated (ARI/rho — tile-order f32 Gram accumulation is a
            different summation order, so bitwise is not the contract
            there), gated only at hardware shapes like the sketched
            stage.
        sparse
            ``make_sparse_design`` at 90%/99% sparsity: restarts/s for
            the BCOO ingestion path vs the densified twin through the
            plain dense path, plus their agreement report (the hard
            sparse==densified gates live in tests/test_sparse.py at
            controlled shapes; the bench records the measurement).
        resume (exit-2 gates)
            kill-at-<=50% for a TILED checkpointed run: the child
            re-enters this entrypoint with --atlas-tile-rows, the
            injected preemption lands mid-matrix AFTER a partial
            checkpoint record (.part.npz) hit disk, the child exits
            137; the parent asserts the partial survived, resumes,
            asserts the partial was CONSUMED (the
            nmfx_tile_partial_resumes_total counter moved) and then
            cleared, and gates the resumed result bit-identical to an
            uninterrupted run."""
        import dataclasses as _dc
        import shutil
        import subprocess
        import tempfile

        from nmfx import checkpoint as ckpt
        from nmfx import tiles
        from nmfx.agreement import consensus_agreement
        from nmfx.api import nmfconsensus
        from nmfx.config import TILED_ALGORITHMS, CheckpointConfig
        from nmfx.datasets import make_sparse_design

        scfg_base = cfgs[args.backend]
        if scfg_base.algorithm not in TILED_ALGORITHMS:
            return {"skipped": f"algorithm {scfg_base.algorithm!r} is "
                               "outside the Gram-accumulation tiled "
                               f"family {TILED_ALGORITHMS}"}
        if scfg_base.backend in ("pallas", "sketched"):
            return {"skipped": f"backend {scfg_base.backend!r} cannot "
                               "stream tiles (SolverConfig contract)"}
        # stage-local iteration budget: the rung measures streaming
        # mechanics and parity, not convergence depth
        mi_t = min(args.maxiter, 500)
        scfg_dense = _dc.replace(scfg_base, max_iter=mi_t)
        ks_t = ks[:2]
        restarts_t = min(args.restarts, 8)
        itemsize = np.dtype(scfg_dense.dtype).itemsize

        def gate(problems, what):
            if problems:
                for prob in problems:
                    print(f"bench ATLAS {what} FAILURE: {prob}",
                          file=sys.stderr)
                raise SystemExit(2)

        def run_one(mat, scfg_r, *, prof=None, ckpt_cfg=None,
                    seed_r=seed):
            t0 = time.perf_counter()
            if prof is not None:
                with prof:
                    res = nmfconsensus(
                        mat, ks=ks_t, restarts=restarts_t, seed=seed_r,
                        solver_cfg=scfg_r, use_mesh=False,
                        profiler=prof, checkpoint=ckpt_cfg)
            else:
                res = nmfconsensus(
                    mat, ks=ks_t, restarts=restarts_t, seed=seed_r,
                    solver_cfg=scfg_r, use_mesh=False,
                    checkpoint=ckpt_cfg)
            return res, time.perf_counter() - t0

        total_restarts_t = restarts_t * len(ks_t)
        detail = {}
        try:
            # --- ladder: force the budget so the FULL shape overflows
            # a single tile (two resident buffers fit the budget, so
            # tiles are sized budget/2 -> the smallest rung streams 2
            # tiles, the full rung ~8)
            m_rungs = sorted({max(64, args.genes // 4),
                              max(64, args.genes // 2), args.genes})
            budget = 2 * max(64, args.genes // 8) * args.samples \
                * itemsize
            tiles.set_tile_budget_bytes(budget)
            scfg_auto = _dc.replace(scfg_dense, tile_rows="auto")
            ladder = []
            for m_r in m_rungs:
                a_r = a[:m_r]
                plan_r = tiles.plan_for(a_r, scfg_auto)
                prof = Profiler()
                passes0 = tiles._tile_passes_total.value()
                h2d0 = tiles._tile_h2d_bytes_total.value()
                _, wall_r = run_one(a_r, scfg_auto, prof=prof)
                xfer = prof.phases.get(tiles.TILE_XFER_PHASE)
                wait = prof.phases.get(tiles.TILE_WAIT_PHASE)
                xfer_s = xfer.seconds if xfer is not None else 0.0
                wait_s = wait.seconds if wait is not None else 0.0
                h2d_total = xfer_s + wait_s
                ladder.append({
                    "shape": f"{m_r}x{args.samples}",
                    "device_bytes": m_r * args.samples * itemsize,
                    "tile_rows": plan_r.tile_rows,
                    "n_tiles": plan_r.n_tiles,
                    "wall_s": round(wall_r, 3),
                    "restarts_per_s": round(total_restarts_t / wall_r,
                                            2),
                    "tile_passes": int(
                        tiles._tile_passes_total.value() - passes0),
                    "h2d_bytes": int(
                        tiles._tile_h2d_bytes_total.value() - h2d0),
                    "h2d_xfer_s": round(xfer_s, 3),
                    "h2d_wait_s": round(wait_s, 3),
                    # fraction of tile-transfer time hidden behind
                    # compute (dispatch vs stall); 1.0 = fully
                    # overlapped
                    "h2d_hidden_frac": round(
                        xfer_s / h2d_total, 3) if h2d_total > 0
                    else None,
                    "overlap_ratio": prof.audit(
                        wall_r)["overlap_ratio"],
                })
            top = ladder[-1]
            if top["n_tiles"] < 2:
                gate([f"full rung {top['shape']} planned "
                      f"{top['n_tiles']} tile(s) under the forced "
                      f"{budget}-byte budget — the larger-than-budget "
                      "condition never happened"], "LADDER")
            detail["ladder"] = ladder
            detail["out_of_core"] = top
            detail["tile_budget_bytes"] = budget
            tiles.set_tile_budget_bytes(None)

            # --- parity gates on the smallest rung (cost-bounded)
            m0 = m_rungs[0]
            a0 = a[:m0]
            ref_dense, _ = run_one(a0, scfg_dense)
            single, _ = run_one(
                a0, _dc.replace(scfg_dense, tile_rows=m0))
            gate(_serve_parity_problems(single, ref_dense,
                                        "atlas single-tile delegation"),
                 "PARITY")
            tr_multi = max(1, m0 // 3)
            multi_on, _ = run_one(
                a0, _dc.replace(scfg_dense, tile_rows=tr_multi))
            tiles.set_tile_prefetch(False)
            multi_off, _ = run_one(
                a0, _dc.replace(scfg_dense, tile_rows=tr_multi))
            tiles.set_tile_prefetch(True)
            gate(_serve_parity_problems(multi_on, multi_off,
                                        "atlas prefetch on-vs-off"),
                 "PARITY")
            agree = consensus_agreement(multi_on, ref_dense)
            # same TOY-SHAPE policy as the sketched stage: at smoke
            # shapes the dense consensus is itself unstable, so the
            # agreement numbers are recorded but only gated at
            # hardware shapes
            agreement_gated = args.genes >= 1000 and args.samples >= 100
            if agreement_gated and agree["min_ari"] < 0.75:
                gate([f"multi-tile vs dense min ARI "
                      f"{agree['min_ari']:.3f} < 0.75"], "AGREEMENT")
            if agreement_gated and agree["max_rho_gap"] > 0.15:
                gate([f"multi-tile vs dense |d rho| "
                      f"{agree['max_rho_gap']:.3f} > 0.15"],
                     "AGREEMENT")
            detail["parity"] = {
                "single_tile_delegation": "bitwise-ok",
                "prefetch_on_off": "bitwise-ok",
                "multi_tile_tiles": -(-m0 // tr_multi),
                "vs_dense_min_ari": round(agree["min_ari"], 3),
                "vs_dense_max_rho_gap": round(agree["max_rho_gap"], 4),
                "agreement_gated": agreement_gated,
            }

            # --- sparse ingestion: 90% / 99% sparsity vs the
            # densified twin through the plain dense path
            m_sp = min(args.genes, 1500)
            n_sp = min(args.samples, 200)
            sparse_detail = {}
            for dens, tag in ((0.10, "density_90"), (0.01,
                                                     "density_99")):
                sp = make_sparse_design(m_sp, n_sp, k=4, density=dens,
                                        seed=11)
                res_sp, wall_sp = run_one(sp, scfg_dense)
                res_dn, wall_dn = run_one(sp.toarray(), scfg_dense)
                rep = consensus_agreement(res_sp, res_dn)
                sparse_detail[tag] = {
                    "shape": f"{m_sp}x{n_sp}",
                    "nnz": int(sp.nnz),
                    "density": round(sp.density, 4),
                    "sparse_wall_s": round(wall_sp, 3),
                    "dense_wall_s": round(wall_dn, 3),
                    "sparse_restarts_per_s": round(
                        total_restarts_t / wall_sp, 2),
                    "dense_restarts_per_s": round(
                        total_restarts_t / wall_dn, 2),
                    # >1 = the nonzero-only contraction beats the
                    # dense GEMM on this host (expect <1 on CPU
                    # containers, >1 only where nnz/mn is far below
                    # the host's GEMM efficiency crossover)
                    "speedup_vs_dense": round(wall_dn / wall_sp, 3),
                    "min_ari_vs_densified": round(rep["min_ari"], 3),
                }
            detail["sparse"] = sparse_detail

            # --- kill-at-<=50% mid-matrix resume (tiled + durable
            # ledger)
            tr_kill = max(1, args.genes // 4)
            scfg_kill = _dc.replace(scfg_dense, tile_rows=tr_kill)
            chunk_t = max(1, restarts_t // 4)
            total_chunks = len(ckpt.plan_chunks(restarts_t, chunk_t)) \
                * len(ks_t)
            ref_dir = tempfile.mkdtemp(prefix="nmfx-bench-atlas-ref-")
            kill_dir = tempfile.mkdtemp(prefix="nmfx-bench-atlas-kill-")
            try:
                t0 = time.perf_counter()
                ref = nmfconsensus(
                    a, ks=ks_t, restarts=restarts_t, seed=seed,
                    solver_cfg=scfg_kill, use_mesh=False,
                    checkpoint=CheckpointConfig(
                        ref_dir, every_n_restarts=chunk_t))
                full_wall = time.perf_counter() - t0
                # every tiled chunk polls the preempt site at each
                # check boundary AND once post-solve (>= 2 polls per
                # chunk), so the Nth poll with N = total_chunks lands
                # inside the first half of the chunk sequence —
                # kill-at-<=50%, mid-matrix
                cmd = [sys.executable, os.path.abspath(__file__),
                       "--durability-child", kill_dir,
                       "--preempt-after", str(total_chunks),
                       "--durability-chunk", str(chunk_t),
                       "--atlas-tile-rows", str(tr_kill),
                       "--genes", str(args.genes),
                       "--samples", str(args.samples),
                       "--kmax", str(ks_t[-1]),
                       "--restarts", str(restarts_t),
                       "--maxiter", str(mi_t),
                       "--precision", args.precision,
                       "--algorithm", args.algorithm,
                       "--backend", args.backend]
                proc = subprocess.run(cmd, capture_output=True,
                                      text=True)
                if proc.returncode != 137:
                    gate([f"kill child exited {proc.returncode}, "
                          "expected 137 (injected preemption)\n"
                          + proc.stderr[-2000:]], "RESUME")
                parts = [name for name in os.listdir(kill_dir)
                         if name.endswith(".part.npz")]
                if not parts:
                    gate(["no .part.npz partial survived the kill — "
                          "the preemption did not land mid-matrix"],
                         "RESUME")
                committed = sum(
                    1 for name in os.listdir(kill_dir)
                    if name.endswith(".npz")
                    and not name.endswith(".part.npz"))
                resumes0 = tiles._tile_partial_resumes_total.value()
                t0 = time.perf_counter()
                res = nmfconsensus(
                    a, ks=ks_t, restarts=restarts_t, seed=seed,
                    solver_cfg=scfg_kill, use_mesh=False,
                    checkpoint=CheckpointConfig(
                        kill_dir, every_n_restarts=chunk_t))
                resume_wall = time.perf_counter() - t0
                partial_resumes = int(
                    tiles._tile_partial_resumes_total.value()
                    - resumes0)
                gate(_serve_parity_problems(res, ref,
                                            "atlas kill-resume"),
                     "RESUME")
                if partial_resumes < 1:
                    gate(["the surviving partial was recomputed, not "
                          "resumed (nmfx_tile_partial_resumes_total "
                          "did not move)"], "RESUME")
                leftover = [name for name in os.listdir(kill_dir)
                            if name.endswith(".part.npz")]
                if leftover:
                    gate([f"partials not cleared after commit: "
                          f"{leftover}"], "RESUME")
                detail["resume"] = {
                    "tile_rows": tr_kill,
                    "total_chunks": total_chunks,
                    "partials_at_kill": len(parts),
                    "committed_at_kill": committed,
                    "partial_resumes": partial_resumes,
                    "full_wall_s": round(full_wall, 3),
                    "resume_wall_s": round(resume_wall, 3),
                    "resume_overhead_s": round(
                        max(resume_wall - full_wall
                            * ((total_chunks - committed)
                               / total_chunks), 0.0), 3),
                    "parity": "ok",
                }
            finally:
                shutil.rmtree(ref_dir, ignore_errors=True)
                shutil.rmtree(kill_dir, ignore_errors=True)
        finally:
            tiles.set_tile_budget_bytes(None)
            tiles.set_tile_prefetch(True)
        return detail

    # --- observability stage (ISSUE 10/13, detail.obs) -----------------
    # The telemetry layer's own cost, tracked across BENCH rounds so it
    # can never silently grow: warm-path reps with the structured
    # tracer AND per-dispatch roofline attribution enabled vs both
    # disabled (the metrics registry is ALWAYS on — it IS the module
    # counters every gate above reads — so the toggleable cost is span
    # recording plus the costmodel arithmetic/histograms each dispatch
    # books), gated at < 3% of the warm e2e wall (exit 2). Also records
    # the per-sweep trace event count, the attributed-dispatch count,
    # and the flight-recorder postmortem size, so a span-explosion or
    # event-flood regression shows up as a number, not a vibe.
    def run_fleet_rung():
        """Multi-process aggregation rung (ISSUE 14): subprocess
        publishers write known series into a shared telemetry_dir and
        the merged fleet view must be EXACT — counters equal the
        per-instance sums, merged histogram quantiles equal the
        union-of-observations quantiles — then an injected failure
        breach (the chaos rung's ``solve.nonfinite`` site quarantining
        every lane under a ``min_restarts`` floor, so live served
        requests FAIL) must flip the fleet availability burn alert
        with the transition landing in the flight recorder, and
        ``nmfx-top`` must render a non-empty dashboard from the run's
        live telemetry dir. Exit 2 on any miss."""
        import shutil
        import subprocess
        import tempfile
        import textwrap

        from nmfx import faults as faults_mod
        from nmfx.datasets import grouped_matrix
        from nmfx.obs import flight as obs_flight
        from nmfx.obs import metrics as obs_metrics
        from nmfx.obs import slo as obs_slo
        from nmfx.obs import top as obs_top
        from nmfx.obs.aggregate import FleetCollector
        from nmfx.serve import NMFXServer, ServeConfig

        here_dir = os.path.dirname(os.path.abspath(__file__))
        tdir = tempfile.mkdtemp(prefix="nmfx-bench-fleet-")
        n_children = 2
        child_src = textwrap.dedent("""
            import sys
            from nmfx.obs import export, metrics
            tdir, idx = sys.argv[1], int(sys.argv[2])
            reg = metrics.MetricsRegistry()
            c = reg.counter("nmfx_serve_dispatches_total",
                            "dispatches", ("packed",))
            c.inc(10 + idx, packed="false")
            h = reg.histogram("nmfx_serve_solve_seconds", "solve wall")
            for i in range(40):
                h.observe(0.002 * (i + 1) * (idx + 1))
            export.TelemetryPublisher(
                tdir, instance=f"bench-child-{idx}", role="bench",
                registry=reg).publish_once()
        """)
        try:
            script = os.path.join(tdir, "publisher.py")
            with open(script, "w") as f:
                f.write(child_src)
            env = dict(os.environ, JAX_PLATFORMS="cpu",
                       PYTHONPATH=here_dir + os.pathsep
                       + os.environ.get("PYTHONPATH", ""))
            procs = [subprocess.Popen(
                [sys.executable, script, tdir, str(i)],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True, env=env) for i in range(n_children)]
            errs = []
            for p in procs:
                _, e = p.communicate(timeout=240)
                if p.returncode != 0:
                    errs.append(e[-2000:])
            if errs:
                print("bench FLEET FAILURE: subprocess publisher "
                      f"died: {errs}", file=sys.stderr)
                raise SystemExit(2)
            collector = FleetCollector(tdir, stale_after_s=600.0)
            snap = collector.fleet_snapshot()
            got = snap["nmfx_serve_dispatches_total"]["series"][
                ("false",)]
            want = sum(10 + i for i in range(n_children))
            if got != want:
                print("bench FLEET FAILURE: merged counter "
                      f"{got} != exact per-instance sum {want}",
                      file=sys.stderr)
                raise SystemExit(2)
            # merged quantiles vs one histogram over the union of every
            # child's observations — equality, not tolerance
            union = obs_metrics.MetricsRegistry().histogram(
                "bench_fleet_union_seconds", "")
            for idx in range(n_children):
                for i in range(40):
                    union.observe(0.002 * (i + 1) * (idx + 1))
            for q in (0.5, 0.9, 0.99):
                mq = collector.quantile("nmfx_serve_solve_seconds", q,
                                        snapshot=snap)
                uq = union.quantile(q)
                if mq != uq:
                    print("bench FLEET FAILURE: merged quantile "
                          f"q={q} {mq} != union quantile {uq}",
                          file=sys.stderr)
                    raise SystemExit(2)
            # SLO breach: serve requests whose every lane the armed
            # chaos site quarantines (InsufficientRestarts =>
            # outcome=failed on the live e2e histogram), published
            # into the same ledger
            engine = obs_slo.SLOEngine(
                snapshot_fn=collector.fleet_snapshot)
            a_f = grouped_matrix(60, (4, 4, 4, 4), effect=2.0, seed=0)
            ks_f, restarts_f = (2,), 2
            faults_mod.arm("solve.nonfinite",
                           lanes=tuple((ks_f[0], r)
                                       for r in range(restarts_f)))
            try:
                with NMFXServer(ServeConfig(
                        pack=False, telemetry_dir=tdir,
                        telemetry_interval_s=0.2)) as srv:
                    # baseline AFTER the server's first publish: the
                    # published registry is process-CUMULATIVE, so the
                    # earlier bench stages' e2e history must be in the
                    # t0 cut — the windowed delta below is then exactly
                    # this rung's injected failures, whether the rung
                    # runs standalone or after the full traffic stage
                    srv._publisher.publish_once()
                    t0 = time.time()
                    engine.evaluate(now=t0)
                    futs = [srv.submit(
                        a_f, ks=ks_f, restarts=restarts_f,
                        min_restarts=restarts_f,
                        solver_cfg=SolverConfig(max_iter=60))
                        for _ in range(3)]
                    failed = sum(
                        1 for f in futs
                        if f.exception(timeout=240) is not None)
            finally:
                faults_mod.disarm("solve.nonfinite")
            if failed != 3:
                print("bench FLEET FAILURE: expected every "
                      "quarantined request to fail typed, got "
                      f"{failed}/3", file=sys.stderr)
                raise SystemExit(2)
            status = engine.evaluate(now=t0 + 300)
            avail = status["objectives"]["availability"]
            transitions = obs_flight.default_recorder().events(
                "slo.transition")
            flipped = [e for e in transitions
                       if e["objective"] == "availability"
                       and e["to_state"] == "fast_burn"]
            if avail["state"] != "fast_burn" or not flipped:
                print("bench FLEET FAILURE: injected failure breach "
                      "did not flip the availability burn alert "
                      f"(state={avail['state']}, "
                      f"transitions={len(flipped)})", file=sys.stderr)
                raise SystemExit(2)
            # nmfx-top renders a non-empty dashboard from the live dir
            frame = obs_top.gather(
                FleetCollector(tdir, stale_after_s=600.0),
                obs_slo.SLOEngine(snapshot_fn=collector.fleet_snapshot))
            text = obs_top.render_text(frame, tdir)
            if "bench-child-0" not in text \
                    or "slo availability" not in text:
                print("bench FLEET FAILURE: nmfx-top rendered an "
                      f"empty/incomplete dashboard:\n{text}",
                      file=sys.stderr)
                raise SystemExit(2)
            return {
                "instances": len(frame["instances"]),
                "counter_merge": "exact",
                "quantile_merge": "exact",
                "slo_alert_flip": "ok",
                "top_render": "ok",
                "failed_requests": failed,
            }
        finally:
            shutil.rmtree(tdir, ignore_errors=True)

    def run_obs_stage():
        from nmfx.obs import costmodel, flight, metrics, trace

        scfg_o = cfgs[args.backend]
        tracer = trace.default_tracer()
        walls = {False: [], True: []}
        trace_events = 0
        attributed = 0
        obs_reps = 3
        try:
            for _ in range(obs_reps):
                # interleaved off/on so session drift penalizes
                # neither arm
                for enabled in (False, True):
                    if enabled:
                        tracer.clear()
                        trace.enable()
                        costmodel.reset_perf()
                        costmodel.enable_attribution()
                    else:
                        costmodel.disable_attribution()
                    try:
                        _, e2e_wall_o, _, _, _ = timed_sweep(scfg_o,
                                                             seed)
                    finally:
                        if enabled:
                            trace_events = tracer.event_count()
                            attributed = sum(
                                rec["dispatches"] for rec in
                                costmodel.perf_summary()
                                ["kinds"].values())
                            trace.disable()
                    walls[enabled].append(e2e_wall_o)
        finally:
            # attribution is ON by default — the off arm's disable
            # must never leak past this stage
            costmodel.enable_attribution()
        off = min(walls[False])
        on = min(walls[True])
        overhead_frac = (on - off) / off
        # the postmortem artifact as it would be written right now
        # (built in-memory; no dump directory is configured in bench)
        flight.dump("bench-obs-probe")
        dump_bytes = len(json.dumps(flight.last_dump()))
        snap = metrics.registry().snapshot()
        series_count = sum(len(rec["series"]) for rec in snap.values())
        # min-of-reps is the low-noise estimator, but single-digit-ms
        # timer scatter on a loaded host can still exceed 3% of a short
        # wall; the 50 ms absolute floor only matters when 3% of the
        # wall is smaller than timer noise
        budget = max(0.03 * off, 0.05)
        if on - off >= budget:
            print("bench OBS OVERHEAD FAILURE: warm e2e wall "
                  f"{off:.3f}s untraced vs {on:.3f}s traced+attributed "
                  f"({overhead_frac:.1%} overhead, gate < 3%) — span "
                  "recording or dispatch attribution has crept into a "
                  "hot path (per-iteration instead of per-phase/"
                  "per-dispatch?)", file=sys.stderr)
            raise SystemExit(2)
        if attributed < 1:
            print("bench OBS FAILURE: the attributed arm recorded no "
                  "perf-attributed dispatches — the per-dispatch "
                  "attribution wiring is dead (sweep/exec_cache "
                  "_attribute_dispatch)", file=sys.stderr)
            raise SystemExit(2)
        fleet = run_fleet_rung()
        print(f"bench: fleet aggregation rung: {json.dumps(fleet)}",
              file=sys.stderr)
        return {
            "fleet": fleet,
            "wall_untraced_s": round(off, 3),
            "wall_traced_s": round(on, 3),
            "overhead_frac": round(overhead_frac, 4),
            "overhead_gate": "ok",
            "reps": obs_reps,
            "trace_events_per_sweep": trace_events,
            "perf_attributed_dispatches": attributed,
            "flight_dump_bytes": dump_bytes,
            "metric_series": series_count,
        }

    # --- sketched-engine stage (ISSUE 12, detail.sketched) -------------
    # backend="sketched" vs the exact engine on the bench matrix:
    # restarts/s for both arms, ANALYTIC FLOPs-per-restart, and the
    # consensus-level agreement gate (exit 2 on a miss).
    def run_sketched_stage():
        """Measurement protocol (the cold_persist discipline —
        documented here because the numbers need interpreting):
        wall-clock compression on a CPU container is meaningless (the
        container's GEMM throughput bears no relation to the MXU's the
        engine targets), so FLOPs-per-restart are recorded
        ANALYTICALLY — model FLOPs/iteration are exact shape-derived
        functions for both engines (``nmfx.obs.costmodel``'s mu and
        sketched-family entries), multiplied by
        the iteration counts each arm actually ran — which makes
        ``flops_compression_per_restart`` meaningful on every host.
        The restarts/s walls ride along as hardware-host measurements;
        only a TPU session's numbers are comparable across rounds. The
        AGREEMENT gate is hardware-independent: at the bench matrix's
        structured rank the sketched and exact pipelines' consensus
        memberships must agree (min ARI over seeds >= the recorded
        threshold, rho gap bounded) — the same statistical contract
        tests/test_sketched.py pins on the bundled dataset — and every
        sketched result must carry the quality tag. Exit 2 on any
        miss."""
        import dataclasses as _dc

        from nmfx.agreement import consensus_agreement
        from nmfx.api import nmfconsensus
        from nmfx.config import SKETCHED_ALGORITHMS
        from nmfx.solvers.sketched import resolve_dim

        scfg_e = cfgs[args.backend]
        if scfg_e.algorithm != "mu":
            # the AGREEMENT gate is calibrated on mu (ISSUE 12
            # development measurements; the other sketched algorithm,
            # hals, has an exact consensus that is itself unstable at
            # the structured rank — ARI ~0.7 vs planted truth — so
            # exact-vs-sketched agreement has no gateable signal there)
            return {"skipped": f"algorithm {scfg_e.algorithm!r}: the "
                               "sketched agreement gate is calibrated "
                               "for mu"
                    + ("" if scfg_e.algorithm in SKETCHED_ALGORITHMS
                       else " (and this algorithm has no sketched "
                            "form)")}
        # STAGE-LOCAL iteration budget (part of the recorded protocol):
        # the agreement contract is pinned at the bounded-budget regime
        # quality-elastic serving actually degrades into. At very long
        # budgets (>= thousands of iterations) an individual sketched
        # restart can settle into a DIFFERENT optimization basin than
        # its exact twin — a legitimate property of an approximate
        # engine, measured ~1 seed in 3 at max_iter=3000 on the 4-group
        # design — which would make a gate at args.maxiter flaky
        # without measuring anything the serving path relies on.
        mi_sk = min(args.maxiter, 500)
        scfg_e = _dc.replace(scfg_e, max_iter=mi_sk)
        scfg_sk = _dc.replace(scfg_e, backend="sketched")
        ks_sk = (2, 4) if args.kmax >= 4 else (2,)
        struct_k = ks_sk[-1]  # the bench matrix plants 4 groups
        restarts_sk = min(args.restarts, 8)
        seeds_sk = (123, 456, 789)
        ARI_GATE = 0.75  # min ARI at the structured rank, over seeds
        RHO_GATE = 0.15  # max |d rho| at the structured rank
        # TOY-SHAPE gate policy (ISSUE 16): the agreement thresholds
        # are calibrated on the hardware-shape planted design
        # (5000×500, effect=2.0), where the 4 groups are recoverable
        # and the EXACT arm itself clusters them cleanly. Group
        # separability scales with the number of genes; at CPU smoke
        # shapes (120×48) the exact consensus is already unstable at
        # the structured rank (ARI ~0.24 vs its sketched twin,
        # reproduced on trunk) — there is no signal to gate, only
        # noise-vs-noise. Below the threshold the stage still runs
        # BOTH arms and keeps every hardware-independent gate that
        # does have signal at any shape (quality tag, stop-reason
        # integrity, screening mask arithmetic) and records the
        # measured agreement ungated.
        agreement_gated = args.genes >= 1000 and args.samples >= 100

        def run_arm(scfg_a):
            t0 = time.perf_counter()
            out = {s: nmfconsensus(a, ks=ks_sk, restarts=restarts_sk,
                                   seed=s, solver_cfg=scfg_a,
                                   use_mesh=False)
                   for s in seeds_sk}
            return out, time.perf_counter() - t0

        exact_res, exact_wall = run_arm(scfg_e)
        sk_res, sk_wall = run_arm(scfg_sk)

        problems = []
        agreements = {}
        for s in seeds_sk:
            if sk_res[s].quality != "sketched":
                problems.append(
                    f"seed={s}: sketched result is untagged "
                    f"(quality={sk_res[s].quality!r}) — the quality-tag "
                    "invariant is broken")
            rep = consensus_agreement(exact_res[s], sk_res[s])
            agreements[s] = rep
            sk_rec = rep["per_k"][struct_k]
            if agreement_gated and sk_rec["ari"] < ARI_GATE:
                problems.append(
                    f"seed={s}: ARI at the structured rank k="
                    f"{struct_k} is {sk_rec['ari']:.3f}, below the "
                    f"{ARI_GATE} agreement gate")
            if agreement_gated and sk_rec["rho_gap"] > RHO_GATE:
                problems.append(
                    f"seed={s}: |d rho| at k={struct_k} is "
                    f"{sk_rec['rho_gap']:.3f}, above the {RHO_GATE} "
                    "gate")
            for arm, res_s in (("exact", exact_res[s]),
                               ("sketched", sk_res[s])):
                scfg_a = scfg_e if arm == "exact" else scfg_sk
                its_a = {k: res_s.per_k[k].iterations for k in ks_sk}
                st_a = {k: res_s.per_k[k].stop_reasons for k in ks_sk}
                # impossible-CLASS_STABLE check only (use_class_stop
                # toggled off for the CHECK, not the run): under the
                # stage-local bounded budget, sub-floor TolX stops are
                # legitimate for BOTH arms on small hosts, so the
                # dominance heuristic has no signal here
                problems += [f"{arm} seed={s}: {p}" for p in
                             _integrity_problems(
                                 _dc.replace(scfg_a,
                                             use_class_stop=False),
                                 its_a, st_a)]
        if problems:
            for prob in problems:
                print(f"bench SKETCHED AGREEMENT FAILURE: {prob}",
                      file=sys.stderr)
            raise SystemExit(2)

        total = len(seeds_sk) * len(ks_sk) * restarts_sk

        def flops_per_restart(scfg_a, res_by_seed, sketch):
            from nmfx.obs import costmodel

            tot = 0.0
            for s, res_s in res_by_seed.items():
                for k in ks_sk:
                    iters_k = float(
                        np.asarray(res_s.per_k[k].iterations).sum())
                    # the shared costmodel table (ISSUE 13): the
                    # "sketched" family entry routes through
                    # sketched_model_flops/resolve_dim itself
                    per_iter = costmodel.iteration_flops(
                        "mu", "sketched" if sketch else "vmap",
                        args.genes, args.samples, k, scfg_a)
                    tot += per_iter * iters_k
            return tot / total

        fpr_exact = flops_per_restart(scfg_e, exact_res, False)
        fpr_sk = flops_per_restart(scfg_sk, sk_res, True)

        # screening mini-rung: the same pool with exact iterations
        # spent only on the top half (screen survivors); the survivor
        # bit-identity contract itself is pinned by
        # tests/test_screening.py — here the books record the wall and
        # the per-rank mask arithmetic
        keep = max(1, restarts_sk // 2)
        scfg_scr = _dc.replace(scfg_e, backend="auto", screen=True,
                               screen_keep=keep)
        from nmfx.solvers.base import StopReason
        t0 = time.perf_counter()
        scr = nmfconsensus(a, ks=ks_sk, restarts=restarts_sk,
                           seed=seeds_sk[0], solver_cfg=scfg_scr,
                           use_mesh=False)
        scr_wall = time.perf_counter() - t0
        for k in ks_sk:
            n_scr = int((np.asarray(scr.per_k[k].stop_reasons)
                         == int(StopReason.SCREENED)).sum())
            if n_scr != restarts_sk - keep:
                print("bench SKETCHED SCREENING FAILURE: k="
                      f"{k}: {n_scr} screened lanes, expected "
                      f"{restarts_sk - keep}", file=sys.stderr)
                raise SystemExit(2)

        detail = {
            "unit": f"ks={list(ks_sk)} x {restarts_sk} restarts x "
                    f"{len(seeds_sk)} seeds over the "
                    f"{args.genes}x{args.samples} bench matrix",
            "sketch_dim": {str(k): resolve_dim(scfg_sk, args.genes,
                                               args.samples, k)
                           for k in ks_sk},
            "exact_restarts_per_s": round(total / exact_wall, 3),
            "sketched_restarts_per_s": round(total / sk_wall, 3),
            "wall_speedup": round(exact_wall / sk_wall, 3),
            "flops_per_restart_exact": round(fpr_exact / 1e9, 4),
            "flops_per_restart_sketched": round(fpr_sk / 1e9, 4),
            "flops_unit": "GFLOP (analytic, shape-derived)",
            "flops_compression_per_restart": round(fpr_exact / fpr_sk,
                                                   3),
            "agreement": {str(s): {
                "min_ari": round(rep["min_ari"], 4),
                "max_rho_gap": round(rep["max_rho_gap"], 4),
                "per_k": {str(k): {kk: round(float(vv), 4)
                                   for kk, vv in v.items()}
                          for k, v in rep["per_k"].items()}}
                for s, rep in agreements.items()},
            "agreement_gate": {"structured_k": struct_k,
                               "min_ari": ARI_GATE,
                               "max_rho_gap": RHO_GATE,
                               "status": "ok" if agreement_gated
                               else ("ungated (toy shape: calibrated "
                                     "for >=1000x100)")},
            "screening": {"screen_keep": keep,
                          "wall_s": round(scr_wall, 3),
                          "restarts_per_s": round(
                              len(ks_sk) * restarts_sk / scr_wall, 3),
                          "mask_arithmetic": "ok"},
            "quality_tag": "ok",
        }
        return detail

    # --- serve traffic stage (nmfx.serve) ------------------------------
    # Multi-tenant serving under load: Poisson arrivals over an
    # offered-load ladder into ONE NMFXServer (async request queue +
    # continuous cross-request restart batching). Per rung: p50/p99
    # latency, goodput vs offered load, and the packing-efficiency
    # counter. EVERY served request is parity-gated bit-identical
    # against a solo run of the same request (exit 2 on mismatch) — the
    # per-rep parity discipline extended to served requests.
    def run_traffic_stage():
        from nmfx import serve as serve_mod
        from nmfx.api import nmfconsensus
        from nmfx.exec_cache import ExecCache
        from nmfx.serve import NMFXServer, ServeConfig

        scfg_t = cfgs[args.backend]
        # the serving unit is a SLICE of the bench sweep (2 ranks,
        # <= 10 restarts): the stage measures serving dynamics — queue
        # wait, packing, tail latency — and the ladder multiplies
        # request count, so the per-request unit must stay small
        ks_t = ks[:2]
        restarts_t = min(args.restarts, 10)
        ccfg_t = ConsensusConfig(ks=ks_t, restarts=restarts_t, seed=seed,
                                 grid_exec=args.grid_exec)
        cache = ExecCache()
        if not cache.cacheable(ccfg_t, scfg_t, None):
            return {"skipped": "configuration not exec-cacheable "
                               "(see ExecCache.cacheable)"}
        # distinct tenants = distinct seeds over the shared matrix (the
        # packable case: one resident buffer, one bucket, one config)
        seeds_t = (123, 456, 789, 1012)
        warm_cfg = ServeConfig(max_batch_requests=4)

        def gate(probs):
            if probs:
                for prob in probs:
                    print(f"bench SERVE PARITY FAILURE: {prob}",
                          file=sys.stderr)
                raise SystemExit(2)

        # warm request: pays the bucket compile once, outside the
        # ladder's books
        with NMFXServer(warm_cfg, exec_cache=cache) as srv:
            warm_res = srv.submit(
                a, ks=ks_t, restarts=restarts_t, seed=seeds_t[0],
                solver_cfg=scfg_t).result()
        # solo-latency floor on the WARM path -> capacity estimate the
        # ladder's offered loads are multiples of
        with NMFXServer(warm_cfg, exec_cache=cache) as srv:
            fut = srv.submit(a, ks=ks_t, restarts=restarts_t,
                             seed=seeds_t[0], solver_cfg=scfg_t)
            fut.result()
        solo_latency_s = fut.stats.latency_s
        capacity = 1.0 / max(solo_latency_s, 1e-6)
        # the ladder serves with a linger of a quarter solo-latency —
        # the continuous-batching knob sized to the workload: near-
        # simultaneous arrivals pack, an isolated request pays at most
        # 25% extra latency (recorded, so the tradeoff is in the books)
        serve_cfg = ServeConfig(
            max_batch_requests=4,
            batch_linger_s=round(0.25 * solo_latency_s, 4))

        # solo references for the parity gate: one per tenant seed,
        # through the SAME serving layer (exec cache, no mesh)
        refs = {sd: nmfconsensus(a, ks=ks_t, restarts=restarts_t,
                                 seed=sd, solver_cfg=scfg_t,
                                 use_mesh=False, exec_cache=cache)
                for sd in seeds_t}
        gate(_serve_parity_problems(warm_res, refs[seeds_t[0]],
                                    "warmup"))

        n_req = 6
        rng = np.random.default_rng(seed)
        ladder = []
        # three Poisson rungs spanning under- to over-load, then a
        # closed-loop burst (every request submitted at once — the
        # regime continuous batching exists for: the queue is deep, so
        # dispatches pack)
        for load_frac in (0.5, 1.0, 2.0, "burst"):
            rate = None if load_frac == "burst" \
                else capacity * load_frac
            with NMFXServer(serve_cfg, exec_cache=cache) as srv:
                t0 = time.perf_counter()
                futs = []
                for i in range(n_req):
                    sd = seeds_t[i % len(seeds_t)]
                    futs.append((sd, srv.submit(
                        a, ks=ks_t, restarts=restarts_t, seed=sd,
                        solver_cfg=scfg_t)))
                    if rate is not None and i < n_req - 1:
                        time.sleep(rng.exponential(1.0 / rate))
                results = [(sd, f, f.result()) for sd, f in futs]
                wall = time.perf_counter() - t0
            for sd, f, res in results:
                gate(_serve_parity_problems(
                    res, refs[sd], f"load={load_frac} seed={sd}"))
            lat = np.asarray(sorted(f.stats.latency_s
                                    for _, f in futs))
            s = srv.stats()
            ladder.append({
                "offered_load": load_frac,
                "offered_req_per_s": (None if rate is None
                                      else round(rate, 4)),
                "goodput_req_per_s": round(len(results) / wall, 4),
                "p50_latency_s": round(float(np.percentile(lat, 50)), 3),
                "p99_latency_s": round(float(np.percentile(lat, 99)), 3),
                "mean_queue_wait_s": round(float(np.mean(
                    [f.stats.queue_wait_s for _, f in futs])), 3),
                "dispatches": s["dispatches"],
                "packed_dispatches": s["packed_dispatches"],
                "packing_efficiency": s["packing_efficiency"],
            })
            print(f"bench: serve traffic load={load_frac}: "
                  f"p50={ladder[-1]['p50_latency_s']}s "
                  f"p99={ladder[-1]['p99_latency_s']}s "
                  f"goodput={ladder[-1]['goodput_req_per_s']} req/s "
                  f"packing={ladder[-1]['packing_efficiency']}",
                  file=sys.stderr)
        # --- chaos rung (ISSUE 7, detail.serve.chaos): the 1.0x
        # offered load again, with faults injected — harvest.worker at
        # a fixed cadence (every 3rd rank-harvest dies; recovery is an
        # exact inline re-run) and one solve.nonfinite lane on the last
        # rank (the in-kernel quarantine stops it with NUMERIC_FAULT
        # and masks it from the consensus). Books: goodput retention
        # and latency overhead vs the clean 1.0x rung. Parity: every
        # request gates bit-identical against a solo reference run
        # under the SAME armed faults, and — fault isolation — the
        # non-poisoned first rank additionally gates bit-identical
        # against the CLEAN references.
        from nmfx import faults as faults_mod
        from nmfx.solvers.base import StopReason

        clean_1x = next(r for r in ladder if r["offered_load"] == 1.0)
        chaos_k = ks_t[-1]
        chaos_lane = (chaos_k, restarts_t - 1)
        faults_mod.arm("harvest.worker", every=3)
        faults_mod.arm("solve.nonfinite", lanes=(chaos_lane,))
        try:
            # references under the same armed generation: the trace
            # token keys the executables, so refs and served requests
            # run the identical quarantined program
            chaos_refs = {sd: nmfconsensus(
                a, ks=ks_t, restarts=restarts_t, seed=sd,
                solver_cfg=scfg_t, use_mesh=False, exec_cache=cache)
                for sd in seeds_t}
            rate = capacity
            with NMFXServer(serve_cfg, exec_cache=cache) as srv:
                t0 = time.perf_counter()
                futs = []
                for i in range(n_req):
                    sd = seeds_t[i % len(seeds_t)]
                    futs.append((sd, srv.submit(
                        a, ks=ks_t, restarts=restarts_t, seed=sd,
                        solver_cfg=scfg_t)))
                    if i < n_req - 1:
                        time.sleep(rng.exponential(1.0 / rate))
                results = [(sd, f, f.result()) for sd, f in futs]
                chaos_wall = time.perf_counter() - t0
            quarantined = 0
            for sd, f, res in results:
                gate(_serve_parity_problems(
                    res, chaos_refs[sd], f"chaos seed={sd}"))
                stops = np.asarray(res.per_k[chaos_k].stop_reasons)
                quarantined += int(
                    (stops == int(StopReason.NUMERIC_FAULT)).sum())
                if len(ks_t) > 1:
                    # fault isolation: the rank with no injected lane
                    # must be bit-identical to the CLEAN reference
                    iso = _serve_parity_problems(
                        res, refs[sd], f"chaos-isolation seed={sd}")
                    iso = [p for p in iso if f"k={chaos_k}" not in p]
                    gate(iso)
            if quarantined != len(results):
                gate([f"chaos: expected 1 quarantined lane per request "
                      f"({len(results)}), saw {quarantined}"])
            lat = np.asarray(sorted(f.stats.latency_s
                                    for _, f in futs))
            chaos = {
                "fault_plan": {
                    "harvest.worker": "every 3rd rank-harvest",
                    "solve.nonfinite":
                        f"lane (k={chaos_lane[0]}, "
                        f"restart={chaos_lane[1]})"},
                "goodput_req_per_s": round(len(results) / chaos_wall,
                                           4),
                "goodput_retention": round(
                    (len(results) / chaos_wall)
                    / max(clean_1x["goodput_req_per_s"], 1e-9), 4),
                "p50_latency_s": round(float(np.percentile(lat, 50)),
                                       3),
                "p99_latency_s": round(float(np.percentile(lat, 99)),
                                       3),
                "p50_overhead_vs_clean": round(
                    float(np.percentile(lat, 50))
                    / max(clean_1x["p50_latency_s"], 1e-9), 4),
                "harvest_fault_fires":
                    faults_mod.fires("harvest.worker"),
                "quarantined_lanes": quarantined,
                "parity": "ok",
                # the armed trace token keys fresh PACKED executables,
                # so their compiles land inside this rung's wall (the
                # clean ladder amortized its layouts across rungs) —
                # on short smoke configs retention under-reads; the
                # steady-state recovery overhead is the hardware
                # measurement at real iteration counts
                "note": "chaos wall includes armed-generation packed "
                        "compiles",
            }
            print(f"bench: serve chaos rung: goodput_retention="
                  f"{chaos['goodput_retention']} "
                  f"p50_overhead={chaos['p50_overhead_vs_clean']} "
                  f"quarantined={quarantined} "
                  f"harvest_fires={chaos['harvest_fault_fires']}",
                  file=sys.stderr)
        finally:
            faults_mod.disarm("harvest.worker")
            faults_mod.disarm("solve.nonfinite")

        # --- quality-elasticity rung (ISSUE 12): goodput under
        # overload, shed vs degraded. 2.0x offered load against a TIGHT
        # admission bound (depth 2): the baseline server SHEDS the
        # overflow (QueueFull — those requests produce nothing), the
        # quality-elastic server admits it degraded to the sketched
        # engine (cause "overload"; a tagged approximate result instead
        # of no result). Books shed-vs-degraded goodput; hard gates:
        # every degraded result is tagged quality="sketched" with a
        # recorded cause and a matching counter increment, and the
        # elastic server's EXACT results still parity-match their solo
        # references bit-for-bit (quality elasticity must never leak
        # approximation into requests served exact). Shed/degraded
        # COUNTS are recorded, not gated — they depend on host timing.
        import dataclasses as _dc

        from nmfx.config import SKETCHED_ALGORITHMS
        qe = {}
        if scfg_t.algorithm in SKETCHED_ALGORITHMS:
            rate2 = 2.0 * capacity
            n_req2 = 8
            for mode, qcfg in (
                    ("shed", _dc.replace(serve_cfg, max_queue_depth=2)),
                    ("degraded", _dc.replace(serve_cfg,
                                             max_queue_depth=2,
                                             quality_elastic=True))):
                rng2 = np.random.default_rng(seed + 7)  # same arrivals
                shed = 0
                futs2 = []
                with NMFXServer(qcfg, exec_cache=cache) as srv:
                    t0 = time.perf_counter()
                    for i in range(n_req2):
                        sd = seeds_t[i % len(seeds_t)]
                        try:
                            futs2.append((sd, srv.submit(
                                a, ks=ks_t, restarts=restarts_t,
                                seed=sd, solver_cfg=scfg_t)))
                        except serve_mod.QueueFull:
                            shed += 1
                        if i < n_req2 - 1:
                            time.sleep(rng2.exponential(1.0 / rate2))
                    results2 = [(sd, f, f.result()) for sd, f in futs2]
                    wall2 = time.perf_counter() - t0
                    s2 = srv.stats()
                n_deg = 0
                for sd, f, res in results2:
                    if f.stats.degraded_cause is not None:
                        n_deg += 1
                        if (res.quality != "sketched"
                                or f.stats.quality != "sketched"):
                            gate([f"quality-elastic {mode}: request "
                                  f"seed={sd} degraded "
                                  f"(cause={f.stats.degraded_cause}) "
                                  "returned an UNTAGGED result — the "
                                  "no-silent-downgrade invariant is "
                                  "broken"])
                    else:
                        gate(_serve_parity_problems(
                            res, refs[sd], f"qe-{mode} seed={sd}"))
                if n_deg != s2["quality_degraded"]:
                    gate([f"quality-elastic {mode}: "
                          f"{n_deg} degraded-tagged results vs "
                          f"quality_degraded counter "
                          f"{s2['quality_degraded']}"])
                qe[mode] = {
                    "offered_load": 2.0,
                    "offered_req_per_s": round(rate2, 4),
                    "requests": n_req2, "shed": shed,
                    "completed": len(results2),
                    "goodput_req_per_s": round(len(results2) / wall2,
                                               4),
                    "degraded_tagged": n_deg,
                    "rejected": s2["rejected"],
                }
                print(f"bench: serve quality-elastic {mode}: "
                      f"goodput={qe[mode]['goodput_req_per_s']} req/s "
                      f"shed={shed} degraded={n_deg}", file=sys.stderr)
            qe["goodput_gain_degraded_vs_shed"] = round(
                qe["degraded"]["goodput_req_per_s"]
                / max(qe["shed"]["goodput_req_per_s"], 1e-9), 4)
            qe["parity"] = "ok"

        # --- service-tier rungs (ISSUE 15, detail.serve.fleet): the
        # router + replica pool over the same serving unit. Three
        # rungs: (a) router OVERHEAD — the identical 1.0x Poisson
        # arrival schedule through a 1-replica thread router sharing
        # the warm exec cache, gated within 5% of the direct
        # NMFXServer p50 (+50ms absolute timer-noise floor, the
        # obs-stage discipline); (b) SCALING — goodput/p99 vs replica
        # count 1/2/3 (thread replicas share ONE device in this
        # process, so CPU-smoke numbers measure router mechanics, not
        # speedup — the hardware host with per-replica devices is the
        # real measurement); (c) KILL-A-REPLICA chaos — 3 subprocess
        # workers against a warm persistent cache, one SIGKILLed at
        # ~50% of the request ladder; gates: zero lost futures (every
        # accepted request resolves a RESULT) and every readmitted
        # request bit-identical to its solo reference (exit 2).
        import shutil
        import tempfile

        from nmfx.replica import ReplicaPool
        from nmfx.router import NMFXRouter, RouterConfig

        fleet = {}
        rung_root = tempfile.mkdtemp(prefix="nmfx-bench-fleet-")
        try:
            # (a) router overhead — PAIRED protocol: the direct server
            # and the 1-replica router serve the IDENTICAL Poisson
            # arrival schedule (same rng seed ⇒ same inter-arrival
            # sleeps), so the comparison isolates the router hop from
            # arrival-pattern luck (the clean ladder's rng had
            # progressed through earlier rungs and is not replayable)
            def _poisson_run(submit_fn):
                rng_f = np.random.default_rng(seed + 99)
                t0 = time.perf_counter()
                futs = []
                for i in range(n_req):
                    sd = seeds_t[i % len(seeds_t)]
                    futs.append((sd, submit_fn(sd)))
                    if i < n_req - 1:
                        time.sleep(rng_f.exponential(1.0 / capacity))
                results = [(sd, f, f.result()) for sd, f in futs]
                wall = time.perf_counter() - t0
                lat = np.asarray(sorted(f.stats.latency_s
                                        for _, f in futs))
                return results, wall, float(np.percentile(lat, 50))

            with NMFXServer(serve_cfg, exec_cache=cache) as srv:
                d_results, d_wall, p50_direct = _poisson_run(
                    lambda sd: srv.submit(a, ks=ks_t,
                                          restarts=restarts_t, seed=sd,
                                          solver_cfg=scfg_t))
            for sd, f, res in d_results:
                gate(_serve_parity_problems(
                    res, refs[sd], f"fleet-overhead-direct seed={sd}"))
            pool = ReplicaPool(
                1, root=os.path.join(rung_root, "overhead"),
                mode="thread", serve_cfg=serve_cfg, exec_cache=cache)
            with NMFXRouter(pool, RouterConfig()) as router:
                results, wall, p50_router = _poisson_run(
                    lambda sd: router.submit(a, ks=ks_t,
                                             restarts=restarts_t,
                                             seed=sd,
                                             solver_cfg=scfg_t))
            for sd, f, res in results:
                gate(_serve_parity_problems(
                    res, refs[sd], f"fleet-overhead seed={sd}"))
            if p50_router > 1.05 * p50_direct + 0.05:
                gate([f"router overhead: p50 {p50_router:.3f}s through "
                      f"a 1-replica router vs {p50_direct:.3f}s direct "
                      "on the identical arrival schedule exceeds the "
                      "5% (+50ms noise floor) bound"])
            fleet["overhead"] = {
                "p50_latency_s": round(p50_router, 3),
                "p50_direct_s": round(p50_direct, 3),
                "p50_ratio": round(p50_router
                                   / max(p50_direct, 1e-9), 4),
                "goodput_req_per_s": round(len(results) / wall, 4),
                "direct_goodput_req_per_s": round(
                    len(d_results) / d_wall, 4),
                "gate": "p50 <= 1.05x direct + 50ms, paired arrivals",
                "parity": "ok",
            }
            print(f"bench: fleet overhead rung: p50_router="
                  f"{fleet['overhead']['p50_latency_s']}s "
                  f"ratio={fleet['overhead']['p50_ratio']}",
                  file=sys.stderr)

            # (b) goodput + p99 vs replica count (burst arrivals,
            # stickiness yields to least-loaded so the pool spreads)
            scaling = []
            for n_rep in (1, 2, 3):
                pool = ReplicaPool(
                    n_rep,
                    root=os.path.join(rung_root, f"scale{n_rep}"),
                    mode="thread", serve_cfg=serve_cfg,
                    exec_cache=cache)
                with NMFXRouter(pool, RouterConfig(
                        stickiness_slack=0)) as router:
                    t0 = time.perf_counter()
                    futs = [(seeds_t[i % len(seeds_t)], router.submit(
                        a, ks=ks_t, restarts=restarts_t,
                        seed=seeds_t[i % len(seeds_t)],
                        solver_cfg=scfg_t)) for i in range(n_req)]
                    results = [(sd, f, f.result()) for sd, f in futs]
                    wall = time.perf_counter() - t0
                    rstats = router.stats()
                for sd, f, res in results:
                    gate(_serve_parity_problems(
                        res, refs[sd],
                        f"fleet-scale{n_rep} seed={sd}"))
                lat = np.asarray(sorted(f.stats.latency_s
                                        for _, f in futs))
                scaling.append({
                    "replicas": n_rep,
                    "goodput_req_per_s": round(len(results) / wall, 4),
                    "p50_latency_s": round(
                        float(np.percentile(lat, 50)), 3),
                    "p99_latency_s": round(
                        float(np.percentile(lat, 99)), 3),
                    "retried": rstats["retried"],
                })
                print(f"bench: fleet scaling replicas={n_rep}: "
                      f"goodput={scaling[-1]['goodput_req_per_s']} "
                      f"req/s p99={scaling[-1]['p99_latency_s']}s",
                      file=sys.stderr)
            fleet["scaling"] = scaling
            fleet["scaling_note"] = (
                "thread replicas share one device in this process — "
                "CPU-smoke scaling measures router mechanics; "
                "per-replica-device speedup is the hardware "
                "measurement")

            # (c) kill-a-replica chaos: subprocess workers against a
            # warm disk cache (the scale-up story: deserialize, don't
            # compile), one SIGKILLed mid-ladder
            from nmfx.api import nmfconsensus as _nc
            from nmfx.config import ExecCacheConfig

            fleet_cache_dir = os.path.join(rung_root, "cache")
            warm_cache = ExecCache(
                ExecCacheConfig(cache_dir=fleet_cache_dir))
            _nc(a, ks=ks_t, restarts=restarts_t, seed=seeds_t[0],
                solver_cfg=scfg_t, use_mesh=False,
                exec_cache=warm_cache)  # one solve persists the bucket
            pool = ReplicaPool(
                3, root=os.path.join(rung_root, "chaos"),
                mode="process", cache_dir=fleet_cache_dir)
            spawn_t0 = time.perf_counter()
            with NMFXRouter(pool, RouterConfig(
                    stickiness_slack=0)) as router:
                while len([p for p in pool.heartbeats(30.0).values()
                           if not p.get("stale")]) < 3:
                    if time.perf_counter() - spawn_t0 > 180:
                        gate(["fleet chaos: replicas failed to "
                              "heartbeat within 180s of spawn"])
                    time.sleep(0.1)
                spawn_wall = time.perf_counter() - spawn_t0
                n_fleet = 8
                t0 = time.perf_counter()
                futs = []
                for i in range(n_fleet):
                    sd = seeds_t[i % len(seeds_t)]
                    futs.append((sd, router.submit(
                        a, ks=ks_t, restarts=restarts_t, seed=sd,
                        solver_cfg=scfg_t)))
                    if i == n_fleet // 2 - 1:
                        # ~50% of the ladder: SIGKILL the busiest
                        loads = router.stats(
                        )["outstanding_per_replica"]
                        victim_id = max(loads, key=loads.get)
                        pool.get(victim_id).kill()
                        print(f"bench: fleet chaos: SIGKILLed "
                              f"{victim_id} at request {i + 1}/"
                              f"{n_fleet}", file=sys.stderr)
                results = []
                lost = []
                for sd, f in futs:
                    try:
                        results.append((sd, f,
                                        f.result(timeout=600)))
                    except Exception as e:
                        # a typed error or a timed-out (stranded)
                        # future — both fail the zero-lost-futures
                        # gate below with the cause in the message
                        lost.append(f"request seed={sd}: {e!r}")
                chaos_wall = time.perf_counter() - t0
                rstats = router.stats()
            gate([f"fleet chaos: {p} — every accepted request must "
                  "resolve a result after the kill" for p in lost])
            for sd, f, res in results:
                gate(_serve_parity_problems(
                    res, refs[sd], f"fleet-chaos seed={sd}"))
            if rstats["readmitted"] < 1:
                gate(["fleet chaos: the kill stranded no requests to "
                      "readmit — the rung did not exercise recovery "
                      "(victim selection failed?)"])
            fleet["chaos"] = {
                "replicas": 3, "killed": victim_id,
                "requests": n_fleet,
                "spawn_to_live_s": round(spawn_wall, 3),
                "goodput_req_per_s": round(
                    len(results) / chaos_wall, 4),
                "readmitted": rstats["readmitted"],
                "recovered_replicas": rstats["recovered"],
                "retried": rstats["retried"],
                "parity": "ok", "lost_futures": 0,
            }
            print(f"bench: fleet chaos rung: killed={victim_id} "
                  f"readmitted={rstats['readmitted']} "
                  f"goodput={fleet['chaos']['goodput_req_per_s']} "
                  "req/s parity=ok", file=sys.stderr)
        finally:
            shutil.rmtree(rung_root, ignore_errors=True)

        # --- request-economics rung (detail.serve.economics): a
        # Zipf-distributed request mix — a few identities dominate,
        # the planet-scale regime where goodput is bounded by
        # hit/coalesce/extend rates rather than raw solve speed.
        # COLD arm: the identical schedule against a plain server
        # (no result cache, no coalescing) — every request solves.
        # MIXED arm: cache + coalescing on; repeats attach to the
        # in-flight leader or hit the cache. WARM arm: the same
        # schedule replayed against the now-warm disk tier — every
        # request must hit. Gates (exit 2): every served result
        # bit-identical to its solo reference (this rung never
        # degrades, so tag-gating degenerates to parity), the warm
        # replay performs ZERO solve dispatches (module dispatch
        # counter), its accounting is exact (hits == requests), and
        # warm goodput >= 5x the cold baseline. The extend mini-rung
        # times the checkpoint ledger's incremental widen (same
        # A/config, 2x the restart budget) against a from-scratch
        # run at the widened budget, bit-identity gated hard.
        import dataclasses as _dc

        n_econ = 24
        rng_e = np.random.default_rng(seed + 16)
        zw = 1.0 / np.arange(1, len(seeds_t) + 1)
        schedule = [seeds_t[i] for i in rng_e.choice(
            len(seeds_t), size=n_econ, p=zw / zw.sum())]

        def _econ_run(cfg_e, label):
            with NMFXServer(cfg_e, exec_cache=cache) as srv:
                d0 = serve_mod.dispatch_count()
                t0 = time.perf_counter()
                futs = [(sd, srv.submit(a, ks=ks_t,
                                        restarts=restarts_t, seed=sd,
                                        solver_cfg=scfg_t))
                        for sd in schedule]
                results = [(sd, f, f.result()) for sd, f in futs]
                wall = time.perf_counter() - t0
                st = srv.stats()
                n_disp = serve_mod.dispatch_count() - d0
            for sd, f, res in results:
                gate(_serve_parity_problems(
                    res, refs[sd], f"economics-{label} seed={sd}"))
            return wall, st, n_disp

        cold_wall_e, _, cold_disp = _econ_run(serve_cfg, "cold")
        econ_dir = tempfile.mkdtemp(prefix="nmfx-bench-rescache-")
        try:
            econ_cfg = _dc.replace(serve_cfg, coalesce_requests=True,
                                   result_cache_dir=econ_dir)
            mixed_wall, mixed_st, mixed_disp = _econ_run(econ_cfg,
                                                         "mixed")
            warm_wall, warm_st, warm_disp = _econ_run(econ_cfg,
                                                      "warm")
        finally:
            shutil.rmtree(econ_dir, ignore_errors=True)
        if warm_disp != 0:
            gate([f"economics: the warm-cache replay dispatched "
                  f"{warm_disp} solve(s) — a warm hit must serve "
                  "with ZERO dispatches"])
        if warm_st["result_cache_hits"] != n_econ:
            gate([f"economics: warm replay hit "
                  f"{warm_st['result_cache_hits']}/{n_econ} — "
                  "request accounting is broken"])
        reused = (mixed_st["result_cache_hits"]
                  + mixed_st["coalesced"])
        if (reused + mixed_disp > n_econ
                or mixed_st["completed"] != n_econ):
            gate([f"economics: mixed-arm books don't balance "
                  f"(hits+coalesced={reused}, "
                  f"dispatches={mixed_disp}, "
                  f"completed={mixed_st['completed']}, "
                  f"requests={n_econ})"])
        goodput_vs_cold = ((n_econ / warm_wall)
                           / max(n_econ / cold_wall_e, 1e-9))
        if goodput_vs_cold < 5.0:
            gate([f"economics: warm goodput is only "
                  f"{goodput_vs_cold:.2f}x the cold-solve baseline "
                  "(gate: >= 5x)"])

        # extend mini-rung: widen the restart budget through the
        # ledger; only the delta chunks solve, and the result must be
        # bit-identical to a from-scratch run at the widened budget
        from nmfx.checkpoint import run_checkpointed_sweep
        from nmfx.config import CheckpointConfig

        ext_root = tempfile.mkdtemp(prefix="nmfx-bench-extend-")
        try:
            r_half = max(2, restarts_t // 2)
            r_full = 2 * r_half
            chunk = max(1, r_half // 2)
            cc_half = ConsensusConfig(ks=ks_t, restarts=r_half,
                                      seed=seed)
            cc_full = ConsensusConfig(ks=ks_t, restarts=r_full,
                                      seed=seed)
            d_inc = os.path.join(ext_root, "inc")
            d_scratch = os.path.join(ext_root, "scratch")
            # untimed warmup at the FULL budget: pays every compile
            # (including the widened-budget consensus finalization)
            # once, outside both timed arms — without it the first
            # timed run eats the compile and the comparison measures
            # ordering, not work
            run_checkpointed_sweep(
                a, cc_full, scfg_t, icfg,
                CheckpointConfig(directory=os.path.join(ext_root, "w"),
                                 every_n_restarts=chunk))
            run_checkpointed_sweep(
                a, cc_half, scfg_t, icfg,
                CheckpointConfig(directory=d_inc,
                                 every_n_restarts=chunk))
            t0 = time.perf_counter()
            out_ext = run_checkpointed_sweep(
                a, cc_full, scfg_t, icfg,
                CheckpointConfig(directory=d_inc,
                                 every_n_restarts=chunk))
            ext_wall = time.perf_counter() - t0
            t0 = time.perf_counter()
            out_scratch = run_checkpointed_sweep(
                a, cc_full, scfg_t, icfg,
                CheckpointConfig(directory=d_scratch,
                                 every_n_restarts=chunk))
            scratch_wall = time.perf_counter() - t0
        finally:
            shutil.rmtree(ext_root, ignore_errors=True)
        for k in ks_t:
            for fld in ("consensus", "best_w", "best_h", "dnorms"):
                if not np.array_equal(
                        np.asarray(getattr(out_ext[k], fld)),
                        np.asarray(getattr(out_scratch[k], fld))):
                    gate([f"economics extend: k={k} field {fld} of "
                          "the extended run differs from the "
                          "from-scratch run at the widened budget — "
                          "the extend-exactness contract is broken"])
        extend_speedup = scratch_wall / max(ext_wall, 1e-9)

        economics = {
            "unit": f"{n_econ} Zipf-mix requests (p ~ 1/rank) over "
                    f"{len(seeds_t)} identities; extend "
                    f"{r_half}->{r_full} restarts, chunk {chunk}",
            "cold_goodput_req_per_s": round(n_econ / cold_wall_e, 4),
            "mixed_goodput_req_per_s": round(n_econ / mixed_wall, 4),
            "warm_goodput_req_per_s": round(n_econ / warm_wall, 4),
            "goodput_vs_cold": round(goodput_vs_cold, 4),
            "hit_rate": round(
                mixed_st["result_cache_hits"] / n_econ, 4),
            "coalesce_rate": round(
                mixed_st["coalesced"] / n_econ, 4),
            "reuse_rate": round(reused / n_econ, 4),
            "cold_dispatches": cold_disp,
            "mixed_dispatches": mixed_disp,
            "warm_dispatches": warm_disp,
            "extend_wall_s": round(ext_wall, 3),
            "from_scratch_wall_s": round(scratch_wall, 3),
            "extend_speedup": round(extend_speedup, 4),
            "extend_parity": "ok",
            "parity": "ok",
        }
        print(f"bench: serve economics rung: goodput_vs_cold="
              f"{economics['goodput_vs_cold']} hit_rate="
              f"{economics['hit_rate']} coalesce_rate="
              f"{economics['coalesce_rate']} extend_speedup="
              f"{economics['extend_speedup']}", file=sys.stderr)

        return {
            "unit": f"ks={list(ks_t)} x {restarts_t} restarts over the "
                    f"{args.genes}x{args.samples} bench matrix",
            "tenants": len(seeds_t),
            "requests_per_rung": n_req,
            "solo_latency_s": round(solo_latency_s, 3),
            "capacity_req_per_s_est": round(capacity, 4),
            "ladder": ladder,
            "chaos": chaos,
            "quality_elastic": qe,
            "fleet": fleet,
            "economics": economics,
            "parity": "ok",
            "module_counters": {
                "dispatches": serve_mod.dispatch_count(),
                "packed_dispatches": serve_mod.packed_dispatch_count(),
                "packing_efficiency": serve_mod.packing_efficiency()},
        }

    # --- kernel-schedule stage (ISSUE 20, detail.kernel) ----------------
    # fused-vs-phased A/B on the pallas block-kernel route + the
    # autotune cold/warm counter-gated rung.
    def run_kernel_stage():
        """Measurement protocol (recorded because the numbers need
        interpreting): the fused_vs_phased rung runs the SAME sweep
        (same matrix, same seeds, the mu pallas block-kernel route)
        under ``experimental.fused_updates="phased"`` vs ``"fused"``,
        reps interleaved. The fused kernel's contract is BIT exactness
        against the phased one — identical dot_generals in identical
        tile order with identical f32 accumulators, only A's read
        schedule changes — so consensus/iterations/stop_reasons are
        asserted exactly equal between the arms on the session's real
        device (exit 2 on drift; interpret-mode pinning lives in
        tests/test_fused_kernel.py). On a TPU session both arms run at
        the bench shape and ``fused.mfu_solve`` feeds the >=0.18
        steering metric; on a CPU host the route runs in interpret
        mode at a smoke shape — walls are recorded but not comparable,
        MFU reports None (no device peak).

        The autotune rung measures the cold candidate-search wall into
        a FRESH store, then simulates a fresh process (in-process memo
        cleared) and re-resolves: the warm path must perform ZERO
        searches, serve >=1 store hit (both by nmfx_autotune_* counter
        deltas — the honesty-counter discipline) and resolve to the
        IDENTICAL config; ``warm_hit`` records that binary verdict for
        the regress judge."""
        import dataclasses as _dc
        import shutil as _sh
        import tempfile as _tf

        from nmfx import autotune as _at
        from nmfx.config import ExperimentalConfig as _Exp
        from nmfx.profiling import Profiler as _Prof

        on_tpu = jax.default_backend() == "tpu"
        if on_tpu:
            genes_k, samples_k = args.genes, args.samples
            ks_k = (max(2, args.kmax // 2), args.kmax)
            restarts_k = min(args.restarts, 16)
            mi_k = min(args.maxiter, 400)
            a_k = a
        else:
            genes_k, samples_k = 96, 48
            ks_k = (2, 3)
            restarts_k = 4
            mi_k = 40
            a_k = grouped_matrix(genes_k, (samples_k // 2,
                                           samples_k // 2),
                                 effect=2.0, seed=0)
        base_k = SolverConfig(algorithm="mu", backend="pallas",
                              max_iter=mi_k,
                              matmul_precision=args.precision)
        ccfg_k = ConsensusConfig(ks=ks_k, restarts=restarts_k, seed=seed)
        cfg_arm = {mode: _dc.replace(
                       base_k, experimental=_Exp(fused_updates=mode))
                   for mode in ("phased", "fused")}

        def run_arm(scfg_a):
            prof_a = _Prof()
            t0 = time.perf_counter()
            with prof_a:
                raw = sweep(a_k, ccfg_k, scfg_a, icfg, None,
                            profiler=prof_a)
                got = jax.device_get({k: (raw[k].consensus,
                                          raw[k].iterations,
                                          raw[k].stop_reasons)
                                      for k in ks_k})
            wall_a = time.perf_counter() - t0
            solve_a = sum(rec.seconds
                          for name, rec in prof_a.phases.items()
                          if name.startswith("solve"))
            return wall_a, solve_a, got

        walls_k = {"phased": [], "fused": []}
        solves_k = {"phased": [], "fused": []}
        outs_k = {}
        for _ in range(2):
            for mode in ("phased", "fused"):
                wall_a, solve_a, got = run_arm(cfg_arm[mode])
                walls_k[mode].append(wall_a)
                solves_k[mode].append(solve_a)
                outs_k[mode] = got
        for k in ks_k:
            for pi, name in ((0, "consensus"), (1, "iterations"),
                             (2, "stop_reasons")):
                if not np.array_equal(np.asarray(outs_k["phased"][k][pi]),
                                      np.asarray(outs_k["fused"][k][pi])):
                    print("bench KERNEL PARITY FAILURE: fused vs "
                          f"phased {name} differ at k={k} — the "
                          "join-the-updates kernel's bit-exactness "
                          "contract is broken on this device",
                          file=sys.stderr)
                    raise SystemExit(2)

        def arm_record(mode):
            min_s = min(walls_k[mode])
            solve_s = min(solves_k[mode])
            mfu_solve = None
            if peak is not None and solve_s > 0:
                fpi = {k: costmodel.iteration_flops(
                           "mu", "pallas", genes_k, samples_k, k,
                           cfg_arm[mode]) for k in ks_k}
                if all(v is not None for v in fpi.values()):
                    its_a = {k: np.asarray(outs_k[mode][k][1])
                             for k in ks_k}
                    model_f = sum(fpi[k] * float(its_a[k].sum())
                                  for k in ks_k)
                    mfu_solve = round(model_f / solve_s
                                      / (peak * len(jax.devices())), 4)
            return {"min_s": round(min_s, 3),
                    "solve_s": round(solve_s, 3),
                    "mfu_solve": mfu_solve}

        fused_rec = arm_record("fused")
        phased_rec = arm_record("phased")
        fused_rec["speedup_vs_phased"] = round(
            phased_rec["min_s"] / fused_rec["min_s"], 4)

        # autotune rung: cold search into a fresh store, then a
        # fresh-process-simulated warm resolution, counter-gated
        from nmfx.ops.sched_mu import _pallas_slot_clamp

        k_hi = ks_k[-1]
        slots_at = _pallas_slot_clamp(ccfg_k.grid_slots, k_hi, genes_k,
                                      samples_k, base_k, None)
        cfg_at = _dc.replace(base_k,
                             experimental=_Exp(autotune="on"))
        at_dir = _tf.mkdtemp(prefix="nmfx-bench-autotune-")
        try:
            s0, h0 = (_at.searches_total.total(), _at.hits_total.total())
            t0 = time.perf_counter()
            cold_cfg = _at.resolve(cfg_at, genes_k, samples_k, k_hi,
                                   slots_at, cache_dir=at_dir)
            cold_at = time.perf_counter() - t0
            s1, h1 = (_at.searches_total.total(), _at.hits_total.total())
            with _at._lock:
                _at._memo.clear()  # fresh-process simulation
            t0 = time.perf_counter()
            warm_cfg = _at.resolve(cfg_at, genes_k, samples_k, k_hi,
                                   slots_at, cache_dir=at_dir)
            warm_at = time.perf_counter() - t0
            s2, h2 = (_at.searches_total.total(), _at.hits_total.total())
        finally:
            _sh.rmtree(at_dir, ignore_errors=True)
        warm_ok = (s1 - s0 == 1 and s2 == s1 and h2 > h1
                   and warm_cfg == cold_cfg)
        if not warm_ok:
            print("bench AUTOTUNE FAILURE: cold searches="
                  f"{s1 - s0} (want 1), warm searches={s2 - s1} "
                  f"(want 0), warm hits={h2 - h1} (want >=1), "
                  f"configs equal={warm_cfg == cold_cfg} — the "
                  "persisted-store warm path is broken",
                  file=sys.stderr)
            raise SystemExit(2)

        return {
            "unit": f"ks={list(ks_k)} x {restarts_k} restarts, "
                    f"{genes_k}x{samples_k}, mu pallas route"
                    + ("" if on_tpu
                       else " (interpret-mode smoke shape — walls not "
                            "cross-round comparable)"),
            "fused_vs_phased": {
                "contract": "same seeds, same matrix; fused gated "
                            "BIT-EXACT vs phased on consensus/"
                            "iterations/stop_reasons (exit 2 on drift)",
                "parity": "ok",
                "phased": phased_rec,
                "fused": fused_rec,
            },
            "autotune": {
                "cold_search_wall_s": round(cold_at, 3),
                "warm_resolve_wall_s": round(warm_at, 4),
                "searches_cold": int(s1 - s0),
                "searches_warm": int(s2 - s1),
                "hits_warm": int(h2 - h1),
                "warm_hit": 1.0 if warm_ok else 0.0,
                "resolved": {
                    "check_block": cold_cfg.check_block,
                    "block_m": cold_cfg.experimental.block_m,
                    "fused_updates":
                        cold_cfg.experimental.fused_updates},
            },
        }

    # headline = the requested backend's same-session minimum; per-backend
    # min/median/all-reps in detail
    primary = args.backend
    wall, e2e_wall, prof, host = best[primary]
    phase_s = {name: round(rec.seconds, 3)
               for name, rec in prof.phases.items()}
    # phase-sum-vs-wall audit against the FULL e2e wall (sweep + host
    # materialization + rank selection): the sequential phases must
    # explain the wall, and the overlapped work (xfer.*, post.*) is
    # reported as a ratio — the accounting that keeps async time from
    # silently migrating between phases (or out of the books entirely,
    # the r05 failure: host rank selection ran outside every phase)
    phase_audit = prof.audit(e2e_wall)
    # the tunneled dev chip inflates transfers far beyond real PCIe/ICI
    # (measured: ~0.7 s for A's 10 MB in slow sessions); the headline
    # stays the honest full wall, but the phase split lets readers
    # separate solve throughput from environment transfer artifacts

    total_restarts = len(ks) * args.restarts
    its = {k: host[k][1] for k in ks}
    iters = {k: float(v.mean()) for k, v in its.items()}

    # MFU accounting through the costmodel registry (every modeled
    # engine family × algorithm — als/neals/snmf included since
    # ISSUE 13; only the COSTMODEL_EXEMPT pg/alspg report None):
    # model FLOPs = Σ_k Σ_restart iters · flops_per_iter(k), achieved
    # rate over the measured wall, utilization vs the devices' bf16
    # peak. Computed per backend from its fastest rep, under the engine
    # FAMILY that backend actually resolves to.
    from nmfx.obs import costmodel
    from nmfx.sweep import resolve_engine_family

    peak_rec = costmodel.device_peak()
    peak = None if peak_rec is None else peak_rec["flops"]

    def mfu_block(b):
        wall_b, _, prof_b, host_b = best[b]
        family = resolve_engine_family(cfgs[b], mesh)
        flops_per_iter = {
            k: costmodel.iteration_flops(args.algorithm, family,
                                         args.genes, args.samples, k,
                                         cfgs[b]) for k in ks}
        if any(v is None for v in flops_per_iter.values()):
            return {"model_tflop": None, "achieved_tflop_per_s": None,
                    "mfu": None, "mfu_solve": None}
        its_b = {k: host_b[k][1] for k in ks}
        model_flops = sum(flops_per_iter[k] * float(its_b[k].sum())
                          for k in ks)
        achieved = model_flops / wall_b
        mfu = mfu_solve = None
        solve_s = sum(rec.seconds for name, rec in prof_b.phases.items()
                      if name.startswith("solve"))
        if peak is not None:
            mfu = achieved / (peak * len(jax.devices()))
            if solve_s > 0:
                # utilization of the solve phase alone — what the
                # device actually sustains, excluding the (tunnel-
                # inflated) host transfers counted in the honest wall
                mfu_solve = model_flops / solve_s / (
                    peak * len(jax.devices()))
        return {"model_tflop": round(model_flops / 1e12, 3),
                "achieved_tflop_per_s": round(achieved / 1e12, 3),
                "mfu": None if mfu is None else round(mfu, 4),
                "mfu_solve": (None if mfu_solve is None
                              else round(mfu_solve, 4))}

    per_backend = {}
    for b in backends:
        per_backend[b] = {**stats(reps[b]),
                          "e2e": stats(e2e_reps[b]),
                          "cold_wall_s": round(cold_wall[b], 3),
                          "compile_wall_s": round(
                              max(cold_wall[b] - min(reps[b]), 0.0), 3),
                          **mfu_block(b)}

    import shutil
    import tempfile

    exec_dir = tempfile.mkdtemp(prefix="nmfx-bench-exec-")
    try:
        serving = run_serving_stage(exec_dir)
        print(f"bench: serving stage: {json.dumps(serving)}",
              file=sys.stderr)
        serving.update(run_cold_persist_stage(exec_dir, serving))
    finally:
        shutil.rmtree(exec_dir, ignore_errors=True)

    traffic = run_traffic_stage()
    print(f"bench: serve traffic stage: {json.dumps(traffic)}",
          file=sys.stderr)

    durability = run_durability_stage()
    print(f"bench: durability stage: {json.dumps(durability)}",
          file=sys.stderr)

    mesh_detail = run_mesh_stage()
    print(f"bench: mesh stage: {json.dumps(mesh_detail)}",
          file=sys.stderr)

    atlas_detail = run_atlas_stage()
    print(f"bench: atlas stage: {json.dumps(atlas_detail)}",
          file=sys.stderr)

    sketched_detail = run_sketched_stage()
    print(f"bench: sketched stage: {json.dumps(sketched_detail)}",
          file=sys.stderr)

    obs_detail = run_obs_stage()
    print(f"bench: observability stage: {json.dumps(obs_detail)}",
          file=sys.stderr)

    kernel_detail = run_kernel_stage()
    print(f"bench: kernel stage: {json.dumps(kernel_detail)}",
          file=sys.stderr)

    # regression tracking: compare against the best prior round's record
    # (the warm metric drifted 1.384 s → 2.041/1.848 s across r03-r05
    # with nothing in the record to flag it) and stamp this run's
    # commit so FUTURE rounds' vs_best can name the producer
    best_prior = _best_prior_record("consensus_sweep_wall_s")
    commit = _git_commit()

    record = {
        "metric": "consensus_sweep_wall_s",
        "value": round(wall, 3),
        "unit": "s",
        "vs_baseline": round(args.target_s / wall, 3),
        # >1 = faster than every prior BENCH_r*.json round; detail
        # names which round/config/commit set that bar
        "vs_best": (round(best_prior["value"] / wall, 3)
                    if best_prior else None),
        "detail": {
            "config": f"k=2..{args.kmax} x {args.restarts} restarts, "
                      f"{args.genes}x{args.samples}, {args.algorithm}, "
                      f"maxiter={args.maxiter}, precision={args.precision}, "
                      f"backend={args.backend}, grid_exec={args.grid_exec}, "
                      "check_block=auto (pallas block-kernel route -> 4, "
                      "else 1)",
            "protocol": f"min of {args.reps} same-session warm reps, "
                        "backends interleaved; integrity- and "
                        "streamed-parity-gated per rep; since r07 the "
                        "warm rep runs the DEFAULT streamed-harvest "
                        "path (worker threads inside the timed window "
                        "— pre-r07 rounds measured the sequential "
                        "path, so vs_best crosses that protocol "
                        "change)",
            "restarts_per_s": round(total_restarts / wall, 2),
            # the FULL warm wall: sweep + host materialization + rank
            # selection complete — the tail the pre-r07 phase books
            # never saw. With the streamed harvest the gap e2e − wall
            # is only the join on the last rank's worker
            "consensus_e2e_wall_s": round(e2e_wall, 3),
            "backends": per_backend,
            "phase_s": phase_s,
            "phase_audit": phase_audit,
            "pipeline_parity": "ok",
            # zero-transfer warm path (gated above): input h2d paid
            # during the warm reps, and the process-wide cache stats
            "warm_h2d_transfers": warm_h2d_transfers,
            "warm_h2d_bytes": warm_h2d_bytes,
            "data_cache": data_cache.default_cache().stats,
            "commit": commit,
            "best_prior": best_prior,
            "exec_cache": serving,
            "serve": traffic,
            "durability": durability,
            "mesh": mesh_detail,
            "atlas": atlas_detail,
            "sketched": sketched_detail,
            "obs": obs_detail,
            "kernel": kernel_detail,
            # cold_wall_s/compile_wall_s are first-session numbers; with
            # a persistent cache dir a second session's cold run re-loads
            # these programs from disk instead of recompiling
            "persistent_compile_cache": args.compile_cache,
            "integrity": "ok",
            "mean_iters_per_k": {str(k): round(v, 1) for k, v in
                                 iters.items()},
            # primary backend's cold/compile/MFU fields mirrored at the
            # top level for cross-round record compatibility
            **{key: per_backend[primary][key]
               for key in ("cold_wall_s", "compile_wall_s", "model_tflop",
                           "achieved_tflop_per_s", "mfu", "mfu_solve")},
            "devices": [str(d) for d in jax.devices()],
        },
    }
    print(json.dumps(record))

    if args.regress:
        # self-judging round: compare what was just measured against
        # the best prior round per metric (the record is already
        # printed above, so the artifact survives the gate either way)
        from nmfx.obs import regress as obs_regress

        here = os.path.dirname(os.path.abspath(__file__))
        rounds = obs_regress.load_rounds(here)
        candidate = {"file": "<this run>",
                     "metrics": obs_regress.extract_metrics(record)}
        verdict = obs_regress.compare(rounds, candidate)
        print(f"bench: regression verdict: {json.dumps(verdict)}",
              file=sys.stderr)
        # the verdict used to be exit-code-only: the markdown trend
        # report now lands as an artifact next to the BENCH_r*.json
        # rounds it judges (the nmfx-perf rendering), so the round's
        # reviewer reads the metric x round table without re-running
        # the judge
        trend_path = os.path.join(here, "PERF_TREND.md")
        try:
            with open(trend_path, "w") as f:
                f.write(obs_regress.markdown_report(
                    rounds + [candidate], verdict) + "\n")
            print(f"bench: trend report written to {trend_path}",
                  file=sys.stderr)
        except OSError as e:
            print(f"bench: could not write trend report "
                  f"({e}); the verdict above still stands",
                  file=sys.stderr)
        if verdict["status"] == "regression":
            for row in verdict["regressions"]:
                print(
                    "bench REGRESSION: "
                    f"{row['metric']} = {row['value']:g} is "
                    f"{row['worse_by']:.1%} worse than the best prior "
                    f"round ({row['best']:g} in {row['best_round']}; "
                    f"threshold {row['threshold']:.0%})",
                    file=sys.stderr)
            raise SystemExit(2)


if __name__ == "__main__":
    main()
