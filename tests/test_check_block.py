"""check_block cadence semantics (round 6 — kernel-resident convergence
checking).

The contract (SolverConfig.check_block, docs/design.md "Check cadence"):
batching N check blocks per scheduler trip NEVER changes the check
cadence — convergence is still evaluated at every ``check_every``
boundary — so per-job stop ITERATIONS and stop REASONS are exactly
invariant on every engine. Factors are exactly invariant on the XLA
engines (converged lanes freeze between sub-blocks); on the pallas
block-kernel engine a lane that stops at an interior boundary of its
in-flight launch keeps iterating to the launch end, so its recorded
factors carry up to ``(check_block-1)*check_every`` post-stop iterations
— the same benign drift class as slot-count drift, bounded here at the
consensus level by the hardware gate's restart-equivalent band.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nmfx.config import ConsensusConfig, InitConfig, SolverConfig
from nmfx.datasets import grouped_matrix
from nmfx.init import initialize
from nmfx.ops.grid_mu import mu_grid
from nmfx.ops.packed_mu import mu_packed
from nmfx.ops.sched_mu import mu_sched
from nmfx.sweep import sweep

KS = (4, 3, 2)
R = 5


@pytest.fixture(scope="module")
def jobs():
    a = jnp.asarray(grouped_matrix(200, (10, 10, 10), effect=2.0, seed=0),
                    jnp.float32)
    k_max = max(KS)
    root = jax.random.key(123)
    w0l, h0l = [], []
    for k in KS:
        keys = jax.random.split(jax.random.fold_in(root, k), R)
        w0s, h0s = jax.vmap(
            lambda kk, k=k: initialize(kk, a, k, InitConfig(),
                                       jnp.float32))(keys)
        w0l.append(jnp.pad(w0s, ((0, 0), (0, 0), (0, k_max - k))))
        h0l.append(jnp.pad(h0s, ((0, 0), (0, k_max - k), (0, 0))))
    return a, jnp.concatenate(w0l), jnp.concatenate(h0l)


def _cfg(backend, check_block, max_iter=600):
    return SolverConfig(max_iter=max_iter, backend=backend,
                        check_block=check_block)


@pytest.mark.parametrize("ncheck", [2, 4])
def test_pallas_multi_check_decisions_exact(jobs, ncheck):
    """The pallas block-kernel route at check_block=N: stop iterations
    and reasons EXACTLY equal the N=1 schedule (the kernel's exported
    boundary snapshots/stats replay the same checks), factors within the
    documented post-stop drift class."""
    a, w0, h0 = jobs
    ref = mu_sched(a, w0, h0, _cfg("pallas", 1), slots=6)
    got = mu_sched(a, w0, h0, _cfg("pallas", ncheck), slots=6)
    np.testing.assert_array_equal(np.asarray(ref.iterations),
                                  np.asarray(got.iterations))
    np.testing.assert_array_equal(np.asarray(ref.stop_reason),
                                  np.asarray(got.stop_reason))
    # factors: the drift class, not exactness — a converged job carries
    # at most (N-1)*check_every extra MU iterations, which near a class-
    # stable fixed point moves entries at the few-percent level
    w_ref, w_got = np.asarray(ref.w), np.asarray(got.w)
    denom = np.maximum(np.abs(w_ref), 1e-3)
    assert np.max(np.abs(w_ref - w_got) / denom) < 0.25
    # and the user-visible labels barely move: the per-job label flip
    # fraction stays inside the class-stability tolerance band
    l_ref = np.asarray(jnp.argmax(ref.h, axis=1))
    l_got = np.asarray(jnp.argmax(got.h, axis=1))
    flip_frac = (l_ref != l_got).mean(axis=1)
    assert flip_frac.max() <= 0.05, flip_frac


def test_pallas_auto_resolution_matches_explicit(jobs):
    """check_block='auto' (the default) resolves to 4 on the pallas
    block-kernel route — bit-identical to the explicit value."""
    a, w0, h0 = jobs
    auto = mu_sched(a, w0, h0, SolverConfig(max_iter=600,
                                            backend="pallas"), slots=6)
    explicit = mu_sched(a, w0, h0, _cfg("pallas", 4), slots=6)
    np.testing.assert_array_equal(np.asarray(auto.iterations),
                                  np.asarray(explicit.iterations))
    np.testing.assert_array_equal(np.asarray(auto.w),
                                  np.asarray(explicit.w))


def test_dense_sched_check_block_bit_exact(jobs):
    """The XLA-dense scheduler at check_block=N interleaves the checks
    between sequential sub-blocks — converged lanes freeze before the
    next sub-block, so results are BIT-exact vs N=1 (only the harvest
    cadence changes, and harvests never change recorded results)."""
    a, w0, h0 = jobs
    ref = mu_sched(a, w0, h0, _cfg("auto", 1), slots=6)
    for ncheck in (2, 4):
        got = mu_sched(a, w0, h0, _cfg("auto", ncheck), slots=6)
        np.testing.assert_array_equal(np.asarray(ref.iterations),
                                      np.asarray(got.iterations))
        np.testing.assert_array_equal(np.asarray(ref.stop_reason),
                                      np.asarray(got.stop_reason))
        np.testing.assert_array_equal(np.asarray(ref.w),
                                      np.asarray(got.w))
        np.testing.assert_array_equal(np.asarray(ref.h),
                                      np.asarray(got.h))


def test_pallas_multi_check_max_iter_fence(jobs):
    """A cap crossing mid-launch: the in-kernel budget fence freezes the
    lane at exactly max_iter, so every job records max_iter/MAX_ITER and
    the capped factors are bit-identical to the N=1 schedule (no
    post-stop drift at the cap — the fence stops the arithmetic)."""
    from nmfx.solvers.base import StopReason

    a, w0, h0 = jobs
    # 20 % (2*4) != 0: launches of 4 sub-blocks overshoot the cap, the
    # budget fence must cut them mid-launch
    ref = mu_sched(a, w0, h0, _cfg("pallas", 1, max_iter=20), slots=4)
    got = mu_sched(a, w0, h0, _cfg("pallas", 4, max_iter=20), slots=4)
    assert np.all(np.asarray(got.iterations) == 20)
    assert np.all(np.asarray(got.stop_reason) == StopReason.MAX_ITER)
    np.testing.assert_array_equal(np.asarray(ref.w), np.asarray(got.w))
    np.testing.assert_array_equal(np.asarray(ref.h), np.asarray(got.h))


def test_fixed_batch_drivers_check_block_exact(jobs):
    """mu_grid / mu_packed honor check_block with exact semantics: the
    unrolled sub-blocks check at every check_every boundary and converged
    lanes freeze, so results are bit-identical — only the while-loop trip
    count changes. max_iter=601 makes (max_iter // check_every) NOT a
    multiple of check_block: the main loop then hands up to
    N*check_every-1 trailing iterations to the per-iteration tail loop,
    whose checks are no-ops off the check_every boundaries
    (batch_convergence's is_check gate) — so the cadence, and hence the
    results, stay exact even there."""
    a, w0, h0 = jobs
    job_ks = tuple(k for k in KS for _ in range(R))
    for max_iter in (600, 601):
        ref_g = mu_grid(a, w0, h0, _cfg("auto", 1, max_iter=max_iter),
                        job_ks=job_ks)
        got_g = mu_grid(a, w0, h0, _cfg("auto", 3, max_iter=max_iter),
                        job_ks=job_ks)
        np.testing.assert_array_equal(np.asarray(ref_g.iterations),
                                      np.asarray(got_g.iterations))
        np.testing.assert_array_equal(np.asarray(ref_g.stop_reason),
                                      np.asarray(got_g.stop_reason))
        np.testing.assert_array_equal(np.asarray(ref_g.w),
                                      np.asarray(got_g.w))

    k = KS[0]
    w0s, h0s = w0[:R, :, :k], h0[:R, :k, :]
    ref_p = mu_packed(a, w0s, h0s, _cfg("auto", 1))
    got_p = mu_packed(a, w0s, h0s, _cfg("auto", 3))
    np.testing.assert_array_equal(np.asarray(ref_p.iterations),
                                  np.asarray(got_p.iterations))
    np.testing.assert_array_equal(np.asarray(ref_p.wp),
                                  np.asarray(got_p.wp))


def test_sweep_level_parity_within_gate_band(jobs):
    """Full sweep through the pallas grid engine at check_block=4 vs 1:
    per-restart iterations/stop reasons exact, consensus within the
    hardware gate's restart-equivalent band (mean|dC|*R <= 0.6 — the
    same band bench.py --verify holds engines to on real hardware)."""
    a, _, _ = jobs
    ks = (2, 3, 4)
    out = {}
    for ncheck in (1, 4):
        scfg = SolverConfig(max_iter=600, backend="pallas",
                            check_block=ncheck)
        out[ncheck] = sweep(a, ConsensusConfig(ks=ks, restarts=R,
                                               grid_exec="grid"),
                            scfg, InitConfig(), None)
    for k in ks:
        np.testing.assert_array_equal(
            np.asarray(out[1][k].iterations),
            np.asarray(out[4][k].iterations))
        np.testing.assert_array_equal(
            np.asarray(out[1][k].stop_reasons),
            np.asarray(out[4][k].stop_reasons))
        dc = np.abs(np.asarray(out[1][k].consensus)
                    - np.asarray(out[4][k].consensus))
        assert dc.mean() * R <= 0.6, (k, dc.mean() * R)


def test_check_block_validation():
    with pytest.raises(ValueError, match="check_block"):
        SolverConfig(check_block=0)
    with pytest.raises(ValueError, match="check_block"):
        SolverConfig(check_block="fast")
    # ragged pool is check-per-trip: explicit batching must be rejected
    from nmfx.config import ExperimentalConfig

    with pytest.raises(ValueError, match="check_block"):
        mu_sched(jnp.ones((8, 8)), jnp.ones((2, 8, 2)),
                 jnp.ones((2, 2, 8)),
                 SolverConfig(backend="pallas", check_block=2,
                              max_iter=10,
                              experimental=ExperimentalConfig(ragged=True)),
                 slots=2, job_ks=(2, 2))


def test_ragged_estimates_helper(jobs):
    """ragged_estimates_from_iterations turns a previous run's per-job
    iteration counts into the hashable per-class table
    ExperimentalConfig.ragged_iters_est takes; the layout consumes it
    (and the default model WARNs when extrapolating)."""
    import logging

    from nmfx.ops.sched_mu import (_ragged_layout,
                                   ragged_estimates_from_iterations)

    job_ks = (4, 4, 3, 2, 2, 2)
    iters = [800, 600, 500, 400, 500, 600]
    est = ragged_estimates_from_iterations(job_ks, iters)
    assert est == ((2, 500.0), (3, 500.0), (4, 700.0))
    layout = _ragged_layout(job_ks, 16, iters_est=est, max_iter=10000)
    assert sum(c.slots * c.k for c in layout) <= 16
    with pytest.raises(ValueError, match="ragged_iters_est"):
        _ragged_layout(job_ks, 16, iters_est=((2, 500.0),),
                       max_iter=10000)
    with pytest.raises(ValueError, match="iterations"):
        ragged_estimates_from_iterations((2, 3), [1, 2, 3])
    # default model outside its calibrated profile: loud, not silent
    logger = logging.getLogger("nmfx")
    records = []
    handler = logging.Handler()
    handler.emit = records.append
    logger.addHandler(handler)
    try:
        _ragged_layout((12, 12, 2), 40, max_iter=10000)
    finally:
        logger.removeHandler(handler)
    assert any("calibrated" in r.getMessage() for r in records)
