"""Block-shape autotuner store semantics (round 7).

The contract under test (nmfx/autotune.py): a COLD resolve at an
unseen (config, shape-bucket, env) key runs exactly one timed
candidate search; every WARM resolve — same process (memo) or a fresh
process reading the persisted entry — serves the identical resolved
config with ZERO searches, gated by the
``nmfx_autotune_{searches,hits}_total`` counter pair; and nothing
short of a full key match is ever served (corrupt entries, foreign
env fingerprints and differing config fields all degrade to a
re-measure, never to a mis-applied shape). All interpret-mode on CPU —
what's pinned is the store logic, not kernel speed.
"""

import dataclasses
import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from nmfx import autotune, exec_cache
from nmfx.config import (ConsensusConfig, ExperimentalConfig, InitConfig,
                         SolverConfig)
from nmfx.datasets import grouped_matrix
from nmfx.sweep import sweep

M, N, K, SLOTS = 64, 32, 2, 2


@pytest.fixture(autouse=True)
def _fresh_store():
    """Each test starts as a fresh process would: empty in-process memo
    and re-armed warn-once set (counters are global monotonic — tests
    assert on deltas)."""
    with autotune._lock:
        autotune._memo.clear()
        autotune._warned.clear()
    yield
    with autotune._lock:
        autotune._memo.clear()
        autotune._warned.clear()


@pytest.fixture
def small_grid(monkeypatch):
    """Key-isolation tests force repeated cold searches but don't need
    the FULL candidate grid each time — trim it to two candidates so
    every forced re-search stays cheap. The full grid's cold path is
    exercised once, in test_cold_search_warm_memo_warm_disk."""
    real = autotune._candidates
    monkeypatch.setattr(autotune, "_candidates",
                        lambda *a, **k: real(*a, **k)[:2])


def _cfg(**exp_kw):
    exp_kw.setdefault("autotune", "on")
    return SolverConfig(backend="pallas", max_iter=40,
                        experimental=ExperimentalConfig(**exp_kw))


def _counters():
    return autotune.searches_total.total(), autotune.hits_total.total()


def _resolve(cfg, cache_dir=None):
    return autotune.resolve(cfg, M, N, K, SLOTS, cache_dir=cache_dir)


def test_cold_search_warm_memo_warm_disk(tmp_path):
    """The lifecycle: one search cold; memo hit warm; after a simulated
    process restart (memo cleared) the persisted entry serves the
    IDENTICAL config with zero further searches."""
    d = str(tmp_path)
    s0, h0 = _counters()
    cold = _resolve(_cfg(), d)
    s1, h1 = _counters()
    assert (s1 - s0, h1 - h0) == (1, 0)
    # resolved = fully explicit, flag off — downstream keys see numerics
    assert cold.experimental.autotune == "off"
    assert cold.check_block != "auto"
    assert cold.experimental.block_m is not None
    assert cold.experimental.fused_updates in ("phased", "fused")

    warm_memo = _resolve(_cfg(), d)
    s2, h2 = _counters()
    assert (s2 - s1, h2 - h1) == (0, 1)
    assert warm_memo == cold

    with autotune._lock:
        autotune._memo.clear()
    warm_disk = _resolve(_cfg(), d)
    s3, h3 = _counters()
    assert (s3 - s2, h3 - h2) == (0, 1)
    assert warm_disk == cold


def test_corrupt_entry_warns_once_and_researches(tmp_path, small_grid):
    """A truncated/garbage entry is a warn-once + remove + fresh search
    — and the re-search republishes a valid entry."""
    d = str(tmp_path)
    _resolve(_cfg(), d)
    path = autotune._disk_path(d, autotune._key_repr(_cfg(), M, N, K,
                                                     SLOTS))
    assert os.path.exists(path)
    with open(path, "w") as f:
        f.write('{"format": 1, "best"')  # truncated mid-record
    with autotune._lock:
        autotune._memo.clear()
    s0, _ = _counters()
    with pytest.warns(RuntimeWarning, match="corrupt"):
        again = _resolve(_cfg(), d)
    s1, _ = _counters()
    assert s1 - s0 == 1
    # the re-search resolves fully (the winner itself is a timing
    # verdict — not asserted; what matters is no corrupt value leaked)
    assert again.check_block != "auto"
    assert again.experimental.block_m is not None
    with open(path) as f:
        rec = json.load(f)  # republished entry is whole again
    assert rec["format"] == autotune._FORMAT


def test_foreign_key_entry_never_served(tmp_path, small_grid):
    """An entry whose recorded key differs from the requested one (a
    hand-moved file, a hash collision) is removed and re-searched —
    the stored shape is never applied across the mismatch."""
    d = str(tmp_path)
    _resolve(_cfg(), d)
    path = autotune._disk_path(d, autotune._key_repr(_cfg(), M, N, K,
                                                     SLOTS))
    with open(path) as f:
        rec = json.load(f)
    rec["key"] = "something else entirely"
    with open(path, "w") as f:
        json.dump(rec, f)
    with autotune._lock:
        autotune._memo.clear()
    s0, _ = _counters()
    with pytest.warns(RuntimeWarning, match="different key"):
        _resolve(_cfg(), d)
    s1, _ = _counters()
    assert s1 - s0 == 1


def test_env_mismatch_not_served(tmp_path, monkeypatch, small_grid):
    """A tuned shape never crosses an environment change: a different
    device kind / jax version fingerprint keys a DIFFERENT entry, so
    the warm path misses and a fresh search runs."""
    d = str(tmp_path)
    _resolve(_cfg(), d)
    with autotune._lock:
        autotune._memo.clear()
    monkeypatch.setattr(exec_cache, "_env_fingerprint",
                        lambda: ("jax-9.9.9", "jaxlib-9.9.9", "tpu",
                                 "TPU v9", "0.0.0"))
    s0, h0 = _counters()
    _resolve(_cfg(), d)
    s1, h1 = _counters()
    assert (s1 - s0, h1 - h0) == (1, 0)


def test_config_field_splits_key(tmp_path, small_grid):
    """Every non-tunable config field reaches the key: a different
    matmul_precision must search fresh, not inherit the tuned shape."""
    d = str(tmp_path)
    _resolve(_cfg(), d)
    s0, h0 = _counters()
    _resolve(dataclasses.replace(_cfg(), matmul_precision="highest"), d)
    s1, h1 = _counters()
    assert (s1 - s0, h1 - h0) == (1, 0)


def test_explicit_overrides_win_and_share_entry(tmp_path, small_grid):
    """Tunable fields are exempt from the key, so an explicit-override
    config WARM-hits the entry a pure-auto resolve stored — and the
    explicit values survive apply (tuned values fill only auto/None
    gaps)."""
    d = str(tmp_path)
    _resolve(_cfg(), d)
    s0, h0 = _counters()
    explicit = SolverConfig(
        backend="pallas", max_iter=40, check_block=2,
        experimental=ExperimentalConfig(autotune="on", block_m=128,
                                        fused_updates="fused"))
    got = autotune.resolve(explicit, M, N, K, SLOTS, cache_dir=d)
    s1, h1 = _counters()
    assert (s1 - s0, h1 - h0) == (0, 1)
    assert got.check_block == 2
    assert got.experimental.block_m == 128
    assert got.experimental.fused_updates == "fused"


def test_off_and_non_pallas_are_noops():
    """autotune='off' is an exact identity (the store is never read);
    'on' off the pallas route or on the ragged pool resolves to just
    the flag flipped off — no search, no counters, no tuned fields."""
    s0, h0 = _counters()
    off = SolverConfig(backend="pallas", max_iter=40)
    assert _resolve(off) is off
    xla = _resolve(SolverConfig(
        backend="auto", max_iter=40,
        experimental=ExperimentalConfig(autotune="on")))
    assert xla.experimental.autotune == "off"
    assert xla.check_block == "auto"
    assert xla.experimental.block_m is None
    ragged = _resolve(_cfg(ragged=True))
    assert ragged.experimental.autotune == "off"
    assert ragged.experimental.ragged is True
    assert ragged.check_block == "auto"
    s1, h1 = _counters()
    assert (s1 - s0, h1 - h0) == (0, 0)


def test_resolve_idempotent(tmp_path, small_grid):
    """Resolving a resolved config is an identity — the warm process's
    second resolve can never drift the numerics a checkpoint was
    written under."""
    d = str(tmp_path)
    once = _resolve(_cfg(), d)
    assert _resolve(once, d) is once


def test_hals_candidates_respect_tolfun():
    """The candidate grid mirrors the scheduler's hals restriction:
    with TolFun armed only check-per-trip phased candidates exist;
    disarming TolFun re-opens the multi-check rungs."""
    armed = autotune._candidates(
        SolverConfig(algorithm="hals", backend="pallas", max_iter=40),
        256, 64, K, SLOTS)
    assert armed and all(c["check_block"] == 1 for c in armed)
    assert all(c["fused_updates"] == "phased" for c in armed)
    open_ = autotune._candidates(
        SolverConfig(algorithm="hals", backend="pallas", max_iter=40,
                     use_tol_checks=False),
        256, 64, K, SLOTS)
    assert any(c["check_block"] > 1 for c in open_)
    assert all(c["fused_updates"] == "phased" for c in open_)


def test_autotune_key_fields_hook():
    """The NMFX001-family introspection hook: exactly the declared
    tunables are missing from the covered sets, nothing else."""
    solver, exp = autotune.autotune_key_fields()
    assert "check_block" not in solver
    assert "backend" in solver and "max_iter" in solver
    assert {"autotune", "block_m", "fused_updates"}.isdisjoint(exp)
    assert "factor_dtype" in exp and "ragged" in exp


def test_sweep_resolves_before_solving(tmp_path, small_grid):
    """End to end: a sweep with experimental.autotune='on' resolves
    host-side before any tracing (one cold search), and a second
    identical sweep is fully warm — zero searches, bit-identical
    results (the resolved config, hence the numerics, are stable
    across cold and warm)."""
    a = jnp.asarray(grouped_matrix(96, (48, 48), effect=2.0, seed=0),
                    jnp.float32)
    ccfg = ConsensusConfig(ks=(2, 3), restarts=3, grid_exec="grid")
    scfg = _cfg()
    s0, h0 = _counters()
    cold = sweep(a, ccfg, scfg, InitConfig(), None)
    s1, h1 = _counters()
    assert s1 - s0 == 1
    warm = sweep(a, ccfg, scfg, InitConfig(), None)
    s2, h2 = _counters()
    assert (s2 - s1, h2 > h1) == (0, True)
    for k in (2, 3):
        np.testing.assert_array_equal(np.asarray(cold[k].consensus),
                                      np.asarray(warm[k].consensus))
        np.testing.assert_array_equal(np.asarray(cold[k].iterations),
                                      np.asarray(warm[k].iterations))
