"""Multi-tenant serving engine (ISSUE 6 tentpole): async request queue
+ continuous cross-request restart batching.

The acceptance property is counter-gated, not wall-clock-gated: with
>= 2 concurrent compatible requests, at least one executable dispatch
must contain lanes from >= 2 distinct requests
(``serve.packed_dispatch_count()``), while each request's
ConsensusResult stays BIT-IDENTICAL to its solo ``nmfconsensus`` run of
the same request through the same serving layer — the same exactness
discipline the streamed-vs-sequential harvest parity pins.

Queue mechanics (admission control, priority order, deadlines,
cancellation, close semantics) are driven against a fake
:class:`nmfx.serve.Engine` so they run in milliseconds with no device
dispatch; the real ``ExecCacheEngine`` is exercised by the parity and
degradation tests on the smallest shapes (tier-1 budget discipline)."""

import threading
import time

import numpy as np
import pytest

import nmfx.serve as serve
from nmfx.config import InitConfig, SolverConfig
from nmfx.serve import (DeadlineExceeded, NMFXServer, QueueFull,
                        ServeConfig, ServerClosed, serve_key_fields)

KS = (2, 3)
RESTARTS = 2
MAX_ITER = 30


@pytest.fixture(scope="module")
def small_data():
    from nmfx.datasets import two_group_matrix

    return two_group_matrix(n_genes=60, n_per_group=10, seed=3)


@pytest.fixture(scope="module")
def scfg():
    return SolverConfig(max_iter=MAX_ITER)


def _solo(data, exec_cache, *, ks=KS, restarts=RESTARTS, seed=11,
          scfg=None, **kw):
    """The solo reference: the SAME request through nmfconsensus on the
    same serving layer (exec cache, mesh=None) — the exactness
    contract's right-hand side."""
    from nmfx.api import nmfconsensus

    return nmfconsensus(data, ks=ks, restarts=restarts, seed=seed,
                        solver_cfg=scfg, use_mesh=False,
                        exec_cache=exec_cache, **kw)


def assert_result_bit_equal(got, ref):
    assert set(got.per_k) == set(ref.per_k)
    for k in ref.per_k:
        s, q = got.per_k[k], ref.per_k[k]
        assert np.array_equal(np.asarray(s.consensus),
                              np.asarray(q.consensus)), f"consensus k={k}"
        assert s.rho == q.rho, f"rho k={k}"
        assert np.array_equal(np.asarray(s.membership),
                              np.asarray(q.membership)), f"membership k={k}"
        assert np.array_equal(np.asarray(s.order),
                              np.asarray(q.order)), f"order k={k}"
        assert np.array_equal(np.asarray(s.iterations),
                              np.asarray(q.iterations)), f"iterations k={k}"
        assert np.array_equal(np.asarray(s.dnorms),
                              np.asarray(q.dnorms)), f"dnorms k={k}"
        assert np.array_equal(np.asarray(s.stop_reasons),
                              np.asarray(q.stop_reasons)), \
            f"stop_reasons k={k}"
        assert np.array_equal(np.asarray(s.best_w),
                              np.asarray(q.best_w)), f"best_w k={k}"
        assert np.array_equal(np.asarray(s.best_h),
                              np.asarray(q.best_h)), f"best_h k={k}"


# ---------------------------------------------------------------------
# the acceptance criterion: cross-request lane packing, counter-gated,
# bit-identical per request
# ---------------------------------------------------------------------

def test_cross_request_packing_bit_identical(small_data, scfg):
    from nmfx.exec_cache import ExecCache

    cache = ExecCache()
    before = serve.packed_dispatch_count()
    with NMFXServer(ServeConfig(), exec_cache=cache,
                    start=False) as srv:
        # paused submit: both requests are queued before the scheduler
        # runs, so batch construction is deterministic
        f1 = srv.submit(small_data, ks=KS, restarts=RESTARTS, seed=11,
                        solver_cfg=scfg)
        f2 = srv.submit(small_data, ks=(2,), restarts=RESTARTS, seed=29,
                        solver_cfg=scfg)
        srv.resume()
        r1 = f1.result(timeout=600)
        r2 = f2.result(timeout=600)
    # the packing contract is gated on the module counter, not timing
    assert serve.packed_dispatch_count() == before + 1
    assert srv.stats()["packed_requests"] == 2
    assert f1.stats.packed_requests == 2
    assert f1.stats.lanes == len(KS) * RESTARTS
    assert f1.stats.queue_wait_s is not None
    assert f1.stats.pack_s is not None
    assert f1.stats.latency_s is not None
    # each request's result == its solo run through the same layer
    assert_result_bit_equal(r1, _solo(small_data, cache, seed=11,
                                      scfg=scfg))
    assert_result_bit_equal(r2, _solo(small_data, cache, ks=(2,),
                                      seed=29, scfg=scfg))


def test_three_request_pack_bit_identical_toy_shape():
    """Regression: the PR-12-flagged pre-existing violation — a
    ≥3-request packed dispatch at toy shapes (120×48, maxiter 400,
    bfloat16 precision) drifted bitwise from the solo runs in
    dnorms/best_w/best_h (~1 ulp/iteration) while consensus/labels
    agreed, because the packed pool's wider lane-folded GEMMs
    partitioned their reductions differently from each request's
    narrower solo pool on this 8-virtual-device platform. The fix pads
    every serving-tier dispatch to the same fixed ``grid_slots``-wide
    pool (``sweep._pad_pool_lanes``) with the tail cascade pinned off,
    so per-lane GEMM shapes — and reduction order — are
    composition-independent. This test runs the exact deterministic
    pause/resume composition that reproduced the bug and asserts full
    bit-identity for every request, not just the head."""
    from nmfx.datasets import grouped_matrix
    from nmfx.exec_cache import ExecCache

    a = grouped_matrix(120, (12,) * 4, effect=2.0, seed=0)
    scfg3 = SolverConfig(algorithm="mu", max_iter=400,
                         matmul_precision="bfloat16")
    seeds = (1012, 123, 456)
    cache = ExecCache()
    before = serve.packed_dispatch_count()
    with NMFXServer(ServeConfig(max_batch_requests=4), exec_cache=cache,
                    start=False) as srv:
        futs = [(sd, srv.submit(a, ks=(2, 3), restarts=6, seed=sd,
                                solver_cfg=scfg3)) for sd in seeds]
        srv.resume()
        results = [(sd, f.result(timeout=600)) for sd, f in futs]
    # all three requests must have shared ONE packed dispatch — a
    # degraded (solo) composition would not exercise the bug
    assert serve.packed_dispatch_count() == before + 1
    assert srv.stats()["packed_requests"] == 3
    for sd, res in results:
        assert_result_bit_equal(
            res, _solo(a, cache, ks=(2, 3), restarts=6, seed=sd,
                       scfg=scfg3))


def test_incompatible_matrices_degrade_to_solo(small_data, scfg):
    """Different input matrices share no resident device buffer: they
    must NOT pack (the DataKey is part of the compatibility key), each
    dispatches solo, and both results stay exact."""
    from nmfx.exec_cache import ExecCache

    other = np.asarray(small_data)[:, :18].copy()
    cache = ExecCache()
    packed_before = serve.packed_dispatch_count()
    disp_before = serve.dispatch_count()
    with NMFXServer(ServeConfig(), exec_cache=cache,
                    start=False) as srv:
        f1 = srv.submit(small_data, ks=(2,), restarts=RESTARTS, seed=11,
                        solver_cfg=scfg)
        f2 = srv.submit(other, ks=(2,), restarts=RESTARTS, seed=11,
                        solver_cfg=scfg)
        srv.resume()
        r1 = f1.result(timeout=600)
        r2 = f2.result(timeout=600)
    assert serve.packed_dispatch_count() == packed_before
    assert serve.dispatch_count() == disp_before + 2
    assert_result_bit_equal(r1, _solo(small_data, cache, ks=(2,),
                                      seed=11, scfg=scfg))
    assert_result_bit_equal(r2, _solo(other, cache, ks=(2,), seed=11,
                                      scfg=scfg))


def test_deadline_budget_clamp_matches_clamped_solo(small_data):
    """A deadline request under ``iter_rate_estimate`` dispatches solo
    with its per-lane iteration budget clamped (the in-kernel budget
    mechanism is the only eviction a launched dispatch admits); its
    results are exact against a solo run at the SAME clamped
    max_iter — the documented contract for deadline-degraded output."""
    from nmfx.exec_cache import ExecCache

    scfg = SolverConfig(max_iter=10_000)
    cache = ExecCache()
    cfg = ServeConfig(iter_rate_estimate=4.0)
    with NMFXServer(cfg, exec_cache=cache, start=False) as srv:
        f = srv.submit(small_data, ks=(2,), restarts=RESTARTS, seed=11,
                       solver_cfg=scfg, timeout=600.0)
        srv.resume()
        r = f.result(timeout=600)
    budget = f.stats.budget_iters
    assert budget is not None and budget < scfg.max_iter
    # power-of-two multiple of check_every: bounded executable churn
    step = budget // scfg.check_every
    assert budget % scfg.check_every == 0
    assert step & (step - 1) == 0
    assert srv.stats()["budget_clamped"] == 1
    clamped = SolverConfig(max_iter=budget)
    assert_result_bit_equal(r, _solo(small_data, cache, ks=(2,),
                                     seed=11, scfg=clamped))


# ---------------------------------------------------------------------
# queue mechanics against a fake Engine (no device dispatch)
# ---------------------------------------------------------------------

def _fake_raw(req):
    """A host-side KSweepOutput per rank, shaped like a real sweep's
    output (block-diagonal consensus so host rank selection is
    well-posed) — lets the real harvest workers run end to end."""
    from nmfx.sweep import KSweepOutput

    n = req.a.shape[1]
    m = req.a.shape[0]
    out = {}
    for k in req.ks:
        labels = np.arange(n) * k // n
        cons = (labels[:, None] == labels[None, :]).astype(np.float32)
        out[k] = KSweepOutput(
            consensus=cons,
            iterations=np.full(req.restarts, 7, np.int32),
            dnorms=np.linspace(0.5, 0.6, req.restarts).astype(np.float32),
            stop_reasons=np.zeros(req.restarts, np.int32),
            labels=np.tile(labels, (req.restarts, 1)).astype(np.int32),
            best_w=np.ones((m, k), np.float32),
            best_h=np.ones((k, n), np.float32))
    return out


class FakeEngine:
    """Scriptable :class:`nmfx.serve.Engine`: records dispatch order and
    the SolverConfig each solo dispatch received."""

    def __init__(self, compat="shared", delay=0.0):
        self.compat = compat
        self.delay = delay
        self.solo = []  # (seq, scfg)
        self.packed = []  # tuple of seqs per packed dispatch
        self.placed = 0

    def compatibility_key(self, req):
        return self.compat

    def place(self, req):
        self.placed += 1
        return None

    def dispatch_solo(self, req, placed, scfg):
        if self.delay:
            time.sleep(self.delay)
        self.solo.append((req.seq, scfg))
        return _fake_raw(req)

    def dispatch_packed(self, reqs, placed):
        if self.delay:
            time.sleep(self.delay)
        self.packed.append(tuple(r.seq for r in reqs))
        return [_fake_raw(r) for r in reqs]


def _mat(n=6, m=8):
    rng = np.random.default_rng(0)
    return rng.random((m, n)).astype(np.float32)


def test_queued_deadline_expires_typed_without_dispatch():
    eng = FakeEngine()
    with NMFXServer(ServeConfig(), engine=eng, start=False) as srv:
        f = srv.submit(_mat(), ks=(2,), restarts=2, timeout=0.02)
        time.sleep(0.08)
        srv.resume()
        with pytest.raises(DeadlineExceeded):
            f.result(timeout=30)
    assert eng.solo == [] and eng.packed == []  # never dispatched
    assert srv.stats()["deadline_expired"] == 1
    assert f.stats.latency_s is not None


def test_mid_solve_deadline_resolves_typed():
    """A deadline that expires while the dispatch is in flight resolves
    to DeadlineExceeded at completion — the computed results are
    discarded, never returned silently-late."""
    eng = FakeEngine(compat=None, delay=0.5)
    with NMFXServer(ServeConfig(), engine=eng, start=False) as srv:
        f = srv.submit(_mat(), ks=(2,), restarts=2, timeout=0.25)
        srv.resume()
        with pytest.raises(DeadlineExceeded):
            f.result(timeout=30)
    assert len(eng.solo) == 1  # it DID dispatch; expiry was mid-solve


def test_admission_queue_depth_bound():
    eng = FakeEngine()
    srv = NMFXServer(ServeConfig(max_queue_depth=1), engine=eng,
                     start=False)
    f1 = srv.submit(_mat(), ks=(2,), restarts=2)
    with pytest.raises(QueueFull):
        srv.submit(_mat(), ks=(2,), restarts=2)
    assert srv.stats()["rejected"] == 1
    srv.resume()
    f1.result(timeout=30)
    srv.close()


def test_admission_pending_bytes_bound():
    eng = FakeEngine()
    a = _mat()
    srv = NMFXServer(ServeConfig(max_pending_bytes=a.nbytes + 1),
                     engine=eng, start=False)
    f1 = srv.submit(a, ks=(2,), restarts=2)
    with pytest.raises(QueueFull):
        srv.submit(a, ks=(2,), restarts=2)
    srv.resume()
    f1.result(timeout=30)
    # dispatch released the pending bytes: admission reopens
    f3 = srv.submit(a, ks=(2,), restarts=2)
    f3.result(timeout=30)
    srv.close()


def test_priority_and_deadline_order():
    """Dispatch order is (priority desc, deadline asc, arrival): an
    urgent late arrival overtakes the queue."""
    eng = FakeEngine(compat=None)  # solo: one dispatch per request
    with NMFXServer(ServeConfig(), engine=eng, start=False) as srv:
        f_low = srv.submit(_mat(), ks=(2,), restarts=2, priority=0)
        f_dl = srv.submit(_mat(), ks=(2,), restarts=2, priority=0,
                          timeout=120.0)
        f_hi = srv.submit(_mat(), ks=(2,), restarts=2, priority=5)
        srv.resume()
        for f in (f_low, f_dl, f_hi):
            f.result(timeout=30)
    # priority 5 first; among equal priorities the deadline-bearing
    # request precedes the open-ended earlier arrival (seq = submit
    # order: f_low=0, f_dl=1, f_hi=2)
    assert [s for s, _ in eng.solo] == [2, 1, 0]


def test_packing_respects_max_batch_requests():
    eng = FakeEngine(compat="shared")
    with NMFXServer(ServeConfig(max_batch_requests=2), engine=eng,
                    start=False) as srv:
        futs = [srv.submit(_mat(), ks=(2,), restarts=2)
                for _ in range(4)]
        srv.resume()
        for f in futs:
            f.result(timeout=30)
    assert all(len(p) <= 2 for p in eng.packed)
    assert sum(len(p) for p in eng.packed) + len(eng.solo) == 4


def test_budget_clamped_mate_is_not_packed():
    """A deadline request whose budget would be clamped
    (iter_rate_estimate set) must never ride a packed dispatch as a
    MATE: packed lanes run at the shared max_iter, so a mid-solve
    expiry would discard its computed results. It stays queued, pops as
    head, and dispatches solo with the clamped config."""
    eng = FakeEngine(compat="shared")
    cfg = ServeConfig(max_batch_requests=4, iter_rate_estimate=10.0)
    with NMFXServer(cfg, engine=eng, start=False) as srv:
        # two open-ended requests at high priority become the packed
        # head+mate; the deadline request (lower priority, so never the
        # first head) is the candidate mate the clamp must exclude
        f1 = srv.submit(_mat(), ks=(2,), restarts=2, priority=5)
        f2 = srv.submit(_mat(), ks=(2,), restarts=2, priority=5)
        f_dl = srv.submit(_mat(), ks=(2,), restarts=2, priority=0,
                          timeout=5.0)
        srv.resume()
        for f in (f1, f2, f_dl):
            f.result(timeout=30)
    assert eng.packed == [(0, 1)]  # the open-ended pair packed
    assert [s for s, _ in eng.solo] == [2]  # the deadline req: solo
    clamped = eng.solo[0][1]
    assert clamped.max_iter < SolverConfig().max_iter  # and clamped
    assert f_dl.stats.budget_iters == clamped.max_iter
    assert f_dl.stats.packed_requests == 1


def test_incompatible_engine_key_means_solo():
    """compat=None (NNDSVD-style requests) must never pack."""
    eng = FakeEngine(compat=None)
    with NMFXServer(ServeConfig(), engine=eng, start=False) as srv:
        futs = [srv.submit(_mat(), ks=(2,), restarts=2)
                for _ in range(3)]
        srv.resume()
        for f in futs:
            f.result(timeout=30)
    assert eng.packed == []
    assert len(eng.solo) == 3


def test_pack_disabled_is_the_ab_baseline():
    eng = FakeEngine(compat="shared")
    with NMFXServer(ServeConfig(pack=False), engine=eng,
                    start=False) as srv:
        futs = [srv.submit(_mat(), ks=(2,), restarts=2)
                for _ in range(3)]
        srv.resume()
        for f in futs:
            f.result(timeout=30)
    assert eng.packed == []
    assert len(eng.solo) == 3


def test_batch_linger_packs_near_simultaneous_arrivals():
    """The continuous-batching knob: a compatible request arriving
    within the linger window rides the held dispatch's lanes."""
    eng = FakeEngine(compat="shared")
    with NMFXServer(ServeConfig(batch_linger_s=1.0), engine=eng) as srv:
        f1 = srv.submit(_mat(), ks=(2,), restarts=2)
        time.sleep(0.1)  # scheduler pops f1 and lingers
        f2 = srv.submit(_mat(), ks=(2,), restarts=2)
        f1.result(timeout=30)
        f2.result(timeout=30)
    assert eng.packed == [(0, 1)]


def test_cancellation_before_dispatch():
    eng = FakeEngine()
    with NMFXServer(ServeConfig(), engine=eng, start=False) as srv:
        f = srv.submit(_mat(), ks=(2,), restarts=2)
        assert f.cancel()
        srv.resume()
        time.sleep(0.05)
    assert f.cancelled()
    assert eng.solo == [] and eng.packed == []
    assert srv.stats()["cancelled"] == 1


def test_submit_after_close_raises():
    srv = NMFXServer(ServeConfig(), engine=FakeEngine())
    srv.close()
    with pytest.raises(ServerClosed):
        srv.submit(_mat(), ks=(2,), restarts=2)


def test_close_drains_inflight_requests():
    eng = FakeEngine(delay=0.05)
    srv = NMFXServer(ServeConfig(), engine=eng, start=False)
    futs = [srv.submit(_mat(), ks=(2,), restarts=2) for _ in range(3)]
    srv.resume()
    srv.close()  # must drain, not abandon
    for f in futs:
        assert f.result(timeout=1) is not None


def test_close_cancel_pending_fails_queued():
    eng = FakeEngine()
    srv = NMFXServer(ServeConfig(), engine=eng, start=False)
    f = srv.submit(_mat(), ks=(2,), restarts=2)
    srv.close(cancel_pending=True)
    with pytest.raises(ServerClosed):
        f.result(timeout=5)
    assert eng.solo == []


class _SlowStartEngine(FakeEngine):
    """FakeEngine that signals when a dispatch has ENTERED the engine —
    the close()-race tests use it to call close() while a dispatch is
    genuinely in flight, not merely queued."""

    def __init__(self, compat="shared", delay=0.25, packed_fails=False):
        super().__init__(compat=compat, delay=delay)
        self.packed_fails = packed_fails
        self.started = threading.Event()

    def dispatch_packed(self, reqs, placed):
        self.started.set()
        if self.packed_fails:
            raise RuntimeError("packed path down")
        return super().dispatch_packed(reqs, placed)

    def dispatch_solo(self, req, placed, scfg):
        self.started.set()
        return super().dispatch_solo(req, placed, scfg)


def test_close_races_inflight_packed_dispatch():
    """ISSUE 7 satellite: close() called while a PACKED dispatch is in
    flight must drain it — both packed requests resolve with RESULTS,
    no future is left unresolved, and the close returns only after the
    harvest queue is empty."""
    eng = _SlowStartEngine(delay=0.25)
    srv = NMFXServer(ServeConfig(), engine=eng, start=False)
    f1 = srv.submit(_mat(), ks=(2,), restarts=2)
    f2 = srv.submit(_mat(), ks=(2,), restarts=2)
    srv.resume()
    assert eng.started.wait(timeout=10)
    srv.close()  # racing the in-flight packed dispatch
    # drained: both futures already resolved when close() returned
    assert f1.done() and f2.done()
    assert f1.result(timeout=0).per_k[2] is not None
    assert f2.result(timeout=0).per_k[2] is not None
    assert eng.packed == [tuple(sorted(p)) for p in eng.packed]
    assert srv.stats()["completed"] == 2


def test_close_races_inflight_solo_fallback():
    """close() racing the solo FALLBACK of a failed packed dispatch:
    the degraded per-request solo retries still run to completion under
    close — every future resolves with a result."""
    import nmfx.faults as faults

    faults._reset_warned()
    eng = _SlowStartEngine(delay=0.2, packed_fails=True)
    srv = NMFXServer(ServeConfig(dispatch_retries=1,
                                 retry_backoff_s=0.01),
                     engine=eng, start=False)
    f1 = srv.submit(_mat(), ks=(2,), restarts=2)
    f2 = srv.submit(_mat(), ks=(2,), restarts=2)
    srv.resume()
    assert eng.started.wait(timeout=10)
    srv.close()  # racing the in-flight solo fallback
    assert f1.done() and f2.done()
    assert f1.result(timeout=0).per_k[2] is not None
    assert f2.result(timeout=0).per_k[2] is not None
    assert len(eng.solo) == 2  # both mates degraded to solo
    assert srv.stats()["completed"] == 2


def test_close_cancel_pending_spares_inflight():
    """close(cancel_pending=True) racing a dispatch: the IN-FLIGHT
    request completes with a result, queued-undispatched ones fail with
    ServerClosed — and nothing is left unresolved either way."""
    eng = _SlowStartEngine(compat=None, delay=0.25)
    srv = NMFXServer(ServeConfig(pack=False), engine=eng, start=False)
    futs = [srv.submit(_mat(), ks=(2,), restarts=2) for _ in range(3)]
    srv.resume()
    assert eng.started.wait(timeout=10)  # head is in flight
    srv.close(cancel_pending=True)
    assert all(f.done() for f in futs)
    outcomes = []
    for f in futs:
        try:
            outcomes.append(type(f.result(timeout=0)).__name__)
        except ServerClosed:
            outcomes.append("ServerClosed")
    # exactly the in-flight head completed; the rest were refused typed
    assert outcomes.count("ConsensusResult") == 1
    assert outcomes.count("ServerClosed") == 2


def test_engine_failure_propagates_to_futures():
    """A permanently failing dispatch resolves the future with the
    typed RequestFailed (ISSUE 7) whose __cause__ chains the underlying
    engine error — after exhausting the configured solo retries."""
    from nmfx.serve import RequestFailed

    attempts = []

    class Boom(FakeEngine):
        def dispatch_solo(self, req, placed, scfg):
            attempts.append(time.monotonic())
            raise RuntimeError("device on fire")

    cfg = ServeConfig(dispatch_retries=2, retry_backoff_s=0.01)
    with NMFXServer(cfg, engine=Boom(compat=None)) as srv:
        f = srv.submit(_mat(), ks=(2,), restarts=2)
        with pytest.raises(RequestFailed) as exc:
            f.result(timeout=30)
    assert isinstance(exc.value.__cause__, RuntimeError)
    assert "device on fire" in str(exc.value.__cause__)
    assert len(attempts) == 3  # 1 attempt + dispatch_retries
    assert srv.stats()["failed"] == 1


def test_concurrent_submitters():
    """Many threads submitting at once: every future resolves, counters
    balance — the submit path's lock discipline under contention."""
    eng = FakeEngine(compat="shared")
    results = []
    with NMFXServer(ServeConfig(max_queue_depth=64), engine=eng) as srv:
        def worker():
            f = srv.submit(_mat(), ks=(2,), restarts=2)
            results.append(f.result(timeout=60))

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert len(results) == 8
    s = srv.stats()
    assert s["submitted"] == 8 and s["completed"] == 8
    assert sum(len(p) for p in eng.packed) + len(eng.solo) == 8


# ---------------------------------------------------------------------
# config + module surface
# ---------------------------------------------------------------------

def test_serve_config_validation():
    with pytest.raises(ValueError):
        ServeConfig(max_queue_depth=0)
    with pytest.raises(ValueError):
        ServeConfig(max_batch_requests=0)
    with pytest.raises(ValueError):
        ServeConfig(batch_linger_s=-1.0)
    with pytest.raises(ValueError):
        ServeConfig(default_timeout_s=0.0)
    with pytest.raises(ValueError):
        ServeConfig(iter_rate_estimate=-2.0)
    with pytest.raises(ValueError):
        ServeConfig(harvest_workers=0)


def test_serve_key_fields_covers_every_field():
    import dataclasses

    assert serve_key_fields() == frozenset(
        f.name for f in dataclasses.fields(ServeConfig))


def test_submit_validation():
    srv = NMFXServer(ServeConfig(), engine=FakeEngine(), start=False)
    with pytest.raises(ValueError):
        srv.submit(-_mat(), ks=(2,), restarts=2)  # negative entries
    with pytest.raises(ValueError):
        srv.submit(_mat(), ks=(), restarts=2)
    with pytest.raises(ValueError):
        srv.submit(_mat(), ks=(1,), restarts=2)
    with pytest.raises(ValueError):
        srv.submit(_mat(), ks=(2,), restarts=0)
    with pytest.raises(ValueError):
        srv.submit(_mat(), ks=(2,), restarts=2, timeout=1.0,
                   deadline=time.monotonic() + 1.0)
    srv.close()


def test_default_timeout_applies():
    eng = FakeEngine()
    with NMFXServer(ServeConfig(default_timeout_s=0.02), engine=eng,
                    start=False) as srv:
        f = srv.submit(_mat(), ks=(2,), restarts=2)
        time.sleep(0.08)
        srv.resume()
        with pytest.raises(DeadlineExceeded):
            f.result(timeout=30)


def test_packing_efficiency_counter():
    eng = FakeEngine(compat="shared")
    with NMFXServer(ServeConfig(), engine=eng, start=False) as srv:
        f1 = srv.submit(_mat(), ks=(2,), restarts=3)
        f2 = srv.submit(_mat(), ks=(2,), restarts=3)
        srv.resume()
        f1.result(timeout=30)
        f2.result(timeout=30)
    s = srv.stats()
    assert s["total_lanes"] == 6
    assert s["packed_lanes"] == 6
    assert s["packing_efficiency"] == 1.0
    assert serve.packing_efficiency() is None \
        or 0.0 <= serve.packing_efficiency() <= 1.0


# ---------------------------------------------------------------------
# spill-on-shutdown + re-admission (ISSUE 9 — docs/serving.md
# "Durability model"): close() no longer discards queued-but-
# undispatched requests when ServeConfig.spill_dir is set
# ---------------------------------------------------------------------

def test_close_drains_queued_not_dispatched():
    """Pin the default drain semantics: close() WITHOUT cancel_pending
    completes requests that were queued but not yet dispatched (a
    paused server holds them in the queue until close unpauses it)."""
    eng = FakeEngine()
    srv = NMFXServer(ServeConfig(), engine=eng, start=False)
    f = srv.submit(_mat(), ks=(2,), restarts=2)
    srv.close()  # unpauses and drains — never abandons queued work
    assert f.result(timeout=5) is not None
    assert srv.counters["spilled"] == 0


def test_close_cancel_pending_spills_queued(tmp_path):
    """close(cancel_pending=True) with a spill_dir persists each
    queued request's payload before failing its future, so shutdown
    loses no work."""
    import os

    spill = str(tmp_path / "spill")
    eng = FakeEngine()
    srv = NMFXServer(ServeConfig(spill_dir=spill), engine=eng,
                     start=False)
    f1 = srv.submit(_mat(), ks=(2,), restarts=2, priority=1)
    f2 = srv.submit(_mat(), ks=(2, 3), restarts=3, seed=7)
    srv.close(cancel_pending=True)
    for f in (f1, f2):
        with pytest.raises(ServerClosed, match="spilled"):
            f.result(timeout=5)
    assert srv.counters["spilled"] == 2
    assert len([n for n in os.listdir(spill)
                if n.startswith("spill_")]) == 2
    # a fresh server re-admits them through the normal submit path
    eng2 = FakeEngine()
    with NMFXServer(ServeConfig(spill_dir=spill), engine=eng2) as srv2:
        futs = srv2.readmit()
        assert len(futs) == 2
        for f in futs:
            assert f.result(timeout=10) is not None
    assert srv2.counters["readmitted"] == 2
    assert [n for n in os.listdir(spill)
            if n.startswith("spill_")] == []  # consumed once admitted


def test_close_cancel_pending_without_spill_dir_discards():
    """Without a spill_dir the pre-ISSUE-9 semantics are unchanged:
    queued requests fail with ServerClosed and nothing lands on disk."""
    eng = FakeEngine()
    srv = NMFXServer(ServeConfig(), engine=eng, start=False)
    f = srv.submit(_mat(), ks=(2,), restarts=2)
    srv.close(cancel_pending=True)
    with pytest.raises(ServerClosed) as exc:
        f.result(timeout=5)
    assert "spilled" not in str(exc.value)
    assert srv.counters["spilled"] == 0


def test_readmit_skips_corrupt_spill_record(tmp_path):
    """Torn spill records get the ledger's torn-record tolerance:
    warn-once + skip, never a crash, and healthy records still admit."""
    import os

    spill = tmp_path / "spill"
    spill.mkdir()
    (spill / "spill_0_0.npz").write_bytes(b"not a zip file")
    from nmfx.faults import _reset_warned

    _reset_warned()
    eng = FakeEngine()
    with NMFXServer(ServeConfig(spill_dir=str(spill)),
                    engine=eng) as srv:
        with pytest.warns(RuntimeWarning, match="torn/corrupt"):
            futs = srv.readmit()
    assert futs == []
    assert os.path.exists(spill / "spill_0_0.npz")  # left for forensics


def test_spill_readmit_bit_identical_real_engine(small_data, scfg):
    """The re-admitted request's result is bit-identical to direct
    submission — the serving exactness contract survives the spill
    round-trip (real ExecCacheEngine, smallest shapes)."""
    import os
    import tempfile

    from nmfx.exec_cache import ExecCache

    spill = tempfile.mkdtemp()
    cache = ExecCache()
    srv = NMFXServer(ServeConfig(spill_dir=spill), exec_cache=cache,
                     start=False)
    f = srv.submit(small_data, ks=KS, restarts=RESTARTS, seed=11,
                   solver_cfg=scfg)
    srv.close(cancel_pending=True)
    with pytest.raises(ServerClosed):
        f.result(timeout=5)
    assert len(os.listdir(spill)) == 1
    with NMFXServer(ServeConfig(spill_dir=spill),
                    exec_cache=cache) as srv2:
        futs = srv2.readmit()
        assert len(futs) == 1
        got = futs[0].result(timeout=300)
    ref = _solo(small_data, cache, scfg=scfg)
    assert_result_bit_equal(got, ref)


# ---------------------------------------------------------------------
# unified telemetry (ISSUE 10): one served request = one nested
# cross-thread timeline; metrics windowed to the server
# ---------------------------------------------------------------------

def test_served_request_traces_nested_spans_across_threads(
        small_data, scfg, tmp_path):
    """The ISSUE 10 acceptance: a served request exports Chrome-trace
    JSON whose spans cover queue→pack/dispatch→solve→harvest, the
    serve spans carry the request's RequestStats id in their args, and
    the timeline spans >= 2 threads (scheduler + completion worker).
    Also pins stats_snapshot()/metrics_text() on the same request."""
    import json

    from nmfx.exec_cache import ExecCache
    from nmfx.obs import trace

    tracer = trace.default_tracer()
    tracer.clear()
    trace.enable()
    try:
        with NMFXServer(ServeConfig(), exec_cache=ExecCache()) as srv:
            fut = srv.submit(small_data, ks=KS, restarts=RESTARTS,
                             seed=11, solver_cfg=scfg)
            fut.result(timeout=600)
            snap = srv.stats_snapshot()
            text = srv.metrics_text()
    finally:
        trace.disable()
    path = tmp_path / "serve_trace.json"
    tracer.export(str(path))
    chrome = json.loads(path.read_text())  # valid Chrome trace JSON
    xs = [e for e in chrome["traceEvents"] if e.get("ph") == "X"]
    names = {e["name"] for e in xs}
    # the request path end to end: queue residency, the dispatch step
    # (serve.dispatch wrapping serve.pack), device solve, and the
    # completion worker's harvest with its fetch/rank-selection
    # children
    assert "serve.queue_wait" in names
    assert "serve.dispatch" in names and "serve.pack" in names
    assert any(n.startswith("solve.") for n in names)
    assert "serve.harvest" in names
    assert "xfer.d2h_overlap" in names
    assert "post.rank_selection" in names
    # RequestStats ids ride in the span args (ISSUE 10 satellite)
    rid = fut.stats.request_id
    assert rid is not None
    qw = next(e for e in xs if e["name"] == "serve.queue_wait")
    assert qw["args"]["request_id"] == rid
    disp = next(e for e in xs if e["name"] == "serve.dispatch")
    assert rid in disp["args"]["request_ids"]
    hv = next(e for e in xs if e["name"] == "serve.harvest")
    assert hv["args"]["request_id"] == rid
    # >= 2 threads: the scheduler dispatched, a completion worker
    # harvested
    assert disp["tid"] != hv["tid"]
    # the harvest children nest inside the harvest span on its thread
    lo, hi = hv["ts"], hv["ts"] + hv["dur"]
    child = next(e for e in xs if e["name"] == "post.rank_selection"
                 and e["tid"] == hv["tid"])
    assert lo - 1 <= child["ts"] and child["ts"] + child["dur"] <= hi + 1
    # metrics: the server-windowed delta saw this request's dispatch
    # and latency observation; the exposition carries the histograms
    disp_delta = sum(
        snap["nmfx_serve_dispatches_total"]["series"].values())
    assert disp_delta >= 1
    e2e = snap["nmfx_serve_e2e_seconds"]["series"][("completed",)]
    assert e2e["count"] >= 1
    assert "nmfx_serve_e2e_seconds_bucket" in text
    assert "nmfx_serve_queue_wait_seconds" in text
    tracer.clear()


# ---------------------------------------------------------------------
# mesh-tier engine (ISSUE 19): ServeConfig.mesh_spec + MeshEngine
# ---------------------------------------------------------------------

def test_serve_config_mesh_spec_validated_at_construction():
    from nmfx.distributed import MeshSpecError

    with pytest.raises(MeshSpecError):
        ServeConfig(mesh_spec="two-by-two")
    with pytest.raises(MeshSpecError):
        ServeConfig(mesh_spec="0x2")
    assert ServeConfig(mesh_spec="2x2").mesh_spec == "2x2"


def test_mesh_engine_is_solo_only():
    from nmfx.serve import MeshEngine

    eng = MeshEngine("4")
    assert eng.n_devices == 4
    assert eng.compatibility_key(None) is None  # never packs
    with pytest.raises(RuntimeError, match="solo-only"):
        eng.dispatch_packed([], None)


def test_mesh_server_rejects_exec_cache_and_matches_direct(tmp_path):
    """A meshed server can't also be a cache-tier server (one engine
    per server), and its results are bit-identical to the direct
    meshed sweep — serving is placement, never numerics."""
    from nmfx.config import ConsensusConfig, SolverConfig
    from nmfx.exec_cache import ExecCache
    from nmfx.serve import MeshEngine
    from nmfx.sweep import sweep

    with pytest.raises(ValueError, match="mesh_spec"):
        NMFXServer(ServeConfig(mesh_spec="4"), exec_cache=ExecCache(),
                   start=False)
    a = _mat()
    scfg = SolverConfig(algorithm="mu", max_iter=20)
    with NMFXServer(ServeConfig(mesh_spec="4")) as srv:
        assert isinstance(srv.engine, MeshEngine)
        res = srv.submit(a, ks=(2,), restarts=4, seed=7,
                         solver_cfg=scfg).result(timeout=120)
    ref = sweep(a, ConsensusConfig(ks=(2,), restarts=4, seed=7),
                scfg, mesh=srv.engine.mesh)
    np.testing.assert_array_equal(np.asarray(res.per_k[2].consensus),
                                  np.asarray(ref[2].consensus))
