"""Native C++ hclust library vs the numpy reference implementation."""

import os

import numpy as np
import pytest
import scipy.cluster.hierarchy as sch
import scipy.spatial.distance as ssd

from nmfx import cophenetic as pycoph
from nmfx import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")


def _random_dist(n, seed):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4))
    return ssd.squareform(ssd.pdist(x))


@pytest.mark.parametrize("n,seed", [(5, 0), (20, 1), (60, 2)])
def test_native_matches_numpy(n, seed):
    d = _random_dist(n, seed)
    ours = native.average_linkage(d)
    ref = pycoph.average_linkage_numpy(d)
    np.testing.assert_allclose(ours.linkage, ref.linkage, rtol=1e-12)
    np.testing.assert_allclose(ours.coph, ref.coph, rtol=1e-12)
    np.testing.assert_array_equal(ours.order, ref.order)


@pytest.mark.parametrize("k", [1, 2, 4])
def test_native_cut_tree_matches_numpy(k):
    d = _random_dist(25, 3)
    nat = native.average_linkage(d)
    labels_native = native.cut_tree(nat.linkage, 25, k)
    labels_py = pycoph.cut_tree_numpy(pycoph.average_linkage_numpy(d).linkage, 25, k)
    np.testing.assert_array_equal(labels_native, labels_py)


def test_native_matches_scipy():
    d = _random_dist(30, 4)
    ours = native.average_linkage(d)
    z = sch.linkage(ssd.squareform(d), method="average")
    np.testing.assert_allclose(ours.linkage[:, 2], z[:, 2], rtol=1e-10)
    np.testing.assert_allclose(pycoph.condensed(ours.coph), sch.cophenet(z),
                               rtol=1e-10)


def test_rank_selection_dispatch_parity(monkeypatch):
    # rank_selection must give identical results native vs numpy
    c = np.zeros((10, 10))
    c[:5, :5] = 1.0
    c[5:, 5:] = 1.0
    rho_n, mem_n, ord_n = pycoph.rank_selection(c, 2)
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setenv("NMFX_NATIVE", "0")
    rho_p, mem_p, ord_p = pycoph.rank_selection(c, 2)
    assert rho_n == rho_p
    np.testing.assert_array_equal(mem_n, mem_p)
    np.testing.assert_array_equal(ord_n, ord_p)


def test_stale_library_rebuilds_or_degrades(tmp_path, monkeypatch):
    """A prebuilt .so missing the current symbols must never crash
    available(): with the sources present it rebuilds and binds; without
    them it degrades to the numpy fallback."""
    import shutil
    import subprocess

    from nmfx import native

    src = tmp_path / "dummy.cpp"
    src.write_text('extern "C" int unrelated() { return 0; }\n')

    def make_stale(d):
        d.mkdir(exist_ok=True)
        subprocess.run(["g++", "-shared", "-fPIC", "-o",
                        str(d / "libnmfx_native.so"), str(src)], check=True)

    # case 1: sources + Makefile present -> rebuild heals
    heal = tmp_path / "heal"
    make_stale(heal)
    pkg = os.path.dirname(native.__file__)
    for f in ("Makefile", "hclust.cpp", "gct_io.cpp"):
        shutil.copy(os.path.join(pkg, f), heal / f)
    monkeypatch.setattr(native, "_DIR", str(heal))
    monkeypatch.setattr(native, "_LIB_PATH", str(heal / "libnmfx_native.so"))
    monkeypatch.setattr(native, "_load_attempted", False)
    monkeypatch.setattr(native, "_lib", None)
    assert native.available() is True

    # case 2: no sources -> graceful degradation, no AttributeError
    bare = tmp_path / "bare"
    make_stale(bare)
    monkeypatch.setattr(native, "_DIR", str(bare))
    monkeypatch.setattr(native, "_LIB_PATH", str(bare / "libnmfx_native.so"))
    monkeypatch.setattr(native, "_load_attempted", False)
    monkeypatch.setattr(native, "_lib", None)
    assert native.available() is False
