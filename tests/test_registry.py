"""Checkpoint/resume registry tests (SURVEY.md §5: the restartability the
reference's BatchJobs registry provides but never exploits, nmf.r:112-113)."""

import os

import numpy as np
import pytest

from nmfx.api import nmfconsensus
from nmfx.config import InitConfig, SolverConfig
from nmfx.registry import SweepRegistry
from nmfx.sweep import sweep_one_k


SCFG = SolverConfig(algorithm="mu", max_iter=60)
ICFG = InitConfig()


def _open(tmp_path, a, **kw):
    args = dict(a=a, solver_cfg=SCFG, init_cfg=ICFG, restarts=4, seed=1,
                label_rule="argmax")
    args.update(kw)
    return SweepRegistry.open(str(tmp_path), **args)


def test_save_load_roundtrip(tmp_path, two_group_data):
    import jax

    reg = _open(tmp_path / "reg", two_group_data)
    out = sweep_one_k(two_group_data, jax.random.key(0), k=2, restarts=4,
                      solver_cfg=SCFG)
    assert not reg.has(2)
    reg.save(2, out)
    assert reg.has(2)
    assert reg.completed_ks() == [2]
    loaded = reg.load(2)
    for name, orig, back in zip(out._fields, out, loaded):
        np.testing.assert_array_equal(np.asarray(orig), back, err_msg=name)


def test_fingerprint_guard(tmp_path, two_group_data):
    _open(tmp_path / "reg", two_group_data)
    # same dir, different seed -> refuse
    with pytest.raises(ValueError, match="different"):
        _open(tmp_path / "reg", two_group_data, seed=2)
    # same everything -> reopen fine
    _open(tmp_path / "reg", two_group_data)


def test_nmfconsensus_resume(tmp_path, two_group_data):
    ckpt = str(tmp_path / "ckpt")
    r1 = nmfconsensus(two_group_data, ks=(2, 3), restarts=3, seed=5,
                      max_iter=60, use_mesh=False, checkpoint_dir=ckpt)
    # second run resumes entirely from disk and reproduces the result
    r2 = nmfconsensus(two_group_data, ks=(2, 3), restarts=3, seed=5,
                      max_iter=60, use_mesh=False, checkpoint_dir=ckpt)
    for k in (2, 3):
        np.testing.assert_array_equal(r1.per_k[k].consensus,
                                      r2.per_k[k].consensus)
        assert r1.per_k[k].rho == r2.per_k[k].rho
    # widening the sweep reuses finished ranks and computes only the new one
    r3 = nmfconsensus(two_group_data, ks=(2, 3, 4), restarts=3, seed=5,
                      max_iter=60, use_mesh=False, checkpoint_dir=ckpt)
    np.testing.assert_array_equal(r3.per_k[2].consensus, r1.per_k[2].consensus)
    assert set(r3.per_k) == {2, 3, 4}


def test_checkpoint_matches_uncheckpointed(tmp_path, two_group_data):
    plain = nmfconsensus(two_group_data, ks=(2,), restarts=3, seed=9,
                         max_iter=60, use_mesh=False)
    ckpt = nmfconsensus(two_group_data, ks=(2,), restarts=3, seed=9,
                        max_iter=60, use_mesh=False,
                        checkpoint_dir=str(tmp_path / "c"))
    np.testing.assert_allclose(plain.per_k[2].consensus,
                               ckpt.per_k[2].consensus)


def test_fingerprint_forward_compatible_with_default_fields():
    """Only non-default config fields are hashed: adding future config
    fields (with defaults) must not invalidate existing registries, and
    numerics-neutral knobs (restart_chunk) never enter the hash."""
    import dataclasses

    import numpy as np

    from nmfx.config import InitConfig, SolverConfig
    from nmfx.registry import _fingerprint

    a = np.ones((4, 3))
    base_cfg = SolverConfig(algorithm="mu", max_iter=50)
    fp = _fingerprint(a, base_cfg, InitConfig(), 4, 1, "argmax")
    # explicitly passing a default value hashes identically
    same = dataclasses.replace(base_cfg, sparsity_beta=0.01)
    assert _fingerprint(a, same, InitConfig(), 4, 1, "argmax") == fp
    # restart_chunk is bit-identical by construction -> excluded
    chunked = dataclasses.replace(base_cfg, restart_chunk=2)
    assert _fingerprint(a, chunked, InitConfig(), 4, 1, "argmax") == fp
    # a numerics-affecting change does invalidate
    other = dataclasses.replace(base_cfg, tol_x=1e-6)
    assert _fingerprint(a, other, InitConfig(), 4, 1, "argmax") != fp


def test_fingerprint_resolves_hals_engine_with_mesh():
    """hals backend='auto' executes the packed/scheduled family on
    restart-only meshes but the grid-sharded generic (vmap-family) driver
    on a feature/sample-sharded mesh (sweep GRID_SOLVERS routing) — the
    fingerprint must distinguish the two so checkpoints never cross
    engine families, while 'auto' and the explicit equivalent backend
    still hash identically within each family."""
    import dataclasses

    import numpy as np

    from nmfx.config import InitConfig, SolverConfig
    from nmfx.registry import _fingerprint
    from nmfx.sweep import grid_mesh

    a = np.ones((4, 3))
    cfg = SolverConfig(algorithm="hals", max_iter=50)
    mesh = grid_mesh(None, feature_shards=2, sample_shards=1)
    fp_flat = _fingerprint(a, cfg, InitConfig(), 4, 1, "argmax")
    fp_grid = _fingerprint(a, cfg, InitConfig(), 4, 1, "argmax", mesh=mesh)
    assert fp_flat != fp_grid
    # auto == the explicit engine it resolves to, in both regimes
    packed = dataclasses.replace(cfg, backend="packed")
    vmap = dataclasses.replace(cfg, backend="vmap")
    assert _fingerprint(a, packed, InitConfig(), 4, 1, "argmax") == fp_flat
    assert _fingerprint(a, vmap, InitConfig(), 4, 1, "argmax",
                        mesh=mesh) == fp_grid


def test_corrupt_checkpoint_self_heals(low_rank_data, tmp_path, caplog):
    """A truncated/garbage rank file must not crash resume: the sweep logs
    a warning, recomputes the rank, and overwrites a good checkpoint."""
    import logging

    from nmfx.api import nmfconsensus

    a, _ = low_rank_data
    ck = str(tmp_path / "reg")
    first = nmfconsensus(a, ks=(2, 3), restarts=3, max_iter=150,
                         checkpoint_dir=ck, use_mesh=False)
    # corrupt one rank's file in place
    path = os.path.join(ck, "k3.npz")
    with open(path, "wb") as f:
        f.write(b"not an npz")
    with caplog.at_level(logging.WARNING, logger="nmfx"):
        second = nmfconsensus(a, ks=(2, 3), restarts=3, max_iter=150,
                              checkpoint_dir=ck, use_mesh=False)
    assert any("unreadable" in r.message for r in caplog.records)
    assert second.summary() == first.summary()
    # the overwritten checkpoint is good again: third run loads cleanly
    third = nmfconsensus(a, ks=(2, 3), restarts=3, max_iter=150,
                         checkpoint_dir=ck, use_mesh=False)
    assert third.summary() == first.summary()
