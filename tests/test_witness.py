"""Runtime lock-order witness (nmfx.analysis.witness): the dynamic
half of the NMFX013 contract. The static rule proves the lock graph
acyclic from source; the witness records the orders threads ACTUALLY
acquire instrumented locks in, fails on inversions, and feeds observed
edges back so the static graph's completeness is itself testable
(the last test drives a real server and checks every observed
inter-lock edge is one the static model already knows)."""

import threading
import time

import pytest

from nmfx.analysis import witness


@pytest.fixture(autouse=True)
def _clean_witness_state():
    witness.reset()
    yield
    while witness.is_armed():  # a failed test must not leave the patch
        witness.disarm()
    witness.reset()


def test_seeded_inversion_detected():
    """The acceptance fixture: two locks taken in both orders — the
    precondition of every real deadlock — is recorded as a violation
    without the test having to actually deadlock."""
    with witness.armed():
        a = threading.Lock()
        b = threading.Lock()
        with a:
            with b:
                pass
        with b:
            with a:
                pass
    vs = witness.violations()
    assert [v["kind"] for v in vs] == ["inversion"]
    assert "fake" not in witness.render(vs)  # renders real sites
    assert "test_witness.py" in witness.render(vs)


def test_consistent_order_quiet():
    with witness.armed():
        a = threading.Lock()
        b = threading.Lock()
        for _ in range(3):
            with a:
                with b:
                    pass
    assert witness.violations() == []
    assert len(witness.observed_edges()) == 1


def test_cross_thread_inversion_detected():
    """Each thread takes a consistent-looking order locally; only the
    cross-thread merge exposes the inversion — the shape a per-thread
    checker would miss."""
    with witness.armed():
        a = threading.Lock()
        b = threading.Lock()

        def t1():
            with a:
                with b:
                    pass

        def t2():
            with b:
                with a:
                    pass

        th1 = threading.Thread(target=t1)
        th1.start()
        th1.join()
        th2 = threading.Thread(target=t2)
        th2.start()
        th2.join()
    assert any(v["kind"] == "inversion" for v in witness.violations())


def test_rlock_reentry_no_self_edge():
    with witness.armed():
        r = threading.RLock()
        with r:
            with r:
                pass
    assert witness.violations() == []
    assert witness.observed_edges() == {}


def test_nonblocking_probe_not_a_self_deadlock():
    """Condition's fallback _is_owned probes the held lock with
    acquire(False) — non-blocking, so NOT a self-deadlock. Only a
    blocking re-acquire of a plain Lock is flagged."""
    with witness.armed():
        lk = threading.Lock()
        with lk:
            assert lk.acquire(False) is False
    assert witness.violations() == []


def test_condition_on_witnessed_lock_tracks_and_works():
    """threading.Condition built ON an instrumented lock keeps full
    wait/notify semantics (the CPython fallback paths route through
    the proxy's plain acquire/release) and the reacquire after wait()
    still records edges."""
    with witness.armed():
        lk = threading.Lock()
        cond = threading.Condition(lk)
        fired = []

        def waiter():
            with cond:
                while not fired:
                    cond.wait(timeout=5.0)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.05)
        with cond:
            fired.append(1)
            cond.notify()
        t.join(timeout=5.0)
        assert not t.is_alive()
    assert witness.violations() == []


def test_arm_disarm_restore_and_nest():
    real_lock = threading.Lock
    witness.arm()
    witness.arm()
    assert threading.Lock is not real_lock
    witness.disarm()
    assert threading.Lock is not real_lock  # still one arm deep
    witness.disarm()
    assert threading.Lock is real_lock
    witness.disarm()  # over-disarm is a no-op
    assert threading.Lock is real_lock


def test_third_party_locks_untouched():
    """Creation sites outside nmfx/tests pass through unwrapped — the
    witness never instruments jax or stdlib internals."""
    import queue

    with witness.armed():
        q = queue.Queue()  # allocates its locks inside queue.py
        q.put(1)
        assert q.get() == 1
        # and a Future's condition (threading.py creation site)
        from concurrent.futures import Future

        f = Future()
        f.set_result(3)
        assert f.result() == 3
    assert witness.observed_edges() == {}


def test_static_inversion_check_flags_reversed_edge(monkeypatch):
    """An observed order that contradicts an edge the static graph
    pins is reported even when the test never takes the locks in the
    static direction itself (single-sided inversion)."""
    with witness.armed():
        a = threading.Lock()
        b = threading.Lock()
        with b:
            with a:
                pass
    (edge,) = witness.observed_edges()  # (site_b, site_a)
    sb, sa = edge
    monkeypatch.setattr(
        witness, "_static_cache",
        {(sa, sb): ("mod.Cls._a", "mod.Cls._b")})
    problems = witness.check_static_inversions()
    assert len(problems) == 1
    assert problems[0]["kind"] == "static-inversion"
    assert "mod.Cls._b -> mod.Cls._a" in witness.render(problems)


def test_static_graph_covers_observed_serve_edges():
    """Completeness feedback: drive a REAL server (submit through
    resolution and close) with the witness armed; every observed edge
    between locks the static model knows must already be a static
    order edge. A lock-taking path the call-graph resolution misses
    shows up here as a missing edge."""
    from nmfx.serve import NMFXServer, ServeConfig
    from test_serve import FakeEngine, _mat

    with witness.armed():
        eng = FakeEngine()
        with NMFXServer(ServeConfig(), engine=eng, start=False) as srv:
            f1 = srv.submit(_mat(), ks=(2,), restarts=2)
            srv.resume()
            assert f1.result(timeout=60)
    observed = witness.observed_edges()
    assert witness.violations() == []
    static = witness.static_order_edges()
    known_sites = {s for edge in static for s in edge}
    checked = 0
    for (sa, sb) in observed:
        if sa in known_sites and sb in known_sites:
            assert (sa, sb) in static, (
                f"observed lock order {sa} -> {sb} is missing from the "
                "static NMFX013 graph — the call-graph resolution lost "
                "a lock-taking path")
            checked += 1
    # the workload must actually exercise the documented serve
    # discipline (_lock -> _tracked_lock), or this test proves nothing
    assert checked >= 1
