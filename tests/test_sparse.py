"""Sparse ingestion (ISSUE 17): the CSR container's canonical form,
content addressing, tile slicing, the sparse==densified agreement
contract, and the nnz-aware cost-model rows.

Exactness scope: a sparse solve contracts stored nonzeros through BCOO
GEMMs, a different reduction order than the dense GEMM — so the
contract is consensus/label agreement at planted shapes (the
``nmfx/agreement.py`` yardstick), never bit-identity. Everything
host-side (canonicalization, fingerprints, slicing) IS exact and is
pinned exactly.
"""

import numpy as np
import pytest

from nmfx.config import SolverConfig
from nmfx.datasets import make_sparse_design
from nmfx.sparse import SparseMatrix


@pytest.fixture()
def planted():
    return make_sparse_design(120, 40, k=3, density=0.3, seed=3)


# ---------------------------------------------------------------------
# canonical form
# ---------------------------------------------------------------------

def test_from_dense_roundtrip_exact():
    rng = np.random.default_rng(0)
    a = rng.uniform(size=(30, 11))
    a[rng.random(a.shape) < 0.7] = 0.0
    sp = SparseMatrix.from_dense(a)
    np.testing.assert_array_equal(sp.toarray(), a)
    assert sp.nnz == np.count_nonzero(a)
    assert sp.density == pytest.approx(np.count_nonzero(a) / a.size)


def test_from_coo_sums_duplicates_and_drops_zeros():
    sp = SparseMatrix.from_coo(rows=[2, 0, 2, 1, 1],
                               cols=[1, 0, 1, 2, 2],
                               vals=[1.5, 3.0, 0.5, 2.0, -2.0],
                               shape=(3, 4))
    dense = np.zeros((3, 4))
    dense[0, 0] = 3.0
    dense[2, 1] = 2.0  # 1.5 + 0.5 summed; (1, 2) cancelled to zero
    np.testing.assert_array_equal(sp.toarray(), dense)
    assert sp.nnz == 2


def test_two_representations_fingerprint_identically():
    rng = np.random.default_rng(1)
    a = rng.uniform(size=(20, 9))
    a[rng.random(a.shape) < 0.6] = 0.0
    via_dense = SparseMatrix.from_dense(a)
    r, c = np.nonzero(a)
    perm = np.random.default_rng(2).permutation(len(r))
    via_coo = SparseMatrix.from_coo(r[perm], c[perm], a[r, c][perm],
                                    a.shape)
    assert via_dense.fingerprint() == via_coo.fingerprint()


def test_fingerprint_tracks_content_not_identity(planted):
    fp = planted.fingerprint()
    assert fp == planted.fingerprint()  # stable
    mutated = SparseMatrix(indptr=planted.indptr,
                           indices=planted.indices,
                           data=planted.data * 1.0000001,
                           shape=planted.shape)
    assert mutated.fingerprint() != fp


def test_validation_rejects_malformed():
    with pytest.raises(ValueError, match="indptr"):
        SparseMatrix(indptr=np.array([0, 1]), indices=np.array([0]),
                     data=np.array([1.0]), shape=(2, 2))
    with pytest.raises(ValueError, match="out of range"):
        SparseMatrix(indptr=np.array([0, 1, 1]), indices=np.array([5]),
                     data=np.array([1.0]), shape=(2, 2))
    with pytest.raises(ValueError, match="out of range"):
        SparseMatrix.from_coo([0], [9], [1.0], shape=(2, 2))


# ---------------------------------------------------------------------
# tiling queries
# ---------------------------------------------------------------------

def test_row_block_matches_dense_slice(planted):
    dense = planted.toarray()
    block = planted.row_block(40, 100)
    assert block.shape == (60, planted.shape[1])
    np.testing.assert_array_equal(block.toarray(), dense[40:100])


def test_tile_coo_is_row_local_and_cast(planted):
    dense = planted.toarray()
    idx, data = planted.tile_coo(30, 90, np.float32)
    assert idx.dtype == np.int32 and data.dtype == np.float32
    rebuilt = np.zeros((60, planted.shape[1]), np.float32)
    rebuilt[idx[:, 0], idx[:, 1]] = data
    np.testing.assert_array_equal(rebuilt,
                                  dense[30:90].astype(np.float32))


def test_block_sq_norms_match_dense(planted):
    dense = planted.toarray()
    bounds = ((0, 50), (50, 120))
    got = planted.block_sq_norms(bounds)
    want = [float((dense[r0:r1].astype(np.float64) ** 2).sum())
            for r0, r1 in bounds]
    np.testing.assert_allclose(got, want, rtol=1e-12)


# ---------------------------------------------------------------------
# generator
# ---------------------------------------------------------------------

def test_make_sparse_design_properties():
    sp = make_sparse_design(200, 50, k=4, density=0.08, seed=0)
    assert isinstance(sp, SparseMatrix)
    assert sp.shape == (200, 50)
    assert sp.density == pytest.approx(0.08, rel=0.2)
    assert np.all(sp.data > 0)  # non-negative with zeros dropped
    # deterministic in the seed
    again = make_sparse_design(200, 50, k=4, density=0.08, seed=0)
    assert again.fingerprint() == sp.fingerprint()
    with pytest.raises(ValueError, match="density"):
        make_sparse_design(10, 10, k=2, density=1.5)


# ---------------------------------------------------------------------
# content addressing through the cache layers
# ---------------------------------------------------------------------

def test_data_key_hashes_triplets_never_densifies(planted):
    from nmfx.data_cache import DataCache

    cache = DataCache()
    key = cache.key_for(planted, np.float32)
    assert key.fingerprint == planted.fingerprint()
    mutated = SparseMatrix(indptr=planted.indptr,
                           indices=planted.indices,
                           data=planted.data + 1.0,
                           shape=planted.shape)
    assert cache.key_for(mutated, np.float32).fingerprint \
        != key.fingerprint


def test_result_cache_key_covers_sparse_content(planted):
    from nmfx.config import ConsensusConfig, InitConfig
    from nmfx.result_cache import key_for_array

    ccfg = ConsensusConfig(ks=(2,), restarts=2, seed=1)
    scfg = SolverConfig(algorithm="mu", max_iter=10)
    icfg = InitConfig()
    k1 = key_for_array(planted, scfg, ccfg, icfg)
    assert k1 == key_for_array(planted, scfg, ccfg, icfg)
    mutated = SparseMatrix(indptr=planted.indptr,
                           indices=planted.indices,
                           data=planted.data + 1.0,
                           shape=planted.shape)
    assert key_for_array(mutated, scfg, ccfg, icfg) != k1


# ---------------------------------------------------------------------
# the agreement contract: sparse == densified
# ---------------------------------------------------------------------

def test_sparse_agrees_with_densified_consensus():
    """The exactness contract at a planted shape: the BCOO path and the
    densified twin recover the same cluster structure (ARI at the
    planted rank) and rank alike (bounded |d rho|)."""
    from nmfx.agreement import consensus_agreement
    from nmfx.api import nmfconsensus

    sp = make_sparse_design(150, 36, k=3, density=0.25, seed=9)
    scfg = SolverConfig(algorithm="mu", max_iter=200)
    kw = dict(ks=(2, 3), restarts=4, seed=5, use_mesh=False)
    res_sp = nmfconsensus(sp, solver_cfg=scfg, **kw)
    res_dn = nmfconsensus(sp.toarray(), solver_cfg=scfg, **kw)
    rep = consensus_agreement(res_sp, res_dn)
    assert rep["min_ari"] >= 0.9
    assert rep["max_rho_gap"] <= 0.1


def test_sparse_books_nnz_counters():
    from nmfx import sparse as sparse_mod
    from nmfx.api import nmfconsensus

    sp = make_sparse_design(80, 24, k=2, density=0.2, seed=4)
    nnz0 = sparse_mod._sparse_nnz_total.total()
    bytes0 = sparse_mod._sparse_nnz_bytes_total.total()
    nmfconsensus(sp, ks=(2,), restarts=2, seed=1, use_mesh=False,
                 solver_cfg=SolverConfig(algorithm="mu", max_iter=10))
    assert sparse_mod._sparse_nnz_total.total() > nnz0
    assert sparse_mod._sparse_nnz_bytes_total.total() > bytes0


def test_legacy_registry_refuses_sparse(tmp_path):
    from nmfx.api import nmfconsensus

    sp = make_sparse_design(40, 12, k=2, density=0.3, seed=2)
    with pytest.raises(ValueError, match="durable chunked"):
        nmfconsensus(sp, ks=(2,), restarts=2, seed=1, use_mesh=False,
                     checkpoint_dir=str(tmp_path))


# ---------------------------------------------------------------------
# nnz-aware cost model (NMFX009 universe extension)
# ---------------------------------------------------------------------

def test_tiled_engines_modeled_and_universe_clean():
    from nmfx.analysis.rules_perf import _live_universe
    from nmfx.config import TILED_ALGORITHMS
    from nmfx.obs import costmodel as cm
    from nmfx.obs.costmodel import check_costmodel_coverage

    for algo in TILED_ALGORITHMS:
        assert (algo, "tiled") in cm.engine_universe()
        assert (algo, "tiled") in cm.covered_engines()
    assert check_costmodel_coverage(**_live_universe()) == []


def test_nmfx009_fires_if_tiled_model_dropped():
    """Bad universe: a reachable tiled engine without a cost model is
    exactly the mfu-blind-spot NMFX009 exists to catch."""
    from nmfx.analysis.rules_perf import _live_universe
    from nmfx.obs.costmodel import check_costmodel_coverage

    universe = _live_universe()
    universe["covered"] = frozenset(universe["covered"]) \
        - {("mu", "tiled")}
    problems = check_costmodel_coverage(**universe)
    assert any("tiled" in p and "no cost model" in p for p in problems)


def test_sparse_density_scales_data_terms():
    from nmfx.obs import costmodel as cm

    m, n, k = 400, 100, 5
    cfg = SolverConfig(algorithm="mu", tile_rows=64)
    try:
        cm.set_sparse_density(1.0)
        dense_f = cm.iteration_flops("mu", "tiled", m, n, k, cfg)
        dense_b = cm.iteration_bytes("mu", "tiled", m, n, k, cfg)
        cm.set_sparse_density(0.01)
        sp_f = cm.iteration_flops("mu", "tiled", m, n, k, cfg)
        sp_b = cm.iteration_bytes("mu", "tiled", m, n, k, cfg)
    finally:
        cm.set_sparse_density(1.0)
    # data-sized terms scale with density; k-sized terms stay dense
    assert sp_f < dense_f
    assert sp_f > 4.0 * k * k * (m + n) - 1  # floor: the Gram terms
    assert sp_b < dense_b
    with pytest.raises(ValueError, match="density"):
        cm.set_sparse_density(1.5)


def test_sparse_density_hint_validated():
    from nmfx.obs import costmodel as cm

    assert cm.sparse_density() == 1.0
    cm.set_sparse_density(0.25)
    assert cm.sparse_density() == 0.25
    cm.set_sparse_density(1.0)
