"""Slot-scheduled whole-grid solver (nmfx.ops.sched_mu).

The scheduler must be pure execution policy: for every job, the trajectory
(stopping iteration, stop reason, factors) is identical to the fixed-batch
whole-grid solve no matter the slot count, dispatch order, or how jobs
share slots over time — only wall-clock changes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nmfx.config import ExperimentalConfig, InitConfig, SolverConfig
from nmfx.datasets import grouped_matrix
from nmfx.init import initialize
from nmfx.ops.grid_mu import mu_grid
from nmfx.ops.sched_mu import mu_sched

KS = (4, 3, 2)  # rank-descending, as the sweep dispatches
R = 5


@pytest.fixture(scope="module")
def jobs():
    a = jnp.asarray(grouped_matrix(200, (10, 10, 10), effect=2.0, seed=0),
                    jnp.float32)
    k_max = max(KS)
    root = jax.random.key(123)
    w0l, h0l = [], []
    for k in KS:
        keys = jax.random.split(jax.random.fold_in(root, k), R)
        w0s, h0s = jax.vmap(
            lambda kk, k=k: initialize(kk, a, k, InitConfig(),
                                       jnp.float32))(keys)
        w0l.append(jnp.pad(w0s, ((0, 0), (0, 0), (0, k_max - k))))
        h0l.append(jnp.pad(h0s, ((0, 0), (0, k_max - k), (0, 0))))
    return a, jnp.concatenate(w0l), jnp.concatenate(h0l)


@pytest.mark.parametrize("slots", [1, 3, 7, 15, 64])
def test_schedule_free_results(jobs, slots):
    """Identical decisions and factors at ANY slot count — including one
    slot (fully sequential), a pool larger than the job count (degenerates
    to the fixed batch), and pools forcing multi-generation slot reuse."""
    a, w0, h0 = jobs
    cfg = SolverConfig(max_iter=600)
    # job_ks: every caller that knows its lane composition passes the
    # exact per-lane ranks (ADVICE.md round 5 / ISSUE 3 — the inferred
    # mask is the fallback for callers that genuinely don't)
    ref = mu_grid(a, w0, h0, cfg, job_ks=JOB_KS)
    got = mu_sched(a, w0, h0, cfg, slots=slots, job_ks=JOB_KS)
    np.testing.assert_array_equal(np.asarray(ref.iterations),
                                  np.asarray(got.iterations))
    np.testing.assert_array_equal(np.asarray(ref.stop_reason),
                                  np.asarray(got.stop_reason))
    np.testing.assert_allclose(np.asarray(ref.dnorm),
                               np.asarray(got.dnorm), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(ref.w), np.asarray(got.w),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(ref.h), np.asarray(got.h),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("slots,max_iter", [(3, 600), (15, 600), (5, 601)])
def test_pallas_scheduler_matches_dense(jobs, slots, max_iter):
    """backend='pallas' runs the same scheduler with packed-column slot
    state through the fused kernels (interpret mode on CPU executes XLA's
    own arithmetic, so decisions and factors match the dense path
    tightly). max_iter=601 covers the per-iteration fallback (the
    block kernel needs max_iter % check_every == 0)."""
    a, w0, h0 = jobs
    cfg = SolverConfig(max_iter=max_iter)
    ref = mu_sched(a, w0, h0, cfg, slots=slots)
    got = mu_sched(a, w0, h0, SolverConfig(max_iter=max_iter,
                                           backend="pallas",
                                           check_block=1), slots=slots)
    np.testing.assert_array_equal(np.asarray(ref.iterations),
                                  np.asarray(got.iterations))
    np.testing.assert_array_equal(np.asarray(ref.stop_reason),
                                  np.asarray(got.stop_reason))
    np.testing.assert_allclose(np.asarray(ref.w), np.asarray(got.w),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(ref.dnorm),
                               np.asarray(got.dnorm), rtol=1e-5)


@pytest.mark.parametrize("backend", ["auto", "pallas"])
@pytest.mark.parametrize("tail", [1, 2, 5, (4, 2), (5, 3, 1)])
def test_tail_compaction_schedule_free(jobs, backend, tail):
    """The straggler tail phase (compact survivors into a narrow pool once
    the queue drains) is pure execution policy: per-job iterations and
    stop reasons are IDENTICAL with the tail enabled at any width or
    disabled; factors agree to the same float tolerance as any other
    width change (GEMM tiling differs per batch width — measured ~1e-6
    relative). Exercises compaction mid-flight: 6 slots over 15 jobs with
    tail widths below, at, and above the live-job count at drain, and
    multi-stage cascades."""
    a, w0, h0 = jobs
    cfg = SolverConfig(max_iter=600, backend=backend)
    ref = mu_sched(a, w0, h0, cfg, slots=6, tail_slots=None)
    got = mu_sched(a, w0, h0, cfg, slots=6, tail_slots=tail)
    np.testing.assert_array_equal(np.asarray(ref.iterations),
                                  np.asarray(got.iterations))
    np.testing.assert_array_equal(np.asarray(ref.stop_reason),
                                  np.asarray(got.stop_reason))
    np.testing.assert_allclose(np.asarray(ref.w), np.asarray(got.w),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(ref.h), np.asarray(got.h),
                               rtol=2e-4, atol=2e-5)


def test_tail_auto_default(jobs):
    """tail_slots='auto' (the default) makes the same per-job decisions as
    the disabled path and is a no-op when the pool is already narrower
    than the auto width."""
    a, w0, h0 = jobs
    cfg = SolverConfig(max_iter=600)
    ref = mu_sched(a, w0, h0, cfg, slots=15, tail_slots=None)
    got = mu_sched(a, w0, h0, cfg, slots=15)  # auto
    np.testing.assert_array_equal(np.asarray(ref.iterations),
                                  np.asarray(got.iterations))
    np.testing.assert_allclose(np.asarray(ref.w), np.asarray(got.w),
                               rtol=2e-4, atol=2e-5)
    narrow = mu_sched(a, w0, h0, cfg, slots=2)  # auto >= s -> single phase
    np.testing.assert_array_equal(np.asarray(ref.iterations),
                                  np.asarray(narrow.iterations))


def test_pallas_pool_clamps_to_vmem_envelope(jobs):
    """k_max beyond the resident-W VMEM envelope shrinks the pallas pool
    (``_pallas_slot_clamp``'s measured byte model of m, n, k_max and the
    A dtype — far fewer than the requested 48 slots at k=52) instead of
    hitting a Mosaic VMEM rejection; results stay schedule-free."""
    a, w0, h0 = jobs
    k_big = 52  # the clamp model admits only a handful of 52-wide slots
    w0b = jnp.pad(w0, ((0, 0), (0, 0), (0, k_big - w0.shape[2])))
    h0b = jnp.pad(h0, ((0, 0), (0, k_big - h0.shape[1]), (0, 0)))
    cfg = SolverConfig(max_iter=100)
    ref = mu_sched(a, w0b, h0b, cfg, slots=48)
    got = mu_sched(a, w0b, h0b, SolverConfig(max_iter=100,
                                             backend="pallas",
                                             check_block=1), slots=48)
    np.testing.assert_array_equal(np.asarray(ref.iterations),
                                  np.asarray(got.iterations))
    np.testing.assert_allclose(np.asarray(ref.w), np.asarray(got.w),
                               rtol=2e-4, atol=2e-5)


def test_max_iter_budget(jobs):
    """A cap below convergence evicts every job at exactly max_iter with
    MAX_ITER recorded — the queue still drains (no livelock on jobs that
    never converge)."""
    from nmfx.solvers.base import StopReason

    a, w0, h0 = jobs
    cfg = SolverConfig(max_iter=20)
    got = mu_sched(a, w0, h0, cfg, slots=4)
    assert np.all(np.asarray(got.iterations) == 20)
    assert np.all(np.asarray(got.stop_reason) == StopReason.MAX_ITER)


def test_single_job_matches_solve(jobs):
    """The degenerate 1-job grid equals the plain single-restart solver
    (same update math, no scheduling to do)."""
    from nmfx.solvers.base import solve

    a, w0, h0 = jobs
    k = KS[0]
    cfg = SolverConfig(max_iter=300)
    ref = solve(a, w0[0, :, :k], h0[0, :k, :], cfg)
    got = mu_sched(a, w0[:1], h0[:1], cfg, slots=8)
    np.testing.assert_array_equal(int(ref.iterations),
                                  int(got.iterations[0]))
    np.testing.assert_array_equal(int(ref.stop_reason),
                                  int(got.stop_reason[0]))
    np.testing.assert_allclose(np.asarray(ref.w),
                               np.asarray(got.w[0, :, :k]),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(ref.h),
                               np.asarray(got.h[0, :k, :]),
                               rtol=2e-4, atol=2e-5)


def test_unblocked_algorithm_rejected(jobs):
    # pg has no dense-batched block (grid_mu.BLOCKS) — als joined the
    # scheduler in round 5, so it no longer serves as the reject case
    a, w0, h0 = jobs
    with pytest.raises(ValueError, match="scheduler"):
        mu_sched(a, w0, h0, SolverConfig(algorithm="pg"))


JOB_KS = tuple(k for k in KS for _ in range(R))


@pytest.mark.parametrize("backend", ["auto", "pallas"])
def test_evict_batch_is_schedule_only(jobs, backend):
    """evict_batch (round-5 harvest hysteresis) batches the heavy half
    of eviction behind pending slots; recorded per-job results must be
    EXACTLY invariant — the prototype leaked the pending slots'
    iteration counters into their successors (reload started at the
    waited-trips count) and this is the regression lock. On hardware,
    reload timing shifts column positions and Mosaic drift can move
    stops a few percent (benign, gate-covered); on CPU the runs are
    bit-identical."""
    a, w0, h0 = jobs
    cfg = SolverConfig(algorithm="mu", backend=backend, max_iter=600)
    base = mu_sched(a, w0, h0, cfg, slots=6, job_ks=JOB_KS)
    for eb in (4, 8):
        r = mu_sched(a, w0, h0,
                     SolverConfig(algorithm="mu", backend=backend,
                                  max_iter=600,
                                  experimental=ExperimentalConfig(
                                      evict_batch=eb)),
                     slots=6, job_ks=JOB_KS)
        np.testing.assert_array_equal(np.asarray(base.iterations),
                                      np.asarray(r.iterations))
        np.testing.assert_array_equal(np.asarray(base.stop_reason),
                                      np.asarray(r.stop_reason))
        np.testing.assert_array_equal(np.asarray(base.w),
                                      np.asarray(r.w))
        np.testing.assert_array_equal(np.asarray(base.h),
                                      np.asarray(r.h))


def test_ragged_pool_matches_uniform(jobs):
    """The opt-in ragged class-blocked pool (mu_sched(ragged=True)) must
    reproduce the uniform pool's per-job stop decisions exactly and its
    factors to float tolerance — trajectories are per-job, only the
    schedule (and GEMM tiling) changes. Exercises mixed-rank classes,
    per-class queues with reloads (slots < jobs), the tail handover,
    and composition with evict_batch."""
    a, w0, h0 = jobs
    cfg = SolverConfig(algorithm="mu", backend="pallas", max_iter=600,
                       check_block=1)
    base = mu_sched(a, w0, h0, cfg, slots=6, job_ks=JOB_KS)
    for eb in (1, 8):
        r = mu_sched(a, w0, h0,
                     SolverConfig(algorithm="mu", backend="pallas",
                                  max_iter=600, check_block=1,
                                  experimental=ExperimentalConfig(
                                      ragged=True, evict_batch=eb)),
                     slots=6, job_ks=JOB_KS)
        np.testing.assert_array_equal(np.asarray(base.iterations),
                                      np.asarray(r.iterations))
        np.testing.assert_array_equal(np.asarray(base.stop_reason),
                                      np.asarray(r.stop_reason))
        np.testing.assert_allclose(np.asarray(base.w), np.asarray(r.w),
                                   rtol=2e-4, atol=5e-5)
        np.testing.assert_allclose(np.asarray(base.h), np.asarray(r.h),
                                   rtol=2e-4, atol=5e-5)
    # the ragged stage's occupancy diagnostics: a main stage at the
    # class-blocked width plus the uniform tail
    assert np.asarray(r.pool_widths).shape[0] == 2
    with pytest.raises(ValueError, match="ragged"):
        mu_sched(a, w0, h0,
                 SolverConfig(algorithm="mu", backend="auto",
                              max_iter=600,
                              experimental=ExperimentalConfig(
                                  ragged=True)),
                 slots=6, job_ks=JOB_KS)


def test_factor_dtype_bf16_pool(jobs):
    """The factor_dtype='bfloat16' wide-pool EXPERIMENT (measured and
    rejected as a default — see probe_bf16_pool.py / RESULTS.md): the
    knob must validate its preconditions and produce a finite,
    converging solve with f32 result buffers. Trajectory equality is
    deliberately NOT asserted — bf16 factor storage is a real numerics
    change (on hardware it reaches bf16 fixed points and stops at the
    class floor)."""
    from nmfx.solvers.base import StopReason

    a, w0, h0 = jobs
    cfg = SolverConfig(algorithm="mu", backend="pallas", max_iter=600,
                       experimental=ExperimentalConfig(
                           factor_dtype="bfloat16"))
    r = mu_sched(a, w0, h0, cfg, slots=6)
    assert np.asarray(r.w).dtype == np.float32
    assert np.isfinite(np.asarray(r.w)).all()
    assert np.isfinite(np.asarray(r.dnorm)).all()
    its = np.asarray(r.iterations)
    assert (its > 0).all() and (its <= 600).all()
    assert set(np.asarray(r.stop_reason)) <= {int(StopReason.CLASS_STABLE),
                                              int(StopReason.TOL_X),
                                              int(StopReason.MAX_ITER)}
    # preconditions are enforced, not silently ignored
    with pytest.raises(ValueError, match="factor_dtype"):
        ExperimentalConfig(factor_dtype="float16")
    with pytest.raises(ValueError, match="bfloat16"):
        mu_sched(a, w0, h0,
                 SolverConfig(algorithm="mu", backend="auto",
                              max_iter=600,
                              experimental=ExperimentalConfig(
                                  factor_dtype="bfloat16")),
                 slots=6)
    with pytest.raises(ValueError, match="bfloat16"):
        mu_sched(a, w0, h0,
                 SolverConfig(algorithm="mu", backend="pallas",
                              max_iter=600,
                              experimental=ExperimentalConfig(
                                  ragged=True,
                                  factor_dtype="bfloat16")),
                 slots=6, job_ks=JOB_KS)


def test_alias_io_schedule_free(jobs):
    """alias_io donates the block kernel's input buffers as outputs —
    the round-3 hazard class, so its invariant is the strongest one:
    BIT-EXACT results vs the non-aliased path (the explicit step-0 DMA
    is the data path; the alias only affects buffer reuse). Verified
    on hardware at three levels by benchmarks/probe_alias_io.py; this
    locks the interpret-mode equivalence in CI."""
    a, w0, h0 = jobs
    cfg = SolverConfig(algorithm="mu", backend="pallas", max_iter=600)
    base = mu_sched(a, w0, h0, cfg, slots=6)
    al = mu_sched(a, w0, h0,
                  SolverConfig(algorithm="mu", backend="pallas",
                               max_iter=600,
                               experimental=ExperimentalConfig(
                                   alias_io=True)),
                  slots=6)
    np.testing.assert_array_equal(np.asarray(base.iterations),
                                  np.asarray(al.iterations))
    np.testing.assert_array_equal(np.asarray(base.stop_reason),
                                  np.asarray(al.stop_reason))
    np.testing.assert_array_equal(np.asarray(base.w), np.asarray(al.w))
    np.testing.assert_array_equal(np.asarray(base.h), np.asarray(al.h))


def test_job_ks_length_validation(jobs):
    """A wrong-length job_ks must fail loudly instead of silently
    corrupting results through clamped gathers (ADVICE.md round 5)."""
    a, w0, h0 = jobs
    cfg = SolverConfig(max_iter=10)
    with pytest.raises(ValueError, match="job_ks"):
        mu_sched(a, w0, h0, cfg, slots=4, job_ks=JOB_KS[:-1])
    with pytest.raises(ValueError, match="job_ks"):
        mu_grid(a, w0, h0, cfg, job_ks=JOB_KS + (2,))
    from nmfx.ops.grid_mu import pad_live_mask

    with pytest.raises(ValueError, match="job_ks"):
        pad_live_mask(w0, h0, JOB_KS[:3])


def test_fault_inject_requires_explicit_optin(jobs, monkeypatch, capsys):
    """The stale-reload fault injection arms ONLY through an explicit
    in-process call — since ISSUE 7 the ``nmfx.faults`` registry (site
    ``sched.stale_reload``), with ``enable_stale_reload_fault()`` kept
    as the deprecation shim the ``bench.py --verify`` env→call
    subprocess protocol targets. An inherited
    NMFX_FAULT_INJECT_STALE_RELOAD env var alone is inert in library
    code (but announces its inertness at import), so a test-harness
    environment can no longer corrupt a production run silently
    (ADVICE.md round 5; ISSUE 3 satellite; lint rule NMFX002)."""
    from nmfx import faults
    from nmfx.ops import sched_mu

    faults.disarm("sched.stale_reload")
    try:
        # env var alone: inert — the library never reads it at trace
        # time
        monkeypatch.setenv("NMFX_FAULT_INJECT_STALE_RELOAD", "0.5")
        monkeypatch.setitem(sched_mu._announced, "done", False)
        assert sched_mu._stale_reload_fraction() == 0.0
        # the import-time notice names the explicit opt-in it requires
        sched_mu._warn_inert_env_hook()
        err = capsys.readouterr().err
        assert "IGNORED" in err
        assert "enable_stale_reload_fault" in err
        # explicit opt-in: the SHIM arms the registry (deprecation
        # warning + the loud banner, banner exactly once)
        with pytest.deprecated_call():
            sched_mu.enable_stale_reload_fault(0.5)
        assert sched_mu._stale_reload_fraction() == 0.5
        spec = faults.armed("sched.stale_reload")
        assert spec is not None and spec.rate == 0.5
        err = capsys.readouterr().err
        assert "ARMED" in err
        assert "INVALID" in err
        with pytest.deprecated_call():
            sched_mu.enable_stale_reload_fault(0.5)
        assert "ARMED" not in capsys.readouterr().err
        # direct registry arming is equivalent (the canonical spelling)
        faults.arm("sched.stale_reload", rate=0.25)
        assert sched_mu._stale_reload_fraction() == 0.25
        faults.arm("sched.stale_reload", rate=0.5)
        # and the armed state is what the reload path consumes: the
        # mask now drops factor writes (identity when disarmed)
        load = jnp.ones((8,), bool)
        gather = jnp.arange(8, dtype=jnp.int32)
        masked = np.asarray(sched_mu._stale_load_mask(load, gather))
        assert masked.sum() < 8  # some reloads deliberately dropped
        faults.disarm("sched.stale_reload")
        np.testing.assert_array_equal(
            np.asarray(sched_mu._stale_load_mask(load, gather)),
            np.asarray(load))
        # arming a trace-affecting site keys the builder caches
        assert faults.trace_token() is None
        faults.arm("sched.stale_reload", rate=0.5)
        tok = faults.trace_token()
        assert tok is not None
        faults.disarm("sched.stale_reload")
        assert faults.trace_token() is None
        # out-of-range fractions are rejected
        with pytest.raises(ValueError, match="fraction"):
            sched_mu.enable_stale_reload_fault(1.5)
        # unset env: the import-time notice stays silent
        monkeypatch.delenv("NMFX_FAULT_INJECT_STALE_RELOAD")
        sched_mu._warn_inert_env_hook()
        assert "NMFX_FAULT_INJECT" not in capsys.readouterr().err
    finally:
        faults.disarm("sched.stale_reload")
